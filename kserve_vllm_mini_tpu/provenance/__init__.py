"""Provenance & supply chain (framework L8): reproducible artifact bundles,
cluster facts, SBOM/signing hooks (reference tools/{bundle_run,
collect_cluster_facts,sbom,sign}.sh)."""
