"""Cluster + environment fact collection for reproducibility bundles.

Reference behavior (tools/collect_cluster_facts.sh): capture k8s/KServe/
Knative/Istio versions (:46-67), accelerator node labels (:52-60), deployed
pod image digests (:85-89), git state (:95-108), and helm releases
(:111-121) into one JSON document. Every probe degrades gracefully — a
missing binary or unreachable cluster yields a null section, never a crash
(the harness must produce bundles from air-gapped result dirs too).

TPU adaptations: node facts select GKE TPU labels
(``cloud.google.com/gke-tpu-accelerator``, ``gke-tpu-topology``) instead of
GPU product labels, and local facts record the JAX/libtpu runtime versions
that determine XLA codegen.
"""

from __future__ import annotations

import argparse
import json
import platform
import subprocess
from typing import Any, Optional

from kserve_vllm_mini_tpu.deploy.kubectl import Kubectl


def _git(args: list[str], cwd: Optional[str] = None) -> Optional[str]:
    try:
        proc = subprocess.run(
            ["git", *args], capture_output=True, text=True, timeout=10, cwd=cwd
        )
    except (OSError, subprocess.TimeoutExpired):
        return None
    return proc.stdout.strip() if proc.returncode == 0 else None


def git_facts(repo_dir: Optional[str] = None) -> dict[str, Any]:
    """Commit/branch/dirty state of the harness itself
    (collect_cluster_facts.sh:95-108)."""
    commit = _git(["rev-parse", "HEAD"], repo_dir)
    if commit is None:
        return {"available": False}
    return {
        "available": True,
        "commit": commit,
        "branch": _git(["rev-parse", "--abbrev-ref", "HEAD"], repo_dir),
        "describe": _git(["describe", "--always", "--dirty"], repo_dir),
        "dirty": bool(_git(["status", "--porcelain"], repo_dir)),
    }


def local_facts() -> dict[str, Any]:
    """Host + JAX runtime facts — the TPU analog of driver/CUDA versions."""
    facts: dict[str, Any] = {
        "python": platform.python_version(),
        "platform": platform.platform(),
    }
    try:
        import jax

        facts["jax_version"] = jax.__version__
        try:
            import jaxlib

            facts["jaxlib_version"] = jaxlib.__version__
        except ImportError:
            pass
        # devices() initializes the backend; tolerate init failure on
        # harness-only installs
        try:
            devices = jax.devices()
            facts["devices"] = [
                {"platform": d.platform, "kind": getattr(d, "device_kind", "?")}
                for d in devices
            ]
        except Exception as e:  # noqa: BLE001
            facts["devices_error"] = f"{type(e).__name__}: {e}"
    except ImportError:
        facts["jax_version"] = None
    return facts


def cluster_facts(
    namespace: str = "", kubectl: Optional[Kubectl] = None
) -> dict[str, Any]:
    kc = kubectl or Kubectl()
    facts: dict[str, Any] = {}

    ver = kc.run(["version", "-o", "json"], timeout_s=15.0)
    if not ver.ok:
        return {"reachable": False, "error": ver.stderr.strip()[:200]}
    facts["reachable"] = True
    try:
        facts["kubernetes"] = json.loads(ver.stdout)
    except json.JSONDecodeError:
        facts["kubernetes"] = {"raw": ver.stdout[:500]}

    # component versions from deployment image tags (reference :46-67)
    for name, (ns, deploy) in {
        "kserve": ("kserve", "kserve-controller-manager"),
        "knative": ("knative-serving", "controller"),
        "istio": ("istio-system", "istiod"),
    }.items():
        res = kc.run(
            ["get", "deployment", deploy, "-n", ns,
             "-o", "jsonpath={.spec.template.spec.containers[0].image}"]
        )
        facts[f"{name}_image"] = res.stdout.strip() if res.ok else None

    # TPU node inventory by GKE labels (GPU-label analog of :52-60)
    nodes = kc.run(
        ["get", "nodes", "-l", "cloud.google.com/gke-tpu-accelerator", "-o", "json"]
    )
    tpu_nodes = []
    if nodes.ok:
        try:
            for item in json.loads(nodes.stdout).get("items", []):
                labels = item["metadata"].get("labels", {})
                tpu_nodes.append(
                    {
                        "name": item["metadata"]["name"],
                        "accelerator": labels.get("cloud.google.com/gke-tpu-accelerator"),
                        "topology": labels.get("cloud.google.com/gke-tpu-topology"),
                        "machine_type": labels.get("node.kubernetes.io/instance-type"),
                        "tpu_capacity": item.get("status", {})
                        .get("capacity", {})
                        .get("google.com/tpu"),
                    }
                )
        except (json.JSONDecodeError, KeyError):
            pass
    facts["tpu_nodes"] = tpu_nodes

    # deployed image digests in the benchmark namespace (:85-89)
    if namespace:
        pods = kc.run(
            ["get", "pods", "-n", namespace,
             "-o", "jsonpath={range .items[*]}{.status.containerStatuses[*].imageID}{'\\n'}{end}"]
        )
        if pods.ok:
            facts["image_digests"] = sorted(
                {line.strip() for line in pods.stdout.splitlines() if line.strip()}
            )
    return facts


def collect_facts(
    namespace: str = "",
    repo_dir: Optional[str] = None,
    kubectl: Optional[Kubectl] = None,
    include_cluster: bool = True,
) -> dict[str, Any]:
    return {
        "git": git_facts(repo_dir),
        "local": local_facts(),
        "cluster": cluster_facts(namespace, kubectl) if include_cluster
        else {"reachable": False, "skipped": True},
    }


# -- CLI (exposed through `kvmini-tpu bundle --facts-only`) ------------------

def register(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("--namespace", default="")
    parser.add_argument("--no-cluster", action="store_true")


def run(args: argparse.Namespace) -> int:
    print(json.dumps(
        collect_facts(args.namespace, include_cluster=not args.no_cluster), indent=2
    ))
    return 0
