"""Audit-grade, byte-reproducible artifact bundles of a benchmark run.

Reference behavior (tools/bundle_run.sh): copy the run dir's artifacts
(:110-137), write provenance.json (:139-173), capture cluster facts
(:150-151), render a human SUMMARY.md (:254-300), hook SBOM/signing
(:302-326), and produce a deterministic tar (fixed mtime, sorted names,
:329-333) so two bundles of the same run are byte-identical.

Implementation notes: tar determinism is done with Python ``tarfile`` by
sorting members and zeroing per-entry mtime/uid/gid — and gzip with
``mtime=0`` so the compressed stream is stable too. The bundle id is the
run id, not a timestamp, for the same reason.
"""

from __future__ import annotations

import argparse
import gzip
import io
import json
import tarfile
import time
from pathlib import Path
from typing import Any, Optional

from kserve_vllm_mini_tpu.core.rundir import RunDir
from kserve_vllm_mini_tpu.provenance.facts import collect_facts
from kserve_vllm_mini_tpu.provenance.sbom import generate_sboms, sign_artifact

# run-dir files included in every bundle, when present (bundle_run.sh:110-137)
ARTIFACT_FILES = [
    "requests.csv",
    "requests_classified.csv",
    "meta.json",
    "results.json",
    "power.json",
    "energy.json",
    "io_probe.json",
    "fairness_summary.json",
    "traces/traces.json",
]


def build_provenance(
    run_dir: RunDir,
    facts: dict[str, Any],
    extra: Optional[dict[str, Any]] = None,
) -> dict[str, Any]:
    meta = run_dir.read_meta()
    results = run_dir.read_results()
    return {
        "schema": "kvmini-tpu/provenance/v1",
        "run_id": run_dir.path.name,
        "created_at": meta.get("finished_at") or meta.get("started_at"),
        "workload": {
            k: meta.get(k)
            for k in ("model", "backend", "runtime", "pattern", "requests",
                      "concurrency", "streaming", "max_tokens", "seed")
        },
        "headline": {
            k: results.get(k)
            for k in ("p95_ms", "ttft_p95_ms", "throughput_rps", "tokens_per_sec",
                      "error_rate", "cost_per_1k_tokens", "energy_wh_per_1k_tokens")
        },
        "facts": facts,
        **(extra or {}),
    }


def render_summary(provenance: dict[str, Any]) -> str:
    """Human-readable SUMMARY.md (bundle_run.sh:254-300)."""
    w = provenance["workload"]
    h = provenance["headline"]

    def fmt(v: Any, suffix: str = "") -> str:
        return f"{v:.2f}{suffix}" if isinstance(v, (int, float)) else "n/a"

    git = provenance["facts"].get("git", {})
    lines = [
        f"# Benchmark bundle: {provenance['run_id']}",
        "",
        "## Workload",
        f"- model: {w.get('model')}  backend: {w.get('backend') or w.get('runtime')}",
        f"- load: {w.get('requests')} requests @ concurrency {w.get('concurrency')},"
        f" pattern {w.get('pattern')}, streaming {w.get('streaming')}",
        f"- seed: {w.get('seed')} (rerun with the same seed for byte-identical load)",
        "",
        "## Headline results",
        f"- p95 latency: {fmt(h.get('p95_ms'), ' ms')}",
        f"- TTFT p95: {fmt(h.get('ttft_p95_ms'), ' ms')}",
        f"- throughput: {fmt(h.get('throughput_rps'), ' rps')}"
        f" ({fmt(h.get('tokens_per_sec'), ' tok/s')})",
        f"- error rate: {fmt(h.get('error_rate'))}",
        f"- cost: ${h.get('cost_per_1k_tokens'):.4f}/1K tokens"
        if isinstance(h.get("cost_per_1k_tokens"), (int, float))
        else "- cost: n/a",
        f"- energy: {fmt(h.get('energy_wh_per_1k_tokens'), ' Wh/1K tokens')}",
        "",
        "## Provenance",
        f"- harness commit: {git.get('commit', 'unknown')}"
        + (" (dirty)" if git.get("dirty") else ""),
        f"- jax: {provenance['facts'].get('local', {}).get('jax_version')}",
        "",
        "## Reproduce",
        "```",
        f"kvmini-tpu bench --url <endpoint> --requests {w.get('requests')}"
        f" --concurrency {w.get('concurrency')} --pattern {w.get('pattern')}"
        f" --seed {w.get('seed')}",
        "```",
    ]
    return "\n".join(lines) + "\n"


def _deterministic_targz(src_dir: Path, dest: Path) -> None:
    """Sorted members, zeroed mtimes/owners, gzip mtime=0 → byte-stable
    (the tarfile equivalent of `tar --sort=name --mtime=@0 --owner=0`)."""
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w") as tar:
        for p in sorted(src_dir.rglob("*")):
            arcname = f"{dest.stem.removesuffix('.tar')}/{p.relative_to(src_dir)}"
            info = tar.gettarinfo(p, arcname=arcname)
            info.mtime = 0
            info.uid = info.gid = 0
            info.uname = info.gname = ""
            if p.is_file():
                with p.open("rb") as f:
                    tar.addfile(info, f)
            else:
                tar.addfile(info)
    dest.parent.mkdir(parents=True, exist_ok=True)
    with dest.open("wb") as f:
        with gzip.GzipFile(fileobj=f, mode="wb", mtime=0) as gz:
            gz.write(buf.getvalue())


def bundle_run(
    run_dir: RunDir,
    out_dir: Path,
    namespace: str = "",
    include_cluster: bool = False,
    sbom: bool = False,
    sign_key: Optional[str] = None,
    repo_dir: Optional[str] = None,
    kubectl=None,
) -> Path:
    """Assemble and tar one run. Returns the bundle path."""
    bundle_id = run_dir.path.name
    stage = Path(out_dir) / f"stage-{bundle_id}"
    if stage.exists():
        import shutil as _sh

        _sh.rmtree(stage)
    stage.mkdir(parents=True)

    copied = []
    for rel in ARTIFACT_FILES:
        src = run_dir.path / rel
        if src.exists():
            dest = stage / rel
            dest.parent.mkdir(parents=True, exist_ok=True)
            dest.write_bytes(src.read_bytes())
            copied.append(rel)

    facts = collect_facts(
        namespace, repo_dir=repo_dir, kubectl=kubectl, include_cluster=include_cluster
    )
    sbom_report: dict[str, Any] = {"available": False, "reason": "not requested"}
    if sbom:
        images = facts.get("cluster", {}).get("image_digests", [])
        sbom_report = generate_sboms(list(images), stage / "sbom")

    provenance = build_provenance(
        run_dir, facts, extra={"artifacts": copied, "sbom": sbom_report}
    )
    (stage / "provenance.json").write_text(json.dumps(provenance, indent=2, sort_keys=True))
    (stage / "SUMMARY.md").write_text(render_summary(provenance))

    bundle_path = Path(out_dir) / f"{bundle_id}.tar.gz"
    _deterministic_targz(stage, bundle_path)
    import shutil as _sh

    _sh.rmtree(stage)

    if sign_key is not None:
        sig = sign_artifact(bundle_path, key=sign_key or None)
        if sig.get("signed"):
            print(f"bundle: signed -> {sig['signature']}")
        elif not sig.get("available"):
            print(f"bundle: signing skipped ({sig.get('reason')})")
    return bundle_path


# -- CLI ---------------------------------------------------------------------

def register(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("--run-dir", required=True)
    parser.add_argument("--output-dir", default="artifacts")
    parser.add_argument("--namespace", default="")
    parser.add_argument("--cluster-facts", action="store_true",
                        help="Query the live cluster for facts (off: local facts only)")
    parser.add_argument("--sbom", action="store_true")
    parser.add_argument("--sign", nargs="?", const="", default=None, metavar="KEY",
                        help="cosign-sign the bundle (optional key path)")


def run(args: argparse.Namespace) -> int:
    run_dir = RunDir(args.run_dir)
    if not run_dir.results_json.exists():
        print(f"bundle: no results.json in {run_dir.path} — run analyze first")
        return 1
    t0 = time.time()
    path = bundle_run(
        run_dir,
        Path(args.output_dir),
        namespace=args.namespace,
        include_cluster=args.cluster_facts,
        sbom=args.sbom,
        sign_key=args.sign,
    )
    print(f"bundle: {path} ({path.stat().st_size} bytes, {time.time() - t0:.1f}s)")
    return 0
