"""SBOM generation + artifact signing hooks.

Reference behavior: tools/sbom.sh runs ``syft`` per deployed image into SPDX
JSON (:60-79); tools/sign.sh signs bundles with ``cosign``. Both tools are
optional externals — the harness checks availability first and records the
skip in the bundle instead of failing (the reference's "binary guard" lint
rule enforces the same, lint-test.yml:267-291)."""

from __future__ import annotations

import shutil
import subprocess
from pathlib import Path
from typing import Any, Optional


def _run(cmd: list[str], timeout_s: float = 300.0) -> tuple[bool, str]:
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=timeout_s)
    except (OSError, subprocess.TimeoutExpired) as e:
        return False, str(e)
    return proc.returncode == 0, proc.stderr.strip()[:300]


def generate_sboms(images: list[str], out_dir: Path) -> dict[str, Any]:
    """One SPDX JSON per image under ``out_dir`` (sbom.sh:60-79)."""
    if shutil.which("syft") is None:
        return {"available": False, "reason": "syft not on PATH", "generated": []}
    out_dir.mkdir(parents=True, exist_ok=True)
    generated, failed = [], []
    for image in images:
        safe = image.replace("/", "_").replace(":", "_").replace("@", "_")
        dest = out_dir / f"{safe}.spdx.json"
        ok, err = _run(["syft", image, "-o", f"spdx-json={dest}"])
        (generated if ok else failed).append(
            {"image": image, "path": str(dest)} if ok else {"image": image, "error": err}
        )
    return {"available": True, "generated": generated, "failed": failed}


def sign_artifact(path: Path, key: Optional[str] = None) -> dict[str, Any]:
    """Detached cosign signature next to the artifact (sign.sh)."""
    if shutil.which("cosign") is None:
        return {"available": False, "reason": "cosign not on PATH"}
    sig = path.with_suffix(path.suffix + ".sig")
    cmd = ["cosign", "sign-blob", "--yes", "--output-signature", str(sig), str(path)]
    if key:
        cmd += ["--key", key]
    ok, err = _run(cmd)
    return {"available": True, "signed": ok, "signature": str(sig) if ok else None,
            **({} if ok else {"error": err})}
