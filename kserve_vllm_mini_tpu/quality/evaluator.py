"""Mini quality-eval suite: quantify accuracy loss from quantization/config.

Reference behavior (/root/reference/quality/evaluator.py:20-338): small
embedded task sets scored 0-100 against an OpenAI-compatible endpoint, a
Pareto bucket classifier over (quality, latency, cost), and results.json
integration. The reference's 3-sample toy tasks are a noted weakness
(SURVEY.md §7.3.6) — sample counts here are 10-16 per task.

Tasks are deterministic and self-contained (no datasets to download):
- ``copy``        — exact-echo instruction following
- ``arithmetic``  — 2-3 digit add/sub/mul word problems
- ``completion``  — high-frequency bigram/world-knowledge cloze
- ``choice``      — 2-way commonsense multiple choice (A/B parsing)
"""

from __future__ import annotations

import argparse
import asyncio
import json
import random
import re
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

import httpx

from kserve_vllm_mini_tpu.loadgen.adapters.base import GenParams
from kserve_vllm_mini_tpu.loadgen.adapters.openai_chat import OpenAIChatAdapter


@dataclass
class Sample:
    prompt: str
    check: Callable[[str], bool]


def _arith_samples(rng: random.Random, n: int) -> list[Sample]:
    out = []
    for _ in range(n):
        op = rng.choice(["+", "-", "*"])
        if op == "*":
            a, b = rng.randint(2, 19), rng.randint(2, 12)
        else:
            a, b = rng.randint(10, 499), rng.randint(10, 499)
        ans = str(eval(f"{a}{op}{b}"))
        prompt = (
            f"What is {a} {op} {b}? Answer with only the number, no other text."
        )
        out.append(Sample(prompt, lambda t, ans=ans: _first_number(t) == ans))
    return out


def _first_number(text: str) -> Optional[str]:
    m = re.search(r"-?\d+", text.replace(",", ""))
    return m.group(0) if m else None


def _copy_samples(rng: random.Random, n: int) -> list[Sample]:
    out = []
    for _ in range(n):
        word = "".join(rng.choice("abcdefghikmnprstuw") for _ in range(6))
        prompt = f"Repeat exactly this word and nothing else: {word}"
        out.append(Sample(prompt, lambda t, w=word: w in t.lower()))
    return out


_COMPLETIONS = [
    ("The capital of France is", "paris"),
    ("Water freezes at zero degrees", "celsius"),
    ("The opposite of hot is", "cold"),
    ("Two plus two equals", "four|4"),
    ("The sun rises in the", "east"),
    ("The first month of the year is", "january"),
    ("A triangle has how many sides? Answer in one word:", "three|3"),
    ("The chemical symbol for water is", "h2o"),
    ("The largest planet in our solar system is", "jupiter"),
    ("The color of a clear daytime sky is", "blue"),
]

_CHOICES = [
    ("To cut paper you should use (A) scissors (B) a spoon.", "a"),
    ("Ice is (A) hot (B) cold.", "b"),
    ("Fish live in (A) water (B) sand.", "a"),
    ("At night you can usually see (A) the sun (B) the moon.", "b"),
    ("Bread is made primarily from (A) flour (B) rocks.", "a"),
    ("To write you would use (A) a hammer (B) a pen.", "b"),
    ("Rain falls from (A) clouds (B) the ground.", "a"),
    ("A dictionary is used to look up (A) recipes (B) word meanings.", "b"),
]


def _completion_samples() -> list[Sample]:
    out = []
    for prompt, answer in _COMPLETIONS:
        pattern = re.compile(rf"\b({answer})\b", re.IGNORECASE)
        out.append(
            Sample(prompt + " Answer in one word.", lambda t, p=pattern: bool(p.search(t)))
        )
    return out


def _choice_samples() -> list[Sample]:
    out = []
    for prompt, answer in _CHOICES:
        def check(t: str, ans=answer) -> bool:
            m = re.search(r"\b([ab])\b", t.strip().lower())
            return bool(m and m.group(1) == ans)

        out.append(Sample(prompt + " Answer A or B only.", check))
    return out


def build_tasks(seed: int = 42) -> dict[str, list[Sample]]:
    rng = random.Random(seed)
    return {
        "copy": _copy_samples(rng, 10),
        "arithmetic": _arith_samples(rng, 16),
        "completion": _completion_samples(),
        "choice": _choice_samples(),
    }


async def evaluate_async(
    url: str,
    model: str = "default",
    seed: int = 42,
    max_tokens: int = 32,
    timeout_s: float = 60.0,
) -> dict[str, Any]:
    tasks = build_tasks(seed)
    adapter = OpenAIChatAdapter()
    params = GenParams(max_tokens=max_tokens, temperature=0.0)
    scores: dict[str, float] = {}
    n_total = n_correct = 0
    async with httpx.AsyncClient(timeout=timeout_s) as client:
        for name, samples in tasks.items():
            correct = 0
            for s in samples:
                res = await adapter.generate(
                    client, url, model, s.prompt, params, stream=False
                )
                if res.ok and s.check(res.text):
                    correct += 1
            scores[name] = 100.0 * correct / len(samples)
            n_total += len(samples)
            n_correct += correct
    return {
        "quality_score": 100.0 * n_correct / n_total if n_total else 0.0,
        "quality_tasks": scores,
        "quality_samples": n_total,
    }


def evaluate(url: str, **kwargs) -> dict[str, Any]:
    return asyncio.run(evaluate_async(url, **kwargs))


# -- fidelity vs a reference configuration -----------------------------------
# The task suite above needs a *trained* model to discriminate; on the
# random-weight smoke models CI uses, every config scores ~chance and the
# Pareto quality axis is noise (round-2 VERDICT Weak #8). Fidelity is the
# signal that works regardless of training: how closely does a quantized
# config's GREEDY output distribution track the unquantized baseline on the
# same prompts? int8 weights, int8 KV, and their combination measurably
# diverge in token-prefix agreement and first-token logprob — a real
# quantization-quality ordering with no dataset dependency.

def fidelity_prompts(seed: int = 42, n: int = 20) -> list[str]:
    rng = random.Random(seed)
    prompts = [p for p, _ in _COMPLETIONS[:6]]
    for _ in range(n - len(prompts)):
        words = " ".join(
            "".join(rng.choice("aehilmnorstu") for _ in range(rng.randint(3, 7)))
            for _ in range(rng.randint(4, 10))
        )
        prompts.append(f"Continue this text: {words}")
    return prompts[:n]


async def capture_outputs_async(
    url: str,
    model: str = "default",
    prompts: Optional[list[str]] = None,
    max_tokens: int = 24,
    timeout_s: float = 120.0,
) -> list[dict[str, Any]]:
    """Greedy outputs + per-token logprobs for each prompt — the comparable
    record fidelity_metrics consumes (capture once, compare many configs)."""
    prompts = prompts or fidelity_prompts()
    out: list[dict[str, Any]] = []
    async with httpx.AsyncClient(timeout=timeout_s) as client:
        for prompt in prompts:
            resp = await client.post(
                url.rstrip("/") + "/v1/chat/completions",
                json={
                    "model": model,
                    "messages": [{"role": "user", "content": prompt}],
                    "max_tokens": max_tokens,
                    "temperature": 0.0,
                    "logprobs": True,
                },
            )
            # a failed capture must FAIL, not score as divergence: an empty
            # token list reads as fidelity 0 and silently misranks the config
            if resp.status_code != 200:
                raise RuntimeError(
                    f"fidelity capture got HTTP {resp.status_code} for "
                    f"prompt {prompt[:40]!r}"
                )
            try:
                data = resp.json()
            except ValueError as e:
                raise RuntimeError(f"fidelity capture got non-JSON body: {e}") from e
            choice = (data.get("choices") or [{}])[0]
            entries = ((choice.get("logprobs") or {}).get("content")) or []
            if entries:
                tokens = [e.get("token", "") for e in entries]
                lps = [float(e.get("logprob", 0.0)) for e in entries]
            else:  # backend without logprobs: fall back to text split
                tokens = list((choice.get("message") or {}).get("content") or "")
                lps = []
            out.append({"prompt": prompt, "tokens": tokens, "logprobs": lps})
    return out


def capture_outputs(url: str, **kwargs) -> list[dict[str, Any]]:
    return asyncio.run(capture_outputs_async(url, **kwargs))


def fidelity_metrics(
    reference: list[dict[str, Any]], candidate: list[dict[str, Any]]
) -> dict[str, Any]:
    """Compare captured greedy outputs: token-prefix agreement (greedy
    decode diverges permanently at the first mismatch, so the common prefix
    is the right unit), exact-output rate, and mean |Δ logprob| of the
    first token (same-context comparison unaffected by drift)."""
    prefix_fracs: list[float] = []
    exact = 0
    lp_deltas: list[float] = []
    for ref, cand in zip(reference, candidate):
        rt, ct = ref["tokens"], cand["tokens"]
        denom = max(len(rt), len(ct), 1)
        common = 0
        for a, b in zip(rt, ct):
            if a != b:
                break
            common += 1
        prefix_fracs.append(common / denom)
        exact += int(rt == ct and len(rt) > 0)
        if ref["logprobs"] and cand["logprobs"]:
            lp_deltas.append(abs(ref["logprobs"][0] - cand["logprobs"][0]))
    n = max(len(prefix_fracs), 1)
    out: dict[str, Any] = {
        "quality_fidelity": round(100.0 * sum(prefix_fracs) / n, 2),
        "fidelity_exact_match": round(exact / n, 4),
        "fidelity_prompts": n,
    }
    if lp_deltas:
        out["fidelity_first_logprob_mad"] = round(sum(lp_deltas) / len(lp_deltas), 5)
    return out


# -- Pareto bucket classifier (reference evaluator.py:260-314) ---------------

def classify_pareto_bucket(
    quality: float, p95_ms: float, cost_per_1k: float,
    quality_floor: float = 90.0, p95_budget_ms: float = 1200.0,
    cost_budget: float = 0.05,
) -> str:
    """3-axis bucket: which constraints does a config satisfy?"""
    q_ok = quality >= quality_floor
    l_ok = p95_ms <= p95_budget_ms
    c_ok = cost_per_1k <= cost_budget
    if q_ok and l_ok and c_ok:
        return "sweet-spot"
    if q_ok and l_ok:
        return "quality-latency"
    if q_ok and c_ok:
        return "quality-cost"
    if l_ok and c_ok:
        return "cheap-fast-degraded"
    if q_ok:
        return "quality-only"
    return "dominated"


def pareto_frontier(points: list[dict[str, float]],
                    minimize: tuple[str, ...] = ("p95_ms", "cost_per_1k_tokens"),
                    maximize: tuple[str, ...] = ("quality_score",)) -> list[int]:
    """Indices of non-dominated points (O(n^2) dominance, reference
    quantization_sweep.py:510-549)."""
    def dominates(a: dict, b: dict) -> bool:
        no_worse = all(a.get(k, 0) >= b.get(k, 0) for k in maximize) and all(
            a.get(k, float("inf")) <= b.get(k, float("inf")) for k in minimize
        )
        strictly = any(a.get(k, 0) > b.get(k, 0) for k in maximize) or any(
            a.get(k, float("inf")) < b.get(k, float("inf")) for k in minimize
        )
        return no_worse and strictly

    return [
        i for i, p in enumerate(points)
        if not any(dominates(q, p) for j, q in enumerate(points) if j != i)
    ]


# -- CLI ---------------------------------------------------------------------

def register(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("--url", required=True)
    parser.add_argument("--model", default="default")
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--run-dir", default=None,
                        help="Merge quality_* keys into this run's results.json")


def run(args: argparse.Namespace) -> int:
    result = evaluate(args.url, model=args.model, seed=args.seed)
    print(json.dumps(result, indent=2))
    if args.run_dir:
        from kserve_vllm_mini_tpu.core.rundir import RunDir

        RunDir(args.run_dir).merge_into_results(result)
    return 0
