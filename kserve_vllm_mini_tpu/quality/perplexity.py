"""Likelihood-based quality metric: teacher-forced NLL on real text.

The generate-and-check task suite (quality/evaluator.py) measures behavior
through the HTTP surface, but on small or random-weight models every
quantization config scores ~chance, so the sweep's quality axis cannot
detect real model damage (round-3 verdict weak #4; reference counterpart
/root/reference/quality/evaluator.py:75-224 has the same blindness with 3
samples). Per-token negative log-likelihood on curated real text is the
discriminating axis: it is computed in ONE teacher-forced forward per
batch, needs no generation loop, and responds monotonically to the logit
perturbations quantization introduces — int8 vs int4 produce measurably
different numbers even on a tiny checkpoint.

Used by the quantization sweep (in-process, through LocalServer.engine)
and by the CI-optional real-checkpoint lane
(tests/test_quality_real_checkpoint.py).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import numpy as np

from kserve_vllm_mini_tpu.models.config import ModelConfig
from kserve_vllm_mini_tpu.quality.texts import EVAL_TEXTS


def eval_text_nll(
    params: dict[str, Any],
    cfg: ModelConfig,
    tokenizer,
    texts: Optional[Sequence[str]] = None,
    max_len: int = 192,
) -> dict[str, float]:
    """Mean NLL/token (and perplexity) of ``texts`` under the model.

    One jitted forward over a padded [N, max_len] batch; pad positions are
    masked out of the mean. Deterministic — no sampling, no server."""
    import jax
    import jax.numpy as jnp

    from kserve_vllm_mini_tpu.models.llama import forward

    texts = list(texts if texts is not None else EVAL_TEXTS)
    rows, masks = [], []
    for t in texts:
        ids = tokenizer.encode(t)[:max_len]
        pad = max_len - len(ids)
        rows.append(ids + [tokenizer.pad_id] * pad)
        masks.append([1.0] * len(ids) + [0.0] * pad)
    tokens = jnp.asarray(rows, dtype=jnp.int32)
    mask = jnp.asarray(masks, dtype=jnp.float32)

    @jax.jit
    def batch_nll(params, tokens, mask):
        inp, tgt = tokens[:, :-1], tokens[:, 1:]
        pos = jnp.broadcast_to(
            jnp.arange(inp.shape[1], dtype=jnp.int32), inp.shape
        )
        logits, _ = forward(params, cfg, inp, pos)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        tok_lp = jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
        m = mask[:, 1:]  # a target counts only where the TARGET is real
        return -(tok_lp * m).sum(), m.sum()

    total_nll, n_tok = batch_nll(params, tokens, mask)
    nll = float(total_nll) / max(float(n_tok), 1.0)
    return {
        "nll_per_token": nll,
        "perplexity": float(np.exp(min(nll, 30.0))),
        "n_tokens": int(n_tok),
        "n_texts": len(texts),
    }
