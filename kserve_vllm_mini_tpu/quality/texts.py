"""Curated real-text evaluation passages for the perplexity quality axis.

Original prose written for this repository (no external corpus, no network
fetch — the air-gapped CI constraint). What matters for the metric is that
the byte statistics are REAL natural language: ordinary words, ordinary
grammar, varied vocabulary. On text like this, quantization error moves
next-token likelihoods in measurable ways that the generate-and-check task
suite cannot detect on small models (SURVEY.md §7.3.6; round-3 verdict
weak #4).
"""

EVAL_TEXTS: list[str] = [
    (
        "The morning train left the station four minutes late, which was "
        "enough to miss the connection at the junction. Passengers waited "
        "on the platform under a gray sky, watching the signal lights "
        "change from red to amber and back again while the announcer "
        "apologized twice for the delay."
    ),
    (
        "To make the soup, chop two onions and a carrot, then cook them "
        "slowly in a little oil until they soften. Add the stock, the "
        "beans, and a bay leaf, and let everything simmer for half an "
        "hour. Season with salt near the end, because the stock reduces "
        "and grows saltier as it cooks."
    ),
    (
        "The bridge was finished in the autumn of the third year. Its two "
        "towers carried the weight of the deck through long steel cables, "
        "each spun from thousands of individual wires. Engineers measured "
        "the sag of the cables every week during construction, comparing "
        "the numbers against the tables they had computed by hand."
    ),
    (
        "She kept the garden small on purpose: a row of tomatoes, some "
        "beans on poles, and a border of herbs she could reach from the "
        "path. In July the basil grew faster than she could use it, and "
        "the neighbors learned to expect a bundle of it left by the door "
        "with no note."
    ),
    (
        "A library is a patient kind of place. Books wait decades between "
        "readers without complaint, and the catalog remembers every title "
        "long after the shelves have been rearranged. The librarian knew "
        "the collection the way a pilot knows a coastline, by landmarks "
        "rather than by the map."
    ),
    (
        "The experiment failed twice before anyone thought to check the "
        "thermometer itself. It read three degrees high, a small error "
        "that compounded through every calculation that followed. After "
        "the instrument was replaced, the results matched the prediction "
        "within the stated uncertainty."
    ),
    (
        "Rain came early that winter and stayed. The river rose to the "
        "second mark on the old stone gauge, then to the third, and the "
        "town moved its market up the hill for the season. By spring the "
        "water had returned to its usual channel, leaving a line of silt "
        "on the fences to show where it had been."
    ),
    (
        "He wrote letters the old way, on paper, with a pen that leaked a "
        "little. Each one took an evening, and most said ordinary things: "
        "the weather, the dog, a repair to the porch step. Years later, "
        "those ordinary things were exactly what his granddaughter wanted "
        "to read."
    ),
]
