"""Energy collection + integration: Wh, Wh/request, Wh/1K tokens.

The math is the reference's, verbatim in behavior (trapezoidal integration
over 1 s power samples, idle-tax ``series``/``baseline`` modes —
/root/reference/energy/collector.py:133-149, 254-381); the *source* chain is
TPU-native:

1. Prometheus TPU power metrics (measured)
2. runtime /metrics duty cycle x TDP (modeled)
3. flat TDP x duty assumption (modeled, worst case)

``energy.json`` always records ``provenance`` so modeled numbers are never
mistaken for measured ones (SURVEY.md §7.3.3). Two subcommands mirror the
reference CLI: ``collect`` (sampling daemon) and ``integrate`` (post-run).
"""

from __future__ import annotations

import argparse
import json
import time
from typing import Any, Optional

from kserve_vllm_mini_tpu.analysis import telemetry
from kserve_vllm_mini_tpu.core.rundir import RunDir, window_bounds


def sample_power_once(
    prom_url: Optional[str],
    endpoint: Optional[str],
    accelerator: Optional[str] = None,
    timeout_s: float = 2.0,
) -> tuple[Optional[float], str]:
    """One instantaneous total-power sample -> (watts, provenance).

    Short timeouts: this runs inside a 1 Hz sampling loop that must remain
    responsive to its stop signal even when sources are unreachable."""
    if prom_url:
        for q in telemetry.TPU_POWER_QUERIES:
            v = telemetry.prom_instant_query(prom_url, q, timeout_s=timeout_s)
            if v is not None:
                return v, "measured"
    if endpoint:
        m = telemetry.scrape_runtime_metrics(endpoint, timeout_s=timeout_s)
        duty = m.get("kvmini_tpu_duty_cycle")
        if duty is not None:
            return telemetry.modeled_power(duty, accelerator), "modeled"
    return None, "unavailable"


def power_from_timeline(
    timeline: list[dict[str, Any]],
    accelerator: Optional[str] = None,
    interval_s: float = 1.0,
) -> dict[str, Any]:
    """Derive a power.json-shaped doc from monitor timeline samples
    (monitor/sampler.py) instead of running a second 1 Hz scrape loop
    against the same endpoint during a benchmark (docs/MONITORING.md).

    Per-sample duty prefers the windowed value — delta of the
    kvmini_tpu_busy_seconds_total counter over the sample gap, falling
    back to the cumulative duty-cycle gauge (telemetry.
    windowed_duty_series, shared with the report's timeline lane); watts
    are always modeled (duty x TDP), provenance says so."""
    duties = telemetry.windowed_duty_series([
        (float(s["t"]), s["runtime"])
        for s in timeline
        if isinstance(s.get("t"), (int, float))
        and isinstance(s.get("runtime"), dict)
    ])
    pts = [
        {"t": t, "watts": telemetry.modeled_power(duty, accelerator)}
        for t, duty in duties
    ]
    return {
        "samples": pts,
        "provenance": "modeled" if pts else "unavailable",
        "interval_s": interval_s,
        "source": "timeline",
        "started_at": pts[0]["t"] if pts else None,
        "finished_at": pts[-1]["t"] if pts else None,
    }


def collect_power(
    run_dir: RunDir,
    prom_url: Optional[str],
    endpoint: Optional[str],
    interval_s: float = 1.0,
    duration_s: Optional[float] = None,
    accelerator: Optional[str] = None,
    stop_check=None,
    timeline: Optional[list[dict[str, Any]]] = None,
) -> dict[str, Any]:
    """Sampling loop -> power.json. Runs until duration elapses or
    ``stop_check()`` returns True.

    ``timeline``: pre-collected monitor samples — converts them instead
    of opening a second scrape loop against the same endpoint (the
    monitor already paid those scrapes; see power_from_timeline)."""
    if timeline is not None:
        doc = power_from_timeline(timeline, accelerator, interval_s=interval_s)
        run_dir.write_power(doc)
        return doc
    samples: list[dict[str, float]] = []
    provenance = "unavailable"
    t_start = time.time()
    while True:
        now = time.time()
        if duration_s is not None and now - t_start >= duration_s:
            break
        if stop_check is not None and stop_check():
            break
        watts, prov = sample_power_once(prom_url, endpoint, accelerator)
        if watts is not None:
            samples.append({"t": now, "watts": watts})
            provenance = prov
        time.sleep(max(interval_s - (time.time() - now), 0.0))
    doc = {
        "samples": samples,
        "provenance": provenance,
        "interval_s": interval_s,
        "started_at": t_start,
        "finished_at": time.time(),
    }
    run_dir.write_power(doc)
    return doc


def trapezoidal_wh(samples: list[dict[str, float]], t0: float, t1: float) -> float:
    """Integrate watts over [t0, t1] (seconds) -> watt-hours.

    Samples outside the window are clipped; gaps integrate linearly
    between neighbors (reference collector.py:133-149). Unsorted input
    is sorted first and zero-width segments (duplicate timestamps — two
    collectors writing the same tick) are skipped, so the integral can
    never go negative or divide by a zero gap; a single usable sample
    has no span at all and integrates to 0.0 (the caller records WHY —
    see integrate_energy's provenance note)."""
    pts = sorted((s["t"], s["watts"]) for s in samples)
    pts = [(t, w) for t, w in pts if t0 - 60 <= t <= t1 + 60]
    if len(pts) < 2 or t1 <= t0:
        return 0.0
    total_ws = 0.0
    for (ta, wa), (tb, wb) in zip(pts, pts[1:]):
        a, b = max(ta, t0), min(tb, t1)
        if b <= a or tb == ta:
            continue
        # linear interp of watts at the clipped endpoints
        w_a = wa + (wb - wa) * (a - ta) / (tb - ta)
        w_b = wa + (wb - wa) * (b - ta) / (tb - ta)
        total_ws += 0.5 * (w_a + w_b) * (b - a)
    return max(total_ws, 0.0) / 3600.0


def integrate_energy(
    run_dir: RunDir,
    idle_tax: str = "none",            # none | series | baseline
    idle_baseline_watts: float = 0.0,
    merge: bool = True,
) -> dict[str, Any]:
    """power.json + requests.csv -> energy.json (+ merge into results.json).

    Idle-tax modes (reference collector.py:307-347):
    - ``series``: subtract the lowest-decile sample power (measured idle) from
      every sample before integrating — attributes only marginal energy.
    - ``baseline``: subtract an explicit idle wattage.
    - ``none``: full draw attributed to the run.
    """
    power = run_dir.read_power()
    samples = power.get("samples", [])
    if not samples:
        # no power.json (or an empty one): integrate from the monitor's
        # timeline when the run has one — the sampler already carried
        # duty/busy at 1 Hz, there is no reason to report 0 Wh
        timeline = run_dir.read_timeline()
        if timeline:
            power = power_from_timeline(
                timeline, run_dir.read_meta().get("accelerator")
            )
            samples = power.get("samples", [])
            if samples:
                run_dir.write_power(power)
    records = run_dir.read_requests()
    t0, t1 = window_bounds(records)

    # degenerate sample sets integrate to 0.0 by construction
    # (trapezoidal_wh); say WHY in the doc so a 0 Wh row is attributable
    # instead of looking like a measured-idle run
    note = None
    distinct_ts = {float(s["t"]) for s in samples}
    if len(samples) == 1:
        note = "single power sample: no span to integrate; energy 0.0"
    elif samples and len(distinct_ts) < 2:
        note = ("power samples share one timestamp (duplicate ticks): "
                "no span to integrate; energy 0.0")
    raw_wh = trapezoidal_wh(samples, t0, t1)
    idle_w = 0.0
    if idle_tax == "series" and samples:
        watts_sorted = sorted(s["watts"] for s in samples)
        decile = watts_sorted[: max(len(watts_sorted) // 10, 1)]
        idle_w = sum(decile) / len(decile)
    elif idle_tax == "baseline":
        idle_w = idle_baseline_watts
    active_wh = max(raw_wh - idle_w * (t1 - t0) / 3600.0, 0.0)

    ok = [r for r in records if r.ok]
    tokens_out = sum(r.tokens_out for r in ok)
    doc: dict[str, Any] = {
        "window": {"start": t0, "end": t1, "duration_s": t1 - t0},
        "energy_wh": active_wh,
        "energy_wh_raw": raw_wh,
        "idle_tax_mode": idle_tax,
        "idle_watts": idle_w,
        "samples": len(samples),
        "provenance": power.get("provenance", "unavailable"),
    }
    if note:
        doc["note"] = note
    if ok:
        doc["energy_wh_per_request"] = active_wh / len(ok)
    if tokens_out:
        doc["energy_wh_per_1k_tokens"] = active_wh * 1000.0 / tokens_out
    run_dir.write_energy(doc)
    if merge and samples:
        run_dir.merge_into_results(
            {
                "energy_wh": doc["energy_wh"],
                "energy_wh_per_request": doc.get("energy_wh_per_request"),
                "energy_wh_per_1k_tokens": doc.get("energy_wh_per_1k_tokens"),
                "power_provenance": doc["provenance"],
            }
        )
    return doc


# -- CLI ---------------------------------------------------------------------

def register(parser: argparse.ArgumentParser) -> None:
    sub = parser.add_subparsers(dest="mode", required=True)
    c = sub.add_parser("collect", help="Sample chip power into power.json")
    c.add_argument("--run-dir", required=True)
    c.add_argument("--prom-url", default=None)
    c.add_argument("--endpoint", default=None)
    c.add_argument("--interval", type=float, default=1.0)
    c.add_argument("--duration", type=float, default=None)
    c.add_argument("--accelerator", default=None)
    i = sub.add_parser("integrate", help="power.json -> energy.json")
    i.add_argument("--run-dir", required=True)
    i.add_argument("--idle-tax", choices=["none", "series", "baseline"], default="none")
    i.add_argument("--idle-watts", type=float, default=0.0)
    i.add_argument("--no-merge", action="store_true")


def run(args: argparse.Namespace) -> int:
    rd = RunDir(args.run_dir)
    if args.mode == "collect":
        doc = collect_power(
            rd, args.prom_url, args.endpoint,
            interval_s=args.interval, duration_s=args.duration,
            accelerator=args.accelerator,
        )
        print(f"energy collect: {len(doc['samples'])} samples "
              f"({doc['provenance']}) -> {rd.power_json}")
        return 0
    doc = integrate_energy(
        rd, idle_tax=args.idle_tax, idle_baseline_watts=args.idle_watts,
        merge=not args.no_merge,
    )
    print(
        f"energy integrate: {doc['energy_wh']:.4f} Wh "
        f"({doc.get('energy_wh_per_1k_tokens', 0):.3f} Wh/1K tok, "
        f"{doc['provenance']}) -> {rd.energy_json}"
    )
    return 0
