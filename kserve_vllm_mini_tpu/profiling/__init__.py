"""Never-dark perf observability (docs/PROFILING.md).

Three layers, importable separately so the JAX-free harness stages never
pay the runtime import:

- ``compile_stats`` — explicit ``lower().compile()`` capture around the
  repo's jitted executables: compile wall time, XLA cost-model FLOPs and
  bytes-accessed, ``memory_analysis`` peak-buffer estimates, and HLO op
  histograms. ``InstrumentedJit`` wraps the engine's compiled steps so a
  serving process accumulates the same stats into ``/metrics``.
- ``headroom`` — the admission/headroom guard: analytic HBM estimates for
  a serving config pre-flighted against device capacity, downshifting
  slots/context (labeled, never crashed) when a config would
  RESOURCE_EXHAUST.
- ``proxy`` — the CPU-mesh proxy bench tier: when the TPU probe fails,
  bench.py degrades to the forced 8-device host platform and reports
  compile stats, cost-model FLOPs/bytes, and sync-vs-pipelined step-count
  ratios as clearly-labeled ``proxy:`` metrics instead of going dark.
"""

from kserve_vllm_mini_tpu.profiling.compile_stats import (  # noqa: F401
    CompileRecorder,
    CompileStats,
    InstrumentedJit,
    capture_compile_stats,
    extract_compile_stats,
    hlo_op_histogram,
)
from kserve_vllm_mini_tpu.profiling.headroom import (  # noqa: F401
    HeadroomPlan,
    device_hbm_bytes,
    estimate_serving_bytes,
    plan_admission,
    serving_headroom_plan,
)
