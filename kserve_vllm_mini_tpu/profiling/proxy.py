"""CPU-mesh proxy bench tier: tracked metrics with zero device time.

Three of five driver bench rounds produced no perf signal (wedged TPU
relay, one OOM). This tier is the fallback bench.py runs when the TPU
probe fails: on the forced 8-device host platform (the same
``--xla_force_host_platform_device_count=8`` virtual mesh every tier-1
test and multichip dryrun uses) it

1. **compiles the flagship program abstractly** — ``lower().compile()``
   over ``ShapeDtypeStruct`` trees, so the real 8B-class prefill/decode
   executables are built WITHOUT materializing 16 GB of weights — and
   extracts the XLA cost model's FLOPs/bytes-accessed, the
   buffer-assignment peak estimate, HLO op histograms, and compile wall
   time (``profiling.compile_stats``);
2. **executes a small config end-to-end** on the host mesh (real params,
   real prefill + decode loops) and measures the sync-vs-chained
   step-count ratio — how much per-step host synchronization costs
   relative to pipelined dispatch, the shape-level signal behind the
   decode pipeline's benefit;
3. **pre-flights the flagship against HBM capacity** (the headroom guard)
   so the round also reports whether the config would have fit.

Everything is labeled ``series: "proxy"`` and kept as its own trajectory
series (analysis/trajectory.py) — proxy rounds track compile-level and
cost-model drift, they never claim device throughput
(docs/PROFILING.md spells out what proxy metrics can and cannot say).
"""

from __future__ import annotations

import time
from typing import Any, Optional

from kserve_vllm_mini_tpu.profiling.compile_stats import capture_compile_stats
from kserve_vllm_mini_tpu.profiling.headroom import (
    estimate_serving_bytes,
    serving_headroom_plan,
)


def _build_step_fns(cfg, slots: int, prompt_len: int):
    """The bench serving child's prefill/decode shapes, minimal: batch
    fresh-prefill (donated cache, last-position logits) and one fused
    sampling decode step — the two executables every serving number in
    this repo flows through."""
    import jax
    import jax.numpy as jnp

    from functools import partial

    from kserve_vllm_mini_tpu.models.llama import forward
    from kserve_vllm_mini_tpu.runtime.sampling import sample_tokens

    @partial(jax.jit, donate_argnums=(1,))
    def prefill(params, cache, toks, pos):
        last = jnp.full((slots,), prompt_len - 1, dtype=jnp.int32)
        logits, cache = forward(
            params, cfg, toks, pos, cache, jnp.zeros((slots,), jnp.int32),
            fresh_prefill=True, logit_index=last,
        )
        return cache, jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)

    @partial(jax.jit, donate_argnums=(1,))
    def decode(params, cache, tokens, lengths, rng):
        logits, cache = forward(params, cfg, tokens[:, None],
                                lengths[:, None], cache, lengths)
        nxt = sample_tokens(
            logits[:, 0, :], rng,
            jnp.zeros((slots,), jnp.float32),
            jnp.zeros((slots,), jnp.int32),
            jnp.ones((slots,), jnp.float32),
        )
        return cache, nxt

    return prefill, decode


def _build_chunk_prefill_fn(cfg, chunk_len: int):
    """The engine's CONTINUATION-chunk prefill shape (runtime/engine.py
    _get_chunk_prefill_fn): one chunk written at a running offset,
    attending the whole cache with positional masking — the executable
    whose KV READ is what int8-KV prefill halves (the fresh-prefill path
    never reads the cache). B=1, the engine's per-request admission
    shape."""
    import jax
    import jax.numpy as jnp

    from functools import partial

    from kserve_vllm_mini_tpu.models.llama import forward

    @partial(jax.jit, donate_argnums=(1,))
    def chunk_prefill(params, cache, toks, offset):
        pos = offset + jnp.arange(chunk_len, dtype=jnp.int32)[None]
        logits, cache = forward(
            params, cfg, toks, pos, cache, offset[None],
            logit_index=jnp.full((1,), chunk_len - 1, jnp.int32),
        )
        return cache, logits[0, 0]

    return chunk_prefill


def _build_disagg_prefill_fn(cfg, prompt_len: int):
    """The disaggregated prefill LANE's staging executable
    (runtime/disagg.py PrefillLane): a B=1 fresh prefill into the lane's
    single-slot staging cache, last-position logits only — the program
    that runs on lane devices instead of the decode lane's sweep loop,
    so its compile stats are the proxy rail the disagg sweep axis and
    the dark-round trajectory track."""
    import jax
    import jax.numpy as jnp

    from functools import partial

    from kserve_vllm_mini_tpu.models.llama import forward

    @partial(jax.jit, donate_argnums=(1,))
    def disagg_prefill(params, cache, toks):
        pos = jnp.arange(toks.shape[1], dtype=jnp.int32)[None]
        logits, cache = forward(
            params, cfg, toks, pos, cache, jnp.zeros((1,), jnp.int32),
            fresh_prefill=True,
            logit_index=jnp.full((1,), prompt_len - 1, jnp.int32),
        )
        return cache, logits[0, 0]

    return disagg_prefill


def cost_model_stats(
    model: str,
    quant: str,
    slots: int,
    max_seq: int,
    prompt_len: int = 128,
    kv_quant: bool = False,
    quant_mode: str = "dequant",
    prefill_chunk: int = 64,
) -> dict[str, Any]:
    """Abstract-compile the flagship config's prefill + decode and return
    their compile stats. No weights are ever materialized — ``eval_shape``
    over the initializers yields the exact parameter/cache avals, and
    ``lower()`` accepts them directly.

    ``quant`` selects the abstract tree the program compiles against:
    int8/int4 trees come from ``init_params_quantized``'s avals, so the
    cost model's bytes_accessed prices the int8/packed-uint8 weight stream
    the quantized deployment actually reads — the rail the W8A8
    compiled-bytes acceptance pin rides (tests/test_qmatmul.py).
    ``quant_mode`` rides cfg (static) and selects the dequant vs int8-MXU
    contraction in the compiled program.

    A third entry, ``chunk_prefill``, compiles the engine's continuation-
    chunk prefill at ``prefill_chunk`` tokens against a 1-slot cache —
    the prefill executable that READS the cache, so its bytes_accessed is
    the rail the int8-KV prefill acceptance pin rides (``kv_quant=True``
    streams int8 stripes instead of the bf16 read)."""
    import jax
    import jax.numpy as jnp

    from kserve_vllm_mini_tpu.models.config import get_config
    from kserve_vllm_mini_tpu.models.llama import (
        init_kv_cache,
        init_params,
        init_params_quantized,
    )

    cfg = get_config(model, max_seq_len=max_seq, quant_mode=quant_mode)
    if quant in ("int8", "int4"):
        from functools import partial as _p

        init_fn = _p(init_params_quantized, bits=4 if quant == "int4" else 8)
    else:
        init_fn = init_params
    abs_params = jax.eval_shape(lambda k: init_fn(k, cfg),
                                jax.random.PRNGKey(0))
    abs_cache = jax.eval_shape(
        lambda: init_kv_cache(cfg, slots, max_seq=max_seq, quantized=kv_quant)
    )
    prefill, decode = _build_step_fns(cfg, slots, prompt_len)

    toks = jax.ShapeDtypeStruct((slots, prompt_len), jnp.int32)
    pos = jax.ShapeDtypeStruct((slots, prompt_len), jnp.int32)
    _, pf_stats = capture_compile_stats(
        prefill, abs_params, abs_cache, toks, pos,
        label=f"proxy.prefill[{model}]",
    )
    tok1 = jax.ShapeDtypeStruct((slots,), jnp.int32)
    lens = jax.ShapeDtypeStruct((slots,), jnp.int32)
    rng = jax.eval_shape(lambda: jax.random.PRNGKey(2))
    _, dec_stats = capture_compile_stats(
        decode, abs_params, abs_cache, tok1, lens, rng,
        label=f"proxy.decode[{model}]",
    )
    chunk_len = max(min(int(prefill_chunk), max_seq - 1), 1)
    chunk_fn = _build_chunk_prefill_fn(cfg, chunk_len)
    abs_cache1 = jax.eval_shape(
        lambda: init_kv_cache(cfg, 1, max_seq=max_seq, quantized=kv_quant)
    )
    ctoks = jax.ShapeDtypeStruct((1, chunk_len), jnp.int32)
    coff = jax.ShapeDtypeStruct((), jnp.int32)
    _, ch_stats = capture_compile_stats(
        chunk_fn, abs_params, abs_cache1, ctoks, coff,
        label=f"proxy.chunk_prefill[{model}]",
    )
    # the disaggregated prefill LANE's staging executable (runtime/
    # disagg.py; docs/DISAGGREGATION.md): compiled unconditionally so the
    # dark-round trajectory tracks it whether or not the round ran with
    # KVMINI_BENCH_DISAGG — drift in the lane program must be visible
    # before a disagg round ever lands on hardware
    dg_fn = _build_disagg_prefill_fn(cfg, prompt_len)
    dtoks = jax.ShapeDtypeStruct((1, prompt_len), jnp.int32)
    _, dg_stats = capture_compile_stats(
        dg_fn, abs_params, abs_cache1, dtoks,
        label=f"proxy.disagg_prefill[{model}]",
    )
    # quant shapes BOTH the abstract tree (int8/packed-uint8 avals fed to
    # lower(), so the cost model prices the quantized weight stream) and
    # the analytic estimate below; quant_mode selects the contraction
    # (dequant epilogue vs int8 MXU + activation-quant workspace)
    est = estimate_serving_bytes(cfg, slots, max_seq, quant=quant,
                                 kv_quant=kv_quant, quant_mode=quant_mode)
    return {
        "model": cfg.name,
        "param_count": cfg.param_count,
        "quant": quant,
        "quant_mode": quant_mode,
        "kv_quant": kv_quant,
        "prefill": pf_stats.to_dict(),
        "decode": dec_stats.to_dict(),
        "chunk_prefill": {**ch_stats.to_dict(), "chunk_len": chunk_len},
        "disagg_prefill": {**dg_stats.to_dict(), "prompt_len": prompt_len},
        "analytic": est,
    }


def exec_proxy(
    model: str,
    slots: int,
    decode_steps: int,
    prompt_len: int = 32,
    max_seq: int = 128,
) -> dict[str, Any]:
    """Run a SMALL config's real prefill + decode on the host mesh and
    measure the sync-vs-chained step ratio.

    ``chained`` dispatches every step back-to-back and synchronizes once
    (device-limited); ``sync`` reads back after every step (the serving
    engine's per-sweep shape). ratio = sync/chained >= 1: how many chained
    steps fit in one served step — a host-overhead number that exists with
    or without a TPU, tracked per round so a dispatch-path regression
    shows up even in dark rounds."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from kserve_vllm_mini_tpu.models.config import get_config
    from kserve_vllm_mini_tpu.models.llama import init_kv_cache, init_params

    # the cache must hold EVERY step this run writes (warmup + chained +
    # sync windows) — a fixed window would let a large --proxy-steps knob
    # silently clamp writes onto the last position and corrupt the timing
    total_steps = 4 + decode_steps + max(decode_steps // 2, 4)
    max_seq = max(max_seq, prompt_len + total_steps + 1)
    cfg = get_config(model, max_seq_len=max_seq)
    params = init_params(jax.random.PRNGKey(0), cfg)
    cache = init_kv_cache(cfg, slots, max_seq=max_seq)
    prefill, decode = _build_step_fns(cfg, slots, prompt_len)

    toks = jax.random.randint(jax.random.PRNGKey(1), (slots, prompt_len), 0,
                              cfg.vocab_size)
    pos = jnp.broadcast_to(jnp.arange(prompt_len, dtype=jnp.int32),
                           (slots, prompt_len))
    t0 = time.perf_counter()
    cache, tokens = prefill(params, cache, toks, pos)
    _ = np.asarray(tokens)
    prefill_first_s = time.perf_counter() - t0

    lengths = jnp.full((slots,), prompt_len, dtype=jnp.int32)
    rng = jax.random.PRNGKey(2)

    def run(n: int, cache, tokens, lengths, rng, sync_each: bool):
        for _ in range(n):
            rng, sub = jax.random.split(rng)
            cache, tokens = decode(params, cache, tokens, lengths, sub)
            lengths = lengths + 1
            if sync_each:
                _ = np.asarray(tokens)
        _ = np.asarray(tokens)
        return cache, tokens, lengths, rng

    # warm (compiles the decode), then chained and per-step-sync windows
    cache, tokens, lengths, rng = run(4, cache, tokens, lengths, rng, False)
    t0 = time.perf_counter()
    cache, tokens, lengths, rng = run(decode_steps, cache, tokens, lengths,
                                      rng, False)
    chained_ms = (time.perf_counter() - t0) / decode_steps * 1000.0
    n_sync = max(decode_steps // 2, 4)
    t0 = time.perf_counter()
    cache, tokens, lengths, rng = run(n_sync, cache, tokens, lengths, rng,
                                      True)
    sync_ms = (time.perf_counter() - t0) / n_sync * 1000.0
    return {
        "model": cfg.name,
        "slots": slots,
        "decode_steps": decode_steps,
        "prefill_first_s": round(prefill_first_s, 3),
        "chained_step_ms": round(chained_ms, 3),
        "sync_step_ms": round(sync_ms, 3),
        "step_count_ratio": round(sync_ms / max(chained_ms, 1e-9), 3),
        "proxy_tokens_per_sec": round(slots / max(chained_ms / 1000.0, 1e-9), 1),
    }


def run_proxy_tier(
    model: str,
    exec_model: str = "llama-tiny",
    quant: str = "int8",
    slots: int = 80,
    max_seq: int = 512,
    prompt_len: int = 128,
    decode_steps: int = 24,
    kv_quant: bool = False,
    quant_mode: str = "dequant",
    hbm_bytes: Optional[int] = None,
    prefill_chunk: Optional[int] = None,
) -> dict[str, Any]:
    """The full proxy round: flagship cost model + headroom pre-flight +
    executed small-config step ratio. Returns the schema-valid ``proxy``
    block (core/schema.py ``validate_proxy``). ``quant_mode``/``kv_quant``
    label the block so dark rounds track QUANTIZED compile drift as their
    own trajectory points — a w8a8 regression must not hide behind a
    dequant-round comparison. ``prefill_chunk`` sizes the chunk-prefill
    cost entry (the executable that READS the cache; the int8-KV prefill
    rail) so sweeps can put the chunk size on an axis; None keeps the
    default entry size but prices the headroom pre-flight monolithically
    (chunking off in the serving config means the guard must not assume
    the smaller per-chunk workspace)."""
    import jax

    cost = cost_model_stats(model, quant, slots, max_seq,
                            prompt_len=prompt_len, kv_quant=kv_quant,
                            quant_mode=quant_mode,
                            prefill_chunk=prefill_chunk or 64)
    execd = exec_proxy(exec_model, min(slots, 8), decode_steps)
    pf, dec = cost["prefill"], cost["decode"]
    block: dict[str, Any] = {
        "series": "proxy",
        "platform": jax.default_backend(),
        "n_devices": jax.device_count(),
        "model": cost["model"],
        "exec_model": execd["model"],
        "quant": quant,
        "quant_mode": quant_mode,
        "kv_quant": kv_quant,
        "slots": slots,
        "max_seq": max_seq,
        # acceptance pins: the five headline proxy metrics, flat
        "flops": dec["flops"],
        "bytes_accessed": dec["bytes_accessed"],
        "compile_wall_s": round(pf["compile_wall_s"] + dec["compile_wall_s"], 4),
        "peak_bytes": max(pf["peak_bytes"], dec["peak_bytes"]),
        "step_count_ratio": execd["step_count_ratio"],
        # full detail, per executable (chunk_prefill: the continuation-
        # chunk executable that reads the cache — the int8-KV prefill
        # rail and the chunked-prefill sweep axis; disagg_prefill: the
        # prefill LANE's staging executable — the disaggregated-serving
        # rail, docs/DISAGGREGATION.md)
        "compile_stats": {"prefill": pf, "decode": dec,
                          "chunk_prefill": cost["chunk_prefill"],
                          "disagg_prefill": cost["disagg_prefill"]},
        "analytic_bytes": cost["analytic"],
        "exec": execd,
    }
    if hbm_bytes:
        block["hbm_headroom"] = serving_headroom_plan(
            model, slots, max_seq, quant, kv_quant, hbm_bytes,
            quant_mode=quant_mode, prefill_chunk=prefill_chunk,
        ).to_dict()
    return block
