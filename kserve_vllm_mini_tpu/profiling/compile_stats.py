"""Compile-stats capture: wrap ``lower().compile()`` and keep the numbers.

XLA already computes an analytic cost model (FLOPs, bytes accessed) and a
buffer-assignment memory estimate for every executable it builds; the repo
used to throw both away. This module makes them first-class run metrics:

- ``capture_compile_stats(jfn, *args)`` — explicit AOT compile of a jitted
  callable, timed, with ``cost_analysis()`` / ``memory_analysis()`` / HLO
  op histogram extracted into a ``CompileStats`` record. The compiled
  executable is returned so callers run exactly what was measured.
- ``InstrumentedJit`` — a drop-in wrapper around a jitted callable: the
  first call per abstract signature compiles explicitly (stats land in a
  ``CompileRecorder``), later calls hit the cached executable. Any failure
  in the AOT path falls back to the plain jit call — instrumentation must
  never cost correctness.
- ``CompileRecorder`` — thread-safe accumulator the engine exports through
  ``snapshot_stats`` / ``/metrics`` (docs/API.md metrics table).

These numbers are the proxy tier's backbone (docs/PROFILING.md): on a
CPU mesh the cost model is the same analytic function of the program as
on TPU, so FLOPs/bytes stay comparable across rounds even when no device
time exists.
"""

from __future__ import annotations

import re
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable

# `%dot.3 = f32[64,64]{1,0} dot(...)` / `ROOT %t = (f32[2]{0}) tuple(...)`:
# the opcode is the first lowercase identifier directly followed by "(" on
# the right-hand side of the assignment (types carry brackets, not parens).
_HLO_OPCODE = re.compile(r"([a-z][a-z0-9_\-]*)\(")
_TOP_OPS = 16  # histogram cap: top-N opcodes, remainder folded into "other"


@dataclass
class CompileStats:
    """One executable's compile-time facts (all analytic — no device time)."""

    label: str
    compile_wall_s: float
    flops: float                  # cost-model FLOPs per invocation
    bytes_accessed: float         # cost-model HBM traffic per invocation
    peak_bytes: int               # buffer-assignment peak estimate (args+temp+out+code)
    argument_bytes: int
    output_bytes: int
    temp_bytes: int
    generated_code_bytes: int
    hlo_ops: dict[str, int] = field(default_factory=dict)

    def to_dict(self) -> dict[str, Any]:
        return {
            "label": self.label,
            "compile_wall_s": round(self.compile_wall_s, 4),
            "flops": self.flops,
            "bytes_accessed": self.bytes_accessed,
            "peak_bytes": self.peak_bytes,
            "argument_bytes": self.argument_bytes,
            "output_bytes": self.output_bytes,
            "temp_bytes": self.temp_bytes,
            "generated_code_bytes": self.generated_code_bytes,
            "hlo_ops": dict(self.hlo_ops),
        }


def hlo_op_histogram(hlo_text: str, top: int = _TOP_OPS) -> dict[str, int]:
    """Opcode -> instruction count over an HLO module's ``as_text()`` dump.

    Keeps the ``top`` most frequent opcodes and folds the tail into
    ``other`` so the histogram stays artifact-sized for big modules."""
    counts: dict[str, int] = {}
    for line in hlo_text.splitlines():
        eq = line.find(" = ")
        if eq < 0:
            continue
        m = _HLO_OPCODE.search(line, eq + 3)
        if m:
            counts[m.group(1)] = counts.get(m.group(1), 0) + 1
    if len(counts) <= top:
        return counts
    ranked = sorted(counts.items(), key=lambda kv: (-kv[1], kv[0]))
    out = dict(ranked[:top])
    out["other"] = sum(c for _, c in ranked[top:])
    return out


def extract_compile_stats(
    compiled: Any, compile_wall_s: float, label: str = ""
) -> CompileStats:
    """Pull cost/memory/HLO facts out of a ``jax.stages.Compiled``.

    Every extraction is individually best-effort: a backend that lacks one
    analysis (e.g. no cost model on an exotic plugin) yields zeros there,
    never an exception — these stats decorate a run, they must not kill it.
    """
    flops = bytes_accessed = 0.0
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        flops = float(ca.get("flops", 0.0) or 0.0)
        bytes_accessed = float(ca.get("bytes accessed", 0.0) or 0.0)
    except Exception:  # noqa: BLE001 — analysis availability is backend-specific
        pass
    arg = out = temp = code = alias = 0
    try:
        ma = compiled.memory_analysis()
        arg = int(getattr(ma, "argument_size_in_bytes", 0) or 0)
        out = int(getattr(ma, "output_size_in_bytes", 0) or 0)
        temp = int(getattr(ma, "temp_size_in_bytes", 0) or 0)
        code = int(getattr(ma, "generated_code_size_in_bytes", 0) or 0)
        alias = int(getattr(ma, "alias_size_in_bytes", 0) or 0)
    except Exception:  # noqa: BLE001 — same contract as above
        pass
    ops: dict[str, int] = {}
    try:
        ops = hlo_op_histogram(compiled.as_text())
    except Exception:  # noqa: BLE001 — same contract as above
        pass
    return CompileStats(
        label=label,
        compile_wall_s=compile_wall_s,
        flops=flops,
        bytes_accessed=bytes_accessed,
        # aliased (donated) buffers are counted inside argument bytes but
        # reuse their input allocation — subtract so the peak isn't double
        peak_bytes=max(arg + out + temp + code - alias, 0),
        argument_bytes=arg,
        output_bytes=out,
        temp_bytes=temp,
        generated_code_bytes=code,
        hlo_ops=ops,
    )


def capture_compile_stats(
    jfn: Any, *args: Any, label: str = "", **kwargs: Any
) -> tuple[Any, CompileStats]:
    """Explicitly ``lower().compile()`` a jitted callable and keep the
    stats. Arguments may be concrete arrays or ``jax.ShapeDtypeStruct``
    trees (abstract lowering compiles the real program without ever
    materializing the weights — the proxy tier's cost-model path).

    Returns ``(compiled_executable, stats)``; the executable accepts the
    same (concrete) calling convention as the jitted function, donation
    included."""
    t0 = time.perf_counter()
    compiled = jfn.lower(*args, **kwargs).compile()
    wall = time.perf_counter() - t0
    return compiled, extract_compile_stats(compiled, wall, label=label)


class CompileRecorder:
    """Thread-safe compile-stats accumulator.

    The engine's scheduler thread records; the server's request threads
    read ``snapshot()`` — every access is under the one lock (KVM05x
    discipline), and ``snapshot``/``entries`` return copies."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._entries: list[CompileStats] = []
        self._total_s = 0.0
        self._total_flops = 0.0
        self._total_bytes = 0.0
        self._peak_bytes = 0

    def record(self, stats: CompileStats) -> None:
        with self._lock:
            self._entries.append(stats)
            self._total_s += stats.compile_wall_s
            self._total_flops += stats.flops
            self._total_bytes += stats.bytes_accessed
            self._peak_bytes = max(self._peak_bytes, stats.peak_bytes)

    def snapshot(self) -> dict[str, Any]:
        """Flat totals for ``snapshot_stats`` / ``/metrics``."""
        with self._lock:
            return {
                "compiles": len(self._entries),
                "compile_s": self._total_s,
                "compiled_flops": self._total_flops,
                "compiled_bytes": self._total_bytes,
                "compile_peak_bytes": self._peak_bytes,
            }

    def entries(self) -> list[CompileStats]:
        with self._lock:
            return list(self._entries)


def _signature(args: tuple, kwargs: dict) -> tuple:
    """Abstract signature of a call: tree structure + per-leaf aval.

    Matches jit's own cache key closely enough that one signature maps to
    one executable (shape, dtype, weak_type per leaf — a Python scalar and
    a committed array hash differently, exactly like jit retraces)."""
    import jax
    from jax.api_util import shaped_abstractify

    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    sig = []
    for leaf in leaves:
        aval = shaped_abstractify(leaf)
        sig.append((aval.shape, str(aval.dtype), bool(getattr(aval, "weak_type", False))))
    return (treedef, tuple(sig))


class InstrumentedJit:
    """AOT-compiling wrapper around a jitted callable.

    The first call per abstract signature runs ``lower().compile()``
    explicitly (timed, stats into the recorder) and caches the executable;
    later calls dispatch straight to it — one compile total, same donation
    semantics as the wrapped jit. Any failure anywhere in the AOT path
    permanently falls back to the plain jit call for that signature, so
    instrumentation can degrade but never break serving."""

    def __init__(self, fn: Callable, recorder: CompileRecorder,
                 label: str = "") -> None:
        self._fn = fn
        self._recorder = recorder
        self._label = label or getattr(fn, "__name__", "jit")
        self._exes: dict[tuple, Callable] = {}
        # fast path: an engine step is compiled for exactly ONE signature
        # in almost every run, so once a single executable exists we
        # dispatch to it directly instead of re-deriving the abstract key
        # (a ~300-leaf params flatten per decode dispatch would be real
        # host overhead on the pipelined hot path). A structure/shape
        # mismatch raises during the executable's argument VALIDATION —
        # before any buffer is donated — and drops us back to the keyed
        # path permanently.
        self._sole_exe: Any = None

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        if self._sole_exe is not None:
            try:
                return self._sole_exe(*args, **kwargs)
            except (TypeError, ValueError):
                self._sole_exe = None
        try:
            key = _signature(args, kwargs)
        except Exception:  # noqa: BLE001 — unhashable/exotic leaf: plain path
            return self._fn(*args, **kwargs)
        exe = self._exes.get(key)
        if exe is None:
            try:
                compiled, stats = capture_compile_stats(
                    self._fn, *args, label=self._label, **kwargs
                )
                self._recorder.record(stats)
                exe = compiled
            except Exception:  # noqa: BLE001 — AOT unsupported here: plain path
                exe = self._fn
            self._exes[key] = exe
            if len(self._exes) == 1 and exe is not self._fn:
                self._sole_exe = exe
        return exe(*args, **kwargs)


def abstractify(tree: Any) -> Any:
    """Map a pytree of arrays to ``ShapeDtypeStruct`` leaves for abstract
    lowering (compile the flagship program without 16 GB of weights)."""
    import jax

    return jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), tree
    )
