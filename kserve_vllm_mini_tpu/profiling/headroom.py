"""Admission/headroom guard: downshift, never RESOURCE_EXHAUST.

BENCH_r02 died mid-run with ``RESOURCE_EXHAUSTED`` — the 80-slot headline
config plus its KV cache didn't fit the v5e's HBM and the whole round
produced zero signal. The guard pre-flights a serving config's memory
footprint against device capacity BEFORE any weights are materialized and,
when it wouldn't fit, *downshifts* (halve slots, then halve context) and
labels the measurement ``downshifted:`` — a smaller real number beats a
crashed round every time (docs/PROFILING.md).

The estimate is analytic (weights + KV + logits workspace + a fusion
margin), so it is deterministic, testable with mocked capacities, and
costs nothing; when a compiled executable exists its ``memory_analysis``
peak can be passed in to replace the workspace term with XLA's own
buffer-assignment number.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Optional

# Public per-chip HBM capacities by device-kind substring. Matched in
# order: a "v6 lite" (Trillium) kind must hit v6e before the bare "v5"/
# "lite" checks (same pitfall bench.py's economics leg documents).
HBM_BYTES_BY_KIND: tuple[tuple[str, int], ...] = (
    ("v6e", 32_000_000_000),
    ("v6", 32_000_000_000),
    ("v5e", 16_000_000_000),
    ("lite", 16_000_000_000),
    ("v5", 95_000_000_000),   # v5p
    ("v4", 32_000_000_000),
)

# fraction of HBM the plan may fill: XLA needs slack for fusion scratch,
# infeed buffers, and the donation double-buffer window
DEFAULT_HEADROOM_FRACTION = 0.9


def device_hbm_bytes(device: Any = None) -> Optional[int]:
    """Per-chip HBM capacity: runtime ``memory_stats`` when the backend
    reports it, the public spec table by device kind otherwise, ``None``
    on CPU/unknown (no capacity -> the guard disables itself)."""
    if device is None:
        try:
            import jax

            device = jax.devices()[0]
        except Exception:  # noqa: BLE001 — no backend at all
            return None
    try:
        stats = device.memory_stats() or {}
        limit = stats.get("bytes_limit")
        if limit:
            return int(limit)
    except Exception:  # noqa: BLE001 — CPU devices raise/return nothing
        pass
    kind = str(getattr(device, "device_kind", "")).lower()
    if "tpu" not in kind and "cpu" in kind:
        return None
    for sub, cap in HBM_BYTES_BY_KIND:
        if sub in kind:
            return cap
    return None


def hbm_watermarks(device: Any = None) -> dict[str, int]:
    """Live HBM watermarks from ``device.memory_stats()``:
    ``bytes_in_use`` always, ``peak_bytes_in_use``/``bytes_limit`` when
    the backend reports them. Gracefully ABSENT — ``{}``, never
    fabricated zeros — on CPU backends (whose devices raise or return
    None) and when no backend is up at all, so consumers can tell
    "no HBM telemetry" from "HBM empty" (docs/TROUBLESHOOTING.md)."""
    if device is None:
        try:
            import jax

            device = jax.devices()[0]
        except Exception:  # noqa: BLE001 — no backend at all
            return {}
    try:
        stats = device.memory_stats() or {}
    except Exception:  # noqa: BLE001 — CPU devices raise/return nothing
        return {}
    in_use = stats.get("bytes_in_use")
    if in_use is None:
        return {}
    out = {"bytes_in_use": int(in_use)}
    for key in ("peak_bytes_in_use", "bytes_limit"):
        v = stats.get(key)
        if v:
            out[key] = int(v)
    return out


def headroom_error_pct(
    estimate_bytes: Any, observed_peak_bytes: Any
) -> Optional[float]:
    """Headroom-model validation: signed % error of the analytic
    admission estimate vs the observed HBM peak. Positive = the model
    overestimates (safe but wasteful headroom); negative = it
    UNDERESTIMATES — the direction that RESOURCE_EXHAUSTs a run the
    guard admitted (the BENCH_r02 class). None when either side is
    missing or non-positive (no peak observed = nothing to validate)."""
    try:
        est = float(estimate_bytes)
        peak = float(observed_peak_bytes)
    except (TypeError, ValueError):
        return None
    if est <= 0 or peak <= 0:
        return None
    return round((est - peak) / peak * 100.0, 2)


def kv_elem_bytes(head_dim: int, itemsize: float, quantized: bool = False) -> float:
    """Physical bytes one KV element costs: the raw element, or — for
    int8-quantized KV — 1 byte plus the per-head f32 scale amortized
    across head_dim. THE single copy of the quantized-KV price: the
    admission estimate (estimate_serving_bytes) and the engine's observed
    bytes gauges (Engine.kv_bytes_per_token) must price identically or
    headroom_error_pct compares two different models."""
    return (1.0 + 4.0 / head_dim) if quantized else float(itemsize)


def host_tier_block_bytes(cfg: Any, block_size: int,
                          kv_quant: bool = False) -> int:
    """Host-RAM bytes ONE demoted KV block occupies in the host tier
    (Engine._tier, EngineConfig.kv_host_tier_bytes) — the same
    kv_elem_bytes price the HBM estimate uses, applied to HOST memory.
    Deliberately a separate function from estimate_serving_bytes: the
    tier lives in host RAM and must NEVER inflate the HBM admission
    estimate (pinned in tests) — it only bounds how many evicted blocks
    the tier's byte budget can catch."""
    elem = kv_elem_bytes(cfg.head_dim, cfg.jnp_dtype.itemsize, kv_quant)
    return int(2 * cfg.n_layers * cfg.n_kv_heads * block_size
               * cfg.head_dim * elem)


def host_tier_capacity_blocks(cap_bytes: Optional[int], cfg: Any,
                              block_size: int,
                              kv_quant: bool = False) -> int:
    """How many demoted blocks a kv_host_tier_bytes budget can hold —
    the analytic sizing companion operators use to pick the knob (0 when
    the tier is off or the budget is under one block)."""
    if not cap_bytes:
        return 0
    per = host_tier_block_bytes(cfg, block_size, kv_quant)
    return max(int(cap_bytes) // per, 0) if per > 0 else 0


def _weight_bytes_per_param(quant: str) -> float:
    # int8: 1 byte + per-channel f32 scales (~1/256 of elements, rounded
    # up generously); int4: packed nibbles + scales; else dtype width
    if quant == "int8":
        return 1.02
    if quant == "int4":
        return 0.52
    if quant in ("bf16", "fp16", "float16", "bfloat16", ""):
        return 2.0
    return 4.0


def estimate_serving_bytes(
    cfg: Any,
    slots: int,
    max_seq: int,
    quant: str = "bf16",
    kv_quant: bool = False,
    quant_mode: str = "dequant",
    prefill_chunk: Optional[int] = None,
) -> dict[str, int]:
    """Analytic HBM footprint of the bench serving shape: weights + dense
    KV + the f32 logits/workspace the prefill and sampling steps need.
    ``cfg`` is a ``models.config.ModelConfig`` (only dims are read).

    ``quant_mode="w8a8"`` adds the activation-quant workspace: the int8
    copy of the widest activation a projection quantizes ([slots, T,
    max(d_ff, d_model)] for the w_down input) plus one f32 absmax scale
    per row — a transient XLA may or may not fuse away, priced so the
    guard can never admit a shape whose quantize step is the allocation
    that RESOURCE_EXHAUSTs (docs/PROFILING.md).

    ``prefill_chunk`` (EngineConfig.prefill_chunk) bounds the widest
    compiled prefill call: chunked prefill never materializes more than
    one chunk bucket of activations, so BOTH sequence-length workspace
    terms price the chunk instead of the monolithic bucket — chunking
    WIDENS the admissible configs rather than inheriting the monolithic
    estimate."""
    weights = int(cfg.param_count * _weight_bytes_per_param(quant))
    kv_elem = kv_elem_bytes(cfg.head_dim, cfg.jnp_dtype.itemsize, kv_quant)
    kv = int(2 * cfg.n_layers * slots * cfg.n_kv_heads * max_seq
             * cfg.head_dim * kv_elem)
    # widest live activation set tracks the widest compiled call: the
    # full prefill bucket monolithically, one chunk bucket when chunked
    prefill_len = (
        min(int(prefill_chunk), max_seq) if prefill_chunk else max_seq
    )
    # f32 last-position logits for the batch + one full-bucket activation
    # set; the 1.15 margin covers fusion scratch XLA actually allocates
    workspace = int(
        slots * cfg.vocab_size * 4 + slots * prefill_len * cfg.d_model * 2
    )
    if quant_mode == "w8a8":
        widest = max(getattr(cfg, "d_ff", cfg.d_model), cfg.d_model)
        workspace += int(slots * prefill_len * (widest + 4))
    total = int((weights + kv + workspace) * 1.15)
    return {"weight_bytes": weights, "kv_bytes": kv,
            "workspace_bytes": workspace, "total_bytes": total}


@dataclass
class HeadroomPlan:
    """The guard's decision for one config."""

    fits: bool                 # True even after downshifting succeeded
    slots: int                 # admitted slots (may be < requested)
    max_seq: int               # admitted context (may be < requested)
    estimate_bytes: int        # footprint of the ADMITTED shape
    capacity_bytes: int
    budget_bytes: int          # capacity * headroom fraction
    downshifted: Optional[str] = None   # "downshifted: ..." label, or None

    def to_dict(self) -> dict[str, Any]:
        out: dict[str, Any] = {
            "fits": self.fits,
            "slots": self.slots,
            "max_seq": self.max_seq,
            "estimate_bytes": self.estimate_bytes,
            "capacity_bytes": self.capacity_bytes,
            "budget_bytes": self.budget_bytes,
        }
        if self.downshifted:
            out["downshifted"] = self.downshifted
        return out


def plan_admission(
    estimate_fn: Callable[[int, int], int],
    capacity_bytes: int,
    slots: int,
    max_seq: int,
    min_slots: int = 8,
    min_seq: int = 256,
    fraction: float = DEFAULT_HEADROOM_FRACTION,
) -> HeadroomPlan:
    """Fit ``(slots, max_seq)`` under ``fraction * capacity``.

    Downshift order: halve slots to ``min_slots`` first (throughput knob —
    the measurement survives at lower batch), then halve context to
    ``min_seq`` (changes the workload more). The label records every hop
    so a downshifted round can never masquerade as the requested config.
    """
    budget = int(capacity_bytes * fraction)
    req_slots, req_seq = slots, max_seq
    est = estimate_fn(slots, max_seq)
    # clamp the last halving TO the floor rather than refusing it — from
    # the default 80 the sequence must be able to reach min_slots=8
    # (80->40->20->10->8), not stop at 10 and needlessly cut context
    while est > budget and slots > min_slots:
        slots = max(slots // 2, min_slots)
        est = estimate_fn(slots, max_seq)
    while est > budget and max_seq > min_seq:
        max_seq = max(max_seq // 2, min_seq)
        est = estimate_fn(slots, max_seq)
    label = None
    if (slots, max_seq) != (req_slots, req_seq):
        hops = []
        if slots != req_slots:
            hops.append(f"slots {req_slots}->{slots}")
        if max_seq != req_seq:
            hops.append(f"ctx {req_seq}->{max_seq}")
        label = (
            f"downshifted: {', '.join(hops)} "
            f"(est {estimate_fn(req_slots, req_seq) / 1e9:.1f} GB > "
            f"{fraction:.0%} of {capacity_bytes / 1e9:.1f} GB HBM)"
        )
    return HeadroomPlan(
        fits=est <= budget,
        slots=slots,
        max_seq=max_seq,
        estimate_bytes=est,
        capacity_bytes=capacity_bytes,
        budget_bytes=budget,
        downshifted=label,
    )


def serving_headroom_plan(
    model: str,
    slots: int,
    max_seq: int,
    quant: str,
    kv_quant: bool,
    capacity_bytes: int,
    quant_mode: str = "dequant",
    prefill_chunk: Optional[int] = None,
    **plan_kwargs: Any,
) -> HeadroomPlan:
    """``plan_admission`` over the analytic serving estimate for a named
    model config (context changes rebuild the config — the estimate must
    price the shape actually admitted). ``prefill_chunk`` prices the
    per-chunk prefill workspace instead of the monolithic one
    (estimate_serving_bytes)."""
    from kserve_vllm_mini_tpu.models.config import get_config

    def estimate(s: int, ctx: int) -> int:
        cfg = get_config(model, max_seq_len=ctx)
        return estimate_serving_bytes(cfg, s, ctx, quant=quant,
                                      kv_quant=kv_quant,
                                      quant_mode=quant_mode,
                                      prefill_chunk=prefill_chunk,
                                      )["total_bytes"]

    return plan_admission(estimate, capacity_bytes, slots, max_seq,
                          **plan_kwargs)
