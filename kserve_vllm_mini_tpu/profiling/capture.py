"""CLI front for the runtime's /profile endpoint: capture a TensorBoard
trace of a live serving process (SURVEY.md §5.1 runtime-side profiling —
the reference has only client-side spans; with the runtime in-repo we can
trace the actual device timeline of the decode loop).

This is the DEVICE-TIMELINE leg of the profiling subsystem; the
compile-stats/proxy legs (docs/PROFILING.md) live beside it and need no
live server.

Usage: ``kvmini-tpu profile --url http://host:8000 --seconds 3``
Then: ``tensorboard --logdir <trace_dir>`` -> Profile plugin.
"""

from __future__ import annotations

import argparse
import json
import urllib.request


def register(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("--url", required=True, help="Serving runtime base URL")
    parser.add_argument("--seconds", type=float, default=3.0,
                        help="Capture window (server caps at 60)")
    parser.add_argument("--out-dir", default=None,
                        help="Trace directory (server default: runs/profile-<ts>)")
    parser.add_argument("--timeout", type=float, default=120.0)


def run(args: argparse.Namespace) -> int:
    body: dict = {"seconds": args.seconds}
    if args.out_dir:
        body["out_dir"] = args.out_dir
    req = urllib.request.Request(
        args.url.rstrip("/") + "/profile",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=args.timeout) as resp:
            data = json.loads(resp.read())
    except Exception as e:  # noqa: BLE001 — CLI boundary
        print(f"profile capture failed: {type(e).__name__}: {e}")
        return 1
    print(f"trace captured: {data['trace_dir']} ({data['seconds']}s)")
    print(f"view: tensorboard --logdir {data['trace_dir']}")
    return 0
