"""TPU pricing sheet loading + accelerator price matching.

tpu-cost.yaml replaces the reference's GPU cost.yaml; chip-hour prices are
keyed by accelerator-label fragments and matched fuzzily the way the
reference picks GPU prices from node labels
(/root/reference/cost_estimator.py:201-213).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Optional

import yaml

DEFAULT_SHEET = Path(__file__).resolve().parents[2] / "tpu-cost.yaml"


@dataclass
class Pricing:
    tpu_chip_hourly: dict[str, float] = field(default_factory=dict)
    cpu_core_hourly: float = 0.031
    memory_gib_hourly: float = 0.0042
    overhead_factor: float = 0.15
    region_multipliers: dict[str, float] = field(default_factory=dict)
    grid_usd_per_kwh: float = 0.12

    def chip_price(self, accelerator: Optional[str]) -> tuple[float, str]:
        """Fuzzy match an accelerator label (e.g. 'tpu-v5-lite-podslice',
        'v5e-8') to a chip-hour price; falls back to 'default'."""
        if accelerator:
            label = accelerator.lower().replace("-", "").replace("_", "")
            for key, price in self.tpu_chip_hourly.items():
                if key == "default":
                    continue
                if key.lower().replace("-", "") in label:
                    return price, key
        return self.tpu_chip_hourly.get("default", 1.50), "default"

    def region_multiplier(self, region: Optional[str]) -> float:
        if region and region in self.region_multipliers:
            return self.region_multipliers[region]
        return 1.0


# top-level sections a pricing sheet may carry; anything else is almost
# certainly a typo ("tpu_chip_hourli") that would silently price every
# run at the fallback default — fail LOUD at the load, the same
# convention bench.py's _ENV_KNOBS validators follow
_KNOWN_TOP_KEYS = ("tpu_chip_hourly", "host", "calculation", "energy")


def _sheet_num(sheet: Path, where: str, v: Any) -> float:
    """A price that isn't a number must stop the load — ``float("1,20")``
    raising deep inside an analyzer stage points at nothing."""
    if isinstance(v, bool) or not isinstance(v, (int, float, str)):
        raise SystemExit(
            f"{sheet}: {where} = {v!r} is not a number"
        )
    try:
        return float(v)
    except ValueError:
        raise SystemExit(
            f"{sheet}: {where} = {v!r} is not a number"
        ) from None


def load_pricing(path: str | Path | None = None) -> Pricing:
    """Load + validate a pricing sheet. Validation is LOUD (SystemExit
    naming the sheet, the key, and the fix): a garbled sheet silently
    falling back to defaults would price every run wrong under the
    operator's own label (docs/ECONOMICS.md "Pricing provenance")."""
    p = Path(path) if path else DEFAULT_SHEET
    with p.open() as f:
        raw = yaml.safe_load(f) or {}
    if not isinstance(raw, dict):
        raise SystemExit(
            f"{p}: pricing sheet must be a mapping, got "
            f"{type(raw).__name__}"
        )
    unknown = sorted(set(raw) - set(_KNOWN_TOP_KEYS))
    if unknown:
        raise SystemExit(
            f"{p}: unknown top-level key(s) {', '.join(map(repr, unknown))}; "
            f"known: {', '.join(_KNOWN_TOP_KEYS)}"
        )
    chip = raw.get("tpu_chip_hourly") or {}
    if chip and "default" not in chip:
        raise SystemExit(
            f"{p}: tpu_chip_hourly has no 'default' entry — unmatched "
            "accelerators would be priced by a hardcoded fallback "
            "instead of the sheet; add `default: <usd/chip-hr>`"
        )
    host = raw.get("host") or {}
    calc = raw.get("calculation") or {}
    energy = raw.get("energy") or {}
    return Pricing(
        tpu_chip_hourly={
            k: _sheet_num(p, f"tpu_chip_hourly.{k}", v)
            for k, v in chip.items()
        },
        cpu_core_hourly=_sheet_num(
            p, "host.cpu_core_hourly", host.get("cpu_core_hourly", 0.031)),
        memory_gib_hourly=_sheet_num(
            p, "host.memory_gib_hourly", host.get("memory_gib_hourly", 0.0042)),
        overhead_factor=_sheet_num(
            p, "calculation.overhead_factor", calc.get("overhead_factor", 0.15)),
        region_multipliers={
            k: _sheet_num(p, f"calculation.region_multipliers.{k}", v)
            for k, v in (calc.get("region_multipliers") or {}).items()
        },
        grid_usd_per_kwh=_sheet_num(
            p, "energy.grid_usd_per_kwh", energy.get("grid_usd_per_kwh", 0.12)),
    )
