"""TPU pricing sheet loading + accelerator price matching.

tpu-cost.yaml replaces the reference's GPU cost.yaml; chip-hour prices are
keyed by accelerator-label fragments and matched fuzzily the way the
reference picks GPU prices from node labels
(/root/reference/cost_estimator.py:201-213).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Optional

import yaml

DEFAULT_SHEET = Path(__file__).resolve().parents[2] / "tpu-cost.yaml"


@dataclass
class Pricing:
    tpu_chip_hourly: dict[str, float] = field(default_factory=dict)
    cpu_core_hourly: float = 0.031
    memory_gib_hourly: float = 0.0042
    overhead_factor: float = 0.15
    region_multipliers: dict[str, float] = field(default_factory=dict)
    grid_usd_per_kwh: float = 0.12

    def chip_price(self, accelerator: Optional[str]) -> tuple[float, str]:
        """Fuzzy match an accelerator label (e.g. 'tpu-v5-lite-podslice',
        'v5e-8') to a chip-hour price; falls back to 'default'."""
        if accelerator:
            label = accelerator.lower().replace("-", "").replace("_", "")
            for key, price in self.tpu_chip_hourly.items():
                if key == "default":
                    continue
                if key.lower().replace("-", "") in label:
                    return price, key
        return self.tpu_chip_hourly.get("default", 1.50), "default"

    def region_multiplier(self, region: Optional[str]) -> float:
        if region and region in self.region_multipliers:
            return self.region_multipliers[region]
        return 1.0


def load_pricing(path: str | Path | None = None) -> Pricing:
    p = Path(path) if path else DEFAULT_SHEET
    with p.open() as f:
        raw: dict[str, Any] = yaml.safe_load(f) or {}
    host = raw.get("host") or {}
    calc = raw.get("calculation") or {}
    energy = raw.get("energy") or {}
    return Pricing(
        tpu_chip_hourly={k: float(v) for k, v in (raw.get("tpu_chip_hourly") or {}).items()},
        cpu_core_hourly=float(host.get("cpu_core_hourly", 0.031)),
        memory_gib_hourly=float(host.get("memory_gib_hourly", 0.0042)),
        overhead_factor=float(calc.get("overhead_factor", 0.15)),
        region_multipliers={
            k: float(v) for k, v in (calc.get("region_multipliers") or {}).items()
        },
        grid_usd_per_kwh=float(energy.get("grid_usd_per_kwh", 0.12)),
    )
