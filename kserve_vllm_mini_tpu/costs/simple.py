"""Back-of-envelope cost calculator — the reference's simple path
(/root/reference/cost_calculator.py:11-76), TPU-translated.

The reference averages the latency of HTTP-200 lines in a raw results
file and multiplies by (GPU $/s x requests-per-1K-tokens). Here the input
is a run dir's requests.csv (successful rows' latency), the chip price
comes from tpu-cost.yaml by TPU generation (or an explicit --chip-hourly),
and requests-per-1K defaults to MEASURED tokens_out instead of an assumed
constant — with the assumption clearly printed either way.

This is the quick sanity number. The real accounting (`kvmini-tpu cost`,
costs/estimator.py) attributes resource-seconds over the run window; the
two should agree within the latency-vs-occupancy approximation, and the
output says which one to trust.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path
from typing import Any, Optional


def simple_cost(
    run_dir: str | Path,
    chip_hourly_usd: float,
    chips: int = 1,
    requests_per_1k_tokens: Optional[float] = None,
) -> dict[str, Any]:
    """Pure computation over requests.csv; raises on missing/empty input."""
    from kserve_vllm_mini_tpu.core.rundir import RunDir

    path = Path(run_dir) / "requests.csv"
    if not path.exists():
        raise FileNotFoundError(f"{path} not found")
    # the same tolerant reader every other consumer of requests.csv uses
    # (estimator, analyzer, energy) — no second CSV dialect to drift
    ok_rows = [r for r in RunDir(run_dir).read_requests() if r.ok]
    if not ok_rows:
        raise ValueError("no successful requests — cannot calculate cost")
    lat_ms = [r.latency_ms for r in ok_rows]
    toks_out = sum(r.tokens_out for r in ok_rows)
    avg_s = sum(lat_ms) / len(lat_ms) / 1000.0
    if requests_per_1k_tokens is None:
        # measured: how many average requests it takes to emit 1K tokens
        avg_tokens = toks_out / len(lat_ms)
        if avg_tokens <= 0:
            raise ValueError(
                "requests report no tokens_out — pass "
                "--requests-per-1k-tokens to assume a value"
            )
        rp1k = 1000.0 / avg_tokens
        rp1k_provenance = f"measured ({avg_tokens:.1f} avg tokens_out/request)"
    else:
        rp1k = requests_per_1k_tokens
        rp1k_provenance = "assumed (flag)"
    per_second = chip_hourly_usd * chips / 3600.0
    return {
        "successful_requests": len(lat_ms),
        "avg_latency_ms": avg_s * 1000.0,
        "chip_hourly_usd": chip_hourly_usd,
        "chips": chips,
        "chip_price_per_second": per_second,
        "requests_per_1k_tokens": rp1k,
        "requests_per_1k_provenance": rp1k_provenance,
        "cost_per_1k_tokens_usd": per_second * avg_s * rp1k,
    }


def register(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("run_dir", help="Run directory containing requests.csv")
    parser.add_argument("--chip-hourly", type=float, default=None,
                        help="Chip $/hour (default: tpu-cost.yaml for --tpu)")
    parser.add_argument("--tpu", default="v5e",
                        help="TPU generation for the pricing sheet lookup")
    parser.add_argument("--chips", type=int, default=1)
    parser.add_argument("--requests-per-1k-tokens", type=float, default=None,
                        help="Override the measured tokens_out-based value "
                             "(the reference assumed a constant 10)")
    parser.add_argument("--cost-file", default=None)


def run(args: argparse.Namespace) -> int:
    chip_hourly = args.chip_hourly
    price_key = "flag --chip-hourly"
    if chip_hourly is None:
        from kserve_vllm_mini_tpu.costs.pricing import load_pricing

        chip_hourly, price_key = load_pricing(args.cost_file).chip_price(args.tpu)
    try:
        r = simple_cost(args.run_dir, chip_hourly, chips=args.chips,
                        requests_per_1k_tokens=args.requests_per_1k_tokens)
    except (FileNotFoundError, ValueError) as e:
        print(f"cost-simple: {e}", file=sys.stderr)
        return 1
    print("=== SIMPLE COST (latency x chip-price back-of-envelope) ===")
    print(f"chip price: ${chip_hourly:.4f}/hr x{args.chips} ({price_key})")
    print(f"successful requests: {r['successful_requests']}")
    print(f"average latency: {r['avg_latency_ms']:.2f} ms")
    print(f"requests per 1K tokens: {r['requests_per_1k_tokens']:.2f} "
          f"[{r['requests_per_1k_provenance']}]")
    print(f"cost per 1K tokens: ${r['cost_per_1k_tokens_usd']:.6f}")
    print("note: latency-occupancy approximation; `kvmini-tpu cost` does the "
          "resource-seconds accounting")
    return 0
