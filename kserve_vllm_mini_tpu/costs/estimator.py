"""Stage 3: cost attribution — resource-seconds x chip-hour pricing.

Reference behavior (/root/reference/cost_estimator.py:343-501): for each pod
serving the tested model, intersect its lifetime with the test window, sum
(tpu-chip-seconds, cpu-core-seconds, memory-GiB-seconds), multiply by the
price sheet, apply overhead, split cold/warm by request fraction, merge
cost_* keys into results.json. (The reference's mem_gib_seconds=0.2 init bug,
cost_estimator.py:353, is deliberately NOT replicated.)

Clusterless mode: ``--chips N`` attributes N chips over the whole window —
how the in-repo runtime benches on bare metal get costed.
"""

from __future__ import annotations

import argparse
from typing import Any, Optional

from kserve_vllm_mini_tpu.analysis import kube
from kserve_vllm_mini_tpu.core.rundir import RunDir, window_bounds
from kserve_vllm_mini_tpu.costs.pricing import Pricing, load_pricing


def overlap_seconds(
    a0: float, a1: float, b0: float, b1: Optional[float]
) -> float:
    """|[a0,a1] ∩ [b0,b1]| with b1=None meaning 'still running'."""
    end = min(a1, b1) if b1 is not None else a1
    return max(end - max(a0, b0), 0.0)


def sum_resource_seconds(
    pods: list[dict[str, Any]],
    t0: float,
    t1: float,
) -> dict[str, float]:
    totals = {"tpu_chip_seconds": 0.0, "cpu_core_seconds": 0.0, "mem_gib_seconds": 0.0}
    for pod in pods:
        res = kube.pod_resources(pod)
        for start, end in kube.pod_lifetimes([pod]):
            sec = overlap_seconds(t0, t1, start, end)
            totals["tpu_chip_seconds"] += res["tpu_chips"] * sec
            totals["cpu_core_seconds"] += res["cpu_cores"] * sec
            totals["mem_gib_seconds"] += res["memory_bytes"] / (1024**3) * sec
    return totals


def estimate_cost(
    run_dir: RunDir,
    pricing: Pricing,
    namespace: Optional[str] = None,
    service: Optional[str] = None,
    chips: Optional[float] = None,
    accelerator: Optional[str] = None,
    region: Optional[str] = None,
    cpu_cores: float = 0.0,
    memory_gib: float = 0.0,
    merge: bool = True,
) -> dict[str, Any]:
    records = run_dir.read_requests()
    meta = run_dir.read_meta()
    t0, t1 = window_bounds(records)
    duration = max(t1 - t0, 0.0)
    accelerator = accelerator or meta.get("accelerator")

    pods: list[dict[str, Any]] = []
    if namespace and service:
        pods = kube.get_service_pods(namespace, service)
    if pods:
        totals = sum_resource_seconds(pods, t0, t1)
        if accelerator is None:
            accelerator = kube.node_accelerator_of_pod(pods[0])
        source = "cluster"
    else:
        n_chips = chips if chips is not None else meta.get("chips", 1)
        totals = {
            "tpu_chip_seconds": float(n_chips) * duration,
            "cpu_core_seconds": cpu_cores * duration,
            "mem_gib_seconds": memory_gib * duration,
        }
        source = "declared"

    chip_price, price_key = pricing.chip_price(accelerator)
    mult = pricing.region_multiplier(region)
    breakdown = {
        "tpu": totals["tpu_chip_seconds"] / 3600.0 * chip_price * mult,
        "cpu": totals["cpu_core_seconds"] / 3600.0 * pricing.cpu_core_hourly * mult,
        "memory": totals["mem_gib_seconds"] / 3600.0 * pricing.memory_gib_hourly * mult,
    }
    subtotal = sum(breakdown.values())
    breakdown["overhead"] = subtotal * pricing.overhead_factor
    total = subtotal + breakdown["overhead"]

    ok = [r for r in records if r.ok]
    tokens_out = sum(r.tokens_out for r in ok)
    update: dict[str, Any] = {
        "cost_total": total,
        "cost_breakdown": {k: round(v, 6) for k, v in breakdown.items()},
        "cost_source": source,
        "cost_price_key": price_key,
        "cost_chip_hourly": chip_price,
    }
    if ok:
        update["cost_per_request"] = total / len(ok)
    if tokens_out:
        update["cost_per_1k_tokens"] = total * 1000.0 / tokens_out

    # cold/warm split by request-count fraction (reference :289-340)
    cold_flags = run_dir.read_cold_flags()
    if cold_flags and len(cold_flags) == len(records):
        n_cold = sum(cold_flags)
        frac = n_cold / len(records)
        update["cold_cost_total"] = total * frac
        update["warm_cost_total"] = total * (1.0 - frac)

    if merge:
        run_dir.merge_into_results(update)
    return update


# -- CLI ---------------------------------------------------------------------

def register(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("--run-dir", required=True)
    parser.add_argument("--cost-file", default=None, help="Pricing YAML (default: tpu-cost.yaml)")
    parser.add_argument("--namespace", default=None)
    parser.add_argument("--service", default=None)
    parser.add_argument("--chips", type=float, default=None,
                        help="Clusterless: chips used for the whole window")
    parser.add_argument("--cpu-cores", type=float, default=0.0)
    parser.add_argument("--memory-gib", type=float, default=0.0)
    parser.add_argument("--accelerator", default=None)
    parser.add_argument("--region", default=None)


def run(args: argparse.Namespace) -> int:
    pricing = load_pricing(args.cost_file)
    update = estimate_cost(
        RunDir(args.run_dir), pricing,
        namespace=args.namespace, service=args.service,
        chips=args.chips, accelerator=args.accelerator, region=args.region,
        cpu_cores=args.cpu_cores, memory_gib=args.memory_gib,
    )
    print(
        f"cost: total=${update['cost_total']:.6f} "
        f"(${update.get('cost_per_1k_tokens', 0):.6f}/1K tok, "
        f"source={update['cost_source']}, chip=${update['cost_chip_hourly']}/h "
        f"[{update['cost_price_key']}])"
    )
    return 0
