"""Live economics: online $/1K-tok and Wh/1K-tok from counter deltas.

The post-hoc attribution (costs/estimator.py, energy/collector.py) only
prices a run after it ends. This module derives the SAME quantities
online, over a rolling window, from the two counters every serving
surface already exports — ``kvmini_tpu_busy_seconds_total`` and the
generated-token counter — plus modeled-or-measured watts and the
tpu-cost.yaml sheet (docs/ECONOMICS.md):

- ``usd_per_hour``     — the accrual rate of the deployment: chips x
  chip-hour price x region multiplier x (1 + overhead_factor). A level
  gauge; it accrues whether the chip is busy or idle, exactly like the
  post-hoc estimator's ``chip_seconds`` leg.
- ``usd_per_1k_tokens`` — usd_per_hour spread over the window's token
  output: ``usd_per_hour * (dt/3600) / d_tokens * 1000``.
- ``wh_per_1k_tokens`` — window watts (modeled from windowed duty via
  ``analysis/telemetry.modeled_power``, or measured watts when the
  caller has a power rail) x dt, spread the same way.
- ``tokens_per_sec``   — the window token rate itself, exported so the
  fleet router can rank replicas by contribution.

JAX-free on purpose: the engine computes its device info once and hands
in plain (accelerator, chips); everything here is host arithmetic, so
the monitor, the router, and tests run it with no accelerator at all.

Window semantics match ``monitor/burnrate.window_stats``: deltas are
taken between the oldest retained sample and the newest, the retained
span is ``window_s`` (plus one sample so a full window always has a
delta), and a window with no token progress yields NO rates — absence
of output is "can't attribute yet", never "$0/1K tokens".
"""

from __future__ import annotations

from collections import deque
from typing import Any, Optional

from kserve_vllm_mini_tpu.analysis.telemetry import modeled_power
from kserve_vllm_mini_tpu.costs.pricing import Pricing, load_pricing

# Spread an hourly rate over a token rate: $/hr / (tok/s x 3600 s/hr
# / 1000 tok) = $/hr / (3.6 x tok/s) per 1K tokens.
_TOKENS_PER_1K_PER_HOUR = 3.6


def usd_per_1k_tokens(usd_per_hour: float, tokens_per_sec: float) -> float:
    """Hourly accrual -> $/1K tokens at a token rate (0 rate -> 0.0; the
    caller gates on token progress before calling)."""
    if tokens_per_sec <= 0.0:
        return 0.0
    return usd_per_hour / (_TOKENS_PER_1K_PER_HOUR * tokens_per_sec)


def hourly_usd(pricing: Pricing, accelerator: Optional[str], chips: int,
               region: Optional[str] = None) -> tuple[float, str]:
    """The deployment's accrual rate in $/hr and the matched price key —
    the same chip-hour x region x overhead legs the post-hoc estimator
    prices (costs/estimator.py), minus the host legs it can only
    attribute from cluster introspection."""
    chip_hourly, price_key = pricing.chip_price(accelerator)
    rate = (chip_hourly * max(int(chips), 1)
            * pricing.region_multiplier(region)
            * (1.0 + pricing.overhead_factor))
    return rate, price_key


class LiveEconomics:
    """Rolling-window economics over (wall clock, busy-seconds, tokens).

    Feed one ``observe(t, busy_s, tokens)`` per snapshot (the engine's
    ``snapshot_stats`` pass, the monitor tick, or a test loop); each call
    returns the current gauge dict — ``{}`` until the window holds two
    samples with token progress, so a CPU backend or an idle engine
    exports NOTHING rather than a fabricated $0 (absent-not-zero,
    docs/ECONOMICS.md). Not thread-safe by itself: the engine publishes
    it under its observability lock, everyone else runs it single-
    threaded (the PR 8 gauge-cache rule — no new annotations)."""

    def __init__(
        self,
        accelerator: Optional[str] = None,
        chips: int = 1,
        pricing: Optional[Pricing] = None,
        region: Optional[str] = None,
        window_s: float = 10.0,
        watts_fn: Any = None,
    ) -> None:
        self.accelerator = accelerator
        self.chips = max(int(chips), 1)
        self.pricing = pricing if pricing is not None else load_pricing()
        self.window_s = max(float(window_s), 1e-3)
        # measured-power hook: callable () -> Optional[watts]; None keeps
        # the modeled chain (duty x TDP, analysis/telemetry.modeled_power)
        self._watts_fn = watts_fn
        self.usd_per_hour, self.price_key = hourly_usd(
            self.pricing, accelerator, self.chips, region
        )
        self._samples: deque[tuple[float, float, float]] = deque()

    def observe(self, t: float, busy_s: float,
                tokens: float) -> dict[str, float]:
        """Record one (wall, busy-counter, token-counter) sample and
        return the rolling-window gauges (or ``{}`` — see class doc)."""
        self._samples.append((float(t), float(busy_s), float(tokens)))
        # keep window_s of history plus one older anchor so the delta
        # always spans the full window once the run outlives it
        while (len(self._samples) > 2
               and self._samples[1][0] <= t - self.window_s):
            self._samples.popleft()
        return self.snapshot()

    def snapshot(self) -> dict[str, float]:
        if len(self._samples) < 2:
            return {}
        t0, busy0, tok0 = self._samples[0]
        t1, busy1, tok1 = self._samples[-1]
        dt = t1 - t0
        d_tokens = tok1 - tok0
        if dt <= 0.0 or d_tokens <= 0.0:
            # no wall progress or no token progress: nothing to attribute
            # (a counter reset also lands here — never a negative rate)
            return {}
        tokens_per_sec = d_tokens / dt
        duty = min(max((busy1 - busy0) / dt, 0.0), 1.0)
        watts = self._watts_fn() if self._watts_fn is not None else None
        provenance = "measured"
        if not isinstance(watts, (int, float)) or watts <= 0.0:
            watts = modeled_power(duty, self.accelerator) * self.chips
            provenance = "modeled"
        wh = watts * dt / 3600.0
        return {
            "usd_per_1k_tokens": usd_per_1k_tokens(self.usd_per_hour,
                                                   tokens_per_sec),
            "wh_per_1k_tokens": wh / d_tokens * 1000.0,
            "usd_per_hour": self.usd_per_hour,
            "tokens_per_sec": tokens_per_sec,
            "window_s": dt,
            "duty": duty,
            "watts": watts,
            "power_provenance_measured": 1.0 if provenance == "measured"
            else 0.0,
        }


def marginal_replica_usd_per_1k_tokens(
    per_replica_tokens_per_sec: list[float],
    usd_per_hour_per_replica: float,
) -> Optional[float]:
    """The fleet's marginal-replica attribution: the LEAST-productive
    healthy replica's hourly price spread over its own token output.
    This is the number the cost-aware autoscaler and the
    ``replica_unprofitable`` monitor rule compare against the $/1K-tok
    budget — if the marginal replica's tokens are worth less than it
    costs, the fleet is over-provisioned (docs/ECONOMICS.md). Returns
    None when no replica shows token progress (absent, not $0)."""
    rates = [r for r in per_replica_tokens_per_sec if r > 0.0]
    if not rates or usd_per_hour_per_replica <= 0.0:
        return None
    return usd_per_1k_tokens(usd_per_hour_per_replica, min(rates))
