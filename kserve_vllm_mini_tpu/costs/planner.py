"""Capacity planner: "how many TPU chips for N RPS under an SLO, at what
monthly cost?"

TPU-native rebuild of the reference planner (/root/reference/planner.py:
17-413): hardcoded per-accelerator baselines (here tokens/sec/chip, not
RPS/GPU), optional calibration from a sweep CSV or a measured results.json,
cold-start/burst headroom multipliers, warm-pool sizing, region-multiplied
monthly costs, ranked recommendations, and a markdown report.

Cold-start defaults are TPU-pool realities: node provisioning + weight
loading is minutes, not the 45 s GPU assumption baked into the reference
(planner.py:428; SURVEY.md §7.3.4).
"""

from __future__ import annotations

import argparse
import csv
import json
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Optional

from kserve_vllm_mini_tpu.costs.pricing import Pricing, load_pricing

# (accelerator, model-size bucket) -> steady-state decode tokens/sec/chip.
# The v5e figures are measured by this repo's bench.py on real hardware
# (docs/PERFORMANCE.md: llama-1b bf16 @ round 1; llama-3.1-8b int8,
# 80 slots @ round 4). Other rows scale the measured v5e numbers by HBM
# bandwidth ratio (v5p 2765/819 ≈ 3.4x, v6e 1640/819 ≈ 2x — decode is
# weight-streaming-bound) discounted ~20% for the unknowns, and the 70B
# rows additionally by parameter ratio across a tp-sharded slice; all
# should be recalibrated from sweep CSVs as they land.
BASELINE_TOKENS_PER_SEC_PER_CHIP: dict[tuple[str, str], float] = {
    ("v5e", "1b"): 4645.0,    # measured (BENCH_r01)
    ("v5e", "8b"): 3066.7,    # measured (docs/PERFORMANCE.md, 80 slots r4)
    ("v5e", "70b"): 280.0,    # scaled: 8B figure x 8/70, tp-efficiency ~0.8
    ("v5p", "1b"): 12540.0,   # scaled: v5e 1b x (2765/819) x ~0.8
    ("v5p", "8b"): 8280.0,    # scaled: v5e 8b x (2765/819) x ~0.8
    ("v5p", "70b"): 760.0,    # scaled: v5p 8b x 8/70 x tp-efficiency ~0.8
    ("v6e", "8b"): 4900.0,    # scaled: v5e 8b x (1640/819) x ~0.8
}

# Per-row provenance, surfaced in every plan report (round-3 verdict weak
# #6: extrapolations must be labeled where the USER sees them, not only in
# a source comment). "measured" = this repo's bench.py on real hardware;
# everything else is "scaled" from those measurements as described above.
BASELINE_PROVENANCE: dict[tuple[str, str], str] = {
    ("v5e", "1b"): "measured",
    ("v5e", "8b"): "measured",
}

# rows measured (or scaled from measurements) under int8 weights — the bf16
# halving applies to these; the 1b rows are bf16-measured already (no int8
# boost is assumed for them: conservative)
INT8_MEASURED_SIZES = {"8b", "70b"}

HOURS_PER_MONTH = 730.0

# TPU pools take minutes to provision + load weights (SURVEY.md §7.3.4)
DEFAULT_COLD_START_S = 300.0
DEFAULT_COLD_FREQUENCY = 0.05


@dataclass
class PlanInput:
    target_rps: float
    p95_budget_ms: float = 1200.0
    avg_output_tokens: float = 128.0
    model_size: str = "8b"
    accelerators: list[str] = field(default_factory=lambda: ["v5e", "v5p"])
    region: Optional[str] = None
    burst_headroom: float = 1.3
    cold_start_s: float = DEFAULT_COLD_START_S
    cold_frequency: float = DEFAULT_COLD_FREQUENCY
    calibrated: dict[str, float] = field(default_factory=dict)  # accel -> tok/s/chip
    # weight quantization the deployment will run. The measured baselines
    # are int8 (docs/PERFORMANCE.md); bf16 streams 2x the weight bytes on a
    # weight-bound decode, so aggregate throughput halves.
    quantization: str = "int8"
    # the measured aggregate throughput batches this many concurrent slots;
    # a SINGLE request decodes at roughly tps_chip / serving_slots (the p95
    # heuristic must use per-request speed, not the aggregate)
    serving_slots: int = 64


@dataclass
class PlanOption:
    accelerator: str
    chips: int
    warm_pool_chips: int
    tokens_per_sec_per_chip: float
    expected_rps_capacity: float
    utilization_at_target: float
    monthly_cost_usd: float
    warm_pool_monthly_usd: float
    meets_p95: bool
    # "measured" (bench.py on hardware) / "scaled" (HBM-ratio extrapolation)
    # / "calibrated" (user-supplied sweep CSV)
    baseline_provenance: str = "scaled"
    notes: list[str] = field(default_factory=list)

    @property
    def total_monthly_usd(self) -> float:
        return self.monthly_cost_usd + self.warm_pool_monthly_usd


def breakeven_events_per_hour(cold_start_s: float) -> float:
    """Cold-starts/hour above which a warm replica is cheaper: one warm chip
    costs price/h; each avoided cold start saves ``cold_start_s`` of wasted
    chip time, so the chip price cancels out. Shared with the report's
    prewarm-breakeven model so the two user-facing numbers can't drift."""
    return 3600.0 / max(cold_start_s, 1e-9)


def baseline_for(
    accel: str, model_size: str, calibrated: dict[str, float]
) -> tuple[Optional[float], str]:
    """(tokens/sec/chip, provenance) for the accelerator/size pair."""
    if accel in calibrated:
        return calibrated[accel], "calibrated"
    tps = BASELINE_TOKENS_PER_SEC_PER_CHIP.get((accel, model_size))
    return tps, BASELINE_PROVENANCE.get((accel, model_size), "scaled")


def plan(inputs: PlanInput, pricing: Pricing) -> list[PlanOption]:
    options: list[PlanOption] = []
    required_tokens_per_sec = inputs.target_rps * inputs.avg_output_tokens
    for accel in inputs.accelerators:
        tps_chip, provenance = baseline_for(
            accel, inputs.model_size, inputs.calibrated
        )
        if tps_chip is None:
            continue
        if (
            inputs.quantization in ("none", "bf16")
            and accel not in inputs.calibrated
            and inputs.model_size in INT8_MEASURED_SIZES
        ):
            tps_chip *= 0.5  # these rows are int8-measured; bf16 doubles bytes
        needed = required_tokens_per_sec * inputs.burst_headroom / tps_chip
        chips = max(int(needed) + (1 if needed % 1 else 0), 1)
        capacity_rps = chips * tps_chip / inputs.avg_output_tokens
        util = inputs.target_rps / capacity_rps if capacity_rps else 1.0

        # warm pool sized to absorb cold-frequency of traffic while a new
        # slice provisions (reference planner.py:173-202, recalibrated)
        warm_rps = inputs.target_rps * inputs.cold_frequency
        warm_chips = max(
            int(warm_rps * inputs.avg_output_tokens / tps_chip + 0.999), 1
        ) if inputs.cold_frequency > 0 else 0

        price, _ = pricing.chip_price(accel)
        mult = pricing.region_multiplier(inputs.region)
        monthly = chips * price * HOURS_PER_MONTH * mult
        warm_monthly = warm_chips * price * HOURS_PER_MONTH * mult
        breakeven = breakeven_events_per_hour(inputs.cold_start_s)

        # p95 heuristic: the budget binds on ONE request's decode speed —
        # the aggregate baseline divided by the concurrent slots it was
        # measured at (x1.5 tail factor)
        per_req_tps = tps_chip / max(inputs.serving_slots, 1)
        per_req_ms = inputs.avg_output_tokens / per_req_tps * 1000.0 * 1.5
        meets = per_req_ms <= inputs.p95_budget_ms
        notes = []
        if provenance == "scaled":
            notes.append(
                "baseline is SCALED from v5e measurements (HBM-bandwidth "
                "ratio, ~20% discount), not measured on this accelerator — "
                "calibrate with --calibrate-csv when a sweep lands"
            )
        if not meets:
            notes.append(
                f"estimated per-request decode {per_req_ms:.0f}ms exceeds "
                f"p95 budget {inputs.p95_budget_ms:.0f}ms — consider a faster "
                "accelerator or smaller model"
            )
        if util > 0.85:
            notes.append("utilization at target >85%; little burst headroom")
        notes.append(
            f"warm pool pays for itself above ~{breakeven:.1f} "
            f"cold starts/hour (each wastes ~{inputs.cold_start_s:.0f}s of chip time)"
        )
        options.append(
            PlanOption(
                accelerator=accel,
                chips=chips,
                warm_pool_chips=warm_chips,
                tokens_per_sec_per_chip=tps_chip,
                expected_rps_capacity=capacity_rps,
                utilization_at_target=util,
                monthly_cost_usd=monthly,
                warm_pool_monthly_usd=warm_monthly,
                meets_p95=meets,
                baseline_provenance=provenance,
                notes=notes,
            )
        )
    # ranked: SLO-meeting options first, then by total cost
    return sorted(options, key=lambda o: (not o.meets_p95, o.total_monthly_usd))


def calibrate_from_sweep_csv(path: str | Path) -> dict[str, float]:
    """accel -> max observed tokens/sec/chip from a sweep CSV with
    `accelerator` and `tokens_per_sec_per_chip` (or tokens_per_sec + chips)
    columns (reference planner.py:246-271)."""
    out: dict[str, float] = {}
    with Path(path).open(newline="") as f:
        for row in csv.DictReader(f):
            accel = (row.get("accelerator") or "").strip()
            if not accel:
                continue
            v = row.get("tokens_per_sec_per_chip")
            if not v and row.get("tokens_per_sec") and row.get("chips"):
                try:
                    v = float(row["tokens_per_sec"]) / float(row["chips"])
                except (ValueError, ZeroDivisionError):
                    v = None
            try:
                val = float(v)
            except (TypeError, ValueError):
                continue
            key = accel.lower()
            for frag in ("v5e", "v5p", "v4", "v6e"):
                if frag in key:
                    key = frag
                    break
            out[key] = max(out.get(key, 0.0), val)
    return out


def markdown_report(inputs: PlanInput, options: list[PlanOption]) -> str:
    lines = [
        "# TPU capacity plan",
        "",
        f"- target: **{inputs.target_rps:.1f} RPS** at p95 <= {inputs.p95_budget_ms:.0f} ms",
        f"- model size: {inputs.model_size}, ~{inputs.avg_output_tokens:.0f} output tokens/request",
        f"- burst headroom x{inputs.burst_headroom}, cold start {inputs.cold_start_s:.0f}s "
        f"@ {inputs.cold_frequency:.0%} frequency",
        "",
        "| rank | accel | chips | warm pool | tok/s/chip | capacity RPS | util | $/month | meets p95 |",
        "|---|---|---|---|---|---|---|---|---|",
    ]
    for i, o in enumerate(options, 1):
        lines.append(
            f"| {i} | {o.accelerator} | {o.chips} | {o.warm_pool_chips} | "
            f"{o.tokens_per_sec_per_chip:.0f} ({o.baseline_provenance}) | "
            f"{o.expected_rps_capacity:.1f} | "
            f"{o.utilization_at_target:.0%} | ${o.total_monthly_usd:,.0f} | "
            f"{'yes' if o.meets_p95 else 'NO'} |"
        )
    for o in options:
        for n in o.notes:
            lines.append(f"- **{o.accelerator}**: {n}")
    return "\n".join(lines) + "\n"


# -- CLI ---------------------------------------------------------------------

def register(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("--target-rps", type=float, required=True)
    parser.add_argument("--p95-budget", type=float, default=1200.0, help="ms")
    parser.add_argument("--avg-output-tokens", type=float, default=128.0)
    parser.add_argument("--model-size", default="8b", choices=["1b", "8b", "70b"])
    parser.add_argument("--accelerators", default="v5e,v5p")
    parser.add_argument("--region", default=None)
    parser.add_argument("--burst-headroom", type=float, default=1.3)
    parser.add_argument("--cold-start-s", type=float, default=DEFAULT_COLD_START_S)
    parser.add_argument("--cold-frequency", type=float, default=DEFAULT_COLD_FREQUENCY)
    parser.add_argument("--calibrate-csv", default=None,
                        help="Sweep CSV to calibrate tokens/sec/chip from")
    parser.add_argument("--quantization", default="int8",
                        choices=["int8", "int4", "bf16", "none"],
                        help="Weight quantization of the planned deployment "
                             "(baselines are int8-measured; bf16 halves them)")
    parser.add_argument("--serving-slots", type=int, default=64,
                        help="Concurrent decode slots the throughput baseline "
                             "assumes (per-request p95 speed = baseline/slots)")
    parser.add_argument("--cost-file", default=None)
    parser.add_argument("--output", default=None, help="Write markdown report here")
    parser.add_argument("--json", action="store_true", dest="as_json")


def run(args: argparse.Namespace) -> int:
    calibrated = calibrate_from_sweep_csv(args.calibrate_csv) if args.calibrate_csv else {}
    inputs = PlanInput(
        target_rps=args.target_rps,
        p95_budget_ms=args.p95_budget,
        avg_output_tokens=args.avg_output_tokens,
        model_size=args.model_size,
        accelerators=[a.strip() for a in args.accelerators.split(",") if a.strip()],
        region=args.region,
        burst_headroom=args.burst_headroom,
        cold_start_s=args.cold_start_s,
        cold_frequency=args.cold_frequency,
        calibrated=calibrated,
        quantization=args.quantization,
        serving_slots=args.serving_slots,
    )
    options = plan(inputs, load_pricing(args.cost_file))
    if not options:
        print("plan: no baseline for the requested accelerator/model combination")
        return 1
    if args.as_json:
        print(json.dumps([o.__dict__ for o in options], indent=2, default=str))
    else:
        report = markdown_report(inputs, options)
        print(report)
        if args.output:
            Path(args.output).write_text(report)
    return 0
