"""``kvmini-tpu bench`` — the one-command pipeline (reference bench.sh).

Stages (reference bench.sh:201-289): validate -> [serve in-process] ->
load-test (+ concurrent power sampling) -> io probe -> analyze -> energy
integrate -> cost -> [gate] — all in-process against a run directory, no
bash heredocs. With ``--self-serve`` the in-repo runtime is started for the
duration, so the full pipeline runs with no cluster at all (SURVEY.md §7.1b).
"""

from __future__ import annotations

import argparse
import sys
import threading
from typing import Any, Optional

import yaml

from kserve_vllm_mini_tpu.core.rundir import RunDir
from kserve_vllm_mini_tpu.core.validate import validate_profile


def _monitor_budgets(monitor_slo: Any) -> dict[str, float]:
    """Budgets for live burn-rates: a dict is taken as-is, a string is a
    budgets JSON path (the same file format gates/slo.py loads)."""
    if isinstance(monitor_slo, dict):
        return {k: float(v) for k, v in monitor_slo.items()}
    if isinstance(monitor_slo, str):
        from kserve_vllm_mini_tpu.gates.slo import load_slo

        return load_slo(monitor_slo)
    return {}


def run_bench(
    url: Optional[str],
    profile: dict[str, Any],
    run_dir: Optional[RunDir] = None,
    self_serve: bool = False,
    prom_url: Optional[str] = None,
    namespace: Optional[str] = None,
    service: Optional[str] = None,
    cost_file: Optional[str] = None,
    chips: Optional[float] = None,
    slo_file: Optional[str] = None,
    idle_tax: str = "none",
    monitor: bool = True,
    monitor_slo: Any = None,
    monitor_abort: bool = False,
    cost_budget_usd_per_1k_tok: Optional[float] = None,
) -> tuple[dict[str, Any], int]:
    """Returns (results, exit_code).

    ``monitor`` runs the 1 Hz live sampler (docs/MONITORING.md) for the
    duration of the load stage: timeline.jsonl, rolling burn-rates
    against ``monitor_slo`` budgets (path or dict; also profile keys
    ``monitor_slo`` / ``monitor_abort`` / ``monitor_interval_s``), and —
    with ``monitor_abort`` — early termination of hopeless runs."""
    from kserve_vllm_mini_tpu.energy.collector import collect_power

    if not url and not self_serve:
        print("bench: either --url or --self-serve is required", file=sys.stderr)
        return {}, 2

    # Stage 0: validate — against the limits the run will actually use (the
    # self-serve engine defaults max_model_len to 1024, not the validator's
    # external-backend default)
    if self_serve:
        profile.setdefault("max_model_len", 1024)
    rep = validate_profile(profile)
    for w in rep.warnings:
        print(f"WARNING: {w}")
    if not rep.ok:
        for e in rep.errors:
            print(f"ERROR: {e}")
        return {}, 1

    run_dir = run_dir or RunDir.create()
    run_dir.path.mkdir(parents=True, exist_ok=True)
    print(f"bench: run dir {run_dir.path}")

    server = None
    cold_start_instants: list[float] = []
    cold_window_s = 30.0
    if self_serve:
        # start the in-repo runtime on a free port; its startup IS a cold
        # start — the cold-start instant is when boot BEGAN (pod-startedAt
        # analog), not when readiness was observed
        from kserve_vllm_mini_tpu.runtime.local import start_local_server

        server = start_local_server(profile)
        url = server.url
        cold_start_instants = [server.boot_began]
        # requests can only begin after readiness, so the cold window must
        # cover boot (weights + XLA compile) plus the usual 30 s of load
        cold_window_s += server.boot_seconds
        print(f"bench: self-serve runtime up in {server.boot_seconds:.1f}s at {url}")

    # Live monitor (docs/MONITORING.md): profile keys override the
    # arguments so sweeps can vary monitoring per cell
    monitor_on = bool(profile.get("monitor", monitor))
    run_monitor = None
    live = None
    abort = None
    if monitor_on:
        from kserve_vllm_mini_tpu.loadgen.runner import LiveStats
        from kserve_vllm_mini_tpu.monitor import (
            AbortSignal,
            MonitorConfig,
            RunMonitor,
        )

        budgets = _monitor_budgets(profile.get("monitor_slo", monitor_slo))
        live = LiveStats()
        abort = AbortSignal()
        run_monitor = RunMonitor(
            run_dir.timeline_jsonl,
            endpoint=url,
            live=live,
            cfg=MonitorConfig(
                interval_s=float(profile.get("monitor_interval_s", 1.0)),
                budgets=budgets,
                abort_enabled=bool(profile.get("monitor_abort", monitor_abort)),
                cost_budget_usd_per_1k_tok=(
                    float(profile["cost_budget_usd_per_1k_tok"])
                    if profile.get("cost_budget_usd_per_1k_tok") is not None
                    else cost_budget_usd_per_1k_tok
                ),
            ),
            abort=abort,
        )
        run_monitor.start()

    # Stage 1: load test with concurrent power sampling. Everything from here
    # to the SLO gate runs under try/finally: a failing stage must still stop
    # the sampler and the self-serve engine (its decode-loop thread and KV
    # cache would otherwise outlive the run — sweeps record-and-continue on
    # failure, so a leak here skews every subsequent config).
    #
    # With the monitor on and no Prometheus, the dedicated power-sampler
    # thread is NOT started: the monitor's timeline already carries
    # duty/busy from the same endpoint at the same 1 Hz, and power.json is
    # derived from it after the load stage (energy/collector.py
    # power_from_timeline) — one scrape loop, not two, against the
    # endpoint being measured. A Prometheus URL still gets the sampling
    # loop (measured node power beats modeled duty x TDP).
    stop_sampling = threading.Event()
    sampler: Optional[threading.Thread] = None
    if run_monitor is None or prom_url:
        sampler = threading.Thread(
            target=collect_power,
            args=(run_dir, prom_url, url),
            kwargs={
                "interval_s": 1.0,
                "accelerator": profile.get("accelerator"),
                "stop_check": stop_sampling.is_set,
            },
            daemon=True,
            name="power-sampler",
        )
        sampler.start()

    try:
        return _run_stages(
            profile,
            url,
            run_dir,
            server,
            cold_start_instants,
            cold_window_s,
            sampler,
            stop_sampling,
            prom_url=prom_url,
            namespace=namespace,
            service=service,
            cost_file=cost_file,
            chips=chips,
            slo_file=slo_file,
            idle_tax=idle_tax,
            run_monitor=run_monitor,
            live=live,
            abort=abort,
        )
    finally:
        stop_sampling.set()
        if run_monitor is not None:
            run_monitor.stop()
        if server is not None:
            server.stop()


def _run_stages(
    profile: dict[str, Any],
    url: str,
    run_dir: RunDir,
    server,
    cold_start_instants: list[float],
    cold_window_s: float,
    sampler: Optional[threading.Thread],
    stop_sampling: threading.Event,
    *,
    prom_url: Optional[str],
    namespace: Optional[str],
    service: Optional[str],
    cost_file: Optional[str],
    chips: Optional[float],
    slo_file: Optional[str],
    idle_tax: str,
    run_monitor=None,
    live=None,
    abort=None,
) -> tuple[dict[str, Any], int]:
    from kserve_vllm_mini_tpu.analysis.analyzer import analyze_run
    from kserve_vllm_mini_tpu.costs.estimator import estimate_cost
    from kserve_vllm_mini_tpu.costs.pricing import load_pricing
    from kserve_vllm_mini_tpu.energy.collector import integrate_energy
    from kserve_vllm_mini_tpu.loadgen.runner import LoadConfig, run_load

    cfg = LoadConfig(
        url=url,
        model=profile.get("model", "default"),
        models=profile.get("models"),
        backend=profile.get("backend", "openai"),
        num_requests=int(profile.get("requests", 100)),
        concurrency=int(profile.get("concurrency", 10)),
        pattern=profile.get("pattern", "steady"),
        target_rps=profile.get("target_rps"),
        duration_s=profile.get("duration_s"),
        streaming=bool(profile.get("streaming", True)),
        max_tokens=int(profile.get("max_tokens", 64)),
        temperature=float(profile.get("temperature", 0.0)),
        n=int(profile.get("n", 1)),
        presence_penalty=float(profile.get("presence_penalty", 0.0)),
        frequency_penalty=float(profile.get("frequency_penalty", 0.0)),
        stop=profile.get("stop"),
        prompt_set=profile.get("prompt_set", "default"),
        input_tokens=int(profile.get("input_tokens", 0)),
        seed=int(profile.get("seed", 42)),
        connect_timeout_s=float(profile.get("connect_timeout_s", 10.0)),
        read_timeout_s=float(profile.get("read_timeout_s", 30.0)),
        max_retries=int(profile.get("max_retries", 3)),
        deadline_ms=(
            float(profile["deadline_ms"])
            if profile.get("deadline_ms") is not None else None
        ),
        extra_body=profile.get("extra_body", {}) or {},
    )
    records = run_load(cfg, run_dir, live=live, abort=abort)
    stop_sampling.set()
    monitor_summary: Optional[dict[str, Any]] = None
    if run_monitor is not None:
        # stop BEFORE analyze: the analyzer reads timeline.jsonl and the
        # last line must be flushed
        monitor_summary = run_monitor.stop()
        if sampler is None:
            # the monitor replaced the power-sampling loop — derive
            # power.json from its timeline (one scrape loop, not two)
            from kserve_vllm_mini_tpu.energy.collector import collect_power

            collect_power(
                run_dir, None, None,
                accelerator=profile.get("accelerator"),
                # snapshot, not the live list: stop()'s join is bounded, so
                # a wedged scrape can leave the sampler thread appending
                # while the energy integration iterates (KVM055 bug class)
                timeline=run_monitor.timeline(),
            )
    if sampler is not None:
        # worst-case iteration = power-query timeouts (~8 s with 2 s
        # timeouts); power.json must exist before Stage 4 integrates it
        sampler.join(timeout=30.0)
    ok = sum(1 for r in records if r.ok)
    print(f"bench: load complete {ok}/{len(records)} ok")

    # annotate meta for downstream stages
    meta = run_dir.read_meta()
    meta.update(
        {
            "accelerator": profile.get("accelerator"),
            "chips": chips or profile.get("chips", 1),
            "runtime": "jax-native" if server is not None else profile.get("backend", "openai"),
        }
    )
    run_dir.write_meta(meta)

    # Stage 2: io probe (best-effort RTT against the endpoint)
    try:
        from kserve_vllm_mini_tpu.probes.net_storage import measure_http_rtt

        run_dir.write_io_probe(measure_http_rtt(url))
    except Exception:  # kvmini: workload-ok — optional probe; absence shows
        pass           # up as missing network_rtt_* fields, not silence

    # Stage 3: analyze
    results = analyze_run(
        run_dir,
        prom_url=prom_url,
        endpoint=url,
        namespace=namespace,
        service=service,
        cold_start_times=cold_start_instants or None,
        cold_window_s=cold_window_s,
    )

    # self-serve boot time is the run's measured cold start; persist it so
    # downstream consumers (autoscale sweep deploy_time_s) can read it
    if server is not None:
        run_dir.merge_into_results(
            {"cold_start_seconds": round(server.boot_seconds, 2)}
        )

    # live-monitor summary (docs/MONITORING.md): burn rates, events,
    # sampler accounting, and — when the abort hook fired — the reason,
    # which sweeps surface per cell as aborted_early
    if monitor_summary is not None:
        run_dir.merge_into_results({"monitor": monitor_summary})
        if abort is not None and abort.is_set():
            run_dir.merge_into_results({"aborted_early": abort.reason})

    # Stage 4: energy
    integrate_energy(run_dir, idle_tax=idle_tax)

    # Stage 5: cost
    estimate_cost(
        run_dir,
        load_pricing(cost_file),
        namespace=namespace,
        service=service,
        chips=chips or profile.get("chips"),
        accelerator=profile.get("accelerator"),
    )

    # self-serve: the engine is in-process, so record its decode-pipeline
    # counters (docs/DECODE_PIPELINE.md) authoritatively — the analyzer's
    # /metrics scrape covers external endpoints, but a direct snapshot
    # can't race the server teardown
    if server is not None:
        es = server.engine.snapshot_stats()
        run_dir.merge_into_results({
            "pipeline_dispatch_depth": es["dispatch_depth"],
            "pipeline_pipelined_sweeps": es["pipelined_sweeps"],
            "pipeline_host_overlap_s": round(es["host_overlap_s"], 6),
            "pipeline_bubble_s": round(es["bubble_s"], 6),
            # chunked-prefill rail (docs/TROUBLESHOOTING.md "Long prompts
            # stall streaming"): same authoritative-direct-snapshot rule
            "prefill_chunks": es["prefill_chunks"],
            "prefill_chunk_stall_s": round(es["prefill_chunk_stall_s"], 6),
        })
        # compile-stats block (docs/PROFILING.md): the direct snapshot is
        # authoritative (per-executable entries included) and replaces
        # whatever the /metrics scrape merged above
        cs = server.engine.compile_stats_snapshot()
        if cs.get("compiles"):
            run_dir.merge_into_results({"compile_stats": cs})
        # KV-cache & HBM block (docs/TROUBLESHOOTING.md): same
        # authoritative-direct-snapshot rule, and the headroom-model
        # validation closes here when the device reported a peak
        kv = server.engine.kv_cache_snapshot()
        run_dir.merge_into_results({"kv_cache": kv})
        # resilience block (docs/RESILIENCE.md): authoritative direct
        # snapshot, present only when the run saw resilience activity
        # (same zero-activity absence rule as the /metrics scrape)
        res = {
            key: es[key]
            for key in ("requests_shed", "watchdog_trips", "engine_faults",
                        "degrade_level", "faults_armed")
        }
        if any(res.values()):
            res["source"] = "engine:snapshot"
            run_dir.merge_into_results({"resilience": res})
        # live-economics block (docs/ECONOMICS.md): same authoritative-
        # direct-snapshot rule; engines without the rail (CPU backends
        # with no econ_accelerator) get no block — absent, never $0
        econ = server.engine.economics_snapshot()
        if econ:
            run_dir.merge_into_results({"economics": econ})
        # disaggregated-serving block (docs/DISAGGREGATION.md): same
        # authoritative-direct-snapshot rule; colocated engines (and
        # disagg runs with zero handoff activity) get no block
        dg = server.engine.disagg_snapshot()
        if dg and any(
            dg[k] for k in ("handoffs", "handoff_drops",
                            "colocated_fallbacks")
        ):
            run_dir.merge_into_results({"disagg": dg})
        from kserve_vllm_mini_tpu.profiling.headroom import headroom_error_pct

        err = headroom_error_pct(
            kv.get("headroom_estimate_bytes"), kv.get("hbm_peak_bytes")
        )
        if err is not None:
            run_dir.merge_into_results({"headroom_error_pct": err})
    results = run_dir.read_results()

    code = 0
    if slo_file:
        from kserve_vllm_mini_tpu.gates.slo import gate_results, load_slo, print_table

        verdicts = gate_results(results, load_slo(slo_file))
        print_table(verdicts)
        code = 0 if all(v.ok for v in verdicts) else 3

    p95 = results.get("p95_ms")
    print(
        f"bench: done p95={p95:.1f}ms " if p95 is not None else "bench: done ",
        end="",
    )
    print(
        f"rps={results.get('throughput_rps', 0):.2f} "
        f"cost/1Ktok=${results.get('cost_per_1k_tokens', 0):.6f} "
        f"-> {run_dir.results_json}"
    )
    return results, code


# -- CLI ---------------------------------------------------------------------

def register(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("--url", default=None, help="Existing endpoint base URL")
    parser.add_argument("--self-serve", action="store_true",
                        help="Start the in-repo runtime for the bench")
    parser.add_argument("--profile", default=None, help="Profile YAML")
    parser.add_argument("--requests", type=int, default=None)
    parser.add_argument("--concurrency", type=int, default=None)
    parser.add_argument("--pattern", default=None)
    parser.add_argument("--max-tokens", type=int, default=None)
    parser.add_argument("--model", default=None)
    parser.add_argument("--run-dir", default=None)
    parser.add_argument("--prom-url", default=None)
    parser.add_argument("--namespace", default=None)
    parser.add_argument("--service", default=None)
    parser.add_argument("--cost-file", default=None)
    parser.add_argument("--chips", type=float, default=None)
    parser.add_argument("--slo", default=None, help="SLO budgets JSON; exit 3 on violation")
    parser.add_argument("--idle-tax", choices=["none", "series", "baseline"], default="none")
    parser.add_argument("--no-monitor", action="store_true",
                        help="Disable the 1 Hz live run monitor "
                             "(timeline.jsonl, burn rates, events — "
                             "docs/MONITORING.md)")
    parser.add_argument("--monitor-slo", default=None,
                        help="Budgets JSON for LIVE rolling burn-rates "
                             "(default: none; --slo still gates post-hoc)")
    parser.add_argument("--monitor-abort", action="store_true",
                        help="Let the monitor abort the run on sustained "
                             "budget burn or a decode stall (records "
                             "aborted_early in results.json)")
    parser.add_argument("--cost-budget-usd-per-1k-tok", type=float,
                        default=None,
                        help="Live $/1K-token budget for the "
                             "cost_burn_exceeded / replica_unprofitable "
                             "monitor events (docs/ECONOMICS.md; also "
                             "KVMINI_BENCH_COST_BUDGET and the profile "
                             "key cost_budget_usd_per_1k_tok)")


def run(args: argparse.Namespace) -> int:
    profile: dict[str, Any] = {}
    if args.profile:
        with open(args.profile) as f:
            profile = yaml.safe_load(f) or {}
    for key in ("requests", "concurrency", "pattern", "max_tokens", "model"):
        v = getattr(args, key)
        if v is not None:
            profile[key] = v
    _, code = run_bench(
        url=args.url,
        profile=profile,
        run_dir=RunDir(args.run_dir) if args.run_dir else None,
        self_serve=args.self_serve,
        prom_url=args.prom_url,
        namespace=args.namespace,
        service=args.service,
        cost_file=args.cost_file,
        chips=args.chips,
        slo_file=args.slo,
        idle_tax=args.idle_tax,
        monitor=not args.no_monitor,
        monitor_slo=args.monitor_slo,
        monitor_abort=args.monitor_abort,
        cost_budget_usd_per_1k_tok=args.cost_budget_usd_per_1k_tok,
    )
    return code
