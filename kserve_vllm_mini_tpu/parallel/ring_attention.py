"""Ring attention: sequence/context parallelism over the ``sp`` mesh axis.

The reference has no sequence parallelism at all (SURVEY.md §5.7 — sequence
length is just a config knob handed to external engines). The TPU build owns
the runtime, so long context is real work: the sequence axis is sharded over
``sp``, each device holds a [B, H, T/sp, D] block of Q/K/V, and K/V blocks
rotate around the ring via ``jax.lax.ppermute`` while a numerically-stable
online-softmax accumulator (flash-attention style m/l/acc triplet) folds in
one block per step. Peak memory per device is O(T/sp) instead of O(T), and
the ppermute rides ICI neighbor links.

Causality is positional: absolute position ids travel with each K block, so
the mask never depends on ring step index and uneven/rotated layouts stay
correct.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kserve_vllm_mini_tpu.ops.attention import repeat_kv

try:  # jax >= 0.8
    from jax import shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

# jax.lax.pvary landed with the 0.9 shard_map typing rules; on older jax
# the accumulators need no device-varying declaration — identity is exact
_pvary = getattr(jax.lax, "pvary", lambda x, axes: x)


def _block_accumulate(q, k, v, q_pos, k_pos, m, l, acc, scale):
    """Fold one K/V block into the online-softmax state.

    q: [B,H,Tq,D]; k,v: [B,H,Tk,D]; *_pos: [B,Tq]/[B,Tk];
    m,l: [B,H,Tq]; acc: [B,H,Tq,D] (all f32).
    """
    logits = jnp.einsum("bhtd,bhsd->bhts", q, k).astype(jnp.float32) * scale
    mask = (k_pos[:, None, None, :] <= q_pos[:, None, :, None])
    logits = jnp.where(mask, logits, -jnp.inf)
    m_blk = jnp.max(logits, axis=-1)                      # [B,H,Tq]
    m_new = jnp.maximum(m, m_blk)
    # guard fully-masked rows (m_new == -inf): contribute nothing
    safe_m = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    p = jnp.exp(logits - safe_m[..., None])
    p = jnp.where(mask, p, 0.0)
    corr = jnp.where(jnp.isfinite(m), jnp.exp(m - safe_m), 0.0)
    l_new = l * corr + jnp.sum(p, axis=-1)
    acc_new = acc * corr[..., None] + jnp.einsum(
        "bhts,bhsd->bhtd", p.astype(v.dtype), v
    ).astype(jnp.float32)
    return m_new, l_new, acc_new


def _ring_attention_local(q, k, v, q_pos, k_pos, axis_name: str, scale: float,
                          sp: int):
    """Per-device body run under shard_map. Shapes are the local blocks.

    ``sp`` is the ring size, passed statically from the mesh (the perm
    list needs a Python int; jax.lax.axis_size is not on older jax)."""
    n_rep = q.shape[1] // k.shape[1]
    if n_rep > 1:
        k = repeat_kv(k, n_rep)
        v = repeat_kv(v, n_rep)
    B, H, Tq, D = q.shape
    # pvary: the accumulators are logically device-varying over the ring axis
    # from step 1 on; JAX 0.9's shard_map typing requires declaring that up
    # front or the fori_loop carry types mismatch.
    m = _pvary(jnp.full((B, H, Tq), -jnp.inf, dtype=jnp.float32), (axis_name,))
    l = _pvary(jnp.zeros((B, H, Tq), dtype=jnp.float32), (axis_name,))
    acc = _pvary(jnp.zeros((B, H, Tq, D), dtype=jnp.float32), (axis_name,))

    perm = [(i, (i + 1) % sp) for i in range(sp)]

    def step(i, carry):
        m, l, acc, k, v, k_pos = carry
        m, l, acc = _block_accumulate(q, k, v, q_pos, k_pos, m, l, acc, scale)
        # rotate K/V (and their positions) to the next ring neighbor
        k = jax.lax.ppermute(k, axis_name, perm)
        v = jax.lax.ppermute(v, axis_name, perm)
        k_pos = jax.lax.ppermute(k_pos, axis_name, perm)
        return m, l, acc, k, v, k_pos

    m, l, acc, *_ = jax.lax.fori_loop(0, sp, step, (m, l, acc, k, v, k_pos))
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype)


def ring_attention(
    q: jnp.ndarray,       # [B, H, T, D], T sharded over sp
    k: jnp.ndarray,       # [B, KVH, T, D]
    v: jnp.ndarray,       # [B, KVH, T, D]
    positions: jnp.ndarray,  # [B, T] absolute positions, sharded with T
    mesh: Mesh,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Causal ring attention over the mesh's ``sp`` axis. Returns [B, H, T, D]
    with the same sequence sharding as q."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    seq = P(None, None, "sp", None)
    pos_spec = P(None, "sp")
    fn = shard_map(
        partial(_ring_attention_local, axis_name="sp", scale=scale,
                sp=int(mesh.shape["sp"])),
        mesh=mesh,
        in_specs=(seq, seq, seq, pos_spec, pos_spec),
        out_specs=seq,
    )
    return fn(q, k, v, positions, positions)
