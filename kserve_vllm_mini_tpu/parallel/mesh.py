"""Device-mesh construction: the TPU-native replacement for the reference's
passthrough parallelism knobs.

The reference forwards TENSOR_PARALLEL_SIZE / PIPELINE_PARALLEL_SIZE env vars
to external engines (/root/reference/runners/backends/vllm/deploy.sh:78-79,
triton/deploy.sh:84-86) and never owns a communicator. Here parallelism is a
``jax.sharding.Mesh`` over ICI/DCN with four named axes:

- ``dp`` — data parallel (request-batch replicas)
- ``tp`` — tensor parallel (attention heads / FFN columns)
- ``sp`` — sequence/context parallel (ring attention over long sequences)
- ``pp`` — pipeline parallel (layer stages)
- ``ep`` — expert parallel (MoE expert shards, models/moe.py)

XLA compiles the collectives (psum / all-gather / reduce-scatter / ppermute)
onto ICI links; multi-host meshes extend the same axes over DCN via
``jax.distributed.initialize`` (see parallel/distributed.py).

Topology presets mirror GKE TPU node-pool shapes the deployment layer
schedules (v5e-1/-4/-8 slices replacing the reference's MIG profiles,
SURVEY.md §2.2; v5p-16 for the multi-host 70B config, BASELINE.json
configs[4]).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional, Sequence

import jax
from jax.sharding import Mesh

AXES = ("dp", "sp", "pp", "tp", "ep")


@dataclass(frozen=True)
class MeshSpec:
    dp: int = 1
    sp: int = 1
    pp: int = 1
    tp: int = 1
    ep: int = 1

    @property
    def n_devices(self) -> int:
        return self.dp * self.sp * self.pp * self.tp * self.ep

    def axis_sizes(self) -> tuple[int, int, int, int, int]:
        return (self.dp, self.sp, self.pp, self.tp, self.ep)

    @classmethod
    def fill(
        cls,
        n_devices: int,
        tp: Optional[int] = None,
        sp: int = 1,
        pp: int = 1,
        ep: int = 1,
    ) -> "MeshSpec":
        """tp defaults to all remaining devices — the serving-friendly layout
        (TP over ICI minimizes per-token latency)."""
        rem = n_devices // (sp * pp * ep)
        tp = tp if tp is not None else rem
        dp = n_devices // (sp * pp * tp * ep)
        spec = cls(dp=dp, sp=sp, pp=pp, tp=tp, ep=ep)
        if spec.n_devices != n_devices:
            raise ValueError(
                f"axis sizes {spec.axis_sizes()} do not factor {n_devices} devices"
            )
        return spec


# name -> (chips, default MeshSpec kwargs)
TOPOLOGY_PRESETS: dict[str, dict] = {
    "v5e-1": {"chips": 1, "tp": 1},
    "v5e-4": {"chips": 4, "tp": 4},
    "v5e-8": {"chips": 8, "tp": 8},
    "v5p-8": {"chips": 8, "tp": 8},
    "v5p-16": {"chips": 16, "tp": 16},   # 2 hosts over ICI (BASELINE configs[4])
    # long-context serving: the KV cache's SEQ axis shards over sp, so each
    # chip holds max_seq/sp of every slot's cache — 4x the context per HBM
    # at the same tp width (parallel/sharding.py kv_cache_shardings)
    "v5e-8-longctx": {"chips": 8, "tp": 2, "sp": 4},
    "v5p-16-longctx": {"chips": 16, "tp": 4, "sp": 4},
    "cpu-8": {"chips": 8, "tp": 4},      # virtual CPU mesh for tests
}


def make_mesh(spec: MeshSpec, devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    if len(devices) < spec.n_devices:
        raise ValueError(
            f"mesh spec needs {spec.n_devices} devices, have {len(devices)}"
        )
    devices = devices[: spec.n_devices]
    import numpy as np

    arr = np.array(devices).reshape(spec.axis_sizes())
    return Mesh(arr, AXES)


def lane_meshes(
    prefill_devices: int,
    devices: Optional[Sequence[jax.Device]] = None,
    prefill_tp: Optional[int] = None,
    decode_tp: Optional[int] = None,
) -> tuple[Mesh, Mesh]:
    """Split one device set into DISJOINT (prefill, decode) submeshes for
    disaggregated serving (runtime/disagg.py, docs/DISAGGREGATION.md):
    the first ``prefill_devices`` devices become the prefill lane's
    tp-only mesh and the rest the decode engine's — e.g. a 2+6 split of
    the virtual 8-device CPU test mesh, or 2+6 of a v5e-8 slice. Both
    lanes default to tp over their whole subset (the serving-friendly
    layout, and the ONLY shape disagg engines accept — dp/sp/pp decode
    meshes are rejected at Engine construction); ``prefill_tp``/
    ``decode_tp`` exist for explicitness but must still cover their
    subset exactly — when the model's head count doesn't divide a lane,
    change the SPLIT, not the tp (a dp>1 lane would be refused
    downstream anyway, so this raises here with the real fix).
    Disjointness is the point: a prefill running on lane devices can
    never contend with a decode sweep's collectives."""
    devices = list(devices if devices is not None else jax.devices())
    if not 0 < prefill_devices < len(devices):
        raise ValueError(
            f"prefill_devices={prefill_devices} must leave both lanes at "
            f"least one device (have {len(devices)})"
        )
    n_decode = len(devices) - prefill_devices
    pre_spec = MeshSpec.fill(prefill_devices, tp=prefill_tp)
    dec_spec = MeshSpec.fill(n_decode, tp=decode_tp)
    for lane, spec, n in (("prefill", pre_spec, prefill_devices),
                          ("decode", dec_spec, n_decode)):
        if spec.dp > 1:
            raise ValueError(
                f"{lane}_tp={spec.tp} does not cover the {lane} lane's "
                f"{n} devices (would leave dp={spec.dp}, which disagg "
                "engines reject); resize the split so tp covers the "
                "lane exactly"
            )
    return (
        make_mesh(pre_spec, devices[:prefill_devices]),
        make_mesh(dec_spec, devices[prefill_devices:]),
    )


def mesh_for_topology(name: str, devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    if name not in TOPOLOGY_PRESETS:
        raise ValueError(f"unknown topology {name!r}; known: {sorted(TOPOLOGY_PRESETS)}")
    p = TOPOLOGY_PRESETS[name]
    spec = MeshSpec.fill(p["chips"], tp=p.get("tp"), sp=p.get("sp", 1))
    return make_mesh(spec, devices)
