from kserve_vllm_mini_tpu.parallel.mesh import MeshSpec, make_mesh, TOPOLOGY_PRESETS
from kserve_vllm_mini_tpu.parallel.sharding import (
    param_shardings,
    shard_params,
    activation_sharding,
    kv_cache_shardings,
)

__all__ = [
    "MeshSpec",
    "make_mesh",
    "TOPOLOGY_PRESETS",
    "param_shardings",
    "shard_params",
    "activation_sharding",
    "kv_cache_shardings",
]
