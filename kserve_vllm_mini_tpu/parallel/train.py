"""Sharded training step: next-token cross-entropy + SGD/AdamW over the mesh.

A serving framework still needs a training step for drafter fine-tuning
(speculative-decoding profiles, BASELINE.json configs[3]) and for the
multi-chip dry-run contract (__graft_entry__.dryrun_multichip): the full
dp/tp/sp/pp sharding story must compile and execute end-to-end, collectives
included. Sequence parallelism uses the real ring-attention path
(parallel/ring_attention.py), not a resharding fallback.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kserve_vllm_mini_tpu.models.config import ModelConfig
from kserve_vllm_mini_tpu.models.llama import forward
from kserve_vllm_mini_tpu.parallel.ring_attention import ring_attention
from kserve_vllm_mini_tpu.parallel.sharding import _axis, param_shardings, shard_params


def loss_fn(
    params: dict[str, Any],
    cfg: ModelConfig,
    tokens: jnp.ndarray,       # [B, T+1]: inputs tokens[:, :-1], targets tokens[:, 1:]
    mesh: Optional[Mesh] = None,
    use_ring_attention: bool = False,
) -> jnp.ndarray:
    inp, tgt = tokens[:, :-1], tokens[:, 1:]
    B, T = inp.shape
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
    attn = None
    if use_ring_attention and mesh is not None and mesh.shape.get("sp", 1) > 1:
        def attn(q, k, v, pos):
            return ring_attention(q, k, v, pos, mesh)
    logits, _ = forward(params, cfg, inp, positions, attention_fn=attn)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
    return jnp.mean(nll)


def sgd_train_step(
    params: dict[str, Any],
    cfg: ModelConfig,
    tokens: jnp.ndarray,
    lr: float = 1e-3,
    mesh: Optional[Mesh] = None,
    use_ring_attention: bool = False,
) -> tuple[dict[str, Any], jnp.ndarray]:
    """One SGD step; params keep their shardings (grads inherit them)."""
    loss, grads = jax.value_and_grad(loss_fn)(
        params, cfg, tokens, mesh=mesh, use_ring_attention=use_ring_attention
    )
    new_params = jax.tree.map(
        lambda p, g: (p.astype(jnp.float32) - lr * g.astype(jnp.float32)).astype(p.dtype),
        params,
        grads,
    )
    return new_params, loss


def make_sharded_train_step(cfg: ModelConfig, mesh: Mesh, lr: float = 1e-3,
                            use_ring_attention: bool = True):
    """jit-compiled train step with explicit in/out shardings on the mesh.

    Token batch shards [B] over dp and [T] over sp; params over tp/pp per
    parallel/sharding.py; outputs pinned back to the same layout so the step
    can be called in a loop without resharding.
    """
    p_sh = param_shardings(cfg, mesh)
    tok_sh = NamedSharding(mesh, P(_axis(mesh, "dp"), None))

    @partial(
        jax.jit,
        in_shardings=(p_sh, tok_sh),
        out_shardings=(p_sh, NamedSharding(mesh, P())),
        donate_argnums=(0,),
    )
    def step(params, tokens):
        return sgd_train_step(
            params, cfg, tokens, lr=lr, mesh=mesh,
            use_ring_attention=use_ring_attention,
        )

    return step
