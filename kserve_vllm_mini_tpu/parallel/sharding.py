"""Sharding rules: how Llama params, activations, and KV cache lay out on the
mesh.

Megatron-style tensor parallelism expressed as NamedShardings and left to XLA
to lower into collectives (scaling-book recipe: pick a mesh, annotate, let
XLA insert the all-reduces):

- column-parallel: wq/wk/wv, w_gate/w_up shard their output dim over ``tp``
- row-parallel: wo, w_down shard their input dim over ``tp`` (XLA inserts the
  psum on the residual add)
- embeddings / lm_head shard vocab over ``tp`` (logits all-gathered only if
  the consumer needs them replicated)
- KV cache shards batch-slots over ``dp`` and KV heads over ``tp`` when
  divisible (GQA with tp > n_kv_heads replicates KV, the standard fallback)
- layer-stacked leading axis shards over ``pp`` when pp > 1

Pytree-shaped rule maps keep this in one place instead of scattering
with_sharding_constraint calls through the model.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kserve_vllm_mini_tpu.models.config import ModelConfig


def _axis(mesh: Mesh, name: str) -> Optional[str]:
    """Use a mesh axis only if it exists and is >1 (else replicate)."""
    return name if name in mesh.axis_names and mesh.shape[name] > 1 else None


def param_specs(
    cfg: ModelConfig, mesh: Mesh, params: Optional[dict[str, Any]] = None
) -> dict[str, Any]:
    tp = _axis(mesh, "tp")
    pp = _axis(mesh, "pp")
    if pp and tp:
        # Layer-range (pp) layouts are executed by the stage-partitioned
        # executors — parallel/pipeline.py (training) and
        # parallel/serving_pp.py (serving) — which run shard_map over pp
        # with everything else replicated. tp-within-stage is not composed
        # there; reject the combination instead of emitting specs the
        # scan-rolled forward would silently allgather through.
        raise ValueError(
            "pp > 1 needs a stage-partitioned executor: serving uses "
            "pure-pp meshes (parallel/serving_pp.py), training composes "
            "pp with dp (parallel/pipeline.py); neither composes pp with tp"
        )
    kv_tp = tp if tp and cfg.n_kv_heads % mesh.shape["tp"] == 0 else None
    specs: dict[str, Any] = {
        "embed": P(tp, None),
        "layers": {
            "attn_norm": P(pp, None),
            "wq": P(pp, None, tp),
            "wk": P(pp, None, kv_tp),
            "wv": P(pp, None, kv_tp),
            "wo": P(pp, tp, None),
        },
        "final_norm": P(None),
    }
    if cfg.block == "phi":
        # phi: fc1 column-parallel (bias shards with it), fc2 row-parallel
        # (output bias replicated, like the o-projection bias); biased norms
        specs["layers"].update({
            "attn_norm_b": P(pp, None),
            "bo": P(pp, None),
            "w_up": P(pp, None, tp),
            "b_up": P(pp, tp),
            "w_down": P(pp, tp, None),
            "b_down": P(pp, None),
        })
        specs["final_norm_b"] = P(None)
        specs["lm_head_b"] = P(tp)
    elif cfg.is_moe:
        specs["layers"]["mlp_norm"] = P(pp, None)
        # expert-parallel: the expert axis shards over ``ep``; inside each
        # expert the FFN is Megatron column/row over ``tp`` exactly like the
        # dense MLP. The router is d_model x E — replicated.
        ep = _axis(mesh, "ep")
        ep = ep if ep and cfg.n_experts % mesh.shape["ep"] == 0 else None
        specs["layers"].update({
            "w_gate": P(pp, ep, None, tp),
            "w_up": P(pp, ep, None, tp),
            "w_down": P(pp, ep, tp, None),
            "router": P(pp, None, None),
        })
    else:
        specs["layers"].update({
            "mlp_norm": P(pp, None),
            "w_gate": P(pp, None, tp),
            "w_up": P(pp, None, tp),
            "w_down": P(pp, tp, None),
        })
    if cfg.block == "gemma2":
        # sandwich post-norms: vectors, replicated across tp like the
        # other norm weights
        specs["layers"]["post_attn_norm"] = P(pp, None)
        specs["layers"]["post_mlp_norm"] = P(pp, None)
    if cfg.attn_bias:
        specs["layers"].update({
            "bq": P(pp, tp),
            "bk": P(pp, kv_tp),
            "bv": P(pp, kv_tp),
        })
    if not cfg.tie_embeddings:
        specs["lm_head"] = P(tp, None)
    if params is not None:
        _expand_quantized(specs["layers"], params.get("layers", {}))
    return specs


def _expand_quantized(specs: dict[str, Any], leaves: dict[str, Any]) -> None:
    """Int8 weight leaves are ``{"q": [L,in,out], "s": [L,out]}`` dicts
    (ops/quant.py): shard ``q`` like the original weight and ``s`` along the
    output axis (the last entry of the weight spec), so a tp-sharded matmul's
    epilogue scale is local to each shard — no collective added."""
    from kserve_vllm_mini_tpu.ops.quant import is_quantized

    for name, leaf in leaves.items():
        spec = specs.get(name)
        if is_quantized(leaf) and isinstance(spec, P) and "a" in leaf:
            # AWQ leaf: q/s as below, plus the input-channel multiplier
            # sharded along the weight's INPUT axis
            specs[name] = {
                "q": spec,
                "s": P(*spec[:-2], spec[-1]),
                "a": P(*spec[:-2], spec[-2]),
            }
            continue
        if is_quantized(leaf) and isinstance(spec, P):
            # scale shape = weight shape minus the input (second-to-last)
            # axis: [L, in, out] -> [L, out]; MoE [L, E, in, out] -> [L, E, out]
            specs[name] = {"q": spec, "s": P(*spec[:-2], spec[-1])}


def param_shardings(
    cfg: ModelConfig, mesh: Mesh, params: Optional[dict[str, Any]] = None
) -> dict[str, Any]:
    return jax.tree.map(
        lambda spec: NamedSharding(mesh, spec),
        param_specs(cfg, mesh, params),
        is_leaf=lambda x: isinstance(x, P),
    )


def shard_params(params: dict[str, Any], cfg: ModelConfig, mesh: Mesh) -> dict[str, Any]:
    """device_put the param pytree onto the mesh per the rules."""
    shardings = param_shardings(cfg, mesh, params)
    return jax.device_put(params, shardings)


def activation_sharding(mesh: Mesh, with_seq: bool = False) -> NamedSharding:
    """[B, T, D] activations: batch over dp, optionally sequence over sp."""
    dp, sp = _axis(mesh, "dp"), _axis(mesh, "sp")
    return NamedSharding(mesh, P(dp, sp if with_seq else None, None))


def token_sharding(mesh: Mesh) -> NamedSharding:
    """[B, T] token/position ids: batch over dp."""
    return NamedSharding(mesh, P(_axis(mesh, "dp"), None))


def kv_cache_shardings(
    cfg: ModelConfig, mesh: Mesh, quantized: bool = False
) -> dict[str, NamedSharding]:
    """[L, B, KVH, S, D] layout. The SEQUENCE axis shards over ``sp`` —
    long-context serving: each chip holds max_seq/sp of every slot's
    cache, and decode attention's softmax/contraction over the sharded S
    axis lowers to XLA-inserted collectives (GSPMD reduction handling;
    the scaling-book recipe — annotate, let XLA place the psums)."""
    tp, dp, pp = _axis(mesh, "tp"), _axis(mesh, "dp"), _axis(mesh, "pp")
    sp = _axis(mesh, "sp")
    kv_tp = tp if tp and cfg.n_kv_heads % mesh.shape["tp"] == 0 else None
    spec = P(pp, dp, kv_tp, sp, None)  # [L, B, KVH, S, D]
    s = NamedSharding(mesh, spec)
    out = {"k": s, "v": s}
    if quantized:
        # int8-KV scales: same layout minus the head_dim axis
        s4 = NamedSharding(mesh, P(pp, dp, kv_tp, sp))  # [L, B, KVH, S]
        out["k_s"] = out["v_s"] = s4
    return out


def paged_kv_cache_shardings(
    cfg: ModelConfig, mesh: Mesh, quantized: bool = False
) -> dict[str, NamedSharding]:
    """[L, P, KVH, BLK, D] block-pool layout: KV heads shard over ``tp``
    (the same head partitioning as dense), block/position axes stay
    replicated — the table-driven gather indexes the P axis identically on
    every tp shard, so GSPMD partitions the paged read per head with no
    cross-shard traffic. Paged pools do not compose with dp/sp/pp meshes
    (the engine rejects them); only the tp axis matters here."""
    tp = _axis(mesh, "tp")
    kv_tp = tp if tp and cfg.n_kv_heads % mesh.shape["tp"] == 0 else None
    s = NamedSharding(mesh, P(None, None, kv_tp, None, None))
    out = {"k": s, "v": s}
    if quantized:
        s4 = NamedSharding(mesh, P(None, None, kv_tp, None))
        out["k_s"] = out["v_s"] = s4
    return out


def logits_sharding(mesh: Mesh) -> NamedSharding:
    """[B, T, V]: batch over dp; vocab gathered (sampling wants full vocab)."""
    return NamedSharding(mesh, P(_axis(mesh, "dp"), None, None))
