"""Multi-host distributed runtime: process initialization and global meshes.

This is the TPU-native replacement for the comm backend the reference never
owns — its NCCL/MPI lives inside external engine images and the only related
surface is the PP/TP passthrough env (/root/reference/runners/backends/
triton/deploy.sh:84-86). Here the runtime is in-repo, so multi-host is real:

- ``initialize()`` wraps ``jax.distributed.initialize`` with environment
  autodiscovery. On GKE TPU node pools libtpu + the TPU metadata already
  carry host topology, so a bare ``initialize()`` works; for CPU-based CI
  (and any explicit deployment) the coordinator/process counts come from
  arguments or ``KVMINI_COORDINATOR`` / ``KVMINI_NUM_PROCESSES`` /
  ``KVMINI_PROCESS_ID`` env vars.
- ``global_mesh(spec)`` builds the serving/training mesh over **all** hosts'
  devices. Within one TPU slice (e.g. v5p-16 = 16 chips / 4 hosts) every
  chip pair is ICI-connected, so one flat mesh is correct. Across slices
  (multi-pod), ``dcn_dp > 1`` lays data-parallel outermost over DCN via
  ``mesh_utils.create_hybrid_device_mesh`` so only dp-gradient/replica
  traffic crosses the slow network — tp/sp/pp collectives stay on ICI
  (scaling-book recipe: DCN-outermost).
- ``is_primary()`` — the process-0 frontend pattern: exactly one host runs
  the HTTP server / writes artifacts; the others participate in collectives
  only (SURVEY.md §7.3.2 "the harness only sees one URL").

The 2-process CPU localhost test in tests/test_distributed.py exercises
initialize + v5p-16 mesh construction + a psum over DCN without hardware.
"""

from __future__ import annotations

import os
from typing import Optional

import jax

from kserve_vllm_mini_tpu.parallel.mesh import (
    AXES,
    TOPOLOGY_PRESETS,
    MeshSpec,
    make_mesh,
)

_initialized = False


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids: Optional[list[int]] = None,
) -> bool:
    """Join (or create) the multi-host JAX runtime. Idempotent.

    Returns True if ``jax.distributed.initialize`` was called, False when
    running single-process (no coordinator configured anywhere) — callers
    can treat False as "single-host mode" and skip the frontend split.

    Resolution order per field: explicit argument > KVMINI_* env var >
    JAX/cloud autodiscovery (TPU metadata on GKE). A single process with no
    coordinator anywhere is the common local case and is NOT an error.
    """
    global _initialized
    if _initialized:
        return True

    coordinator_address = coordinator_address or os.environ.get("KVMINI_COORDINATOR")
    if num_processes is None and "KVMINI_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["KVMINI_NUM_PROCESSES"])
    if process_id is None and "KVMINI_PROCESS_ID" in os.environ:
        process_id = int(os.environ["KVMINI_PROCESS_ID"])

    on_tpu_pod = bool(
        os.environ.get("TPU_WORKER_HOSTNAMES") or os.environ.get("MEGASCALE_COORDINATOR_ADDRESS")
    )
    if coordinator_address is None and not on_tpu_pod:
        return False  # single-process mode

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids,
    )
    _initialized = True
    return True


def coordinator_host() -> str:
    """Best-known hostname/IP of process 0, for host-level side channels
    (e.g. the multi-host serving command stream). Mirrors initialize()'s
    resolution: explicit env first, then TPU-pod autodiscovery sources,
    loopback only as the single-machine fallback."""
    coord = os.environ.get("KVMINI_COORDINATOR", "")
    if coord:
        return coord.rsplit(":", 1)[0]
    hostnames = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    if hostnames:
        return hostnames.split(",")[0].strip()
    mega = os.environ.get("MEGASCALE_COORDINATOR_ADDRESS", "")
    if mega:
        return mega.rsplit(":", 1)[0]
    return "127.0.0.1"


def process_count() -> int:
    return jax.process_count()


def process_index() -> int:
    return jax.process_index()


def is_primary() -> bool:
    """True on the process that runs the HTTP frontend and writes artifacts
    (process 0). All processes execute the same jitted computations; only
    the primary talks to clients."""
    return jax.process_index() == 0


def global_mesh(spec: MeshSpec, dcn_dp: int = 1) -> jax.sharding.Mesh:
    """Mesh over every device of every host.

    ``spec`` describes the per-slice (ICI) axis sizes. With ``dcn_dp > 1``
    the data-parallel axis is laid outermost over DCN — each of the
    ``dcn_dp`` slices holds a full model replica, and only dp collectives
    (request routing / gradient psum) cross DCN. dp inside the spec
    multiplies with the DCN replicas.
    """
    n_global = len(jax.devices())
    if dcn_dp <= 1:
        if spec.n_devices != n_global:
            raise ValueError(
                f"mesh spec {spec.axis_sizes()} needs {spec.n_devices} devices; "
                f"{n_global} present across {jax.process_count()} processes"
            )
        return make_mesh(spec)

    from jax.experimental import mesh_utils

    per_slice = spec.axis_sizes()
    if dcn_dp * spec.n_devices != n_global:
        raise ValueError(
            f"dcn_dp={dcn_dp} x per-slice {spec.n_devices} != {n_global} global devices"
        )
    # dp outermost over DCN; every other axis confined to one ICI slice
    devices = mesh_utils.create_hybrid_device_mesh(
        mesh_shape=per_slice,
        dcn_mesh_shape=(dcn_dp,) + (1,) * (len(per_slice) - 1),
        devices=jax.devices(),
        allow_split_physical_axes=True,
    )
    return jax.sharding.Mesh(devices, AXES)


def mesh_for_topology(name: str, dcn_dp: int = 1) -> jax.sharding.Mesh:
    """Global (multi-host-aware) mesh for a topology preset.

    Unlike mesh.mesh_for_topology (single-process, local devices), this
    counts devices across all initialized processes, so ``v5p-16`` (16
    chips / 4 hosts) builds when 4 hosts of 4 chips — or, in CI, 2 CPU
    processes of 8 virtual devices — have joined.
    """
    if name not in TOPOLOGY_PRESETS:
        raise ValueError(f"unknown topology {name!r}; known: {sorted(TOPOLOGY_PRESETS)}")
    p = TOPOLOGY_PRESETS[name]
    spec = MeshSpec.fill(p["chips"], tp=p.get("tp"), sp=p.get("sp", 1))
    return global_mesh(spec, dcn_dp=dcn_dp)
