"""Pipeline parallelism: stage-partitioned execution with microbatching.

The reference only *passes through* a PIPELINE_PARALLEL_SIZE knob to Triton
(/root/reference/runners/backends/triton/deploy.sh:84-86); here the
mechanism is owned. TPU-native design:

- The stacked layer axis [L, ...] shards over the ``pp`` mesh axis, so each
  stage holds ``L / pp`` contiguous layers — *layer-range sharding*, not an
  annotation: inside ``shard_map`` each device literally has only its own
  stage's weights.
- A GPipe-style schedule runs ``M`` microbatches through ``P`` stages in
  ``M + P - 1`` ticks. Every tick each stage applies its local layers
  (a ``lax.scan`` over them) to its current activation buffer, then hands
  the result to the next stage with a single ``lax.ppermute`` — the
  activation transfer rides ICI, once per tick, instead of every layer
  (which is what naively scanning pp-sharded layers would do;
  VERDICT.md round-1 Weak #5).
- The schedule is SPMD: all stages execute the same program each tick;
  stage identity comes from ``lax.axis_index("pp")``. Warmup/drain bubbles
  process don't-care data that is never emitted.
- Everything is differentiable (``ppermute`` transposes to the inverse
  permutation), so the same executor serves the training step used by the
  multi-chip dry-run and drafter fine-tuning.

Embedding / final norm / lm head are replicated across stages (they are
small next to the layer stack); the layer weights — the bulk of the model —
are stage-partitioned.
"""

from __future__ import annotations

import inspect
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kserve_vllm_mini_tpu.models.config import ModelConfig
from kserve_vllm_mini_tpu.models.llama import (
    embed_tokens,
    final_logits,
    layer_forward,
)

from kserve_vllm_mini_tpu.ops.rope import rope_frequencies

try:  # jax >= 0.8
    from jax import shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

# jax renamed the replication-check knob check_rep -> check_vma; resolve
# the spelling this jax accepts so the executor traces on both lines
_SM_CHECK_OFF = {
    ("check_vma" if "check_vma" in inspect.signature(shard_map).parameters
     else "check_rep"): False
}


def _pipeline_specs(params: dict[str, Any]) -> dict[str, Any]:
    """shard_map partition specs: layer stack over pp, everything else
    replicated (dp handled on the token spec)."""

    def leaf_spec(path: tuple, leaf) -> P:
        if path and path[0] == "layers":
            return P("pp", *([None] * (leaf.ndim - 1)))
        return P(*([None] * leaf.ndim))

    def walk(node, path=()):
        if isinstance(node, dict):
            return {k: walk(v, path + (k,)) for k, v in node.items()}
        return leaf_spec(path, node)

    return walk(params)


def pipeline_loss_fn(
    params: dict[str, Any],
    cfg: ModelConfig,
    tokens: jnp.ndarray,  # [B, T+1]
    mesh: Mesh,
    n_microbatches: int = 2,
) -> jnp.ndarray:
    """Next-token NLL computed through the pipelined executor."""
    n_pp = mesh.shape["pp"]
    if cfg.n_layers % n_pp:
        raise ValueError(f"n_layers={cfg.n_layers} not divisible by pp={n_pp}")
    n_dp = mesh.shape.get("dp", 1)
    B = tokens.shape[0]
    if B % (n_dp * n_microbatches):
        raise ValueError(
            f"batch {B} must divide dp*microbatches = {n_dp}*{n_microbatches}"
        )

    p_specs = _pipeline_specs(params)
    tok_spec = P("dp", None)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(p_specs, tok_spec),
        out_specs=P(),
        **_SM_CHECK_OFF,
    )
    def spmd_loss(params, tokens):
        inp, tgt = tokens[:, :-1], tokens[:, 1:]
        b, T = inp.shape
        M = n_microbatches
        mb = b // M
        stage = jax.lax.axis_index("pp")
        positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (mb, T))
        cos, sin = rope_frequencies(
            cfg.rotary_dim, cfg.max_seq_len, cfg.rope_theta, cfg.rope_scaling
        )
        layers_local = params["layers"]  # [L/P, ...] — this stage's range only

        x = embed_tokens(params, cfg, inp)             # [b, T, D]
        mbs = x.reshape(M, mb, T, cfg.d_model)

        def run_stage(h):
            # global layer indices: alt-sliding-window masks follow global
            # parity, and this stage owns layers [stage*L/P, (stage+1)*L/P)
            lbase = stage * (cfg.n_layers // n_pp)

            def body(carry, xs):
                p, li = xs
                return layer_forward(
                    p, cfg, carry, positions, cos, sin, layer_idx=li
                ), None

            out, _ = jax.lax.scan(
                body, h,
                (layers_local, lbase + jnp.arange(cfg.n_layers // n_pp)),
            )
            return out

        perm = [(i, (i + 1) % n_pp) for i in range(n_pp)]

        def tick(carry, t):
            state, outputs = carry
            # stage 0 ingests microbatch t while any remain; other stages
            # (and the drain phase) use what the previous tick handed over
            h_in = jnp.where(
                (stage == 0) & (t < M),
                jax.lax.dynamic_index_in_dim(
                    mbs, jnp.clip(t, 0, M - 1), axis=0, keepdims=False
                ),
                state,
            )
            h_out = run_stage(h_in)
            # last stage emits microbatch t-(P-1) once the pipe is full
            out_idx = t - (n_pp - 1)
            emitted = jax.lax.dynamic_update_index_in_dim(
                outputs, h_out, jnp.clip(out_idx, 0, M - 1), axis=0
            )
            outputs = jnp.where((stage == n_pp - 1) & (out_idx >= 0), emitted, outputs)
            state = jax.lax.ppermute(h_out, "pp", perm)
            return (state, outputs), None

        state0 = jnp.zeros((mb, T, cfg.d_model), dtype=x.dtype)
        outputs0 = jnp.zeros((M, mb, T, cfg.d_model), dtype=x.dtype)
        (_, outputs), _ = jax.lax.scan(
            tick, (state0, outputs0), jnp.arange(M + n_pp - 1)
        )

        # only the last stage holds real outputs; broadcast over the pp ring
        outputs = jax.lax.psum(
            jnp.where(stage == n_pp - 1, outputs, jnp.zeros_like(outputs)), "pp"
        )
        h = outputs.reshape(b, T, cfg.d_model)
        # family epilogues (phi bias, gemma (1+w) norm + softcap) live in
        # ONE place — an executor with its own head code drifts silently
        logits = final_logits(params, cfg, h)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
        return jax.lax.pmean(jnp.mean(nll), "dp")

    return spmd_loss(params, tokens)


def make_pipeline_train_step(
    cfg: ModelConfig, mesh: Mesh, lr: float = 1e-3, n_microbatches: int = 2
):
    """jitted SGD step over the pipelined loss; params stay pp-sharded."""
    from kserve_vllm_mini_tpu.parallel.sharding import _axis

    def to_named(spec_tree):
        return jax.tree.map(
            lambda s: NamedSharding(mesh, s), spec_tree,
            is_leaf=lambda x: isinstance(x, P),
        )

    tok_sh = NamedSharding(mesh, P(_axis(mesh, "dp"), None))

    def step(params, tokens):
        loss, grads = jax.value_and_grad(pipeline_loss_fn)(
            params, cfg, tokens, mesh, n_microbatches=n_microbatches
        )
        new_params = jax.tree.map(
            lambda p, g: (p.astype(jnp.float32) - lr * g.astype(jnp.float32)).astype(p.dtype),
            params,
            grads,
        )
        return new_params, loss

    def compile_for(params):
        p_sh = to_named(_pipeline_specs(params))
        return jax.jit(
            step,
            in_shardings=(p_sh, tok_sh),
            out_shardings=(p_sh, NamedSharding(mesh, P())),
            donate_argnums=(0,),
        )

    return compile_for


def shard_params_for_pipeline(params: dict[str, Any], mesh: Mesh) -> dict[str, Any]:
    shardings = jax.tree.map(
        lambda s: NamedSharding(mesh, s),
        _pipeline_specs(params),
        is_leaf=lambda x: isinstance(x, P),
    )
    return jax.device_put(params, shardings)


def dryrun_pipeline(n_devices: int) -> None:
    """pp>=2 stage-partitioned execution on a dp x pp mesh: compile, run one
    train step, verify the loss matches the non-pipelined forward."""
    from kserve_vllm_mini_tpu.models.config import get_config
    from kserve_vllm_mini_tpu.models.llama import init_params
    from kserve_vllm_mini_tpu.parallel.mesh import MeshSpec, make_mesh
    from kserve_vllm_mini_tpu.parallel.train import loss_fn

    cfg = get_config("llama-tiny")
    pp = 2
    while pp * 2 <= min(cfg.n_layers, n_devices // 2) and cfg.n_layers % (pp * 2) == 0:
        pp *= 2
    dp = n_devices // pp
    spec = MeshSpec(dp=dp, sp=1, pp=pp, tp=1)
    mesh = make_mesh(spec)

    params = shard_params_for_pipeline(init_params(jax.random.PRNGKey(0), cfg), mesh)
    M = 2
    B, T = dp * M, 32
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (B, T + 1), 0, cfg.vocab_size, dtype=jnp.int32
    )

    step = make_pipeline_train_step(cfg, mesh, n_microbatches=M)(params)
    ref = float(loss_fn(jax.device_get(params), cfg, tokens))
    params, loss = step(params, tokens)
    loss.block_until_ready()
    got = float(loss)
    assert abs(got - ref) < 5e-2 * max(1.0, abs(ref)), (got, ref)
    print(
        f"dryrun_pipeline ok: mesh dp={dp} pp={pp} (n={n_devices}), "
        f"microbatches={M}, loss={got:.4f} (unpipelined {ref:.4f})"
    )
