"""Serving-side pipeline parallelism: a pp-sharded drop-in for forward().

The reference serves multi-stage models by passing PIPELINE_PARALLEL_SIZE to
Triton (/root/reference/runners/backends/triton/deploy.sh:84-86); here the
mechanism is owned end-to-end. parallel/pipeline.py covers training; this
module covers the *serving* engine: ``make_pp_forward(cfg, mesh)`` returns a
function with forward()'s exact contract (tokens/positions/cache/offsets/
fresh_prefill/logit_index -> logits, cache), so every engine path — flash
prefill, chunked-prefill continuation, fused decode, grammar-masked decode —
runs over a pp mesh unchanged.

TPU-native design:

- **Layer-range sharding**: params["layers"] and the KV cache shard their
  leading L axis over ``pp`` (the cache memory — the serving-scale reason
  for PP — is actually split across stages). Everything else is replicated.
- **SPMD ring, one ppermute per tick**: inside ``shard_map`` each stage
  runs its local ``run_cached_layers`` every tick; activations move to the
  next stage with a single collective-permute. Tick t's compute is real on
  stage t and garbage elsewhere — the standard SPMD bubble.
- **Gated cache writes**: inactive ticks must not corrupt a stage's cache,
  and a full-cache select per tick would copy gigabytes. Instead
  ``run_cached_layers(write_gate=...)`` gathers the existing values at the
  scatter indices and writes them back when the stage is inactive — the
  no-op write stays O(B·KVH·T·D), the same traffic as the real write
  (models/llama.py).
- **Latency model**: a P-stage forward costs P stage-times + (P-1) hops.
  Serving PP buys HBM capacity (each chip holds L/P layers + L/P of the
  cache), not latency — the validator/docs state this tradeoff.

Composition with other axes: ``shard_map`` runs in full-manual mode over
every mesh axis, with non-pp axes unused by the specs (size-1 in serving
topologies this module targets). tp-within-stage composes at the GSPMD
level instead — run tp=1 per stage here, or use the training executor's
explicit-collective route; the validator only advertises pp x dp serving
meshes.
"""

from __future__ import annotations

import inspect
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from kserve_vllm_mini_tpu.models.config import ModelConfig
from kserve_vllm_mini_tpu.models.llama import (
    embed_tokens,
    final_logits,
    run_cached_layers,
)

from kserve_vllm_mini_tpu.ops.rope import rope_frequencies

try:  # jax >= 0.8
    from jax import shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

# jax renamed the replication-check knob check_rep -> check_vma; resolve
# the spelling this jax accepts so the executor traces on both lines
_SM_CHECK_OFF = {
    ("check_vma" if "check_vma" in inspect.signature(shard_map).parameters
     else "check_rep"): False
}


def _pp_param_specs(params: dict[str, Any]) -> dict[str, Any]:
    """Layer stack over pp, everything else replicated (same shape as
    pipeline._pipeline_specs, duplicated here to keep the serving module
    free of the training executor's imports)."""
    def walk(node, under_layers):
        if isinstance(node, dict):
            return {k: walk(v, under_layers or k == "layers") for k, v in node.items()}
        if under_layers:
            return P("pp", *([None] * (jnp.ndim(node) - 1)))
        return P(*([None] * jnp.ndim(node)))

    return {k: walk(v, k == "layers") for k, v in params.items()}


def _cache_specs(cache: dict[str, Any]) -> dict[str, Any]:
    return {k: P("pp", *([None] * (v.ndim - 1))) for k, v in cache.items()}


def make_pp_forward(cfg: ModelConfig, mesh: Mesh, microbatches: int = 1):
    """Build a pp-sharded function with models.llama.forward's signature.

    Requires cfg.n_layers % pp == 0. The returned function must be called
    with a cache (the serving engine always has one) whose leading axis is
    the full n_layers — shard_map hands each stage its L/pp block.

    ``microbatches > 1`` splits the batch's SLOT axis into M groups and
    pipelines them GPipe-style: M + P - 1 ticks instead of M * P, so the
    per-step bubble shrinks from (P-1)/P toward (P-1)/(M+P-1) — decode
    throughput approaches the single-stage rate while the memory split
    stays. Each group writes its own cache slot range
    (run_cached_layers slot_base) and inactive ticks no-op via the write
    gate. Calls whose batch does not divide M (the engine's B=1 prefills)
    fall back to M=1 at trace time.
    """
    n_pp = int(mesh.shape["pp"])
    if cfg.n_layers % n_pp:
        raise ValueError(f"n_layers={cfg.n_layers} not divisible by pp={n_pp}")
    other = {a: s for a, s in mesh.shape.items() if a != "pp" and s > 1}
    if other:
        # the specs below replicate every non-pp axis: a dp>1 mesh would
        # all-gather the dp-sharded cache every forward and duplicate work
        raise ValueError(
            f"serving PP runs on pure-pp meshes; got extra axes {other} — "
            "scale replicas at the deployment layer (Knative dp) instead"
        )
    perm = [(i, (i + 1) % n_pp) for i in range(n_pp)]

    def pp_forward(
        params: dict[str, Any],
        cfg_: ModelConfig,
        tokens: jnp.ndarray,
        positions: jnp.ndarray,
        kv_cache: Optional[dict[str, Any]] = None,
        cache_offsets: Optional[jnp.ndarray] = None,
        fresh_prefill: bool = False,
        logit_index: Optional[jnp.ndarray] = None,
    ):
        if cfg_ is not cfg:
            raise ValueError(
                "pp_forward was built for one specific config (its rope "
                "tables and stage split are baked in); got a different cfg"
            )
        if kv_cache is None:
            raise ValueError("pp_forward is the serving executor — cache required "
                             "(training uses parallel/pipeline.py)")
        B, T = tokens.shape
        if cache_offsets is None:
            cache_offsets = jnp.zeros((B,), dtype=jnp.int32)
        # trace-time microbatch choice: B=1 prefills (and any batch that
        # does not divide M) run unpipelined
        M = microbatches if microbatches > 1 and B % microbatches == 0 else 1
        mb = B // M

        p_specs = _pp_param_specs(params)
        c_specs = _cache_specs(kv_cache)
        rep = P(None, None)

        has_li = logit_index is not None
        li = logit_index if has_li else jnp.zeros((B,), dtype=jnp.int32)

        @partial(jax.jit, donate_argnums=(3,))
        def run(params, tokens, positions, cache, offsets, li):
            # partial form: old-jax shard_map takes f positionally (it is
            # not a decorator factory), new-jax accepts it too
            @partial(
                shard_map,
                mesh=mesh,
                in_specs=(p_specs, rep, rep, c_specs, P(None), P(None)),
                out_specs=(P(None, None, None), c_specs),
                **_SM_CHECK_OFF,
            )
            def inner(params, tokens, positions, cache, offsets, li):
                stage = jax.lax.axis_index("pp")
                cos, sin = rope_frequencies(
                    cfg.rotary_dim, cfg.max_seq_len, cfg.rope_theta, cfg.rope_scaling
                )
                x = embed_tokens(params, cfg, tokens)         # [B, T, D]
                mbs = x.reshape(M, mb, T, -1)
                pos_mb = positions.reshape(M, mb, T)
                off_mb = offsets.reshape(M, mb)

                def tick(carry, t):
                    state, cache_l, outs = carry
                    m = t - stage                  # this stage's microbatch
                    m_idx = jnp.clip(m, 0, M - 1)
                    active = (m >= 0) & (m < M)
                    # stage 0 ingests microbatch t while any remain
                    h_in = jnp.where(
                        (stage == 0) & (t < M),
                        jax.lax.dynamic_index_in_dim(
                            mbs, jnp.clip(t, 0, M - 1), axis=0, keepdims=False
                        ),
                        state,
                    )
                    if M == 1:
                        # keep the unpipelined path free of the per-layer
                        # dynamic K/V slices slot_base implies (trace-time
                        # branch; bit-identical to the pre-microbatch code)
                        pos_t, off_t, base_t = positions, offsets, None
                    else:
                        pos_t = jax.lax.dynamic_index_in_dim(
                            pos_mb, m_idx, 0, keepdims=False
                        )
                        off_t = jax.lax.dynamic_index_in_dim(
                            off_mb, m_idx, 0, keepdims=False
                        )
                        base_t = m_idx * mb
                    h_out, cache_l = run_cached_layers(
                        params["layers"], cfg, h_in, pos_t,
                        cos, sin, cache_l, off_t,
                        fresh_prefill=fresh_prefill,
                        write_gate=active,
                        slot_base=base_t,
                        # global index of this stage's first layer: the
                        # alt-sliding-window phase follows GLOBAL parity
                        layer_offset=stage * (cfg.n_layers // n_pp),
                    )
                    # last stage emits microbatch t-(P-1) once the pipe fills
                    out_idx = t - (n_pp - 1)
                    emitted = jax.lax.dynamic_update_index_in_dim(
                        outs, h_out, jnp.clip(out_idx, 0, M - 1), axis=0
                    )
                    outs = jnp.where(
                        (stage == n_pp - 1) & (out_idx >= 0), emitted, outs
                    )
                    state = jax.lax.ppermute(h_out, "pp", perm)
                    return (state, cache_l, outs), None

                outs0 = jnp.zeros((M, mb, T, x.shape[-1]), dtype=x.dtype)
                (_, cache_out, outs), _ = jax.lax.scan(
                    tick, (jnp.zeros_like(mbs[0]), cache, outs0),
                    jnp.arange(M + n_pp - 1),
                )
                # only the last stage holds real outputs; broadcast, then
                # every stage computes identical (replicated) logits
                outs = jax.lax.psum(
                    jnp.where(stage == n_pp - 1, outs, jnp.zeros_like(outs)), "pp"
                )
                h = outs.reshape(B, T, -1)
                if has_li:
                    h = h[jnp.arange(B)[:, None], li[:, None]]
                # shared family epilogue (phi bias, gemma (1+w) norm +
                # softcap): executor-local head code drifts silently
                logits = final_logits(params, cfg, h)
                # shard_map has no donation knob — the enclosing jit (run,
                # donate_argnums=(3,)) owns the cache  # kvmini: buffer-ok
                return logits, cache_out

            return inner(params, tokens, positions, cache, offsets, li)

        return run(params, tokens, positions, kv_cache, cache_offsets, li)

    pp_forward.n_pp = n_pp
    return pp_forward
