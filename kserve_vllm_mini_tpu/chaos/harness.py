"""Chaos harness: inject faults, measure MTTR and p95-under-fault, gate each.

Reference behavior (tools/chaos_harness.sh): five fault scenarios —
device-plugin restart (:148-161), pod preemption (:163-175), simulated OOM
via ``kill -9 1`` in the container (:177-190), netem packet loss/delay
(:192-206), node drain (:208-225). MTTR is the time for the
InferenceService to report Ready again (:99-109); after recovery a bench
runs and its results are gated, producing one row per fault in
``resilience_table.json`` (:227-240).

TPU adaptations: the device-plugin scenario targets the GKE
``tpu-device-plugin`` DaemonSet (the nvidia-device-plugin analog), and node
drain targets the TPU node pool — on single-host slices a drain forces a
full slice reschedule, on multi-host slices it kills the whole pod group,
so MTTR here includes TPU re-provisioning, which dominates
(SURVEY.md §7.3 hard part 4).

Everything is injectable (kubectl runner, bench function, sleep/clock) so
the full scenario matrix runs in unit tests against a scripted fake cluster
— the reference's mock-kubectl CI pattern (SURVEY.md §4.3), in-process.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Callable, Optional

from kserve_vllm_mini_tpu.deploy.kubectl import Kubectl

FAULTS = ["device-plugin-restart", "pod-kill", "oom-sim", "netem-loss", "node-drain"]


@dataclass
class FaultResult:
    fault: str
    injected: bool
    recovered: bool
    mttr_s: Optional[float] = None
    p95_ms: Optional[float] = None
    error_rate: Optional[float] = None
    shed_rate: Optional[float] = None   # 429-shed fraction under fault
    gate_ok: Optional[bool] = None
    detail: str = ""

    def row(self) -> dict[str, Any]:
        return {
            "fault": self.fault,
            "injected": self.injected,
            "recovered": self.recovered,
            "mttr_s": None if self.mttr_s is None else round(self.mttr_s, 2),
            "p95_ms": self.p95_ms,
            "error_rate": self.error_rate,
            "shed_rate": self.shed_rate,
            "gate_ok": self.gate_ok,
            "detail": self.detail,
        }


@dataclass
class ChaosConfig:
    namespace: str
    service: str
    ready_timeout_s: float = 900.0    # TPU pools recover in minutes, not 45 s
    poll_interval_s: float = 5.0
    quiesce_s: float = 10.0
    netem_loss_pct: int = 10
    netem_delay_ms: int = 50
    netem_duration_s: float = 30.0


class ChaosHarness:
    def __init__(
        self,
        cfg: ChaosConfig,
        kubectl: Optional[Kubectl] = None,
        bench_fn: Optional[Callable[[str], dict[str, Any]]] = None,
        gate_fn: Optional[Callable[[dict[str, Any]], bool]] = None,
        sleep: Callable[[float], None] = time.sleep,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.cfg = cfg
        self.kc = kubectl or Kubectl()
        self.bench_fn = bench_fn        # fault name -> results dict; None skips bench
        self.gate_fn = gate_fn          # results -> bool; None skips gating
        self.sleep = sleep
        self.clock = clock

    # -- cluster helpers ---------------------------------------------------

    def _predictor_pods(self) -> list[str]:
        res = self.kc.run(
            [
                "get", "pods", "-n", self.cfg.namespace,
                "-l", f"serving.kserve.io/inferenceservice={self.cfg.service}",
                "-o", "jsonpath={.items[*].metadata.name}",
            ]
        )
        return res.stdout.split() if res.ok else []

    def _pod_node(self, pod: str) -> str:
        res = self.kc.run(
            ["get", "pod", pod, "-n", self.cfg.namespace,
             "-o", "jsonpath={.spec.nodeName}"]
        )
        return res.stdout.strip() if res.ok else ""

    def _isvc_ready(self) -> bool:
        res = self.kc.run(
            [
                "get", "inferenceservice", self.cfg.service, "-n", self.cfg.namespace,
                "-o", "jsonpath={.status.conditions[?(@.type=='Ready')].status}",
            ]
        )
        return res.ok and res.stdout.strip() == "True"

    def wait_ready(self) -> Optional[float]:
        """MTTR timer (chaos_harness.sh:99-109): seconds until Ready, or
        None on timeout."""
        t0 = self.clock()
        while self.clock() - t0 < self.cfg.ready_timeout_s:
            if self._isvc_ready():
                return self.clock() - t0
            self.sleep(self.cfg.poll_interval_s)
        return None

    # -- fault injectors ---------------------------------------------------
    # each returns (injected_ok, detail)

    def _inject_device_plugin_restart(self) -> tuple[bool, str]:
        res = self.kc.run(
            ["delete", "pods", "-n", "kube-system",
             "-l", "k8s-app=tpu-device-plugin", "--wait=false"]
        )
        return res.ok, res.stderr.strip() or "tpu-device-plugin pods deleted"

    def _inject_pod_kill(self) -> tuple[bool, str]:
        pods = self._predictor_pods()
        if not pods:
            return False, "no predictor pods found"
        res = self.kc.run(
            ["delete", "pod", pods[0], "-n", self.cfg.namespace,
             "--grace-period=0", "--force", "--wait=false"]
        )
        return res.ok, res.stderr.strip() or f"killed {pods[0]}"

    def _inject_oom_sim(self) -> tuple[bool, str]:
        pods = self._predictor_pods()
        if not pods:
            return False, "no predictor pods found"
        # killing PID 1 in the serving container simulates an engine OOM
        # crash (chaos_harness.sh:177-190); the kubelet restarts it
        res = self.kc.run(
            ["exec", pods[0], "-n", self.cfg.namespace,
             "-c", "kserve-container", "--", "kill", "-9", "1"]
        )
        # exec often reports error 137 as the container dies — that IS success
        ok = res.ok or "137" in res.stderr or "connection" in res.stderr.lower()
        return ok, f"kill -9 1 in {pods[0]}"

    def _inject_netem_loss(self) -> tuple[bool, str]:
        pods = self._predictor_pods()
        if not pods:
            return False, "no predictor pods found"
        res = self.kc.run(
            [
                "exec", pods[0], "-n", self.cfg.namespace, "-c", "kserve-container",
                "--", "tc", "qdisc", "add", "dev", "eth0", "root", "netem",
                "loss", f"{self.cfg.netem_loss_pct}%",
                "delay", f"{self.cfg.netem_delay_ms}ms",
            ]
        )
        if not res.ok:
            return False, f"tc unavailable: {res.stderr.strip()[:120]}"
        return True, f"netem {self.cfg.netem_loss_pct}% loss on {pods[0]}"

    def _clear_netem(self) -> None:
        for pod in self._predictor_pods():
            self.kc.run(
                ["exec", pod, "-n", self.cfg.namespace, "-c", "kserve-container",
                 "--", "tc", "qdisc", "del", "dev", "eth0", "root"]
            )

    def _inject_node_drain(self) -> tuple[bool, str]:
        pods = self._predictor_pods()
        node = self._pod_node(pods[0]) if pods else ""
        if not node:
            return False, "could not resolve predictor node"
        self._drained_node = node
        res = self.kc.run(
            ["drain", node, "--ignore-daemonsets", "--delete-emptydir-data",
             "--force", "--grace-period=30"],
            timeout_s=300.0,
        )
        return res.ok, res.stderr.strip() or f"drained {node}"

    def _uncordon(self) -> None:
        node = getattr(self, "_drained_node", "")
        if node:
            self.kc.run(["uncordon", node])

    # -- scenario loop -----------------------------------------------------

    def run_fault(self, fault: str) -> FaultResult:
        injectors = {
            "device-plugin-restart": self._inject_device_plugin_restart,
            "pod-kill": self._inject_pod_kill,
            "oom-sim": self._inject_oom_sim,
            "netem-loss": self._inject_netem_loss,
            "node-drain": self._inject_node_drain,
        }
        if fault not in injectors:
            raise ValueError(f"unknown fault {fault!r} (known: {FAULTS})")
        try:
            ready = self._isvc_ready()
        except Exception as e:  # noqa: BLE001 — a broken kubectl is a result
            return FaultResult(
                fault, False, False,
                detail=f"readiness check failed: {type(e).__name__}: {e}",
            )
        if not ready:
            return FaultResult(fault, False, False, detail="service not Ready before fault")

        # A raising injector (kubectl binary missing, cluster gone mid-run)
        # must SHORT-CIRCUIT to an injected=False row with gate_ok left
        # None: proceeding to bench-and-gate would bench the healthy
        # service and stamp a green gate onto a fault that never happened.
        try:
            injected, detail = injectors[fault]()
        except Exception as e:  # noqa: BLE001 — injection failure is a row
            return FaultResult(
                fault, False, False,
                detail=f"injection failed: {type(e).__name__}: {e}",
            )
        result = FaultResult(fault, injected, False, detail=detail)
        if not injected:
            return result

        try:
            if fault == "netem-loss":
                # degradation fault: service stays Ready; bench DURING the
                # fault, then clear it (chaos_harness.sh:192-206)
                result.recovered = True
                result.mttr_s = 0.0
                self._bench_and_gate(result, during_fault=True)
                return result

            mttr = self.wait_ready()
            result.mttr_s = mttr
            result.recovered = mttr is not None
            if not result.recovered:
                result.detail += f"; not Ready after {self.cfg.ready_timeout_s:.0f}s"
                return result
            self.sleep(self.cfg.quiesce_s)
            self._bench_and_gate(result, during_fault=False)
            return result
        finally:
            if fault == "netem-loss":
                self._clear_netem()
            elif fault == "node-drain":
                self._uncordon()

    def _bench_and_gate(self, result: FaultResult, during_fault: bool) -> None:
        if self.bench_fn is None:
            return
        try:
            results = self.bench_fn(result.fault)
        except Exception as e:  # noqa: BLE001 — a failed bench is a data point
            result.detail += f"; bench failed: {type(e).__name__}: {e}"
            result.gate_ok = False
            return
        result.p95_ms = results.get("p95_ms")
        result.error_rate = results.get("error_rate")
        if self.gate_fn is not None:
            result.gate_ok = bool(self.gate_fn(results))

    def run_all(self, faults: Optional[list[str]] = None) -> list[FaultResult]:
        out = []
        for fault in faults or FAULTS:
            print(f"chaos: injecting {fault}", file=sys.stderr)
            res = self.run_fault(fault)
            status = (
                f"MTTR {res.mttr_s:.0f}s" if res.recovered and res.mttr_s is not None
                else "NOT RECOVERED" if res.injected else "SKIPPED"
            )
            print(f"chaos: {fault}: {status} ({res.detail})", file=sys.stderr)
            out.append(res)
        return out


def write_resilience_table(
    results: list[FaultResult], path: Path, cfg: ChaosConfig,
    target: str = "kserve",
) -> dict[str, Any]:
    """The shared resilience_table.json writer — one shape for the
    cluster harness and `--target local` (core/schema.py
    validate_resilience; `make chaos-smoke` gates on it)."""
    table = {
        "service": cfg.service,
        "namespace": cfg.namespace,
        "target": target,
        "faults": [r.row() for r in results],
        "all_recovered": all(r.recovered for r in results if r.injected),
        "worst_mttr_s": max(
            (r.mttr_s for r in results if r.mttr_s), default=None
        ),
    }
    path.parent.mkdir(parents=True, exist_ok=True)
    with path.open("w") as f:
        json.dump(table, f, indent=2)
    return table


def table_exit_code(table: dict[str, Any]) -> int:
    """CI exit for a resilience table: 0 only when every injected fault
    recovered AND at least one fault was actually injected —
    ``all_recovered`` is vacuously true over an empty injected set, and
    a run where every injection failed (broken kubectl, /faults
    disabled) must not read as a passing chaos matrix."""
    injected_any = any(r.get("injected") for r in table.get("faults", []))
    return 0 if table.get("all_recovered") and injected_any else 1


# -- CLI ---------------------------------------------------------------------

def register(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("--target", default="kserve",
                        choices=["kserve", "local"],
                        help="'kserve' injects at the cluster layer; "
                             "'local' drives a live local server's "
                             "in-process injection points via POST /faults "
                             "(start it with --allow-fault-injection; "
                             "docs/RESILIENCE.md) — same scenario loop, "
                             "same resilience_table.json, no cluster")
    parser.add_argument("--namespace", default=None,
                        help="Required for --target kserve")
    parser.add_argument("--service", default=None,
                        help="Required for --target kserve")
    parser.add_argument("--faults", default=None,
                        help="Comma-separated subset. kserve: "
                             + ", ".join(FAULTS) + ". local: "
                             "sweep-wedge, device-error, kv-alloc-fail, "
                             "sse-disconnect, publish-drop")
    parser.add_argument("--url", default=None,
                        help="Endpoint to bench after each fault (required "
                             "for --target local; optional bench for kserve)")
    parser.add_argument("--requests", type=int, default=50)
    parser.add_argument("--concurrency", type=int, default=5)
    parser.add_argument("--slo", default=None, help="Gate each post-fault bench")
    parser.add_argument("--ready-timeout", type=float, default=900.0)
    parser.add_argument("--recovery-timeout", type=float, default=30.0,
                        help="Local mode: MTTR budget after a fault clears")
    parser.add_argument("--output", default="resilience_table.json")


def _make_bench_fn(url: str, requests: int, concurrency: int):
    def bench_fn(fault: str) -> dict[str, Any]:
        from kserve_vllm_mini_tpu.bench_pipeline import run_bench

        results, _ = run_bench(
            url=url,
            profile={
                "model": "default",
                "requests": requests,
                "concurrency": concurrency,
            },
        )
        if not results:
            raise RuntimeError("bench produced no results")
        return results

    return bench_fn


def _make_gate_fn(slo_path: str):
    from kserve_vllm_mini_tpu.gates.slo import gate_results, load_slo

    budgets = load_slo(slo_path)

    def gate_fn(results: dict[str, Any]) -> bool:
        return all(v.ok for v in gate_results(results, budgets))

    return gate_fn


def run(args: argparse.Namespace) -> int:
    gate_fn = _make_gate_fn(args.slo) if args.slo else None
    fault_list = [
        f.strip() for f in (args.faults or "").split(",") if f.strip()
    ] or None

    if args.target == "local":
        from kserve_vllm_mini_tpu.chaos.local import LocalChaosHarness

        if not args.url:
            print("chaos: --target local requires --url", file=sys.stderr)
            return 2
        bench_fn = _make_bench_fn(args.url, args.requests, args.concurrency)
        harness = LocalChaosHarness(
            args.url, bench_fn=bench_fn, gate_fn=gate_fn,
            recovery_timeout_s=args.recovery_timeout,
        )
        results = harness.run_all(fault_list)
        cfg = ChaosConfig(namespace=args.namespace or "-",
                          service=args.service or "local")
        table = write_resilience_table(
            results, Path(args.output), cfg, target="local"
        )
        print(json.dumps(table, indent=2))
        return table_exit_code(table)

    if not args.namespace or not args.service:
        print("chaos: --target kserve requires --namespace and --service",
              file=sys.stderr)
        return 2
    cfg = ChaosConfig(
        namespace=args.namespace,
        service=args.service,
        ready_timeout_s=args.ready_timeout,
    )
    bench_fn = (
        _make_bench_fn(args.url, args.requests, args.concurrency)
        if args.url else None
    )
    harness = ChaosHarness(cfg, bench_fn=bench_fn, gate_fn=gate_fn)
    results = harness.run_all(fault_list)
    table = write_resilience_table(results, Path(args.output), cfg)
    print(json.dumps(table, indent=2))
    return table_exit_code(table)
