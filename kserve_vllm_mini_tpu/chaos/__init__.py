"""Fault injection + MTTR measurement (reference tools/chaos_harness.sh)."""
