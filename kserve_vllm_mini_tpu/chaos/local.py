"""`kvmini-tpu chaos --target local`: the scenario matrix against a LIVE
local server, no cluster (docs/RESILIENCE.md).

The cluster harness (chaos/harness.py) injects at the Kubernetes layer;
this one drives the runtime's own in-process injection points through
``POST /faults`` (the server must run with ``--allow-fault-injection``;
``tests/mock_server.py`` speaks the same wire shape). Per scenario:

1. verify the endpoint is healthy (one tiny completion),
2. arm the fault,
3. bench DURING the fault (p95-under-fault, error/shed rates via the
   injectable ``bench_fn``, or a small built-in probe burst),
4. clear the fault,
5. MTTR = time to the FIRST healthy completion after the clear,
6. optional gate on the during-fault results.

Output is the same ``resilience_table.json`` the cluster harness writes
(``write_resilience_table``; schema-gated by ``core/schema.py``
``validate_resilience`` in ``make chaos-smoke``).
"""

from __future__ import annotations

import json
import sys
import time
import urllib.error
import urllib.request
from typing import Any, Callable, Optional

from kserve_vllm_mini_tpu.chaos.harness import FaultResult

# local fault classes -> the runtime injection point each exercises
# (runtime/faults.py FAULT_POINTS). One scenario per failure class the
# tentpole threads through the hot paths. `times: 0` = until cleared.
LOCAL_FAULTS = [
    "sweep-wedge",
    "device-error",
    "kv-alloc-fail",
    "sse-disconnect",
    "handoff-drop",
    "publish-drop",
    "replica-kill",
    "replica-wedge",
]

# replica-level scenarios (docs/FLEET.md): injected through the fleet
# router's POST /fleet/chaos, not a single server's /faults. A target
# that is not a fleet router (404) or has no survivors to fail over to
# (409 on single-replica fleets) yields an honest injected=False row —
# the PR-13 handoff-drop pattern. Recovery for replica-kill is the
# supervisor's self-heal; for replica-wedge the router failing over
# plus the cleared fault.
REPLICA_FAULTS: dict[str, dict[str, Any]] = {
    "replica-kill": {"action": "kill"},
    "replica-wedge": {"action": "wedge", "duration": 0.4},
}

FAULT_ARMS: dict[str, dict[str, Any]] = {
    "sweep-wedge": {"name": "sweep_stall", "times": 0, "duration": 0.4},
    # BOUNDED on purpose: each device fault climbs the engine's degrade
    # ladder one level, and an until-cleared error would walk a real
    # engine off the end of it (level 4 = give up) before the harness
    # could clear — 2 faults leaves it serving, degraded, measurable
    "device-error": {"name": "device_error", "times": 2},
    "kv-alloc-fail": {"name": "kv_alloc_fail", "times": 0, "duration": 0.5},
    "sse-disconnect": {"name": "sse_disconnect", "times": 0,
                       "after_tokens": 1},
    # every lane handoff lost until cleared: the engine must DEGRADE to
    # colocated prefill (requests complete, slower) — recovery is the
    # first healthy completion after the clear, and a colocated server
    # refuses the arm (honest injected=False row, same contract as
    # kv_alloc_fail on a dense engine)
    "handoff-drop": {"name": "kv_handoff_drop", "times": 0},
    # publish_drop needs a multihost primary; a single-host target gets
    # an honest injected=False row, never a skipped-silently scenario
    "publish-drop": {"name": "publish_drop", "times": 1},
}


class LocalChaosHarness:
    """In-process chaos against one live endpoint.

    Everything is injectable (probe, bench, gate, clock, sleep) so the
    full scenario loop runs in unit tests against the mock server —
    the same design contract as ChaosHarness."""

    def __init__(
        self,
        url: str,
        bench_fn: Optional[Callable[[str], dict[str, Any]]] = None,
        gate_fn: Optional[Callable[[dict[str, Any]], bool]] = None,
        probe_fn: Optional[Callable[[], bool]] = None,
        fault_hold_s: float = 1.0,
        recovery_timeout_s: float = 30.0,
        poll_interval_s: float = 0.2,
        probe_timeout_s: float = 5.0,
        sleep: Callable[[float], None] = time.sleep,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.url = url.rstrip("/")
        self.bench_fn = bench_fn      # fault name -> results dict; None = skip
        self.gate_fn = gate_fn        # results -> bool; None = no gate
        self.probe_fn = probe_fn or self._default_probe
        self.fault_hold_s = fault_hold_s
        self.recovery_timeout_s = recovery_timeout_s
        self.poll_interval_s = poll_interval_s
        self.probe_timeout_s = probe_timeout_s
        self.sleep = sleep
        self.clock = clock

    # -- endpoint helpers --------------------------------------------------

    def _default_probe(self) -> bool:
        """One tiny NON-streaming completion = 'healthy'. MTTR is the
        time to the first of these succeeding after the fault clears."""
        body = json.dumps({
            "messages": [{"role": "user", "content": "ping"}],
            "max_tokens": 2, "stream": False,
        }).encode()
        req = urllib.request.Request(
            self.url + "/v1/chat/completions", data=body,
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=self.probe_timeout_s) as r:
                return r.status == 200
        except Exception:  # the probe's failure IS the signal
            return False   # (recovery not reached yet)

    def _post_json(self, path: str,
                   payload: dict[str, Any]) -> tuple[bool, str]:
        """ONE POST helper for every injection surface (/faults and the
        fleet router's /fleet/chaos): (ok, body-or-error snippet). An
        HTTP error (404 non-fleet target, 409 no survivors, 403 gated)
        becomes an honest injected=False row upstream."""
        req = urllib.request.Request(
            self.url + path, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=self.probe_timeout_s) as r:
                return r.status == 200, r.read().decode()[:200]
        except urllib.error.HTTPError as e:
            detail = ""
            try:
                detail = e.read().decode()[:200]
            except Exception:  # detail string is best-effort
                pass
            return False, f"HTTP {e.code}: {detail}"
        except Exception as e:  # noqa: BLE001 — injection failure is a row
            return False, f"{type(e).__name__}: {e}"

    def _faults_post(self, payload: dict[str, Any]) -> tuple[bool, str]:
        ok, body = self._post_json("/faults", payload)
        return ok, "" if ok else body

    def _arm(self, fault: str) -> tuple[bool, str]:
        params = dict(FAULT_ARMS[fault])
        ok, detail = self._faults_post({"action": "arm", **params})
        return ok, detail or f"armed {params['name']}"

    def _clear(self, fault: str) -> None:
        self._faults_post({"action": "clear",
                           "name": FAULT_ARMS[fault]["name"]})

    def _fleet_chaos(self, payload: dict[str, Any]) -> tuple[bool, str]:
        return self._post_json("/fleet/chaos", payload)

    # -- scenario loop -----------------------------------------------------

    def run_fault(self, fault: str) -> FaultResult:
        if fault not in FAULT_ARMS and fault not in REPLICA_FAULTS:
            raise ValueError(
                f"unknown local fault {fault!r} (known: {LOCAL_FAULTS})"
            )
        if not self.probe_fn():
            return FaultResult(fault, False, False,
                               detail="endpoint not healthy before fault")
        if fault == "publish-drop":
            # the publish path only exists on a multihost primary; the
            # single-host row stays honest rather than green
            return FaultResult(
                fault, False, False,
                detail="publish_drop needs a multihost primary; covered "
                       "by the unit-level decision-stream test",
            )
        if fault in REPLICA_FAULTS:
            # replica-level scenarios go through the fleet router's
            # POST /fleet/chaos (docs/FLEET.md). The kill's 'clear' is a
            # no-op (recovery = supervisor self-heal + router failover);
            # the wedge's clear disarms sweep_stall on every replica.
            return self._scenario(
                fault,
                inject=lambda: self._fleet_chaos(REPLICA_FAULTS[fault]),
                clear=lambda: self._fleet_chaos({"action": "clear"}),
            )
        return self._scenario(
            fault,
            inject=lambda: self._arm(fault),
            clear=lambda: self._clear(fault),
        )

    def _scenario(self, fault: str, inject, clear) -> FaultResult:
        """The ONE scenario loop every fault class shares: inject, hold,
        bench DURING the fault (p95-under-fault + error/shed rates),
        clear, then MTTR = clear -> first healthy completion."""
        injected, detail = inject()
        result = FaultResult(fault, injected, False, detail=detail)
        if not injected:
            return result  # gate_ok stays None: no fault, no verdict
        try:
            self.sleep(self.fault_hold_s)
            if self.bench_fn is not None:
                try:
                    bench = self.bench_fn(fault)
                except Exception as e:  # noqa: BLE001 — a failed bench is
                    # a data point, same contract as the cluster harness
                    result.detail += f"; bench failed: {type(e).__name__}: {e}"
                    result.gate_ok = False
                    bench = None
                if bench:
                    result.p95_ms = bench.get("p95_ms")
                    result.error_rate = bench.get("error_rate")
                    result.shed_rate = bench.get("shed_rate")
                    if self.gate_fn is not None:
                        result.gate_ok = bool(self.gate_fn(bench))
        finally:
            clear()
        t0 = self.clock()
        while self.clock() - t0 < self.recovery_timeout_s:
            if self.probe_fn():
                result.mttr_s = self.clock() - t0
                result.recovered = True
                return result
            self.sleep(self.poll_interval_s)
        result.detail += (
            f"; no healthy completion {self.recovery_timeout_s:.0f}s "
            "after fault clear"
        )
        return result

    def run_all(self, faults: Optional[list[str]] = None) -> list[FaultResult]:
        out = []
        for fault in faults or LOCAL_FAULTS:
            print(f"chaos[local]: injecting {fault}", file=sys.stderr)
            res = self.run_fault(fault)
            status = (
                f"MTTR {res.mttr_s:.2f}s"
                if res.recovered and res.mttr_s is not None
                else "NOT RECOVERED" if res.injected else "SKIPPED"
            )
            print(f"chaos[local]: {fault}: {status} ({res.detail})",
                  file=sys.stderr)
            out.append(res)
        return out
