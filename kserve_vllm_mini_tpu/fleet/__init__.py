"""Multi-replica serving fleet (docs/FLEET.md).

The paper's autoscale chapter only *measures* Knative's scaler from the
outside; this subsystem owns the capability: N single-engine server
replicas (``runtime/server.py`` unchanged, one subprocess per replica)
behind a cache-aware router, scaled live by a local actuator driven from
the same signals the monitor already computes.

- ``fleet.supervisor`` — spawns/reaps replica subprocesses, restarts
  unexpectedly-dead ones, and accounts scale-up cold starts.
- ``fleet.router`` — asyncio HTTP front: prefix/session-affinity
  placement scored against each replica's live ``estimate_wait_s`` and
  queue depth, fleet-level admission (per-replica 429s re-place before
  the client ever sees them), and an aggregated ``/metrics`` with
  per-replica labels.
- ``fleet.actuator`` — wires ``autoscale/controller.py`` to the
  supervisor so burn-rates/queue pressure add and remove REAL replicas.
- ``fleet.service`` — the ``kvmini-tpu fleet`` CLI gluing the three.
"""

from kserve_vllm_mini_tpu.fleet.router import (  # noqa: F401
    FleetRouter,
    PrefixIndex,
    RouterConfig,
)
from kserve_vllm_mini_tpu.fleet.supervisor import (  # noqa: F401
    FleetSupervisor,
    Replica,
)
