"""``kvmini-tpu fleet`` — N serving replicas behind the cache-aware
router, optionally autoscaled live (docs/FLEET.md).

One command replaces the paper's outside-in autoscale sweep: the
supervisor spawns ``--replicas`` unmodified ``kvmini-tpu serve``
processes, the router fronts them on ``--port``, and ``--autoscale``
arms the local actuator so queue pressure / duty / SLO burn add and
remove replicas for real. Point any existing loadgen/bench/fairness
invocation at the router URL — the wire contract is the single server's.
"""

from __future__ import annotations

import argparse
import signal
import threading
from pathlib import Path

from kserve_vllm_mini_tpu.autoscale.controller import PolicyConfig
from kserve_vllm_mini_tpu.fleet.actuator import FleetAutoscaler
from kserve_vllm_mini_tpu.fleet.router import (
    FleetRouter,
    RouterConfig,
    start_router,
)
from kserve_vllm_mini_tpu.fleet.supervisor import (
    FleetSupervisor,
    serve_replica_cmd,
)


def register(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("--model", default="llama-tiny",
                        help="Model preset each replica serves")
    parser.add_argument("--replicas", type=int, default=2,
                        help="Initial replica count")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8000,
                        help="Router port (replicas take OS-assigned ports)")
    parser.add_argument("--policy", default="cache_aware",
                        choices=["cache_aware", "round_robin"],
                        help="Placement policy (docs/FLEET.md scoring)")
    parser.add_argument("--replica-arg", action="append", default=None,
                        metavar="ARG",
                        help="Extra flag passed verbatim to every "
                             "`kvmini-tpu serve` replica (repeatable), "
                             "e.g. --replica-arg=--prefix-cache")
    parser.add_argument("--log-dir", default=None,
                        help="Per-replica stdout/stderr logs (default: "
                             "discarded)")
    parser.add_argument("--max-replicas", type=int, default=8)
    parser.add_argument("--no-restart", action="store_true",
                        help="Do not respawn replicas that die "
                             "unexpectedly (default: self-heal)")
    parser.add_argument("--autoscale", action="store_true",
                        help="Arm the local actuator: the autoscale "
                             "policy polls the router's aggregated "
                             "/metrics and adds/removes replicas live")
    parser.add_argument("--min", type=int, default=1,
                        help="Autoscale floor")
    parser.add_argument("--target-duty", type=float, default=0.75)
    parser.add_argument("--target-queue", type=float, default=4.0)
    parser.add_argument("--stabilization", type=float, default=120.0,
                        help="Downscale stabilization window (s)")
    parser.add_argument("--autoscale-interval", type=float, default=5.0)
    parser.add_argument("--decision-log", default=None,
                        help="JSONL autoscale decision log")
    parser.add_argument("--allow-fault-injection", action="store_true",
                        help="Enable POST /fleet/chaos (replica kill/"
                             "wedge — what `kvmini-tpu chaos --target "
                             "local` drives against a fleet). Replicas "
                             "are started with --allow-fault-injection "
                             "too so wedges can arm. Never enable in "
                             "production")


def run(args: argparse.Namespace) -> int:
    extra = list(args.replica_arg or [])
    if args.allow_fault_injection and "--allow-fault-injection" not in extra:
        extra.append("--allow-fault-injection")
    sup = FleetSupervisor(
        replica_cmd=serve_replica_cmd(model=args.model, extra_args=extra),
        host=args.host,
        log_dir=Path(args.log_dir) if args.log_dir else None,
        restart_dead=not args.no_restart,
        max_replicas=args.max_replicas,
    )
    print(f"fleet: starting {args.replicas} replica(s) of {args.model} "
          "(cold starts measured)...", flush=True)
    try:
        sup.start(args.replicas)
    except Exception as e:  # noqa: BLE001 — a fleet that can't boot must
        # reap what it spawned, not strand half a fleet of orphans
        sup.stop()
        print(f"fleet: startup failed: {e}")
        return 1
    router = FleetRouter(
        supervisor=sup,
        cfg=RouterConfig(policy=args.policy),
        allow_fault_injection=args.allow_fault_injection,
    )
    handle = start_router(router, host=args.host, port=args.port)
    scaler = None
    if args.autoscale:
        scaler = FleetAutoscaler(
            sup, handle.url,
            cfg=PolicyConfig(
                min_replicas=args.min,
                max_replicas=args.max_replicas,
                target_duty=args.target_duty,
                target_queue_per_replica=args.target_queue,
                stabilization_s=args.stabilization,
            ),
            interval_s=args.autoscale_interval,
            decision_log=Path(args.decision_log) if args.decision_log
            else None,
            initial_replicas=args.replicas,
        ).start()
    cs = sup.counters()
    print(f"kvmini-tpu fleet: router on {handle.url} "
          f"({cs['live']} replica(s), policy={args.policy}, "
          f"last cold start "
          f"{(cs['last_cold_start_s'] or 0.0):.1f}s"
          f"{', autoscaling' if scaler else ''})", flush=True)
    stop = threading.Event()
    signal.signal(signal.SIGINT, lambda *_a: stop.set())
    signal.signal(signal.SIGTERM, lambda *_a: stop.set())
    try:
        while not stop.wait(timeout=1.0):
            pass  # serve until signalled; the timeout keeps the wait
            #       interruptible on platforms with flaky signal wakeups
    finally:
        if scaler is not None:
            scaler.stop()
        handle.stop()
        sup.stop()
    return 0
