"""Local autoscale actuator: the controller's policy, REAL replicas.

``autoscale/controller.py`` stays the policy brain (target tracking,
downscale stabilization, breach latch); this module supplies the two
halves it previously only had in dry-run/KServe form for a local fleet:

- **signals** come from the ROUTER's aggregated ``/metrics`` in one
  scrape: the flat parser sums the per-replica labeled series, so fleet
  queue depth is the true sum and mean duty is sum/live — the exact
  aggregation ``fleet_signals`` does with N scrapes, for one. An
  attached live monitor (docs/MONITORING.md) contributes its rolling
  SLO burn-rates: any burn at/over the threshold counts as a breach and
  forces a step up, which is how "scale on burn-rate" becomes a real
  actuation instead of a dashboard annotation.
- **actuation** is ``FleetSupervisor.scale_to`` — subprocess replicas
  spawn (blocking until healthy, so the next poll sees capacity, not
  promises) and reap, with cold starts measured per scale-up.
"""

from __future__ import annotations

import threading
import time
from pathlib import Path
from typing import Any, Callable, Optional

from kserve_vllm_mini_tpu.analysis.telemetry import scrape_runtime_metrics
from kserve_vllm_mini_tpu.autoscale.controller import (
    Controller,
    PolicyConfig,
    Signals,
)
from kserve_vllm_mini_tpu.fleet.supervisor import FleetSupervisor


def router_signals(
    router_url: str,
    burn_fn: Optional[Callable[[], dict[str, float]]] = None,
    burn_threshold: float = 2.0,
    timeout_s: float = 5.0,
) -> Signals:
    """One poll of the fleet through the router's aggregated /metrics.

    ``burn_fn`` (e.g. ``monitor_burn_fn(run_monitor)``) supplies the
    live monitor's rolling burn-rates; any value >= ``burn_threshold``
    marks the sample SLO-breached, which the policy answers with an
    immediate step up."""
    m = scrape_runtime_metrics(router_url, timeout_s=timeout_s)
    live = m.get("kvmini_tpu_fleet_replicas_live", 0.0)
    # the router re-emits ratio gauges (duty among them) as ONE
    # fleet-level mean (router.MEAN_GAUGES); queue_depth arrives as the
    # per-replica labeled series the flat parser sums = the true total
    duty = m.get("kvmini_tpu_duty_cycle", 0.0)
    sig = Signals(
        duty_cycle=min(duty, 1.0),
        queue_depth=m.get("kvmini_tpu_queue_depth", 0.0),
        # economics rail from the SAME scrape (docs/ECONOMICS.md): the
        # router re-emits $/1K-tok as a healthy-replica mean and derives
        # the marginal-replica gauge; a fleet of unpriced engines exports
        # neither and the cost-aware policy stays inert
        usd_per_1k_tok=m.get("kvmini_tpu_econ_usd_per_1k_tokens"),
        marginal_usd_per_1k_tok=m.get(
            "kvmini_tpu_econ_marginal_replica_usd_per_1k_tokens"
        ),
        ts=time.time(),
        valid=bool(m) and live > 0,
    )
    if burn_fn is not None and sig.valid:
        try:
            burns = burn_fn() or {}
        except Exception:  # noqa: BLE001 — a monitor mid-teardown loses
            burns = {}     # one poll's breach signal, not the loop
        if any(v >= burn_threshold for v in burns.values()):
            sig.slo_breached = True
    return sig


def monitor_burn_fn(monitor: Any) -> Callable[[], dict[str, float]]:
    """Adapt a live ``RunMonitor`` to the actuator's burn source (its
    ``summary()`` carries the latest rolling burn-rates under the same
    1.0-=-on-budget convention the burn threshold compares against)."""

    def burns() -> dict[str, float]:
        return dict(monitor.summary().get("burn_rates", {}))

    return burns


def local_scaler(supervisor: FleetSupervisor) -> Callable[[int], None]:
    """The controller-facing actuation verb. Blocks until new replicas
    are healthy — cold-start wall lands in the supervisor's counters."""

    def scale(n: int) -> None:
        supervisor.scale_to(n)

    return scale


class FleetAutoscaler:
    """A Controller polling the router and actuating the supervisor on
    its own thread — the live loop the paper's autoscale chapter could
    only sweep from outside.

    ``burn_fn`` is optional; with a live monitor attached the loop
    scales on SLO burn-rates as well as duty/queue pressure."""

    def __init__(
        self,
        supervisor: FleetSupervisor,
        router_url: str,
        cfg: Optional[PolicyConfig] = None,
        interval_s: float = 2.0,
        burn_fn: Optional[Callable[[], dict[str, float]]] = None,
        burn_threshold: float = 2.0,
        decision_log: Optional[Path] = None,
        initial_replicas: int = 1,
    ) -> None:
        self.supervisor = supervisor
        self.router_url = router_url
        self.interval_s = interval_s
        self.controller = Controller(
            signal_fn=lambda: router_signals(
                router_url, burn_fn=burn_fn, burn_threshold=burn_threshold
            ),
            scaler=local_scaler(supervisor),
            cfg=cfg,
            initial_replicas=initial_replicas,
            decision_log=decision_log,
        )
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def step(self) -> int:
        return self.controller.step()

    def _loop(self) -> None:
        while not self._stop.wait(timeout=self.interval_s):
            try:
                self.controller.step()
            except Exception as e:  # noqa: BLE001 — an autoscaler that
                # dies on one bad poll stops scaling exactly when churn
                # makes polls flaky (same contract as Controller.run)
                print(f"fleet-autoscale: step failed ({type(e).__name__}: "
                      f"{e}); continuing")

    def start(self) -> "FleetAutoscaler":
        if self._thread is not None:
            raise RuntimeError("autoscaler already started")
        self._thread = threading.Thread(
            target=self._loop, name="fleet-autoscaler", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)

    @property
    def decisions(self) -> list[dict[str, Any]]:
        # snapshot, not the live list: the controller appends on the
        # autoscaler thread while callers iterate
        return list(self.controller.decisions)
