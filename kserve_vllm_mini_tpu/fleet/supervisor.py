"""Replica supervisor: N serving subprocesses, restarted when they die.

Each replica is one unmodified ``kvmini-tpu serve`` process on its own
port (``serve_replica_cmd``); tests substitute any command that answers
``/healthz`` (the mock server's CLI). The supervisor owns the process
table — spawn, readiness, scale up/down, deliberate kills for chaos,
and a watchdog thread that respawns replicas that died WITHOUT being
asked to (a killed replica is a fault, not a scale-down). Scale-up
cold starts (spawn -> first healthy ``/healthz``) are measured per
replica and surfaced through the router's ``/metrics`` — the number the
paper's autoscale chapter could only infer from latency cliffs.

All state is guarded by one lock: the watchdog thread, the actuator
thread (``fleet/actuator.py``) and the router's scoreboard all read and
write the table concurrently.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.request
from dataclasses import dataclass
from pathlib import Path
from typing import Any, Callable, Optional

# replica lifecycle states. "removed" and "stopping" mark DELIBERATE
# exits: the watchdog must not resurrect a scale-down.
STARTING = "starting"
READY = "ready"
DEAD = "dead"
REMOVED = "removed"

# default byte budget one warm-from-sibling transfer may ship: enough
# for a few hundred small blocks of int8 KV, small enough that a respawn
# storm can't saturate the host loopback
WARM_BUDGET_BYTES = 16 * 1024 * 1024


def select_donor(
    owners: dict[str, int],
    candidates: list[tuple[str, str, bool]],
    exclude: str,
) -> Optional[tuple[str, str]]:
    """Pick the KV-migration donor for a cold replica: the DEEPEST-
    owning healthy sibling (``owners`` is the router's
    ``PrefixIndex.owners()`` map, rid -> deepest owned prefix chars).
    ``candidates`` are ``(rid, url, healthy)``; the target itself is
    excluded, unhealthy replicas never donate, and a replica with no
    owned prefix (depth 0 — cold itself, e.g. JUST respawned and purged
    from the index) never donates either: migrating from a cold cache
    would ship nothing and waste the respawn window. Returns
    ``(rid, url)`` or None (cold spawn)."""
    best: Optional[tuple[str, str]] = None
    best_depth = 0
    for rid, url, healthy in candidates:
        if rid == exclude or not healthy:
            continue
        depth = int(owners.get(rid, 0))
        if depth > best_depth:
            best, best_depth = (rid, url), depth
    return best


def free_port(host: str = "127.0.0.1") -> int:
    """An OS-assigned free TCP port (bind-0 probe; the tiny window
    between close and the replica's own bind is acceptable for a local
    fleet)."""
    with socket.socket() as s:
        s.bind((host, 0))
        return s.getsockname()[1]


def serve_replica_cmd(
    model: str = "llama-tiny",
    extra_args: Optional[list[str]] = None,
    env_overrides: Optional[dict[str, str]] = None,
) -> Callable[[int, str], tuple[list[str], dict[str, str]]]:
    """The default replica factory: one ``kvmini-tpu serve`` per port,
    flags appended verbatim. Returns (argv, env) per replica so tests
    and the bench fleet row can force e.g. ``JAX_PLATFORMS=cpu`` without
    touching the parent's environment."""

    def cmd(port: int, rid: str) -> tuple[list[str], dict[str, str]]:
        argv = [
            sys.executable, "-m", "kserve_vllm_mini_tpu", "serve",
            "--model", model, "--port", str(port),
        ] + list(extra_args or [])
        env = dict(os.environ)
        env.update(env_overrides or {})
        return argv, env

    return cmd


@dataclass
class Replica:
    rid: str
    port: int
    url: str
    proc: Optional[subprocess.Popen] = None
    state: str = STARTING
    spawned_at: float = 0.0
    ready_at: Optional[float] = None
    restarts: int = 0
    log_path: Optional[Path] = None

    def cold_start_s(self) -> Optional[float]:
        if self.ready_at is None:
            return None
        return self.ready_at - self.spawned_at

    def view(self) -> dict[str, Any]:
        return {
            "rid": self.rid,
            "port": self.port,
            "url": self.url,
            "state": self.state,
            "pid": self.proc.pid if self.proc else None,
            "cold_start_s": self.cold_start_s(),
            "restarts": self.restarts,
        }


class FleetSupervisor:
    """Owns the replica process table.

    ``replica_cmd(port, rid) -> (argv, env)`` builds each replica's
    command (default: ``serve_replica_cmd()``). ``ready_timeout_s``
    bounds the spawn->healthy wait; a replica that never comes up is
    reaped and the spawn raises. ``restart_dead`` arms the watchdog
    thread (unexpected deaths respawn on the same port/rid, counted in
    ``replica_restarts``)."""

    def __init__(
        self,
        replica_cmd: Optional[Callable[[int, str], tuple[list[str], dict[str, str]]]] = None,
        host: str = "127.0.0.1",
        log_dir: Optional[Path] = None,
        ready_timeout_s: float = 120.0,
        restart_dead: bool = True,
        max_replicas: int = 8,
        poll_interval_s: float = 0.25,
        warm_from_siblings: bool = False,
        router_url: Optional[str] = None,
        warm_budget_bytes: int = WARM_BUDGET_BYTES,
        owners_fn: Optional[Callable[[], dict[str, int]]] = None,
    ) -> None:
        self.replica_cmd = replica_cmd or serve_replica_cmd()
        self.host = host
        self.log_dir = Path(log_dir) if log_dir else None
        self.ready_timeout_s = ready_timeout_s
        self.restart_dead = restart_dead
        self.max_replicas = max_replicas
        self.poll_interval_s = poll_interval_s
        # cross-replica KV migration (docs/FLEET.md): when armed, every
        # respawn/scale-up warms the fresh replica from the deepest-
        # owning healthy sibling via POST /kv/export -> /kv/import.
        # ``owners_fn`` overrides the router scrape (tests / embedded
        # routers); otherwise the ranking comes from GET
        # ``router_url``/fleet -> "kv_owners". STRICTLY best-effort: any
        # failure (donor died mid-export, router down, dense replicas)
        # counts warm_failures and the replica simply starts cold — the
        # watchdog must never wedge on a warmup.
        self.warm_from_siblings = warm_from_siblings
        self.router_url = router_url.rstrip("/") if router_url else None
        self.warm_budget_bytes = int(warm_budget_bytes)
        self._owners_fn = owners_fn
        self._warmed = 0
        self._warm_failures = 0
        # one lock for the whole table: watchdog/actuator/router threads
        # all touch it (docs/FLEET.md thread contract)
        self._lock = threading.Lock()
        self._replicas: dict[str, Replica] = {}
        self._next_id = 0
        self._desired = 0
        self._restarts_total = 0
        self._scale_ups = 0
        self._scale_downs = 0
        self._cold_starts: list[float] = []
        self._stopping = False
        self._watchdog: Optional[threading.Thread] = None

    # -- readiness ---------------------------------------------------------

    def _probe_ready(self, url: str, timeout_s: float = 2.0) -> bool:
        try:
            with urllib.request.urlopen(url + "/healthz", timeout=timeout_s) as r:
                return r.status == 200
        except Exception:  # the probe's failure IS the signal
            return False   # (replica not up yet)

    def _wait_ready(self, rep: Replica) -> None:
        deadline = time.monotonic() + self.ready_timeout_s
        while time.monotonic() < deadline:
            if rep.proc is not None and rep.proc.poll() is not None:
                raise RuntimeError(
                    f"replica {rep.rid} exited rc={rep.proc.returncode} "
                    f"before becoming healthy"
                    + (f" (log: {rep.log_path})" if rep.log_path else "")
                )
            if self._probe_ready(rep.url):
                now = time.time()
                with self._lock:
                    rep.ready_at = now
                    rep.state = READY
                    cs = rep.cold_start_s()
                    if cs is not None:
                        self._cold_starts.append(cs)
                return
            time.sleep(0.1)
        raise RuntimeError(
            f"replica {rep.rid} not healthy within {self.ready_timeout_s}s"
            + (f" (log: {rep.log_path})" if rep.log_path else "")
        )

    # -- spawn / reap ------------------------------------------------------

    def _spawn(self, rep: Replica) -> None:
        argv, env = self.replica_cmd(rep.port, rep.rid)
        if self.log_dir is not None:
            self.log_dir.mkdir(parents=True, exist_ok=True)
            rep.log_path = self.log_dir / f"{rep.rid}.log"
            log_fh = rep.log_path.open("ab")
        else:
            log_fh = open(os.devnull, "wb")
        try:
            rep.proc = subprocess.Popen(
                argv, stdout=log_fh, stderr=subprocess.STDOUT, env=env,
                start_new_session=True,
            )
        finally:
            # the child inherited the descriptor; the parent's copy must
            # not leak one fd per spawn across a long autoscaled run
            log_fh.close()
        rep.spawned_at = time.time()
        rep.ready_at = None
        rep.state = STARTING

    def add_replica(self, wait_ready: bool = True) -> Replica:
        """Spawn one replica (the scale-up step). Blocks until healthy
        unless ``wait_ready=False``; the spawn->healthy wall is the
        cold-start sample the fleet row/report surfaces."""
        with self._lock:
            if self._stopping:
                raise RuntimeError("supervisor is stopping")
            if len(self._live()) >= self.max_replicas:
                raise RuntimeError(
                    f"fleet is at max_replicas={self.max_replicas}"
                )
            rid = f"r{self._next_id}"
            self._next_id += 1
            port = free_port(self.host)
            rep = Replica(rid=rid, port=port,
                          url=f"http://{self.host}:{port}")
            self._replicas[rid] = rep
            self._desired += 1
        self._spawn(rep)
        if wait_ready:
            try:
                self._wait_ready(rep)
            except Exception:
                self._reap(rep, deliberate=True)
                with self._lock:
                    self._desired -= 1
                raise
            self._warm_replica(rep)
        return rep

    def _live(self) -> list[Replica]:
        # caller holds the lock
        return [r for r in self._replicas.values()
                if r.state in (STARTING, READY)]

    def _reap(self, rep: Replica, deliberate: bool) -> None:
        if deliberate:
            # mark BEFORE the kill: a watchdog tick landing between the
            # signal and a late state write would read the death as
            # organic and resurrect a deliberate scale-down
            with self._lock:
                rep.state = REMOVED
        proc = rep.proc
        if proc is not None and proc.poll() is None:
            try:
                # the replica runs in its own session (process group):
                # signal the group so an engine's worker threads can't
                # orphan a wedged child
                os.killpg(proc.pid, signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                pass
            try:
                proc.wait(timeout=10.0)
            except subprocess.TimeoutExpired:
                try:
                    os.killpg(proc.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
                proc.wait(timeout=5.0)
        with self._lock:
            rep.state = REMOVED if deliberate else DEAD

    def remove_replica(self, rid: Optional[str] = None) -> Optional[str]:
        """Graceful scale-down of one replica (the newest by default —
        LIFO keeps the warmed-longest replicas serving). The router's
        scoreboard drops it on its next sync; in-flight requests on it
        drain through the server's own stop path."""
        with self._lock:
            live = self._live()
            if not live:
                return None
            # numeric rid order, NOT lexicographic: past r9 a string sort
            # would pick 'r9' over 'r12' and evict a warmed-old replica
            rep = (self._replicas.get(rid) if rid
                   else sorted(live, key=lambda r: int(r.rid[1:]))[-1])
            if rep is None or rep.state not in (STARTING, READY):
                return None
            self._desired = max(self._desired - 1, 0)
        self._reap(rep, deliberate=True)
        return rep.rid

    def kill_replica(self, rid: str) -> bool:
        """SIGKILL one replica — the chaos injection (``replica-kill``).
        NOT deliberate: desired count is unchanged and the watchdog (if
        armed) respawns it, which is exactly the self-healing the MTTR
        row measures."""
        with self._lock:
            rep = self._replicas.get(rid)
            if rep is None or rep.proc is None or rep.state not in (
                    STARTING, READY):
                return False
        try:
            os.killpg(rep.proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            return False
        rep.proc.wait(timeout=5.0)
        # state stays STARTING/READY on purpose: the watchdog is the one
        # discoverer of deaths — it marks DEAD and respawns, exactly as
        # it would for an organic crash (one code path, one MTTR)
        return True

    # -- scaling -----------------------------------------------------------

    def scale_to(self, n: int) -> int:
        """Bring the live count to ``n`` (the actuator's one verb).
        Scale-ups block until each new replica is healthy so the
        controller's next poll sees real capacity, not pending spawns."""
        n = max(0, min(n, self.max_replicas))
        while True:
            with self._lock:
                live = len(self._live())
            if live == n:
                return n
            if live < n:
                self.add_replica(wait_ready=True)
                with self._lock:
                    self._scale_ups += 1
            else:
                if self.remove_replica() is None:
                    return live
                with self._lock:
                    self._scale_downs += 1

    def start(self, n: int) -> None:
        """Initial spawn + watchdog arm."""
        self.scale_to(n)
        if self.restart_dead and self._watchdog is None:
            self._watchdog = threading.Thread(
                target=self._watch, name="fleet-watchdog", daemon=True
            )
            self._watchdog.start()

    def _watch(self) -> None:
        while True:
            with self._lock:
                if self._stopping:
                    return
                dead = [r for r in self._replicas.values()
                        if r.state in (STARTING, READY)
                        and r.proc is not None and r.proc.poll() is not None]
                for r in dead:
                    r.state = DEAD
            for r in dead:
                try:
                    self._respawn(r)
                except Exception as e:  # noqa: BLE001 — a failed respawn
                    # must not kill the watchdog; the replica stays dead
                    # and the router routes around it (the next tick
                    # retries nothing: restarts are one-shot per death)
                    with self._lock:
                        stopping = self._stopping
                    if not stopping:  # a respawn losing the race against
                        # stop() is teardown, not a failure worth noise
                        print(f"fleet: respawn of {r.rid} failed: {e}",
                              file=sys.stderr)
            time.sleep(self.poll_interval_s)

    def _respawn(self, rep: Replica) -> None:
        """Respawn an unexpectedly-dead replica on its rid/port (the
        self-healing step the replica-kill MTTR row measures)."""
        with self._lock:
            if self._stopping or rep.state != DEAD:
                return
            rep.restarts += 1
            self._restarts_total += 1
        self._spawn(rep)
        self._wait_ready(rep)
        self._warm_replica(rep)

    # -- cross-replica KV migration (docs/FLEET.md) ------------------------

    def _post_json(self, url: str, body: dict[str, Any],
                   timeout_s: float = 30.0) -> dict[str, Any]:
        req = urllib.request.Request(
            url, data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"}, method="POST",
        )
        with urllib.request.urlopen(req, timeout=timeout_s) as r:
            return json.loads(r.read().decode())

    def _owners(self) -> dict[str, int]:
        if self._owners_fn is not None:
            return dict(self._owners_fn() or {})
        if not self.router_url:
            return {}
        with urllib.request.urlopen(
            self.router_url + "/fleet", timeout=5.0
        ) as r:
            doc = json.loads(r.read().decode())
        return dict(doc.get("kv_owners") or {})

    def _warm_replica(self, rep: Replica) -> bool:
        """Warm a freshly-(re)spawned replica's prefix cache from the
        deepest-owning healthy sibling: GET the router's donor ranking,
        POST the donor's /kv/export (bounded byte budget), POST the
        payload into the target's /kv/import. Pure HTTP, pure
        best-effort: every failure path logs, counts warm_failures, and
        returns False — a dead donor mid-export degrades to a cold spawn
        without wedging the watchdog."""
        if not self.warm_from_siblings:
            return False
        try:
            owners = self._owners()
            with self._lock:
                candidates = [
                    (r.rid, r.url, r.state == READY
                     and r.proc is not None and r.proc.poll() is None)
                    for r in self._replicas.values()
                ]
            donor = select_donor(owners, candidates, exclude=rep.rid)
            if donor is None:
                return False
            payload = self._post_json(
                donor[1] + "/kv/export",
                {"budget_bytes": self.warm_budget_bytes},
            )
            if not payload.get("blocks"):
                return False
            res = self._post_json(rep.url + "/kv/import", payload)
            with self._lock:
                self._warmed += 1
            print(
                f"fleet: warmed {rep.rid} from {donor[0]}: "
                f"{res.get('imported', 0)} blocks, "
                f"{res.get('bytes', 0)} bytes", file=sys.stderr,
            )
            return True
        except Exception as e:  # noqa: BLE001 — warmup must never wedge
            # the watchdog or fail a respawn; cold spawn is the fallback
            with self._lock:
                self._warm_failures += 1
            print(f"fleet: warm of {rep.rid} failed (cold spawn): {e}",
                  file=sys.stderr)
            return False

    # -- introspection -----------------------------------------------------

    def replicas(self) -> list[dict[str, Any]]:
        with self._lock:
            return [r.view() for r in self._replicas.values()
                    if r.state != REMOVED]

    def live_urls(self) -> list[tuple[str, str]]:
        """(rid, url) of replicas worth routing to — the router's
        scoreboard syncs from this every tick (pull model: no
        cross-thread pushes into the event loop)."""
        with self._lock:
            return [(r.rid, r.url) for r in self._replicas.values()
                    if r.state in (STARTING, READY)]

    def counters(self) -> dict[str, Any]:
        with self._lock:
            return {
                "desired": self._desired,
                "live": len(self._live()),
                "restarts": self._restarts_total,
                "scale_ups": self._scale_ups,
                "scale_downs": self._scale_downs,
                "last_cold_start_s": (
                    self._cold_starts[-1] if self._cold_starts else None
                ),
                "cold_starts_s": list(self._cold_starts),
                "warmed": self._warmed,
                "warm_failures": self._warm_failures,
            }

    def stop(self) -> None:
        with self._lock:
            self._stopping = True
            reps = list(self._replicas.values())
        for rep in reps:
            if rep.state in (STARTING, READY, DEAD):
                self._reap(rep, deliberate=True)
        if self._watchdog is not None:
            self._watchdog.join(timeout=5.0)
        # second reap pass AFTER the watchdog is gone: a _respawn that
        # passed its _stopping check just before stop() set the flag may
        # have spawned a fresh process (own session — it would outlive
        # us) after the first pass reaped only the old dead pid
        with self._lock:
            reps = list(self._replicas.values())
        for rep in reps:
            if rep.proc is not None and rep.proc.poll() is None:
                self._reap(rep, deliberate=True)

    def __enter__(self) -> "FleetSupervisor":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.stop()


def mock_replica_cmd(
    repo_root: Optional[Path] = None,
    token_delay_s: float = 0.002,
    n_tokens: int = 8,
    metrics: Optional[dict[str, float]] = None,
) -> Callable[[int, str], tuple[list[str], dict[str, str]]]:
    """Replica factory for JAX-free fleets: one ``tests/mock_server.py``
    CLI process per port (the multi-instance satellite). Used by the
    fleet tests and the chaos smoke — a real HTTP socket per replica,
    kill-able, no engine behind it."""
    root = str(repo_root or Path(__file__).resolve().parents[2])

    def cmd(port: int, rid: str) -> tuple[list[str], dict[str, str]]:
        argv = [
            sys.executable, "-m", "tests.mock_server",
            "--port", str(port), "--server-id", rid,
            "--token-delay", str(token_delay_s),
            "--n-tokens", str(n_tokens),
        ]
        if metrics:
            argv += ["--metrics-json", json.dumps(metrics)]
        env = dict(os.environ)
        env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
        return argv, env

    return cmd
