"""Cache-aware fleet router: one HTTP front over N serving replicas.

Placement (docs/FLEET.md) scores every healthy replica with

    score = prefix_affinity(prompt, replica) / len(prompt)
            - load_weight * (est_wait_s + inflight * inflight_cost_s)

where ``prefix_affinity`` is the deepest chunk-hash chain the router has
seen that replica serve (``PrefixIndex`` — the router-side mirror of the
engines' prefix caches: route a prompt to the replica whose retained KV
already holds its longest prefix), ``est_wait_s`` is each replica's own
admission burn-rate estimate scraped from ``/metrics``
(``kvmini_tpu_estimated_wait_seconds`` — the same signal the door-level
deadline shed uses, promoted to fleet-level placement), and ``inflight``
is the router's instant count of requests it has proxied there (feedback
between scrapes). Session affinity (the OpenAI ``user`` field or an
``x-session-id`` header) pins a session to its replica while that
replica's load stays reasonable.

Fleet-level admission: a per-replica 429/503/connect failure re-places
the request on the next-best replica BEFORE the client sees anything;
only when every candidate sheds does the router answer 429 itself, with
the PR-10 ``Retry-After`` contract. A replica that dies mid-stream
cannot hang its clients: bytes-not-yet-sent requests re-place onto
survivors, mid-stream ones get one honest terminal SSE error event.

``/metrics`` aggregates: the router's own ``kvmini_tpu_fleet_*`` series
plus every replica's last scrape re-labeled ``{replica="rN"}`` —
``analysis/telemetry.parse_prometheus_text`` sums duplicate labeled
series, so every existing post-hoc consumer reads fleet totals with no
changes, and per-replica views stay one PromQL ``by (replica)`` away.
"""

from __future__ import annotations

import asyncio
import json
import queue
import threading
import time
import zlib
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Any, Optional

from kserve_vllm_mini_tpu.analysis.telemetry import parse_prometheus_text
from kserve_vllm_mini_tpu.runtime.tracing import (
    ROUTER_SCOPE,
    SpanRecorder,
    new_span_id,
    new_trace_id,
    parse_traceparent,
)

# replica /metrics series the scoreboard folds into placement state
_WAIT_METRIC = "kvmini_tpu_estimated_wait_seconds"
_QUEUE_METRIC = "kvmini_tpu_queue_depth"
_SLOTS_METRIC = "kvmini_tpu_active_slots"

PLACEMENT_REASONS = ("affinity", "prefix", "load", "round_robin")

# ratio/percentile gauges whose per-replica values must NOT be summed:
# the flat scrape parser adds duplicate labeled series (correct for
# counters and level gauges — fleet totals), but 3 replicas at 0.8 duty
# are not 2.4 duty. These are stripped from the per-replica passthrough
# and re-emitted ONCE as the mean over healthy replicas; per-replica
# duty stays derivable from rate(busy_seconds_total{replica=...}).
MEAN_GAUGES = frozenset({
    "kvmini_tpu_duty_cycle",
    "kvmini_tpu_spec_accept_ratio",
    "kvmini_tpu_kv_occupancy",
    "kvmini_tpu_kv_retained_fraction",
    "kvmini_tpu_kv_fragmentation",
    "kvmini_tpu_kv_prefix_hit_depth_p50",
    "kvmini_tpu_kv_prefix_hit_depth_p95",
    "kvmini_tpu_estimated_wait_seconds",
    # live-economics per-token rates (docs/ECONOMICS.md) are ratios: 3
    # replicas each at $0.02/1K-tok are a $0.02/1K-tok fleet, not $0.06.
    # The level gauges (econ_usd_per_hour, econ_tokens_per_sec) stay on
    # the summing passthrough — their label-sum IS the fleet total.
    "kvmini_tpu_econ_usd_per_1k_tokens",
    "kvmini_tpu_econ_wh_per_1k_tokens",
})


@dataclass
class RouterConfig:
    policy: str = "cache_aware"        # "cache_aware" | "round_robin"
    scrape_interval_s: float = 0.5
    scrape_timeout_s: float = 0.4
    unhealthy_after: int = 3           # consecutive scrape failures
    prefix_chunk_chars: int = 128
    prefix_index_entries: int = 8192
    session_entries: int = 4096
    load_weight: float = 0.2
    inflight_cost_s: float = 0.05
    affinity_max_wait_s: float = 5.0   # affinity breaks past this load
    read_timeout_s: float = 120.0      # upstream silence -> failover
    connect_timeout_s: float = 2.0
    trace_capacity: int = 4096         # router span ring (GET /traces)
    decision_capacity: int = 1024      # audit ring (GET /fleet/decisions)

    def __post_init__(self) -> None:
        if self.policy not in ("cache_aware", "round_robin"):
            raise ValueError(
                f"unknown fleet policy {self.policy!r}; known: "
                "cache_aware, round_robin"
            )


class PrefixIndex:
    """Chunk-hash chain -> replica affinity, bounded LRU.

    The prompt is cut into fixed-size character chunks and hashed as a
    CHAIN (crc32 folded left-to-right), so the hash at depth *i* names
    the exact (i+1)-chunk prefix. Recording a served prompt writes every
    depth; matching a new prompt walks its own chain and, per replica,
    keeps the DEEPEST depth that replica owns — the router-side estimate
    of how many leading characters that replica's prefix cache can
    reuse. Character-level on purpose: the router has no tokenizer, and
    the engines' caches match token prefixes that character prefixes
    conservatively under-approximate."""

    def __init__(self, chunk_chars: int = 128, max_entries: int = 8192) -> None:
        self.chunk_chars = max(int(chunk_chars), 1)
        self.max_entries = max(int(max_entries), 1)
        self._map: OrderedDict[int, str] = OrderedDict()
        # entry-hash -> chain depth (1-based chunk index): lets owners()
        # rank replicas by DEEPEST owned prefix without re-hashing any
        # prompt — the donor-selection input for cross-replica KV
        # migration (fleet/supervisor._warm_replica)
        self._depth: dict[int, int] = {}

    def _chain(self, prompt: str) -> list[int]:
        out: list[int] = []
        h = 0
        for i in range(0, len(prompt), self.chunk_chars):
            piece = prompt[i:i + self.chunk_chars]
            if len(piece) < self.chunk_chars:
                break  # only full chunks index — tails rarely repeat
            h = zlib.crc32(piece.encode("utf-8", "surrogatepass"), h)
            out.append(h)
        return out

    def record(self, prompt: str, rid: str) -> None:
        for depth, h in enumerate(self._chain(prompt), start=1):
            if h in self._map:
                self._map.move_to_end(h)
            # every access (record/best/len) runs on the router's ONE
            # event loop; there is no second thread
            self._map[h] = rid
            self._depth[h] = depth
        while len(self._map) > self.max_entries:
            old, _ = self._map.popitem(last=False)
            self._depth.pop(old, None)

    def best(self, prompt: str) -> dict[str, int]:
        """replica id -> matched prefix CHARS (deepest owned depth)."""
        out: dict[str, int] = {}
        for depth, h in enumerate(self._chain(prompt), start=1):
            rid = self._map.get(h)
            if rid is not None:
                out[rid] = depth * self.chunk_chars
        return out

    def owners(self) -> dict[str, int]:
        """replica id -> deepest owned prefix in CHARS. The donor
        ranking for cross-replica KV migration: the supervisor warms a
        respawned replica from the deepest-owning HEALTHY sibling
        (health is the supervisor's call — the index only knows
        ownership)."""
        out: dict[str, int] = {}
        for h, rid in self._map.items():
            chars = self._depth.get(h, 1) * self.chunk_chars
            if chars > out.get(rid, 0):
                out[rid] = chars
        return out

    def purge(self, rid: str) -> None:
        """Forget a replica's affinity — called when it dies: a
        watchdog respawn reuses the rid with a COLD cache, and stale
        chains would route 'prefix'-scored traffic at an empty cache."""
        for h in [h for h, r in self._map.items() if r == rid]:
            del self._map[h]
            self._depth.pop(h, None)

    def __len__(self) -> int:
        return len(self._map)


@dataclass
class ReplicaView:
    """The router's live picture of one replica (event-loop-owned)."""

    rid: str
    url: str
    healthy: bool = True
    est_wait_s: float = 0.0
    queue_depth: float = 0.0
    active_slots: float = 0.0
    inflight: int = 0              # router-side proxied-and-unfinished
    scrape_failures: int = 0
    seen_healthy: bool = False
    metrics_text: str = ""         # last raw exposition, for aggregation
    metrics_map: dict[str, float] = field(default_factory=dict)
    last_scrape_t: float = 0.0

    def view(self) -> dict[str, Any]:
        return {
            "rid": self.rid, "url": self.url, "healthy": self.healthy,
            "est_wait_s": round(self.est_wait_s, 4),
            "queue_depth": self.queue_depth,
            "inflight": self.inflight,
        }


def relabel_exposition(text: str, rid: str, type_seen: set[str],
                       skip: frozenset[str] = frozenset()) -> list[str]:
    """Re-emit one replica's Prometheus exposition with a
    ``replica="<rid>"`` label on every sample line. ``# TYPE`` comments
    are kept once per metric family across the whole aggregation
    (``type_seen`` is shared by the caller). Names in ``skip`` are
    dropped entirely — the caller re-emits those as fleet-level means
    (MEAN_GAUGES: ratios must not label-sum)."""
    out: list[str] = []
    for line in text.splitlines():
        line = line.rstrip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 3 and parts[1] == "TYPE":
                if parts[2] in type_seen or parts[2] in skip:
                    continue
                type_seen.add(parts[2])
                out.append(line)
            continue
        if "{" in line and "}" in line:
            head, rest = line.split("{", 1)
            if head in skip:
                continue
            labels, tail = rest.rsplit("}", 1)
            out.append(f'{head}{{{labels},replica="{rid}"}}{tail}')
        else:
            parts = line.split(None, 1)
            if len(parts) == 2:
                if parts[0] in skip:
                    continue
                out.append(f'{parts[0]}{{replica="{rid}"}} {parts[1]}')
    return out


class FleetRouter:
    """The routing state machine + aiohttp app.

    Replicas come from a ``FleetSupervisor`` (live fleets: the
    scoreboard re-syncs the set every tick, so actuator scale-ups and
    watchdog respawns appear without any cross-thread push) or a static
    ``replicas=[(rid, url), ...]`` list (tests, external fleets). All
    mutable routing state lives on the event loop; the only cross-thread
    reads go through the supervisor's own lock."""

    def __init__(
        self,
        supervisor: Any = None,
        replicas: Optional[list[tuple[str, str]]] = None,
        cfg: Optional[RouterConfig] = None,
        allow_fault_injection: bool = False,
    ) -> None:
        if supervisor is None and not replicas:
            raise ValueError("need a supervisor or a static replica list")
        self.supervisor = supervisor
        self.cfg = cfg or RouterConfig()
        self.allow_fault_injection = allow_fault_injection
        self._static = list(replicas or [])
        self._views: dict[str, ReplicaView] = {}
        self._prefix = PrefixIndex(self.cfg.prefix_chunk_chars,
                                   self.cfg.prefix_index_entries)
        self._sessions: OrderedDict[str, str] = OrderedDict()
        self._rr = 0
        self.placements: dict[str, int] = {r: 0 for r in PLACEMENT_REASONS}
        self.reroutes = 0
        self.sheds = 0
        self.stream_errors = 0
        # router span ring (GET /traces): same bounded/lock-free-by-
        # contract recorder the engine uses; all writes happen on the one
        # event loop, /traces renders from snapshot()
        self.tracer = SpanRecorder(capacity=self.cfg.trace_capacity)
        # routing decision audit ring (GET /fleet/decisions): per-decision
        # explain — every candidate's score terms and why the winner won.
        # Bounded deque, event-loop-owned like every other routing state.
        self._decisions: "deque[dict[str, Any]]" = deque(
            maxlen=max(int(self.cfg.decision_capacity), 1)
        )
        self.decisions_dropped = 0
        self._decision_seq = 0
        self.route_seconds_total = 0.0    # cumulative fleet.route wall
        self._client: Any = None          # aiohttp.ClientSession
        self._scoreboard_task: Any = None
        self._started = time.time()

    def _audit(self, entry: dict[str, Any]) -> None:
        """Append one decision-audit entry. All writers and the
        /fleet/decisions reader run on the router's one event loop."""
        self._decision_seq += 1
        if len(self._decisions) == self._decisions.maxlen:
            self.decisions_dropped += 1
        self._decisions.append(
            {"seq": self._decision_seq, "t": time.time(), **entry}
        )

    # -- replica set + scoreboard -----------------------------------------

    def _sync_replicas(self) -> None:
        pairs = (self.supervisor.live_urls() if self.supervisor is not None
                 else self._static)
        want = dict(pairs)
        for rid, url in want.items():
            if rid not in self._views:
                # all router state (views, counters, prefix index,
                # sessions) is mutated ONLY on the one event loop
                # (handlers + scoreboard task); the only cross-thread
                # traffic goes through the supervisor's lock
                self._views[rid] = ReplicaView(rid=rid, url=url)
        for rid in [r for r in self._views if r not in want]:
            del self._views[rid]

    async def _scrape_one(self, r: ReplicaView) -> None:
        import aiohttp

        try:
            timeout = aiohttp.ClientTimeout(total=self.cfg.scrape_timeout_s)
            async with self._client.get(r.url + "/metrics",
                                        timeout=timeout) as resp:
                text = await resp.text()
            if resp.status != 200:
                raise RuntimeError(f"/metrics HTTP {resp.status}")
        except Exception:  # noqa: BLE001 — scrape failures ARE the
            # health signal: K consecutive ones mark the replica
            # unhealthy and placement routes around it
            r.scrape_failures += 1
            if r.scrape_failures >= self.cfg.unhealthy_after:
                self._mark_unhealthy(r)
            return
        m = parse_prometheus_text(text)
        r.metrics_text = text
        r.metrics_map = m
        r.est_wait_s = m.get(_WAIT_METRIC, 0.0)
        r.queue_depth = m.get(_QUEUE_METRIC, 0.0)
        r.active_slots = m.get(_SLOTS_METRIC, 0.0)
        r.scrape_failures = 0
        r.healthy = True
        r.seen_healthy = True
        r.last_scrape_t = time.time()

    async def _scoreboard(self) -> None:
        while True:
            self._sync_replicas()
            # the scoreboard task runs on the SAME event loop as every
            # handler (see _sync_replicas) — no second thread exists
            views = list(
                self._views.values()
            )
            if views:
                await asyncio.gather(*(self._scrape_one(r) for r in views))
            await asyncio.sleep(self.cfg.scrape_interval_s)

    async def refresh(self) -> None:
        """One synchronous scoreboard pass (tests; the background task
        does this every ``scrape_interval_s``)."""
        self._sync_replicas()
        views = list(self._views.values())
        if views:
            await asyncio.gather(*(self._scrape_one(r) for r in views))

    def _mark_unhealthy(self, r: ReplicaView) -> None:
        """Health flip + affinity invalidation in one place: a dead (or
        soon-respawned-cold) replica must not keep its prefix chains or
        pinned sessions — they would score a cold cache as warm."""
        if r.healthy:
            self._prefix.purge(r.rid)
            # event-loop-only state: all writers and readers live on
            # the router's one loop
            for s in [s for s, rid in self._sessions.items()
                      if rid == r.rid]:
                del self._sessions[s]
            # health flips land in the audit ring too: "why did traffic
            # leave r0 at t?" is answerable from /fleet/decisions alone
            self._audit({"type": "health", "rid": r.rid,
                         "healthy": False,
                         "scrape_failures": r.scrape_failures})
        r.healthy = False

    # -- placement ---------------------------------------------------------

    def _load(self, r: ReplicaView) -> float:
        return r.est_wait_s + r.inflight * self.cfg.inflight_cost_s

    def place(
        self, prompt: str, session: Optional[str],
        exclude: Optional[set[str]] = None,
        trace_id: Optional[str] = None,
    ) -> tuple[Optional[ReplicaView], str]:
        """Pick a replica for this prompt; returns (view, reason) or
        (None, "") when no healthy candidate remains. Every call lands
        one explain entry in the decision audit ring: all candidates'
        score terms plus why the winner won (GET /fleet/decisions)."""
        exclude = exclude or set()
        cands = sorted(
            (r for r in self._views.values()
             if r.healthy and r.rid not in exclude),
            key=lambda r: r.rid,
        )
        hits = self._prefix.best(prompt)
        plen = max(len(prompt), 1)
        scores = [
            (min(hits.get(r.rid, 0), plen) / plen
             - self.cfg.load_weight * self._load(r))
            for r in cands
        ]
        decision: dict[str, Any] = {
            "type": "placement",
            "trace_id": trace_id,
            "policy": self.cfg.policy,
            "prompt_chars": len(prompt),
            "session": session,
            "exclude": sorted(exclude),
            "candidates": [
                {
                    "rid": r.rid,
                    "score": round(score, 6),
                    "matched_prefix_chars": min(hits.get(r.rid, 0), plen),
                    "estimated_wait_s": round(r.est_wait_s, 4),
                    "inflight": r.inflight,
                }
                for r, score in zip(cands, scores)
            ],
        }

        def _decide(chosen: Optional[ReplicaView], reason: str
                    ) -> tuple[Optional[ReplicaView], str]:
            decision["chosen"] = chosen.rid if chosen is not None else None
            decision["reason"] = reason or "no_candidate"
            self._audit(decision)
            return chosen, reason

        if not cands:
            return _decide(None, "")
        if self.cfg.policy == "round_robin":
            self._rr += 1
            return _decide(cands[self._rr % len(cands)], "round_robin")
        if session:
            rid = self._sessions.get(session)
            if rid is not None:
                pinned = next((r for r in cands if r.rid == rid), None)
                if (pinned is not None
                        and self._load(pinned) <= self.cfg.affinity_max_wait_s):
                    return _decide(pinned, "affinity")
        best: Optional[ReplicaView] = None
        best_score = 0.0
        for r, score in zip(cands, scores):
            if best is None or score > best_score:
                best, best_score = r, score
        assert best is not None
        return _decide(best, "prefix" if hits.get(best.rid) else "load")

    def _record_success(self, prompt: str, session: Optional[str],
                        rid: str) -> None:
        self._prefix.record(prompt, rid)
        if session:
            if session in self._sessions:
                self._sessions.move_to_end(session)
            self._sessions[session] = rid
            while len(self._sessions) > self.cfg.session_entries:
                self._sessions.popitem(last=False)

    def _retry_after_s(self, hints: list[float]) -> int:
        waits = [r.est_wait_s for r in self._views.values() if r.healthy]
        base = min(waits) if waits else 1.0
        return max(1, int(max(hints + [base]) + 0.999))

    # -- aiohttp app -------------------------------------------------------

    def make_app(self):
        from aiohttp import web

        async def on_startup(_app) -> None:
            import aiohttp

            # written once at app startup on the event loop, read by
            # handlers on the same loop
            self._client = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(
                    total=None,
                    sock_connect=self.cfg.connect_timeout_s,
                    sock_read=self.cfg.read_timeout_s,
                ),
            )
            self._sync_replicas()
            await self.refresh()
            self._scoreboard_task = asyncio.create_task(self._scoreboard())

        async def on_cleanup(_app) -> None:
            if self._scoreboard_task is not None:
                self._scoreboard_task.cancel()
                try:
                    await self._scoreboard_task
                except asyncio.CancelledError:
                    pass
            if self._client is not None:
                await self._client.close()

        def _shed_response(message: str, hints: list[float]) -> "web.Response":
            # the PR-10 shed wire shape, promoted to fleet level: same
            # code, same Retry-After contract the loadgen retry honors
            self.sheds += 1
            return web.json_response(
                {"error": {"message": message, "type": "overloaded_error",
                           "code": "request_shed"}},
                status=429,
                headers={"Retry-After": str(self._retry_after_s(hints))},
            )

        def _prompt_of(body: dict[str, Any]) -> str:
            msgs = body.get("messages")
            if not isinstance(msgs, list):
                return ""
            return "\n".join(
                f"{m.get('role', 'user')}: {m.get('content', '')}"
                for m in msgs if isinstance(m, dict)
            )

        async def chat(request: "web.Request"):
            raw = await request.read()
            try:
                body = json.loads(raw)
                if not isinstance(body, dict):
                    raise ValueError
            except ValueError:
                return web.json_response(
                    {"error": {"message": "invalid JSON body"}}, status=400
                )
            prompt = _prompt_of(body)
            session = body.get("user") or request.headers.get("x-session-id")
            streaming = bool(body.get("stream", False))
            fwd_headers = {"Content-Type": "application/json"}
            for h in ("x-request-deadline-ms",):
                if h in request.headers:
                    fwd_headers[h] = request.headers[h]
            # the router is a span-producing intermediate: the fleet.route
            # span parents under the client's http.request span (incoming
            # traceparent); each attempt gets a fleet.proxy child whose
            # PRE-MINTED span id is rewritten into the outgoing
            # traceparent, so replica server.* spans parent under the
            # attempt that actually served them (docs/TRACING.md)
            ctx = parse_traceparent(request.headers.get("traceparent"))
            if ctx is not None:
                trace_id, client_span_id = ctx
            else:
                # traceless client: the router becomes the trace root so
                # the fleet lane still joins the replica leg by trace_id
                trace_id, client_span_id = new_trace_id(), None
            route_span_id = new_span_id()
            route_start_ns = time.time_ns()
            attempts = 0
            last_place: dict[str, Any] = {}

            def _finish_route(ok: bool, outcome: str) -> None:
                end_ns = time.time_ns()
                self.route_seconds_total += (end_ns - route_start_ns) / 1e9
                self.tracer.record(
                    "fleet.route", trace_id, route_start_ns, end_ns,
                    parent_span_id=client_span_id, ok=ok,
                    attrs={
                        "outcome": outcome,
                        "candidates": last_place.get("candidates", 0),
                        "replica": last_place.get("rid", ""),
                        "reason": last_place.get("reason", ""),
                        "matched_prefix_chars":
                            last_place.get("matched_prefix_chars", 0),
                        "estimated_wait_s":
                            last_place.get("estimated_wait_s", 0.0),
                        "inflight": last_place.get("inflight", 0),
                        "affinity_hit":
                            last_place.get("reason") == "affinity",
                        "reroutes": max(attempts - 1, 0),
                    },
                    kind=2, span_id=route_span_id,
                )

            tried: set[str] = set()
            retry_hints: list[float] = []
            while True:
                r, reason = self.place(prompt, session, exclude=tried,
                                       trace_id=trace_id)
                if r is None:
                    if not any(v.healthy for v in self._views.values()):
                        _finish_route(False, "no_healthy_replica")
                        return web.json_response(
                            {"error": {"message":
                                       "no healthy replica in the fleet"}},
                            status=503,
                        )
                    # honest terminal status: the shed is the route
                    # span's outcome, not a silent absence
                    _finish_route(False, "shed")
                    return _shed_response(
                        "fleet overloaded: every replica shed or failed "
                        "this request", retry_hints,
                    )
                tried.add(r.rid)
                self.placements[reason] = self.placements.get(reason, 0) + 1
                hits = self._prefix.best(prompt)
                last_place = {
                    "rid": r.rid, "reason": reason,
                    "candidates": sum(
                        1 for v in self._views.values()
                        if v.healthy and v.rid not in (tried - {r.rid})
                    ),
                    "matched_prefix_chars":
                        min(hits.get(r.rid, 0), max(len(prompt), 1)),
                    "estimated_wait_s": round(r.est_wait_s, 4),
                    "inflight": r.inflight,
                }
                r.inflight += 1
                attempts += 1
                attempt_sid = new_span_id()
                fwd_headers["traceparent"] = (
                    f"00-{trace_id}-{attempt_sid}-01"
                )
                attempt_start_ns = time.time_ns()
                attempt: dict[str, Any] = {"outcome": "ok", "status": 0}

                def on_success(rid=r.rid) -> None:
                    # recorded ONLY on clean completions (inside
                    # _proxy_once): a stream that died mid-flight must
                    # not re-pin its session to the dead replica
                    self._record_success(prompt, session, rid)

                try:
                    resp = await _proxy_once(request, r, raw, fwd_headers,
                                             streaming, retry_hints,
                                             on_success, attempt)
                finally:
                    r.inflight -= 1
                    self.tracer.record(
                        "fleet.proxy", trace_id, attempt_start_ns,
                        time.time_ns(),
                        parent_span_id=route_span_id,
                        ok=attempt["outcome"] == "ok",
                        attrs={"replica": r.rid, "attempt": attempts,
                               "outcome": attempt["outcome"],
                               "http.status_code": attempt["status"]},
                        kind=3,  # SPAN_KIND_CLIENT: the router calling out
                        span_id=attempt_sid,
                    )
                if resp is None:
                    # per-replica shed/failure absorbed: re-place before
                    # the client sees anything (fleet-level admission)
                    self.reroutes += 1
                    continue
                _finish_route(attempt["outcome"] == "ok",
                              attempt["outcome"])
                return resp

        async def _proxy_once(request, r: ReplicaView, raw: bytes,
                              fwd_headers: dict[str, str], streaming: bool,
                              retry_hints: list[float], on_success,
                              attempt: dict[str, Any]):
            """One attempt against one replica. Returns a prepared
            response to hand the client, or None = absorb and re-place
            (nothing was sent to the client yet). ``attempt`` is filled
            with the honest outcome/status for this attempt's
            ``fleet.proxy`` span (shed, unavailable, connect_fail,
            replica_lost, upstream_error, ok)."""
            import aiohttp
            from aiohttp import web

            # the session is written once at app startup; handlers run
            # on the same event loop — no cross-thread access exists
            client = self._client
            try:
                async with client.post(
                    r.url + "/v1/chat/completions", data=raw,
                    headers=fwd_headers,
                ) as up:
                    attempt["status"] = up.status
                    if up.status == 429:
                        from kserve_vllm_mini_tpu.loadgen.adapters.base import (
                            parse_retry_after,
                        )

                        attempt["outcome"] = "shed"
                        retry_hints.append(
                            parse_retry_after(up.headers.get("Retry-After"))
                        )
                        await up.read()
                        return None
                    if up.status == 503:
                        # dead scheduler / draining replica: route around
                        attempt["outcome"] = "unavailable"
                        await up.read()
                        self._mark_unhealthy(r)
                        return None
                    ctype = up.headers.get("Content-Type", "")
                    if not streaming or "text/event-stream" not in ctype:
                        payload = await up.read()
                        if up.status < 400:
                            on_success()
                        else:
                            attempt["outcome"] = "upstream_error"
                        return web.Response(
                            body=payload, status=up.status,
                            content_type=ctype.split(";")[0] or
                            "application/json",
                            headers={"x-kvmini-replica": r.rid},
                        )
                    # SSE passthrough: once the first byte reaches the
                    # client, failures become honest terminal events,
                    # never silent hangs and never duplicate streams
                    resp = web.StreamResponse(
                        status=200,
                        headers={"Content-Type": "text/event-stream",
                                 "Cache-Control": "no-cache",
                                 "x-kvmini-replica": r.rid},
                    )
                    sent_bytes = False
                    stream_clean = True
                    try:
                        async for chunk in up.content.iter_any():
                            if not sent_bytes:
                                await resp.prepare(request)
                                sent_bytes = True
                            await resp.write(chunk)
                    except (aiohttp.ClientError, asyncio.TimeoutError,
                            OSError) as e:
                        if not sent_bytes:
                            attempt["outcome"] = "replica_lost"
                            self._mark_unhealthy(r)
                            return None  # re-place: client saw nothing
                        stream_clean = False
                        attempt["outcome"] = "replica_lost"
                        self.stream_errors += 1
                        evt = {"error": {
                            "message": (
                                f"replica {r.rid} lost mid-stream "
                                f"({type(e).__name__}); partial output above"
                            ),
                            "type": "server_error",
                            "code": "replica_lost",
                        }}
                        await resp.write(
                            f"data: {json.dumps(evt)}\n\n".encode()
                        )
                    if not sent_bytes:
                        # a zero-chunk upstream stream (drained before the
                        # first byte): still hand the client a well-formed
                        # (empty) SSE response, never an unprepared write
                        await resp.prepare(request)
                    if stream_clean:
                        on_success()
                    await resp.write_eof()
                    return resp
            except (aiohttp.ClientError, asyncio.TimeoutError, OSError):
                # connect refused / reset before any response: the
                # replica is gone or wedged — absorb and re-place
                attempt["outcome"] = "connect_fail"
                self._mark_unhealthy(r)
                return None

        async def models(_request):
            for r in sorted(self._views.values(), key=lambda v: v.rid):
                if not r.healthy:
                    continue
                try:
                    async with self._client.get(r.url + "/v1/models") as up:
                        return web.json_response(await up.json(),
                                                 status=up.status)
                except Exception:  # noqa: BLE001 — next healthy
                    continue       # replica answers instead
            return web.json_response(
                {"error": {"message": "no healthy replica"}}, status=503
            )

        async def healthz(_request):
            live = sum(1 for r in self._views.values() if r.healthy)
            desired = (self.supervisor.counters()["desired"]
                       if self.supervisor is not None else len(self._views))
            if live == 0:
                return web.json_response(
                    {"status": "unhealthy", "replicas_live": 0,
                     "replicas_desired": desired}, status=503,
                )
            return web.json_response({
                "status": "ok" if live >= desired else "degraded",
                "replicas_live": live,
                "replicas_desired": desired,
                "uptime_s": time.time() - self._started,
            })

        async def fleet_get(_request):
            sup = (self.supervisor.counters()
                   if self.supervisor is not None else {})
            return web.json_response({
                "policy": self.cfg.policy,
                "replicas": [r.view() for r in sorted(
                    self._views.values(), key=lambda v: v.rid)],
                "supervisor": sup,
                "placements": dict(self.placements),
                "reroutes": self.reroutes,
                "sheds": self.sheds,
                "stream_errors": self.stream_errors,
                "prefix_index_entries": len(self._prefix),
                # deepest owned prefix chars per replica — the donor
                # ranking cross-replica KV migration reads
                # (fleet/supervisor._warm_replica)
                "kv_owners": self._prefix.owners(),
            })

        async def fleet_scale(request: "web.Request"):
            if self.supervisor is None:
                return web.json_response(
                    {"error": {"message": "static fleet: no supervisor to "
                               "scale"}}, status=409,
                )
            try:
                body = await request.json()
                n = int(body["replicas"])
            except Exception:
                return web.json_response(
                    {"error": {"message": "need {\"replicas\": N}"}},
                    status=400,
                )
            loop = asyncio.get_running_loop()
            # scale_to blocks on replica readiness — run it off the loop
            # so in-flight streams keep pumping through the cold start
            applied = await loop.run_in_executor(
                None, self.supervisor.scale_to, n
            )
            self._sync_replicas()
            # scale actuations share the audit ring with placements:
            # the decision log reads as one causal sequence
            self._audit({"type": "scale", "requested": n,
                         "replicas": applied})
            return web.json_response(
                {"status": "ok", "replicas": applied}
            )

        def _chaos_victim(named: Optional[str]) -> Optional[ReplicaView]:
            healthy = [r for r in self._views.values() if r.healthy]
            if named:
                return next((r for r in healthy if r.rid == named), None)
            if not healthy:
                return None
            # most-disruptive default: the replica carrying the most
            # router-side in-flight work (ties broken by rid)
            return sorted(healthy,
                          key=lambda r: (-r.inflight, r.rid))[0]

        async def fleet_chaos(request: "web.Request"):
            """Replica-level chaos (docs/FLEET.md failover ladder): kill
            one replica's process, wedge one replica's sweep loop, or
            clear wedges. Gated like POST /faults; refuses on a fleet
            with <= 1 healthy replica — an injection that takes out the
            only replica measures an outage, not failover, so the chaos
            row must stay honestly uninjected (the PR-13 handoff-drop
            pattern)."""
            if not self.allow_fault_injection:
                return web.json_response(
                    {"error": {"message":
                               "fault injection is disabled; start the "
                               "router with --allow-fault-injection"}},
                    status=403,
                )
            try:
                body = await request.json()
            except Exception:
                return web.json_response(
                    {"error": {"message": "invalid JSON"}}, status=400
                )
            action = body.get("action")
            if action == "clear":
                cleared = 0
                for r in self._views.values():
                    try:
                        async with self._client.post(
                            r.url + "/faults",
                            json={"action": "clear", "name": "sweep_stall"},
                        ) as up:
                            if up.status == 200:
                                cleared += 1
                    except Exception:  # noqa: BLE001 — a dead
                        continue       # replica has nothing to clear
                return web.json_response({"status": "ok",
                                          "cleared": cleared})
            if action not in ("kill", "wedge"):
                return web.json_response(
                    {"error": {"message":
                               "need action 'kill'|'wedge'|'clear'"}},
                    status=400,
                )
            healthy = sum(1 for r in self._views.values() if r.healthy)
            if healthy <= 1:
                return web.json_response(
                    {"error": {"message":
                               f"refusing {action}: fleet has {healthy} "
                               "healthy replica(s) — replica chaos needs "
                               "survivors to fail over to"}}, status=409,
                )
            victim = _chaos_victim(body.get("replica"))
            if victim is None:
                return web.json_response(
                    {"error": {"message": "no such healthy replica"}},
                    status=404,
                )
            if action == "kill":
                if self.supervisor is None:
                    return web.json_response(
                        {"error": {"message": "static fleet: no "
                                   "supervisor owns the processes"}},
                        status=409,
                    )
                loop = asyncio.get_running_loop()
                ok = await loop.run_in_executor(
                    None, self.supervisor.kill_replica, victim.rid
                )
                if not ok:
                    return web.json_response(
                        {"error": {"message":
                                   f"kill of {victim.rid} failed"}},
                        status=500,
                    )
                self._mark_unhealthy(victim)
                return web.json_response({"status": "ok", "killed":
                                          victim.rid})
            # wedge: arm sweep_stall on the victim through ITS /faults
            params = {"action": "arm", "name": "sweep_stall", "times": 0,
                      "duration": float(body.get("duration", 0.4))}
            try:
                async with self._client.post(victim.url + "/faults",
                                             json=params) as up:
                    detail = await up.text()
                    if up.status != 200:
                        return web.json_response(
                            {"error": {"message":
                                       f"replica {victim.rid} refused the "
                                       f"wedge: HTTP {up.status} "
                                       f"{detail[:200]}"}},
                            status=502,
                        )
            except Exception as e:  # noqa: BLE001 — surfaced to the caller
                return web.json_response(
                    {"error": {"message":
                               f"wedge of {victim.rid} failed: "
                               f"{type(e).__name__}: {e}"}}, status=502,
                )
            return web.json_response({"status": "ok", "wedged": victim.rid})

        async def traces(_request):
            # snapshot pattern: to_otlp copies the deque once at C level
            # and renders off-ring — a slow /traces reader never blocks
            # the proxy event loop's span appends
            return web.json_response(
                self.tracer.to_otlp(service_name="kvmini-tpu-router",
                                    scope=ROUTER_SCOPE)
            )

        async def fleet_decisions(_request):
            # list(deque) is one C-level copy; handlers and the audit
            # writer share the one event loop anyway
            return web.json_response({
                "decisions": list(self._decisions),
                "dropped": self.decisions_dropped,
                "capacity": self._decisions.maxlen,
            })

        async def metrics(_request):
            views = sorted(self._views.values(), key=lambda v: v.rid)
            live = sum(1 for r in views if r.healthy)
            sup = (self.supervisor.counters()
                   if self.supervisor is not None else None)
            desired = sup["desired"] if sup else len(views)
            s = {
                "fleet_replicas_desired": desired,
                "fleet_replicas_live": live,
                "fleet_reroutes": self.reroutes,
                "fleet_sheds": self.sheds,
                "fleet_stream_errors": self.stream_errors,
                "fleet_replica_restarts": sup["restarts"] if sup else 0,
                "fleet_scale_ups": sup["scale_ups"] if sup else 0,
                "fleet_scale_downs": sup["scale_downs"] if sup else 0,
                "fleet_last_cold_start_s": (
                    (sup or {}).get("last_cold_start_s") or 0.0
                ),
                "fleet_prefix_entries": len(self._prefix),
            }
            lines = [
                "# TYPE kvmini_tpu_fleet_replicas_desired gauge",
                f"kvmini_tpu_fleet_replicas_desired {s['fleet_replicas_desired']}",
                "# TYPE kvmini_tpu_fleet_replicas_live gauge",
                f"kvmini_tpu_fleet_replicas_live {s['fleet_replicas_live']}",
                "# TYPE kvmini_tpu_fleet_reroutes_total counter",
                f"kvmini_tpu_fleet_reroutes_total {s['fleet_reroutes']}",
                "# TYPE kvmini_tpu_fleet_sheds_total counter",
                f"kvmini_tpu_fleet_sheds_total {s['fleet_sheds']}",
                "# TYPE kvmini_tpu_fleet_stream_errors_total counter",
                f"kvmini_tpu_fleet_stream_errors_total {s['fleet_stream_errors']}",
                "# TYPE kvmini_tpu_fleet_replica_restarts_total counter",
                "kvmini_tpu_fleet_replica_restarts_total "
                f"{s['fleet_replica_restarts']}",
                "# TYPE kvmini_tpu_fleet_scale_ups_total counter",
                f"kvmini_tpu_fleet_scale_ups_total {s['fleet_scale_ups']}",
                "# TYPE kvmini_tpu_fleet_scale_downs_total counter",
                f"kvmini_tpu_fleet_scale_downs_total {s['fleet_scale_downs']}",
                "# TYPE kvmini_tpu_fleet_last_cold_start_seconds gauge",
                "kvmini_tpu_fleet_last_cold_start_seconds "
                f"{s['fleet_last_cold_start_s']:.3f}",
                "# TYPE kvmini_tpu_fleet_prefix_index_entries gauge",
                "kvmini_tpu_fleet_prefix_index_entries "
                f"{s['fleet_prefix_entries']}",
                # cumulative fleet.route span wall time: divided by the
                # placements rate it yields mean routing latency (the
                # dashboards/fleet.json routing-latency panel)
                "# TYPE kvmini_tpu_fleet_route_seconds_total counter",
                "kvmini_tpu_fleet_route_seconds_total "
                f"{self.route_seconds_total:.6f}",
                "# TYPE kvmini_tpu_fleet_decisions_dropped_total counter",
                "kvmini_tpu_fleet_decisions_dropped_total "
                f"{self.decisions_dropped}",
                "# TYPE kvmini_tpu_fleet_placements_total counter",
            ]
            for reason in PLACEMENT_REASONS:
                lines.append(
                    "kvmini_tpu_fleet_placements_total"
                    # fixed PLACEMENT_REASONS vocabulary: 0 here means
                    # "observed zero times", not "unmeasured" — the
                    # legitimate enumerated-counter exception to
                    # absent-not-zero (kvmini: contract-ok)
                    f"{{reason=\"{reason}\"}} {self.placements.get(reason, 0)}"
                )
            # ratio/percentile gauges as ONE fleet-level mean each (over
            # healthy scraped replicas): label-summing 3 replicas at 0.8
            # duty would read 2.4 in every flat-scrape consumer
            for name in sorted(MEAN_GAUGES):
                vals = [r.metrics_map[name] for r in views
                        if r.healthy and name in r.metrics_map]
                if vals:
                    lines.append(f"# TYPE {name} gauge")
                    lines.append(f"{name} {sum(vals) / len(vals):.6f}")
            # fleet marginal-replica attribution (docs/ECONOMICS.md):
            # the WORST $/1K-tok any single healthy replica is producing
            # at — each replica's own hourly accrual spread over its own
            # windowed token rate. This is the number the cost-aware
            # autoscaler and the replica_unprofitable monitor rule
            # compare against the budget: when the marginal replica's
            # tokens stop paying for its hour, the fleet is over-
            # provisioned. Absent (no line, never $0) until at least one
            # priced replica shows token progress.
            from kserve_vllm_mini_tpu.costs.live import usd_per_1k_tokens

            marginal = None
            for r in views:
                if not r.healthy:
                    continue
                price = r.metrics_map.get("kvmini_tpu_econ_usd_per_hour")
                rate = r.metrics_map.get("kvmini_tpu_econ_tokens_per_sec")
                if price and rate and rate > 0.0:
                    cand = usd_per_1k_tokens(price, rate)
                    marginal = cand if marginal is None else max(marginal,
                                                                 cand)
            if marginal is not None:
                lines += [
                    "# TYPE kvmini_tpu_econ_marginal_replica"
                    "_usd_per_1k_tokens gauge",
                    "kvmini_tpu_econ_marginal_replica_usd_per_1k_tokens "
                    f"{marginal:.6f}",
                ]
            # per-replica passthrough: every replica's last scrape with a
            # replica label — the flat-scrape parser SUMS duplicates, so
            # post-hoc consumers read fleet totals unchanged (counters
            # and level gauges; the mean-type set above is stripped)
            type_seen: set[str] = set()
            for r in views:
                if r.metrics_text:
                    lines += relabel_exposition(r.metrics_text, r.rid,
                                                type_seen,
                                                skip=MEAN_GAUGES)
            return web.Response(text="\n".join(lines) + "\n",
                                content_type="text/plain")

        app = web.Application()
        app.on_startup.append(on_startup)
        app.on_cleanup.append(on_cleanup)
        app.router.add_post("/v1/chat/completions", chat)
        app.router.add_get("/v1/models", models)
        app.router.add_get("/healthz", healthz)
        app.router.add_get("/metrics", metrics)
        app.router.add_get("/traces", traces)
        app.router.add_get("/fleet", fleet_get)
        app.router.add_get("/fleet/decisions", fleet_decisions)
        app.router.add_post("/fleet/scale", fleet_scale)
        app.router.add_post("/fleet/chaos", fleet_chaos)
        return app


@dataclass
class RouterHandle:
    """A router running on its own thread+loop (tests, the bench fleet
    row, and the ``kvmini-tpu fleet`` CLI's non-blocking mode)."""

    router: FleetRouter
    url: str
    _loop: Any
    _runner: Any
    _thread: threading.Thread
    _stopped: bool = field(default=False)

    def stop(self) -> None:
        if self._stopped:
            return
        self._stopped = True

        async def _cleanup() -> None:
            await self._runner.cleanup()

        fut = asyncio.run_coroutine_threadsafe(_cleanup(), self._loop)
        try:
            fut.result(timeout=10.0)
        finally:
            self._loop.call_soon_threadsafe(self._loop.stop)
            self._thread.join(timeout=5.0)

    def __enter__(self) -> "RouterHandle":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.stop()


def start_router(
    router: FleetRouter, host: str = "127.0.0.1", port: int = 0,
) -> RouterHandle:
    """Run the router app on a dedicated thread; returns a handle with
    the bound URL. Synchronous callers (bench row, chaos harness tests)
    drive it over plain HTTP."""
    from aiohttp import web

    loop = asyncio.new_event_loop()
    started: "queue.Queue[Any]" = queue.Queue()

    def run() -> None:
        asyncio.set_event_loop(loop)

        async def boot() -> Any:
            runner = web.AppRunner(router.make_app())
            await runner.setup()
            site = web.TCPSite(runner, host, port)
            await site.start()
            bound = site._server.sockets[0].getsockname()[1]
            return runner, bound

        try:
            runner, bound = loop.run_until_complete(boot())
        except Exception as e:  # noqa: BLE001 — surfaced to the caller
            started.put(e)
            return
        started.put((runner, bound))
        loop.run_forever()

    thread = threading.Thread(target=run, name="fleet-router", daemon=True)
    thread.start()
    got = started.get(timeout=30.0)
    if isinstance(got, Exception):
        raise got
    runner, bound = got
    return RouterHandle(
        router=router, url=f"http://{host}:{bound}",
        _loop=loop, _runner=runner, _thread=thread,
    )
