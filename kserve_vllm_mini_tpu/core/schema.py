"""The canonical results.json schema — the framework's real API.

Gate, canary, planner, report, and sweeps all key off this flat dict
(reference SURVEY.md §5.5; /root/reference/analyze.py:573-595,
cost_estimator.py:465-482). We keep the reference's key names where the
semantics are hardware-agnostic (p50_ms, ttft_p95_ms, cost_per_1k_tokens, ...)
and replace the GPU-specific keys with TPU-native ones:

- gpu_util_avg        -> tpu_duty_cycle_avg   (duty cycle %, libtpu-style)
- gpu_mem_used_avg    -> tpu_hbm_used_avg_gib
- gpu_power_watts_avg -> tpu_power_watts_avg  (+ power_provenance)

Only knowingly-populated keys are written; merges are last-writer-wins at key
granularity, matching the reference's read-modify-write of results.json.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Optional


@dataclass
class Results:
    """Typed view of results.json. All fields optional; ``to_dict`` drops Nones."""

    # identity / provenance
    run_id: Optional[str] = None
    model: Optional[str] = None
    runtime: Optional[str] = None           # "jax-native" | "jetstream" | "vllm-tpu" | ...
    accelerator: Optional[str] = None       # e.g. "tpu-v5e-8"
    pattern: Optional[str] = None
    requests: Optional[int] = None
    concurrency: Optional[int] = None
    streaming: Optional[bool] = None

    # latency (ms)
    p50_ms: Optional[float] = None
    p95_ms: Optional[float] = None
    p99_ms: Optional[float] = None
    mean_ms: Optional[float] = None
    ttft_p50_ms: Optional[float] = None
    ttft_p95_ms: Optional[float] = None
    ttft_avg_ms: Optional[float] = None
    tpot_p50_ms: Optional[float] = None     # time-per-output-token
    tpot_p95_ms: Optional[float] = None

    # throughput
    throughput_rps: Optional[float] = None
    tokens_per_sec: Optional[float] = None
    tokens_per_sec_per_chip: Optional[float] = None
    error_rate: Optional[float] = None
    # overload shedding (docs/RESILIENCE.md): requests 429-shed past the
    # loadgen's retry budget, counted SEPARATELY from errors (error_rate
    # excludes them — an overload run shedding by design is not broken),
    # and the total 429 resends absorbed into surviving records
    shed_requests: Optional[int] = None
    shed_rate: Optional[float] = None
    retries_total: Optional[int] = None
    truncated_requests: Optional[int] = None  # prompts cut to the prefill
                                              # budget (workload changed)
    truncated_prompt_tokens: Optional[int] = None  # total tokens dropped

    # cold/warm split (reference analyze.py:422-460)
    cold_requests: Optional[int] = None
    warm_requests: Optional[int] = None
    cold_p50_ms: Optional[float] = None
    cold_p95_ms: Optional[float] = None
    warm_p50_ms: Optional[float] = None
    warm_p95_ms: Optional[float] = None
    cold_multiplier: Optional[float] = None
    cold_start_seconds: Optional[float] = None

    # utilization / telemetry (TPU-native). `*_avg` keys are only written
    # when a real window backs them (a Prometheus range or the monitor's
    # timeline — docs/MONITORING.md); a single runtime /metrics snapshot
    # lands in the instant keys with tpu_metrics_source saying so.
    tpu_duty_cycle_avg: Optional[float] = None
    tpu_duty_cycle: Optional[float] = None  # instantaneous, one scrape
    tpu_hbm_used_avg_gib: Optional[float] = None
    tpu_power_watts_avg: Optional[float] = None
    power_provenance: Optional[str] = None  # "measured" | "modeled"
    cpu_util_avg: Optional[float] = None
    host_mem_used_avg_gib: Optional[float] = None
    # queue-depth distribution over the run, from the monitor timeline
    queue_depth_p50: Optional[float] = None
    queue_depth_p95: Optional[float] = None
    queue_depth_max: Optional[float] = None

    # cache
    cache_hit_ratio: Optional[float] = None
    cache_hit_source: Optional[str] = None  # "metrics" | "logs" | "ttft-inference"

    # energy
    energy_wh: Optional[float] = None
    energy_wh_per_request: Optional[float] = None
    energy_wh_per_1k_tokens: Optional[float] = None

    # cost
    cost_total: Optional[float] = None
    cost_per_request: Optional[float] = None
    cost_per_1k_tokens: Optional[float] = None
    cost_breakdown: Optional[dict[str, float]] = None
    cold_cost_total: Optional[float] = None
    warm_cost_total: Optional[float] = None

    # io probe
    network_rtt_p50_ms: Optional[float] = None
    network_rtt_p95_ms: Optional[float] = None
    storage_fetch_mbps: Optional[float] = None

    # quality
    quality_score: Optional[float] = None
    quality_tasks: Optional[dict[str, float]] = None

    # window + distributions
    window: Optional[dict[str, float]] = None        # {"start": t0, "end": t1, "duration_s": d}
    latency_histogram: Optional[dict[str, Any]] = None
    ttft_histogram: Optional[dict[str, Any]] = None
    token_timing: Optional[dict[str, Any]] = None

    # decode-pipeline telemetry (docs/DECODE_PIPELINE.md): the runtime's
    # double-buffering counters, scraped from /metrics (analysis/
    # telemetry.py PIPELINE_METRIC_KEYS) or snapshotted directly in
    # self-serve runs (bench_pipeline). Declared so gates/reports see
    # typed fields instead of untyped extras.
    pipeline_dispatch_depth: Optional[float] = None
    pipeline_pipelined_sweeps: Optional[float] = None
    pipeline_host_overlap_s: Optional[float] = None
    pipeline_bubble_s: Optional[float] = None

    # chunked-prefill telemetry (docs/TROUBLESHOOTING.md "Long prompts
    # stall streaming"): compiled prefill piece dispatches and the prefill
    # wall that ran while decode work was live, scraped from /metrics
    # (analysis/telemetry.py PREFILL_METRIC_KEYS); absent for external
    # engines
    prefill_chunks: Optional[float] = None
    prefill_chunk_stall_s: Optional[float] = None

    # server-side phase attribution (docs/TRACING.md): per-phase duration
    # stats from the runtime's /traces spans merged by the analyzer —
    # {"queue"|"prefill"|"decode": {count, mean_ms, p50_ms, p95_ms,
    # max_ms}, "clock_offset_ms_est": ..., "source": "server:/traces"}.
    # Runs through a fleet router also carry the router-lane phases
    # "route" (placement+proxy window) and "proxy" (per-attempt upstream
    # call), with source "fleet:/traces".
    phase_breakdown: Optional[dict[str, Any]] = None
    # p99-outlier routing attribution (docs/TRACING.md "Fleet tracing"):
    # the slowest request's trace_id joined to its placement decision(s)
    # from the router's audit ring — {trace_id, latency_ms, placements,
    # decisions: [...]}; absent for single-server runs and when the ring
    # already evicted the run's entries.
    routing_outlier: Optional[dict[str, Any]] = None

    # live-monitor summary (docs/MONITORING.md): rolling SLO burn-rates,
    # detected events, sampler accounting and abort info — the shape
    # validate_monitor checks, backed by runs/<id>/timeline.jsonl
    monitor: Optional[dict[str, Any]] = None
    # reason string when the run was early-terminated by the monitor's
    # abort hook (sweeps record it per cell; absent for completed runs)
    aborted_early: Optional[str] = None

    # compile-stats block (docs/PROFILING.md): the runtime's accumulated
    # lower().compile() capture — {compiles, compile_wall_s, flops,
    # bytes_accessed, peak_bytes} — snapshotted directly in self-serve
    # runs or scraped from /metrics (analysis/telemetry.py
    # COMPILE_METRIC_KEYS); absent for external engines
    compile_stats: Optional[dict[str, Any]] = None
    # proxy-tier block (docs/PROFILING.md): the CPU-mesh fallback bench's
    # cost-model metrics, shape gated by validate_proxy — present only
    # for rounds that ran without a device; NEVER carries device
    # throughput claims (series is always "proxy")
    proxy: Optional[dict[str, Any]] = None

    # KV-cache & HBM observability block (docs/TROUBLESHOOTING.md "HBM
    # pressure & KV thrash"): prefix-cache attribution (hit-depth
    # percentiles, bytes reused), paged-block lifecycle counters
    # (allocations, retained-LRU evictions, share reclaims), pool
    # occupancy/fragmentation gauges and HBM watermarks — snapshotted
    # directly in self-serve runs or scraped from /metrics (analysis/
    # telemetry.py KV_METRIC_KEYS); shape gated by validate_kv_cache.
    # Absent for external engines.
    kv_cache: Optional[dict[str, Any]] = None
    # resilience block (docs/RESILIENCE.md): the runtime's shed /
    # watchdog / degrade counters — {requests_shed, watchdog_trips,
    # engine_faults, degrade_level, faults_armed, source} — snapshotted
    # directly in self-serve runs or scraped from /metrics (analysis/
    # telemetry.py RESILIENCE_METRIC_KEYS); absent for external engines
    # and for runs with zero resilience activity.
    resilience: Optional[dict[str, Any]] = None
    # disaggregated-serving block (docs/DISAGGREGATION.md): the prefill-
    # lane handoff rail — {handoffs, handoff_blocks, handoff_wait_s,
    # handoff_drops, handoff_bytes_copied, lane_busy_s,
    # colocated_fallbacks, queue_depth,
    # degraded, source} — snapshotted directly in self-serve runs or
    # scraped from /metrics (analysis/telemetry.py DISAGG_METRIC_KEYS);
    # absent for colocated engines, external engines, and runs with zero
    # handoff activity.
    disagg: Optional[dict[str, Any]] = None
    # fleet block (docs/FLEET.md): the multi-replica router's rail —
    # {replicas_desired, replicas_live, placements, reroutes, sheds,
    # stream_errors, replica_restarts, scale_ups, scale_downs,
    # last_cold_start_s, source} — scraped from the router's aggregated
    # /metrics (analysis/telemetry.py FLEET_METRIC_KEYS); absent for
    # single-server runs and external engines.
    fleet: Optional[dict[str, Any]] = None
    # live-economics block (docs/ECONOMICS.md): the rolling-window cost/
    # energy rail — {usd_per_1k_tokens, wh_per_1k_tokens, usd_per_hour,
    # tokens_per_sec, marginal_replica_usd_per_1k_tokens, source} —
    # snapshotted directly in self-serve runs (engine.economics_snapshot)
    # or scraped from /metrics (analysis/telemetry.py ECON_METRIC_KEYS);
    # shape gated by validate_economics. Absent for CPU backends without
    # an econ_accelerator and for external engines — never a $0 block.
    economics: Optional[dict[str, Any]] = None
    # headroom-model validation (profiling/headroom.py): signed % error
    # of the analytic admission estimate vs the observed HBM peak —
    # negative = the model UNDERESTIMATES (the OOM direction). Present
    # only when the run observed a real (or mocked) HBM watermark.
    headroom_error_pct: Optional[float] = None

    extras: dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> dict[str, Any]:
        out: dict[str, Any] = {}
        for f in dataclasses.fields(self):
            if f.name == "extras":
                continue
            v = getattr(self, f.name)
            if v is not None:
                out[f.name] = v
        out.update(self.extras)
        return out

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "Results":
        known = {f.name for f in dataclasses.fields(cls)} - {"extras"}
        kwargs = {k: v for k, v in d.items() if k in known}
        extras = {k: v for k, v in d.items() if k not in known}
        return cls(**kwargs, extras=extras)


def merge_results(base: dict[str, Any], update: dict[str, Any]) -> dict[str, Any]:
    """Key-granular merge; nested dicts (cost_breakdown, window, ...) are
    replaced wholesale like the reference does."""
    out = dict(base)
    out.update(update)
    return out


# -- traces.json schema -------------------------------------------------------
#
# The OTLP/JSON subset both trace writers (loadgen/tracing.py, runtime/
# tracing.py) emit and the analyzer's merge preserves. Expressed as a
# JSON-Schema document for tooling, enforced by validate_traces (hand-
# rolled — the validation must not grow a jsonschema dependency for the
# harness layers). `make bench-smoke` gates on it.

TRACES_JSON_SCHEMA: dict[str, Any] = {
    "$schema": "http://json-schema.org/draft-07/schema#",
    "title": "kvmini-tpu traces.json (OTLP/JSON subset)",
    "type": "object",
    "required": ["resourceSpans"],
    "properties": {
        "resourceSpans": {
            "type": "array",
            "items": {
                "type": "object",
                "required": ["scopeSpans"],
                "properties": {
                    "scopeSpans": {
                        "type": "array",
                        "items": {
                            "type": "object",
                            "required": ["spans"],
                            "properties": {
                                "spans": {
                                    "type": "array",
                                    "items": {
                                        "type": "object",
                                        "required": [
                                            "traceId", "spanId", "name",
                                            "startTimeUnixNano",
                                            "endTimeUnixNano",
                                        ],
                                        "properties": {
                                            "traceId": {
                                                "type": "string",
                                                "pattern": "^[0-9a-f]{32}$",
                                            },
                                            "spanId": {
                                                "type": "string",
                                                "pattern": "^[0-9a-f]{16}$",
                                            },
                                            "parentSpanId": {
                                                "type": "string",
                                                "pattern": "^[0-9a-f]{16}$",
                                            },
                                            "name": {"type": "string"},
                                            "startTimeUnixNano": {"type": "string"},
                                            "endTimeUnixNano": {"type": "string"},
                                        },
                                    },
                                }
                            },
                        },
                    }
                },
            },
        },
        "clockOffsetNanosEstimate": {"type": "integer"},
        # fleet stitches (analysis/traces.merge_fleet_traces): one offset
        # PER replica keyed by rid — two replicas' clocks can disagree,
        # so a single estimate cannot shift both lanes correctly — plus
        # the router's own offset against the client clock
        "clockOffsetsNanosByReplica": {
            "type": "object",
            "additionalProperties": {"type": "integer"},
        },
        "clockOffsetNanosRouter": {"type": "integer"},
        "droppedSpans": {"type": "integer"},
    },
}


_HEX_CHARS = frozenset("0123456789abcdef")


def _hex_id(v: Any, width: int) -> bool:
    # the SAME strictness as the schema's ^[0-9a-f]{N}$ patterns
    # (lowercase-only; int(v, 16) would accept uppercase/'0x'/underscores
    # and make this gate disagree with the published JSON Schema)
    return (
        isinstance(v, str) and len(v) == width and _HEX_CHARS.issuperset(v)
    )


def validate_traces(doc: Any) -> list[str]:
    """Validate a traces.json document against TRACES_JSON_SCHEMA's
    contract. Returns a list of violation strings — empty means valid.
    Checks the invariants downstream consumers rely on: id shapes, the
    nano-timestamp strings, and end >= start (negative durations were the
    exact bug the export clamp fixed)."""
    errs: list[str] = []
    if not isinstance(doc, dict):
        return ["document is not an object"]
    rss = doc.get("resourceSpans")
    if not isinstance(rss, list):
        return ["resourceSpans missing or not an array"]
    for ri, rs in enumerate(rss):
        if not isinstance(rs, dict):
            errs.append(f"resourceSpans[{ri}] is not an object")
            continue
        sss = rs.get("scopeSpans")
        if not isinstance(sss, list):
            errs.append(f"resourceSpans[{ri}].scopeSpans missing")
            continue
        for si, ss in enumerate(sss):
            spans = ss.get("spans") if isinstance(ss, dict) else None
            if not isinstance(spans, list):
                errs.append(
                    f"resourceSpans[{ri}].scopeSpans[{si}].spans missing"
                )
                continue
            for pi, s in enumerate(spans):
                where = f"resourceSpans[{ri}].scopeSpans[{si}].spans[{pi}]"
                if not isinstance(s, dict):
                    errs.append(f"{where} is not an object")
                    continue
                if not _hex_id(s.get("traceId"), 32):
                    errs.append(f"{where}: bad traceId {s.get('traceId')!r}")
                if not _hex_id(s.get("spanId"), 16):
                    errs.append(f"{where}: bad spanId {s.get('spanId')!r}")
                if "parentSpanId" in s and not _hex_id(s["parentSpanId"], 16):
                    errs.append(
                        f"{where}: bad parentSpanId {s['parentSpanId']!r}"
                    )
                if not isinstance(s.get("name"), str) or not s.get("name"):
                    errs.append(f"{where}: missing name")
                try:
                    start = int(s.get("startTimeUnixNano", ""))
                    end = int(s.get("endTimeUnixNano", ""))
                except (TypeError, ValueError):
                    errs.append(f"{where}: non-integer time stamps")
                    continue
                if end < start:
                    errs.append(
                        f"{where}: negative duration "
                        f"({s.get('name')}: {end} < {start})"
                    )
    return errs


# -- monitor block + timeline.jsonl schemas -----------------------------------
#
# The live-monitor surfaces (docs/MONITORING.md): the `monitor` block the
# sampler merges into results.json and the per-line sample shape of
# runs/<id>/timeline.jsonl. Hand-rolled validators for the same reason as
# validate_traces — no jsonschema dependency in the harness layers.
# `make bench-smoke` gates on both.

MONITOR_JSON_SCHEMA: dict[str, Any] = {
    "$schema": "http://json-schema.org/draft-07/schema#",
    "title": "kvmini-tpu results.json `monitor` block",
    "type": "object",
    "required": ["interval_s", "samples", "skipped_samples", "events",
                 "burn_rates", "burn_rates_peak"],
    "properties": {
        "interval_s": {"type": "number", "exclusiveMinimum": 0},
        "window_s": {"type": "number"},
        "samples": {"type": "integer", "minimum": 0},
        "skipped_samples": {"type": "integer", "minimum": 0},
        "events": {
            "type": "array",
            "items": {
                "type": "object",
                "required": ["t", "type", "detail"],
                "properties": {
                    "t": {"type": "number"},
                    "type": {"type": "string"},
                    "detail": {"type": "string"},
                    "data": {"type": "object"},
                },
            },
        },
        "burn_rates": {
            "type": "object", "additionalProperties": {"type": "number"}
        },
        "burn_rates_peak": {
            "type": "object", "additionalProperties": {"type": "number"}
        },
        "aborted": {"type": "string"},
    },
}

TIMELINE_SAMPLE_SCHEMA: dict[str, Any] = {
    "$schema": "http://json-schema.org/draft-07/schema#",
    "title": "kvmini-tpu timeline.jsonl sample (one JSON object per line)",
    "type": "object",
    "required": ["t"],
    "properties": {
        "t": {"type": "number"},
        "scrape_ms": {"type": "number", "minimum": 0},
        "runtime": {
            "type": "object", "additionalProperties": {"type": "number"}
        },
        "loadgen": {
            "type": "object", "additionalProperties": {"type": "number"}
        },
        "burn_rates": {
            "type": "object", "additionalProperties": {"type": "number"}
        },
        "events": {"type": "array"},
        # trace ids in flight at sample time (docs/MONITORING.md): rides
        # TOP-level, not inside `loadgen` — that block's contract is a
        # flat name->number map and must stay numeric
        "inflight_trace_ids": {
            "type": "array", "items": {"type": "string"}
        },
    },
}


def _num(v: Any) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


# -- proxy block schema -------------------------------------------------------
#
# The CPU-mesh proxy bench tier's output (docs/PROFILING.md): the block
# bench.py emits in its artifact's `detail.proxy` when the TPU probe
# failed, and the `proxy` results.json field. Hand-rolled validator like
# the others — no jsonschema dependency in the harness layers. `make
# bench-proxy-smoke` gates on it.

PROXY_JSON_SCHEMA: dict[str, Any] = {
    "$schema": "http://json-schema.org/draft-07/schema#",
    "title": "kvmini-tpu proxy bench block (CPU-mesh fallback tier)",
    "type": "object",
    "required": ["series", "flops", "bytes_accessed", "compile_wall_s",
                 "peak_bytes", "step_count_ratio"],
    "properties": {
        "series": {"const": "proxy"},
        "platform": {"type": "string"},
        "n_devices": {"type": "integer", "minimum": 1},
        "model": {"type": "string"},
        "exec_model": {"type": "string"},
        # quantization labels: a proxy round's compile drift is only
        # comparable against rounds of the same quant/quant_mode/kv_quant
        "quant": {"type": "string"},
        "quant_mode": {"enum": ["dequant", "w8a8"]},
        "kv_quant": {"type": "boolean"},
        "flops": {"type": "number", "minimum": 0},
        "bytes_accessed": {"type": "number", "minimum": 0},
        "compile_wall_s": {"type": "number", "exclusiveMinimum": 0},
        "peak_bytes": {"type": "number", "minimum": 0},
        "step_count_ratio": {"type": "number", "exclusiveMinimum": 0},
        "compile_stats": {"type": "object"},
        "analytic_bytes": {"type": "object"},
        "exec": {"type": "object"},
        "hbm_headroom": {"type": "object"},
    },
}


def validate_proxy(doc: Any) -> list[str]:
    """Validate a proxy block against PROXY_JSON_SCHEMA's contract.
    Returns violations; empty = valid. The hard rule: ``series`` must be
    the literal "proxy" — a proxy number that could be mistaken for a
    device measurement is worse than no number."""
    errs: list[str] = []
    if not isinstance(doc, dict):
        return ["proxy block is not an object"]
    if doc.get("series") != "proxy":
        errs.append(
            f"series must be the literal 'proxy' (got {doc.get('series')!r})"
        )
    for key in ("flops", "bytes_accessed", "peak_bytes"):
        v = doc.get(key)
        if not _num(v) or v < 0:
            errs.append(f"{key} missing or not a non-negative number")
    for key in ("compile_wall_s", "step_count_ratio"):
        v = doc.get(key)
        if not _num(v) or v <= 0:
            errs.append(f"{key} missing or not a positive number")
    if "n_devices" in doc and (
        not isinstance(doc["n_devices"], int)
        or isinstance(doc["n_devices"], bool)
        or doc["n_devices"] < 1
    ):
        errs.append("n_devices is not a positive integer")
    for key in ("compile_stats", "analytic_bytes", "exec", "hbm_headroom"):
        if key in doc and not isinstance(doc[key], dict):
            errs.append(f"{key} is not an object")
    if "quant_mode" in doc and doc["quant_mode"] not in ("dequant", "w8a8"):
        errs.append(
            f"quant_mode must be 'dequant' or 'w8a8' (got {doc['quant_mode']!r})"
        )
    return errs


# -- kv_cache block schema ----------------------------------------------------
#
# The KV-cache & HBM observability block (docs/TROUBLESHOOTING.md): what
# the engine's kv_cache_snapshot and the analyzer's KV_METRIC_KEYS scrape
# both produce under the `kv_cache` results key. Hand-rolled validator
# like the others — no jsonschema dependency in the harness layers.
# `make bench-smoke` gates on it.

KV_CACHE_JSON_SCHEMA: dict[str, Any] = {
    "$schema": "http://json-schema.org/draft-07/schema#",
    "title": "kvmini-tpu results.json `kv_cache` block",
    "type": "object",
    "required": ["hit_depth_p50", "hit_depth_p95", "reused_bytes",
                 "blocks_allocated", "retained_evictions"],
    "properties": {
        "source": {"type": "string"},
        "hit_depth_p50": {"type": "number", "minimum": 0},
        "hit_depth_p95": {"type": "number", "minimum": 0},
        "bytes_per_token": {"type": "number", "minimum": 0},
        "reused_bytes": {"type": "number", "minimum": 0},
        "blocks_allocated": {"type": "number", "minimum": 0},
        "retained_evictions": {"type": "number", "minimum": 0},
        "share_reclaims": {"type": "number", "minimum": 0},
        "prefix_hits": {"type": "number", "minimum": 0},
        "prefix_lookups": {"type": "number", "minimum": 0},
        "pool_blocks": {"type": "number", "minimum": 0},
        "free_blocks": {"type": "number", "minimum": 0},
        "retained_blocks": {"type": "number", "minimum": 0},
        "used_blocks": {"type": "number", "minimum": 0},
        "block_size": {"type": "number", "minimum": 1},
        "occupancy": {"type": "number", "minimum": 0, "maximum": 1},
        "retained_fraction": {"type": "number", "minimum": 0, "maximum": 1},
        "fragmentation": {"type": "number", "minimum": 0, "maximum": 1},
        "logical_bytes": {"type": "number", "minimum": 0},
        "physical_bytes": {"type": "number", "minimum": 0},
        "hbm_bytes_in_use": {"type": "number", "minimum": 0},
        "hbm_peak_bytes": {"type": "number", "minimum": 0},
        "hbm_bytes_limit": {"type": "number", "minimum": 0},
        "headroom_estimate_bytes": {"type": "number", "minimum": 0},
        "tier_demotions": {"type": "number", "minimum": 0},
        "tier_promotions": {"type": "number", "minimum": 0},
        "tier_hits": {"type": "number", "minimum": 0},
        "tier_blocks": {"type": "number", "minimum": 0},
        "tier_bytes": {"type": "number", "minimum": 0},
        "tier_capacity_bytes": {"type": "number", "minimum": 0},
        "tier_disabled": {"type": "number", "minimum": 0, "maximum": 1},
        "migrated_blocks": {"type": "number", "minimum": 0},
        "migrated_bytes": {"type": "number", "minimum": 0},
        "export_blocks": {"type": "number", "minimum": 0},
    },
}

_KV_FRACTIONS = ("occupancy", "retained_fraction", "fragmentation",
                 "tier_disabled")


def validate_kv_cache(doc: Any) -> list[str]:
    """Validate a results.json ``kv_cache`` block against
    KV_CACHE_JSON_SCHEMA's contract. Returns violations; empty = valid.
    The invariants downstream consumers rely on: the required
    hit-depth/reuse/churn keys present and numeric, every present
    numeric non-negative, ratios inside [0, 1], p95 >= p50, and the
    paged pool arithmetic (free + retained + used == pool) when the
    pool gauges are present."""
    errs: list[str] = []
    if not isinstance(doc, dict):
        return ["kv_cache block is not an object"]
    for key in KV_CACHE_JSON_SCHEMA["required"]:
        if not _num(doc.get(key)) or doc[key] < 0:
            errs.append(f"{key} missing or not a non-negative number")
    for key, spec in KV_CACHE_JSON_SCHEMA["properties"].items():
        if key not in doc or spec.get("type") != "number":
            continue
        v = doc[key]
        if not _num(v):
            errs.append(f"{key} is not a number")
            continue
        if v < spec.get("minimum", 0):
            errs.append(f"{key} below {spec.get('minimum', 0)} ({v})")
        if key in _KV_FRACTIONS and v > 1:
            errs.append(f"{key} above 1 ({v})")
    if (
        _num(doc.get("hit_depth_p50")) and _num(doc.get("hit_depth_p95"))
        and doc["hit_depth_p95"] < doc["hit_depth_p50"]
    ):
        errs.append(
            f"hit_depth_p95 < hit_depth_p50 "
            f"({doc['hit_depth_p95']} < {doc['hit_depth_p50']})"
        )
    pool_keys = ("pool_blocks", "free_blocks", "retained_blocks",
                 "used_blocks")
    if all(_num(doc.get(k)) for k in pool_keys):
        total = (doc["free_blocks"] + doc["retained_blocks"]
                 + doc["used_blocks"])
        if total != doc["pool_blocks"]:
            errs.append(
                f"pool arithmetic broken: free+retained+used={total} "
                f"!= pool_blocks={doc['pool_blocks']}"
            )
    if "source" in doc and not isinstance(doc["source"], str):
        errs.append("source is not a string")
    return errs


# -- economics block schema ---------------------------------------------------
#
# The live cost/energy rail (docs/ECONOMICS.md): what the engine's
# economics_snapshot and the analyzer's ECON_METRIC_KEYS scrape both
# produce under the `economics` results key. Hand-rolled validator like
# the others — no jsonschema dependency in the harness layers. `make
# econ-smoke` gates on it.

ECONOMICS_JSON_SCHEMA: dict[str, Any] = {
    "$schema": "http://json-schema.org/draft-07/schema#",
    "title": "kvmini-tpu results.json `economics` block",
    "type": "object",
    "required": ["usd_per_hour"],
    "properties": {
        "source": {"type": "string"},
        "usd_per_hour": {"type": "number", "exclusiveMinimum": 0},
        "usd_per_1k_tokens": {"type": "number", "minimum": 0},
        "wh_per_1k_tokens": {"type": "number", "minimum": 0},
        "tokens_per_sec": {"type": "number", "minimum": 0},
        "marginal_replica_usd_per_1k_tokens": {
            "type": "number", "minimum": 0
        },
    },
}


def validate_economics(doc: Any) -> list[str]:
    """Validate a results.json ``economics`` block against
    ECONOMICS_JSON_SCHEMA's contract. Returns violations; empty = valid.
    The invariants downstream consumers rely on: the $/hr accrual
    present and strictly positive (a block that exists but prices the
    deployment at $0/hr is a pricing-sheet failure, not a cheap fleet),
    every present rate numeric and non-negative, and — for SINGLE-engine
    blocks, where all three gauges come from one snapshot window — the
    derivation closed: the reported $/1K-tok must equal usd_per_hour /
    (3.6 x tokens_per_sec) to float tolerance. Fleet-scraped blocks are
    exempt (flagged by the marginal-replica key): there usd_per_hour and
    tokens_per_sec are label-SUMMED fleet totals while usd_per_1k_tokens
    is the healthy-replica MEAN of ratios, which legitimately differs
    from the ratio of sums on a skewed fleet."""
    errs: list[str] = []
    if not isinstance(doc, dict):
        return ["economics block is not an object"]
    v = doc.get("usd_per_hour")
    if not _num(v) or v <= 0:
        errs.append("usd_per_hour missing or not a positive number")
    for key in ("usd_per_1k_tokens", "wh_per_1k_tokens", "tokens_per_sec",
                "marginal_replica_usd_per_1k_tokens"):
        if key in doc and (not _num(doc[key]) or doc[key] < 0):
            errs.append(f"{key} not a non-negative number ({doc[key]!r})")
    if (
        "marginal_replica_usd_per_1k_tokens" not in doc
        and _num(doc.get("usd_per_hour"))
        and _num(doc.get("tokens_per_sec")) and doc["tokens_per_sec"] > 0
        and _num(doc.get("usd_per_1k_tokens"))
    ):
        implied = doc["usd_per_hour"] / (3.6 * doc["tokens_per_sec"])
        if abs(doc["usd_per_1k_tokens"] - implied) > max(
            1e-6, 0.01 * implied
        ):
            errs.append(
                f"usd_per_1k_tokens={doc['usd_per_1k_tokens']} does not "
                f"match usd_per_hour/(3.6*tokens_per_sec)={implied:.9f}"
            )
    if "source" in doc and not isinstance(doc["source"], str):
        errs.append("source is not a string")
    return errs


def _rate_map_errs(v: Any, where: str) -> list[str]:
    if not isinstance(v, dict):
        return [f"{where} is not an object"]
    return [
        f"{where}[{k!r}] is not a number"
        for k, val in v.items() if not _num(val)
    ]


def validate_monitor(doc: Any) -> list[str]:
    """Validate a results.json ``monitor`` block against
    MONITOR_JSON_SCHEMA's contract. Returns violations; empty = valid."""
    errs: list[str] = []
    if not isinstance(doc, dict):
        return ["monitor block is not an object"]
    for key in ("interval_s",):
        if not _num(doc.get(key)) or doc.get(key) <= 0:
            errs.append(f"{key} missing or not a positive number")
    for key in ("samples", "skipped_samples"):
        v = doc.get(key)
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            errs.append(f"{key} missing or not a non-negative integer")
    events = doc.get("events")
    if not isinstance(events, list):
        errs.append("events missing or not an array")
    else:
        for i, e in enumerate(events):
            if not isinstance(e, dict):
                errs.append(f"events[{i}] is not an object")
                continue
            if not _num(e.get("t")):
                errs.append(f"events[{i}].t missing or not a number")
            for key in ("type", "detail"):
                if not isinstance(e.get(key), str) or not e.get(key):
                    errs.append(f"events[{i}].{key} missing or empty")
    for key in ("burn_rates", "burn_rates_peak"):
        errs += _rate_map_errs(doc.get(key), key)
    if "aborted" in doc and not isinstance(doc["aborted"], str):
        errs.append("aborted is not a string")
    return errs


# -- resilience_table.json schema ---------------------------------------------
#
# The chaos harness's per-fault table (chaos/harness.py + chaos/local.py,
# docs/RESILIENCE.md): one row per fault scenario with MTTR (time to first
# healthy completion after the fault cleared), p95-under-fault, and shed/
# error rates. Hand-rolled validator like the others — no jsonschema
# dependency in the harness layers. `make chaos-smoke` gates on it.

RESILIENCE_JSON_SCHEMA: dict[str, Any] = {
    "$schema": "http://json-schema.org/draft-07/schema#",
    "title": "kvmini-tpu resilience_table.json (chaos harness output)",
    "type": "object",
    "required": ["faults", "all_recovered"],
    "properties": {
        "service": {"type": "string"},
        "namespace": {"type": "string"},
        "target": {"enum": ["kserve", "local"]},
        "all_recovered": {"type": "boolean"},
        "worst_mttr_s": {"type": ["number", "null"], "minimum": 0},
        "faults": {
            "type": "array",
            "items": {
                "type": "object",
                "required": ["fault", "injected", "recovered"],
                "properties": {
                    "fault": {"type": "string"},
                    "injected": {"type": "boolean"},
                    "recovered": {"type": "boolean"},
                    "mttr_s": {"type": ["number", "null"], "minimum": 0},
                    "p95_ms": {"type": ["number", "null"], "minimum": 0},
                    "error_rate": {"type": ["number", "null"],
                                   "minimum": 0, "maximum": 1},
                    "shed_rate": {"type": ["number", "null"],
                                  "minimum": 0, "maximum": 1},
                    # None when injection failed or no gate was configured:
                    # a broken injector must NEVER read as a green gate
                    "gate_ok": {"type": ["boolean", "null"]},
                    "detail": {"type": "string"},
                },
            },
        },
    },
}


def validate_resilience(doc: Any) -> list[str]:
    """Validate a resilience_table.json document against
    RESILIENCE_JSON_SCHEMA's contract. Returns violations; empty = valid.
    The invariants downstream consumers rely on: per-fault rows typed,
    rates inside [0, 1], MTTR non-negative, a recovered row carrying a
    numeric MTTR, and gate_ok left null (never false-green) on rows whose
    injection failed."""
    errs: list[str] = []
    if not isinstance(doc, dict):
        return ["resilience table is not an object"]
    faults = doc.get("faults")
    if not isinstance(faults, list):
        return ["faults missing or not an array"]
    if not isinstance(doc.get("all_recovered"), bool):
        errs.append("all_recovered missing or not a boolean")
    worst = doc.get("worst_mttr_s")
    if worst is not None and (not _num(worst) or worst < 0):
        errs.append(f"worst_mttr_s not a non-negative number ({worst!r})")
    if "target" in doc and doc["target"] not in ("kserve", "local"):
        errs.append(f"target must be 'kserve'|'local' (got {doc['target']!r})")
    for i, row in enumerate(faults):
        where = f"faults[{i}]"
        if not isinstance(row, dict):
            errs.append(f"{where} is not an object")
            continue
        if not isinstance(row.get("fault"), str) or not row.get("fault"):
            errs.append(f"{where}.fault missing or empty")
        for key in ("injected", "recovered"):
            if not isinstance(row.get(key), bool):
                errs.append(f"{where}.{key} missing or not a boolean")
        for key in ("mttr_s", "p95_ms"):
            v = row.get(key)
            if v is not None and (not _num(v) or v < 0):
                errs.append(f"{where}.{key} not a non-negative number ({v!r})")
        for key in ("error_rate", "shed_rate"):
            v = row.get(key)
            if v is not None and (not _num(v) or not 0 <= v <= 1):
                errs.append(f"{where}.{key} outside [0, 1] ({v!r})")
        if row.get("recovered") is True and not _num(row.get("mttr_s")):
            errs.append(f"{where}: recovered row must carry a numeric mttr_s")
        if row.get("injected") is False and row.get("gate_ok") is not None:
            errs.append(
                f"{where}: gate_ok must be null when injection failed "
                "(a broken injector must not produce a gate verdict)"
            )
        g = row.get("gate_ok")
        if g is not None and not isinstance(g, bool):
            errs.append(f"{where}.gate_ok not a boolean/null ({g!r})")
    return errs


def validate_timeline(samples: list[Any]) -> list[str]:
    """Validate parsed timeline.jsonl samples (RunDir.read_timeline)
    against TIMELINE_SAMPLE_SCHEMA's contract: every line an object with
    a numeric monotone-friendly ``t``, and the runtime/loadgen/burn_rates
    blocks flat name->number maps."""
    errs: list[str] = []
    prev_t: Optional[float] = None
    for i, s in enumerate(samples):
        where = f"sample[{i}]"
        if not isinstance(s, dict):
            errs.append(f"{where} is not an object")
            continue
        t = s.get("t")
        if not _num(t):
            errs.append(f"{where}.t missing or not a number")
        else:
            if prev_t is not None and t < prev_t:
                errs.append(f"{where}.t went backwards ({t} < {prev_t})")
            prev_t = float(t)
        if "scrape_ms" in s and not _num(s["scrape_ms"]):
            errs.append(f"{where}.scrape_ms is not a number")
        for block in ("runtime", "loadgen", "burn_rates"):
            if block in s:
                errs += _rate_map_errs(s[block], f"{where}.{block}")
        if "events" in s and not isinstance(s["events"], list):
            errs.append(f"{where}.events is not an array")
    return errs
