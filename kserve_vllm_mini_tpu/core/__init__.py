from kserve_vllm_mini_tpu.core.rundir import RunDir, RequestRecord, REQUEST_CSV_COLUMNS
from kserve_vllm_mini_tpu.core.schema import Results, merge_results

__all__ = [
    "RunDir",
    "RequestRecord",
    "REQUEST_CSV_COLUMNS",
    "Results",
    "merge_results",
]
