"""Run-directory contract: the filesystem API every pipeline stage speaks.

A benchmark run lives in ``runs/<run_id>/`` and contains:

- ``requests.csv``          per-request records written by the load generator
- ``meta.json``             load-generator invocation metadata
- ``results.json``          the universal merge target every stage updates
- ``power.json``            sampled chip power (energy collector "collect")
- ``energy.json``           integrated energy (energy collector "integrate")
- ``timeline.jsonl``        1 Hz unified monitor samples (monitor/sampler.py,
                            docs/MONITORING.md) — one JSON object per line
- ``traces/traces.json``    OTLP-shaped client trace spans
- ``requests_classified.csv``  requests.csv + cold/warm classification column
- ``io_probe.json``         network/storage probe output

This mirrors the reference's loosely-coupled CLI-stage design (reference
SURVEY.md L1; /root/reference/analyze.py:606-618, cost_estimator.py:457-484,
energy/collector.py:187-200) but with one typed implementation instead of
ad-hoc json.load/dump in each script.
"""

from __future__ import annotations

import csv
import dataclasses
import json
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Iterable, Optional

# Column order of requests.csv. Superset of the reference's column set
# (/root/reference/scripts/loadtest.py:576-591) with TPU-runtime additions:
# server-side first/last token timestamps (the in-repo runtime reports true
# token timing, not just client TTFB approximation) and a prompt_set tag so
# cache probing is first-class rather than monkeypatched.
REQUEST_CSV_COLUMNS = [
    "request_id",
    "scheduled_ts",   # planned arrival (epoch s, float)
    "start_ts",       # actual send time
    "first_token_ts", # client-observed first streamed chunk (0 if non-streaming)
    "last_token_ts",  # client-observed last streamed chunk (0 if non-streaming)
    "end_ts",         # response fully received
    "latency_ms",     # end_ts - start_ts
    "ttft_ms",        # first_token_ts - start_ts (streaming) else latency_ms
    "tokens_in",
    "tokens_out",
    "status_code",
    "ok",             # "1"/"0"
    "error",          # short error string, "" if ok
    "trace_id",
    "prompt_set",     # e.g. "default", "repeat", "unique" (cache probe)
    "tenant",         # multi-tenant fairness runs; "" otherwise
    "server_ttft_ms", # runtime-reported true first-token latency; 0 if unknown
    "truncated",      # "1" if the prompt was cut to the engine's prefill
                      # budget — the run measured a different workload than
                      # requested, and the analyzer must say so
    "truncated_tokens",  # how many prompt tokens the engine dropped (severity)
    "model",          # model/adapter the request was routed to (multi-LoRA
                      # runs rotate adapters; "" = the run's single model)
    "retries",        # 429-shed resends this record absorbed (backoff +
                      # Retry-After, docs/RESILIENCE.md) — honest retry
                      # accounting, never fabricated as fresh requests
    "shed",           # "1" when the server shed the request past the retry
                      # budget: counted separately from errors by the
                      # analyzer (an overloaded-by-design run is not broken)
]


@dataclass
class RequestRecord:
    """One load-generator request; one row of requests.csv."""

    request_id: str
    scheduled_ts: float = 0.0
    start_ts: float = 0.0
    first_token_ts: float = 0.0
    last_token_ts: float = 0.0
    end_ts: float = 0.0
    latency_ms: float = 0.0
    ttft_ms: float = 0.0
    tokens_in: int = 0
    tokens_out: int = 0
    status_code: int = 0
    ok: bool = False
    error: str = ""
    trace_id: str = ""
    prompt_set: str = "default"
    tenant: str = ""
    server_ttft_ms: float = 0.0
    truncated: bool = False
    truncated_tokens: int = 0
    model: str = ""
    retries: int = 0
    shed: bool = False

    def to_row(self) -> dict[str, Any]:
        d = dataclasses.asdict(self)
        d["ok"] = "1" if self.ok else "0"
        d["truncated"] = "1" if self.truncated else "0"
        d["shed"] = "1" if self.shed else "0"
        return d

    @classmethod
    def from_row(cls, row: dict[str, str]) -> "RequestRecord":
        def _f(key: str) -> float:
            v = row.get(key, "")
            try:
                return float(v) if v != "" else 0.0
            except ValueError:
                return 0.0

        def _i(key: str) -> int:
            v = row.get(key, "")
            try:
                return int(float(v)) if v != "" else 0
            except ValueError:
                return 0

        return cls(
            request_id=row.get("request_id", ""),
            scheduled_ts=_f("scheduled_ts"),
            start_ts=_f("start_ts"),
            first_token_ts=_f("first_token_ts"),
            last_token_ts=_f("last_token_ts"),
            end_ts=_f("end_ts"),
            latency_ms=_f("latency_ms"),
            ttft_ms=_f("ttft_ms"),
            tokens_in=_i("tokens_in"),
            tokens_out=_i("tokens_out"),
            status_code=_i("status_code"),
            ok=row.get("ok", "0") in ("1", "true", "True"),
            error=row.get("error", ""),
            trace_id=row.get("trace_id", ""),
            prompt_set=row.get("prompt_set", "default") or "default",
            tenant=row.get("tenant", ""),
            server_ttft_ms=_f("server_ttft_ms"),
            truncated=row.get("truncated", "0") in ("1", "true", "True"),
            truncated_tokens=_i("truncated_tokens"),
            model=row.get("model", ""),
            retries=_i("retries"),
            shed=row.get("shed", "0") in ("1", "true", "True"),
        )


@dataclass
class RunDir:
    """Typed handle on a run directory."""

    path: Path

    def __post_init__(self) -> None:
        self.path = Path(self.path)

    # -- factory -----------------------------------------------------------
    @classmethod
    def create(cls, root: str | Path = "runs", run_id: Optional[str] = None) -> "RunDir":
        if run_id is not None:
            p = Path(root) / run_id
            p.mkdir(parents=True, exist_ok=True)
        else:
            # Auto-generated ids must never collide: two sweeps launched in the
            # same second would otherwise silently share (and clobber) one dir.
            base = time.strftime("%Y%m%d-%H%M%S")
            for suffix in ("", *(f"-{i}" for i in range(1, 1000))):
                p = Path(root) / (base + suffix)
                try:
                    p.mkdir(parents=True, exist_ok=False)
                    break
                except FileExistsError:
                    continue
            else:
                raise RuntimeError(f"could not allocate a unique run dir under {root}")
        (p / "traces").mkdir(exist_ok=True)
        return cls(p)

    # -- file paths --------------------------------------------------------
    @property
    def requests_csv(self) -> Path:
        return self.path / "requests.csv"

    @property
    def requests_classified_csv(self) -> Path:
        return self.path / "requests_classified.csv"

    @property
    def meta_json(self) -> Path:
        return self.path / "meta.json"

    @property
    def results_json(self) -> Path:
        return self.path / "results.json"

    @property
    def power_json(self) -> Path:
        return self.path / "power.json"

    @property
    def energy_json(self) -> Path:
        return self.path / "energy.json"

    @property
    def traces_json(self) -> Path:
        return self.path / "traces" / "traces.json"

    @property
    def io_probe_json(self) -> Path:
        return self.path / "io_probe.json"

    @property
    def timeline_jsonl(self) -> Path:
        return self.path / "timeline.jsonl"

    # -- requests.csv ------------------------------------------------------
    def write_requests(self, records: Iterable[RequestRecord]) -> None:
        with self.requests_csv.open("w", newline="") as f:
            w = csv.DictWriter(f, fieldnames=REQUEST_CSV_COLUMNS)
            w.writeheader()
            for r in records:
                w.writerow(r.to_row())

    def read_requests(self, classified: bool = False) -> list[RequestRecord]:
        src = self.requests_classified_csv if classified else self.requests_csv
        if not src.exists():
            raise FileNotFoundError(f"no {src.name} in {self.path}")
        with src.open(newline="") as f:
            return [RequestRecord.from_row(row) for row in csv.DictReader(f)]

    def write_classified(self, records: Iterable[RequestRecord], cold_flags: list[bool]) -> None:
        """requests.csv plus a trailing `cold` column (reference analyze.py:402-419)."""
        records = list(records)
        if len(records) != len(cold_flags):
            raise ValueError(
                f"records ({len(records)}) and cold_flags ({len(cold_flags)}) "
                "must align one-to-one"
            )
        cols = REQUEST_CSV_COLUMNS + ["cold"]
        with self.requests_classified_csv.open("w", newline="") as f:
            w = csv.DictWriter(f, fieldnames=cols)
            w.writeheader()
            for r, cold in zip(records, cold_flags):
                row = r.to_row()
                row["cold"] = "1" if cold else "0"
                w.writerow(row)

    def read_cold_flags(self) -> list[bool]:
        if not self.requests_classified_csv.exists():
            return []
        with self.requests_classified_csv.open(newline="") as f:
            return [row.get("cold", "0") == "1" for row in csv.DictReader(f)]

    # -- json blobs --------------------------------------------------------
    def _read_json(self, p: Path) -> dict[str, Any]:
        if not p.exists():
            return {}
        with p.open() as f:
            return json.load(f)

    def _write_json(self, p: Path, obj: dict[str, Any]) -> None:
        p.parent.mkdir(parents=True, exist_ok=True)
        tmp = p.with_suffix(p.suffix + ".tmp")
        with tmp.open("w") as f:
            json.dump(obj, f, indent=2, sort_keys=True)
            f.write("\n")
        tmp.replace(p)

    def read_meta(self) -> dict[str, Any]:
        return self._read_json(self.meta_json)

    def write_meta(self, meta: dict[str, Any]) -> None:
        self._write_json(self.meta_json, meta)

    def read_results(self) -> dict[str, Any]:
        return self._read_json(self.results_json)

    def merge_into_results(self, update: dict[str, Any]) -> dict[str, Any]:
        """Read-modify-write results.json — the universal merge the reference
        performs in every stage (analyze.py:606-618 et al)."""
        from kserve_vllm_mini_tpu.core.schema import merge_results

        cur = merge_results(self.read_results(), update)
        self._write_json(self.results_json, cur)
        return cur

    def read_power(self) -> dict[str, Any]:
        return self._read_json(self.power_json)

    def write_power(self, obj: dict[str, Any]) -> None:
        self._write_json(self.power_json, obj)

    def read_energy(self) -> dict[str, Any]:
        return self._read_json(self.energy_json)

    def write_energy(self, obj: dict[str, Any]) -> None:
        self._write_json(self.energy_json, obj)

    def write_traces(self, obj: dict[str, Any]) -> None:
        self._write_json(self.traces_json, obj)

    def read_traces(self) -> dict[str, Any]:
        return self._read_json(self.traces_json)

    def write_io_probe(self, obj: dict[str, Any]) -> None:
        self._write_json(self.io_probe_json, obj)

    def read_io_probe(self) -> dict[str, Any]:
        return self._read_json(self.io_probe_json)

    def read_timeline(self) -> list[dict[str, Any]]:
        """Monitor samples from timeline.jsonl, oldest first. A kill
        mid-append truncates the last line — degrade by dropping it, the
        same tolerance the report applies to decision logs."""
        if not self.timeline_jsonl.exists():
            return []
        out: list[dict[str, Any]] = []
        for line in self.timeline_jsonl.read_text().splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            if isinstance(obj, dict):
                out.append(obj)
        return out


def window_bounds(records: list[RequestRecord]) -> tuple[float, float]:
    """[t0, t1] spanning the active test window (reference analyze.py:183-189)."""
    starts = [r.start_ts for r in records if r.start_ts > 0]
    ends = [r.end_ts for r in records if r.end_ts > 0]
    if not starts or not ends:
        return (0.0, 0.0)
    return (min(starts), max(ends))
