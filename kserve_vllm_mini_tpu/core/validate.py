"""Pre-flight config validation — bench stage 0.

TPU rebuild of the reference validator (/root/reference/scripts/
validate_config.py:16-155): catch known-bad combinations *before* a
20-minute deploy, with actionable messages. GPU-specific guards map to their
TPU equivalents:

- quantization compatibility: awq/gptq are CUDA-kernel formats -> error on
  TPU; int8/aqt pass; fp8 is rejected (no kernel path in this runtime)
- GPU-memory heuristic -> HBM-per-chip fit check from model size vs topology
- nvidia-smi autodetect -> jax.devices() probe (injectable for tests, the
  reference's fake-the-probe pattern, SURVEY.md §4.1)
"""

from __future__ import annotations

import argparse
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

import yaml

from kserve_vllm_mini_tpu.loadgen.arrivals import PATTERNS

HBM_GIB_PER_CHIP = {"v4": 32.0, "v5e": 16.0, "v5p": 95.0, "v6e": 32.0}
# fp8 deliberately NOT advertised: the in-repo runtime has no fp8 kernel
# path and v5e lacks native fp8 — a knob nothing executes is a lie
TPU_QUANT_OK = {"none", "bf16", "int8", "aqt-int8", "int4", "int4-awq"}
GPU_ONLY_QUANT = {"awq", "gptq", "autoawq", "marlin", "squeezellm"}

# rough parameter counts for HBM-fit estimates (bf16 bytes = 2/param + ~30%
# for KV cache and activations at serving batch sizes)
MODEL_SIZE_B = {"125m": 0.125, "1b": 1.5, "7b": 7.0, "8b": 8.0, "13b": 13.0,
                "34b": 34.0, "70b": 70.0, "8x7b": 47.0}  # 8x7b: Mixtral total params


@dataclass
class ValidationReport:
    errors: list[str] = field(default_factory=list)
    warnings: list[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.errors


def _model_size_hint(model: str) -> Optional[float]:
    m = model.lower()
    for hint, size in sorted(MODEL_SIZE_B.items(), key=lambda kv: -len(kv[0])):
        if hint in m:
            return size
    return None


def _chips_of_topology(topology: str) -> Optional[int]:
    try:
        return int(topology.rsplit("-", 1)[1])
    except (IndexError, ValueError):
        return None


def _generation_of_topology(topology: str) -> str:
    return topology.split("-")[0]


def validate_profile(
    profile: dict[str, Any],
    detect_devices: Optional[Callable[[], int]] = None,
) -> ValidationReport:
    rep = ValidationReport()
    pattern = profile.get("pattern", "steady")
    if pattern not in PATTERNS:
        rep.errors.append(
            f"unknown traffic pattern {pattern!r}; choose one of {sorted(PATTERNS)}"
        )
    concurrency = int(profile.get("concurrency", 1) or 0)
    if concurrency <= 0:
        rep.errors.append("concurrency must be >= 1")
    requests = int(profile.get("requests", 1) or 0)
    if requests <= 0:
        rep.errors.append("requests must be >= 1")

    max_tokens = int(profile.get("max_tokens", 64))
    max_model_len = int(profile.get("max_model_len", 4096))
    if max_tokens >= max_model_len:
        rep.errors.append(
            f"max_tokens ({max_tokens}) >= max_model_len ({max_model_len}): "
            "no room for the prompt — lower max_tokens or raise max_model_len"
        )
    elif max_tokens > 2048:
        rep.warnings.append(
            f"max_tokens={max_tokens} produces long decodes; p95 latency "
            "will be dominated by generation length — consider streaming SLOs"
        )

    quant = str(profile.get("quantization", "none")).lower()
    if quant in GPU_ONLY_QUANT:
        rep.errors.append(
            f"quantization '{quant}' requires CUDA kernels and cannot run on "
            "TPU — use 'int8' (AQT) instead"
        )
    elif quant == "fp8":
        rep.errors.append(
            "fp8 has no kernel path in this runtime (and v5e lacks native "
            "fp8) — use 'int8' weights and/or kv_cache_dtype: int8 instead"
        )
    elif quant not in TPU_QUANT_OK:
        rep.warnings.append(f"unrecognized quantization '{quant}'; proceeding unvalidated")

    # paged-KV scope (runtime/engine.py kv_layout): fail the combos the
    # engine would reject BEFORE anything deploys, stage-0 style
    kv_layout = str(profile.get("kv_layout", "dense"))
    if kv_layout not in ("dense", "paged"):
        rep.errors.append(
            f"unknown kv_layout '{kv_layout}'; known: dense, paged"
        )
    elif kv_layout == "paged":
        if profile.get("drafter"):
            rep.errors.append(
                "kv_layout: paged does not support a speculative drafter "
                "yet — drop 'drafter' or use kv_layout: dense"
            )
        pool = profile.get("kv_pool_blocks")
        if pool is not None and int(pool) < 1:
            rep.errors.append(f"kv_pool_blocks ({pool}) must be >= 1")
        blk = profile.get("kv_block_size")
        if blk is not None and int(blk) < 1:
            rep.errors.append(f"kv_block_size ({blk}) must be >= 1")

    # serving pipeline parallelism: layer-range stages via
    # parallel/serving_pp.py (pp-pure meshes). pp x tp is not composed —
    # reject that combination up front instead of letting
    # parallel/sharding.py raise mid-deploy.
    par = profile.get("parallelism") or {}
    pp = int(par.get("pp", 1) or 1)
    if pp > 1:
        extra = {
            a: int(par.get(a, 1) or 1)
            for a in ("tp", "dp", "sp", "ep")
            if int(par.get(a, 1) or 1) > 1
        }
        if extra:
            rep.errors.append(
                f"pp > 1 runs on pure-pp meshes (parallel/serving_pp.py "
                f"layer-range stages); drop {sorted(extra)} or pp — see "
                "docs/TOPOLOGY.md 'Pipeline parallelism'"
            )
        from kserve_vllm_mini_tpu.models.config import PRESETS

        model_name = str(profile.get("model", ""))
        n_layers = None
        if model_name in PRESETS:
            n_layers = PRESETS[model_name].n_layers
        else:
            # size-keyed fallback for non-preset names (Llama-family depths)
            size_b = _model_size_hint(model_name)
            n_layers = {7.0: 32, 8.0: 32, 13.0: 40, 34.0: 48, 47.0: 32, 70.0: 80}.get(size_b)
        if n_layers and n_layers % pp:
            rep.errors.append(
                f"pp={pp} does not divide the model's {n_layers} layers — "
                "the stage executor needs equal layer ranges"
            )

    topology = profile.get("topology")
    if topology:
        gen = _generation_of_topology(topology)
        chips = _chips_of_topology(topology)
        if gen not in HBM_GIB_PER_CHIP:
            rep.errors.append(
                f"unknown TPU generation in topology {topology!r}; "
                f"known: {sorted(HBM_GIB_PER_CHIP)}"
            )
        elif chips:
            size_b = _model_size_hint(str(profile.get("model", "")))
            if size_b is not None:
                bytes_per_param = (
                    0.5 if quant == "int4"
                    # int4-awq SERVES at 0.5 B/param, but calibration
                    # materializes the full-precision tree on device plus
                    # the quantized output (ops/awq.py memory note) — the
                    # startup peak, not the steady state, is what OOMs
                    else 2.5 if quant == "int4-awq"
                    else 1.0 if quant in ("int8", "aqt-int8")
                    else 2.0
                )
                need_gib = size_b * bytes_per_param * 1.3
                have_gib = HBM_GIB_PER_CHIP[gen] * chips
                if need_gib > have_gib:
                    rep.errors.append(
                        f"model (~{size_b:.0f}B params, {quant}) needs "
                        f"~{need_gib:.0f} GiB HBM but {topology} provides "
                        f"{have_gib:.0f} GiB — use a larger slice "
                        f"(e.g. {gen}-{chips * 2}) or quantize to int8"
                        + (" (int4-awq calibration holds the fp tree on "
                           "device: calibrate off-chip and serve the "
                           "quantized tree, or use plain int4)"
                           if quant == "int4-awq" else "")
                    )
                elif need_gib > 0.8 * have_gib:
                    rep.warnings.append(
                        f"model fits {topology} with <20% HBM headroom; "
                        "KV cache pressure will cap batch size"
                    )
            if detect_devices is not None:
                try:
                    n = detect_devices()
                except Exception:
                    n = 0
                if n and chips and n < chips:
                    rep.errors.append(
                        f"topology {topology} needs {chips} chips but only "
                        f"{n} TPU device(s) are visible"
                    )

    spec = profile.get("speculative", {})
    if spec and spec.get("enabled"):
        if not spec.get("draft_model"):
            rep.errors.append("speculative decoding enabled but no draft_model given")
        k = int(spec.get("num_draft_tokens", 4))
        if k > 16:
            rep.warnings.append(
                f"num_draft_tokens={k} is past the acceptance sweet spot; "
                "draft overhead usually dominates above ~8"
            )
    return rep


def jax_device_count() -> int:
    import jax

    return jax.device_count()


# -- CLI ---------------------------------------------------------------------

def register(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("--profile", required=True, help="Profile YAML path")
    parser.add_argument("--detect-devices", action="store_true",
                        help="Also probe visible TPU devices via JAX")


def run(args: argparse.Namespace) -> int:
    with open(args.profile) as f:
        profile = yaml.safe_load(f) or {}
    rep = validate_profile(
        profile, detect_devices=jax_device_count if args.detect_devices else None
    )
    for w in rep.warnings:
        print(f"WARNING: {w}")
    for e in rep.errors:
        print(f"ERROR: {e}")
    if rep.ok:
        print(f"validate: OK ({len(rep.warnings)} warning(s))")
        return 0
    print(f"validate: FAILED with {len(rep.errors)} error(s)")
    return 1
