"""Prompt-cache effectiveness probe: infer hit ratio from TTFT deltas.

Reference behavior (cache-probe.sh): run one deterministic load with a small
pool of repeated prompts and one with all-unique prompts (seed=42 prompt
sets, :83-134), then infer cache effectiveness from the TTFT difference with
a significance test (:229-364). The reference had to monkeypatch its load
generator to vary prompts per request (:163-210, a defect per SURVEY.md
§7.4); here prompt sets are first-class in the loadgen
(loadgen/prompts.py), so the probe is just two normal runs + statistics.

Inference method: a prefill served from cache skips prompt processing, so
repeat-set TTFTs collapse toward the decode floor. We estimate
``inferred_hit_ratio`` as the fraction of repeat-set TTFTs below the
unique-set 10th percentile (anything faster than effectively-all cache
misses), and report a Welch t-test on the means for significance (normal
approximation of the p-value — sample sizes here are ≥30 by default).
"""

from __future__ import annotations

import argparse
import json
import math
from pathlib import Path
from typing import Any, Optional, Sequence

from kserve_vllm_mini_tpu.analysis.metrics import percentile
from kserve_vllm_mini_tpu.core.rundir import RunDir


def welch_t(a: Sequence[float], b: Sequence[float]) -> tuple[float, float]:
    """Welch's t statistic for mean(a) != mean(b) and a two-sided p-value
    via the normal approximation (adequate for n >= ~30, which the probe's
    defaults guarantee)."""
    na, nb = len(a), len(b)
    if na < 2 or nb < 2:
        return 0.0, 1.0
    ma, mb = sum(a) / na, sum(b) / nb
    va = sum((x - ma) ** 2 for x in a) / (na - 1)
    vb = sum((x - mb) ** 2 for x in b) / (nb - 1)
    denom = math.sqrt(va / na + vb / nb)
    if denom == 0:
        return 0.0, 1.0
    t = (ma - mb) / denom
    p = math.erfc(abs(t) / math.sqrt(2.0))
    return t, p


def infer_cache_stats(
    repeat_ttfts: Sequence[float],
    unique_ttfts: Sequence[float],
    alpha: float = 0.05,
) -> dict[str, Any]:
    """Pure statistics core (unit-testable without any endpoint)."""
    if not repeat_ttfts or not unique_ttfts:
        return {"valid": False, "reason": "missing TTFT samples"}
    mean_r = sum(repeat_ttfts) / len(repeat_ttfts)
    mean_u = sum(unique_ttfts) / len(unique_ttfts)
    t, p = welch_t(unique_ttfts, repeat_ttfts)
    significant = p < alpha and mean_r < mean_u
    threshold = percentile(list(unique_ttfts), 10.0)
    hits = sum(1 for x in repeat_ttfts if x < threshold)
    return {
        "valid": True,
        "repeat_ttft_mean_ms": mean_r,
        "repeat_ttft_p50_ms": percentile(list(repeat_ttfts), 50.0),
        "unique_ttft_mean_ms": mean_u,
        "unique_ttft_p50_ms": percentile(list(unique_ttfts), 50.0),
        "ttft_delta_ms": mean_u - mean_r,
        "ttft_speedup": mean_u / mean_r if mean_r > 0 else None,
        "t_statistic": t,
        "p_value": p,
        "significant": significant,
        "hit_threshold_ms": threshold,
        # only claim hits the statistics support
        "inferred_hit_ratio": (hits / len(repeat_ttfts)) if significant else 0.0,
        "samples": {"repeat": len(repeat_ttfts), "unique": len(unique_ttfts)},
    }


def run_cache_probe(
    url: str,
    model: str = "default",
    backend: str = "openai",
    requests: int = 60,
    concurrency: int = 6,
    max_tokens: int = 16,
    input_tokens: int = 256,
    seed: int = 42,
    run_root: Optional[Path] = None,
) -> dict[str, Any]:
    """Two loads (repeat-pool then unique), identical otherwise; returns the
    inference dict and leaves both run dirs on disk for audit."""
    from kserve_vllm_mini_tpu.loadgen.runner import LoadConfig, run_load

    # warmup phase: the first requests to a fresh runtime pay XLA compile /
    # model-load costs; without this the first measured set (repeat) absorbs
    # them and the TTFT comparison is biased toward "no cache effect" or
    # worse, inverted. BOTH prompt sets warm up: a caching server executes
    # different code for a cache hit than a miss (e.g. suffix-only prefill),
    # and measuring its first-ever hits would charge their compile/setup
    # costs to exactly the phenomenon under measurement. The repeat warmup
    # uses the measured pool's seed on purpose — the measurement is of the
    # STEADY-STATE hit path, which is what capacity math needs.
    for warm_set, warm_seed in (("unique", seed + 1000), ("repeat", seed)):
        warmup_dir = RunDir.create(root=run_root or "runs")
        warmup_dir.path.mkdir(parents=True, exist_ok=True)
        run_load(
            LoadConfig(
                url=url, model=model, backend=backend,
                num_requests=max(4, concurrency), concurrency=concurrency,
                max_tokens=max_tokens, input_tokens=input_tokens,
                prompt_set=warm_set, seed=warm_seed,
            ),
            warmup_dir,
        )

    ttfts: dict[str, list[float]] = {}
    run_dirs: dict[str, str] = {}
    for prompt_set in ("repeat", "unique"):
        run_dir = RunDir.create(root=run_root or "runs")
        run_dir.path.mkdir(parents=True, exist_ok=True)
        cfg = LoadConfig(
            url=url,
            model=model,
            backend=backend,
            num_requests=requests,
            concurrency=concurrency,
            pattern="steady",
            max_tokens=max_tokens,
            input_tokens=input_tokens,
            prompt_set=prompt_set,
            seed=seed,
        )
        records = run_load(cfg, run_dir)
        ttfts[prompt_set] = [r.ttft_ms for r in records if r.ok and r.ttft_ms > 0]
        run_dirs[prompt_set] = str(run_dir.path)

    stats = infer_cache_stats(ttfts["repeat"], ttfts["unique"])
    stats["run_dirs"] = run_dirs
    if stats.get("valid"):
        # expose to the gate's cache_hit_ratio_min budget via the repeat run
        RunDir(run_dirs["repeat"]).merge_into_results(
            {"cache_hit_ratio": stats["inferred_hit_ratio"]}
        )
    return stats


# -- CLI ---------------------------------------------------------------------

def register(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("--url", required=True)
    parser.add_argument("--model", default="default")
    parser.add_argument("--backend", default="openai")
    parser.add_argument("--requests", type=int, default=60,
                        help="Per prompt set (two sets are run)")
    parser.add_argument("--concurrency", type=int, default=6)
    parser.add_argument("--max-tokens", type=int, default=16)
    parser.add_argument("--input-tokens", type=int, default=256,
                        help="Prompt length — longer prompts amplify the cache signal")
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--output", default=None)


def run(args: argparse.Namespace) -> int:
    stats = run_cache_probe(
        args.url, model=args.model, backend=args.backend, requests=args.requests,
        concurrency=args.concurrency, max_tokens=args.max_tokens,
        input_tokens=args.input_tokens, seed=args.seed,
    )
    if not stats.get("valid"):
        print(f"cache-probe: invalid ({stats.get('reason')})")
        return 1
    print(
        f"cache-probe: repeat TTFT {stats['repeat_ttft_mean_ms']:.1f}ms vs "
        f"unique {stats['unique_ttft_mean_ms']:.1f}ms "
        f"(delta {stats['ttft_delta_ms']:.1f}ms, p={stats['p_value']:.4f})"
    )
    verdict = (
        f"cache ACTIVE — inferred hit ratio {stats['inferred_hit_ratio']:.2f}"
        if stats["significant"]
        else "no significant cache effect detected"
    )
    print(f"cache-probe: {verdict}")
    if args.output:
        Path(args.output).write_text(json.dumps(stats, indent=2))
    return 0
