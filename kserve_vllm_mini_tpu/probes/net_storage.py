"""Network/storage IO probe — feeds the report's headroom classifier.

Reference behavior (/root/reference/tools/net_storage_probe.py:16-77):
endpoint RTT p50/p95 from repeated small requests, plus model-object fetch
throughput (MB/s) from a storage URL. GCS paths replace s3://; plain HTTP(S)
fetches are measured directly.
"""

from __future__ import annotations

import argparse
import json
import time
import urllib.request
from typing import Any, Optional

from kserve_vllm_mini_tpu.analysis.metrics import percentile


def measure_http_rtt(
    url: str, samples: int = 20, timeout_s: float = 5.0, path: str = "/healthz"
) -> dict[str, Any]:
    """p50/p95 RTT (ms) of small GETs against the endpoint."""
    rtts: list[float] = []
    target = url.rstrip("/") + path
    for _ in range(samples):
        t0 = time.time()
        try:
            with urllib.request.urlopen(target, timeout=timeout_s) as resp:
                resp.read(64)
            rtts.append((time.time() - t0) * 1000.0)
        except Exception:
            continue
    out: dict[str, Any] = {"rtt_samples": len(rtts), "rtt_target": path}
    if rtts:
        out["network_rtt_p50_ms"] = percentile(rtts, 50)
        out["network_rtt_p95_ms"] = percentile(rtts, 95)
    return out


def measure_object_fetch(
    object_url: str, max_bytes: int = 64 * 1024 * 1024, timeout_s: float = 60.0
) -> dict[str, Any]:
    """Sequential-read throughput (MB/s) of a model artifact over HTTP(S)/GCS.

    gs:// URLs rewrite to the public GCS HTTP endpoint; private buckets need
    a pre-signed URL, as with the reference's S3 probe."""
    url = object_url
    if url.startswith("gs://"):
        url = "https://storage.googleapis.com/" + url[len("gs://"):]
    t0 = time.time()
    n = 0
    try:
        with urllib.request.urlopen(url, timeout=timeout_s) as resp:
            while n < max_bytes:
                chunk = resp.read(min(1 << 20, max_bytes - n))
                if not chunk:
                    break
                n += len(chunk)
    except Exception as e:
        return {"storage_error": f"{type(e).__name__}: {e}", "storage_bytes": n}
    dt = max(time.time() - t0, 1e-9)
    return {
        "storage_bytes": n,
        "storage_fetch_mbps": n / dt / (1024 * 1024),
        "storage_fetch_seconds": dt,
    }


# -- CLI ---------------------------------------------------------------------

def register(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("--url", required=True, help="Endpoint base URL")
    parser.add_argument("--object-url", default=None,
                        help="Model artifact URL (gs:// or https://) for fetch test")
    parser.add_argument("--samples", type=int, default=20)
    parser.add_argument("--run-dir", default=None, help="Write io_probe.json here")


def run(args: argparse.Namespace) -> int:
    out = measure_http_rtt(args.url, samples=args.samples)
    if args.object_url:
        out.update(measure_object_fetch(args.object_url))
    print(json.dumps(out, indent=2))
    if args.run_dir:
        from kserve_vllm_mini_tpu.core.rundir import RunDir

        RunDir(args.run_dir).write_io_probe(out)
    return 0
