"""TPU topology catalog — the analog of the reference's MIG profile sheets.

The reference partitions GPUs into MIG slices (profiles/mig/*.yaml,
docs/MIG.md); on TPU the unit of partitioning is the *slice topology* of a
GKE TPU node pool (SURVEY.md §2.2 "MIG's analog is TPU topology slices").
Each entry maps a human name (``v5e-4``) to the GKE scheduling labels and
the chip count used for resources, pricing, and the topology sweep.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class TpuTopology:
    name: str               # human/sweep name, e.g. "v5e-4"
    accelerator: str        # cloud.google.com/gke-tpu-accelerator label
    topology: str           # cloud.google.com/gke-tpu-topology label
    chips: int              # google.com/tpu resource per pod
    hosts: int = 1          # pods in the multi-host set (>1 => v5p pods span hosts)
    hbm_gib_per_chip: float = 16.0
    tdp_w_per_chip: float = 170.0   # modeled-power fallback (energy provenance: modeled)


# v5e: 16 GiB HBM/chip, single-host up to 8 chips. v5p: 95 GiB HBM/chip,
# 4 chips/host, pods scale by adding hosts over ICI.
TOPOLOGIES: dict[str, TpuTopology] = {
    t.name: t
    for t in (
        TpuTopology("v5e-1", "tpu-v5-lite-podslice", "1x1", 1),
        TpuTopology("v5e-4", "tpu-v5-lite-podslice", "2x2", 4),
        TpuTopology("v5e-8", "tpu-v5-lite-podslice", "2x4", 8),
        TpuTopology("v5p-8", "tpu-v5p-slice", "2x2x1", 4, hosts=2,
                    hbm_gib_per_chip=95.0, tdp_w_per_chip=350.0),
        TpuTopology("v5p-16", "tpu-v5p-slice", "2x2x2", 4, hosts=4,
                    hbm_gib_per_chip=95.0, tdp_w_per_chip=350.0),
        TpuTopology("v6e-8", "tpu-v6e-slice", "2x4", 8,
                    hbm_gib_per_chip=32.0, tdp_w_per_chip=200.0),
    )
}


# layout-suffixed names the runtime's mesh presets implement. Kept as a
# literal so the deploy layer stays importable without jax; a test asserts
# it matches parallel/mesh.py TOPOLOGY_PRESETS.
RUNTIME_LAYOUT_PRESETS = {"v5e-8-longctx", "v5p-16-longctx"}


def get_topology(name: str) -> TpuTopology:
    # logical-layout suffixes ride on physical slices: "v5e-8-longctx" is
    # the same 2x4 podslice as "v5e-8" with a tp x sp mesh layout inside
    # the runtime (parallel/mesh.py TOPOLOGY_PRESETS). Resolve the physical
    # slice but keep the requested name so the deploy env can hand the
    # layout to the runtime (KVMINI_TOPOLOGY). Only layouts the RUNTIME
    # actually knows are accepted — rendering an unknown one would ship a
    # manifest that CrashLoops at boot instead of failing here.
    if name.endswith("-longctx"):
        from dataclasses import replace as _replace

        if name not in RUNTIME_LAYOUT_PRESETS:
            raise ValueError(
                f"unknown layout topology {name!r} (runtime presets: "
                f"{', '.join(sorted(RUNTIME_LAYOUT_PRESETS))})"
            )
        base = get_topology(name[: -len("-longctx")])
        return _replace(base, name=name)
    try:
        return TOPOLOGIES[name]
    except KeyError:
        raise ValueError(
            f"unknown TPU topology {name!r} (known: {', '.join(sorted(TOPOLOGIES))})"
        ) from None


def total_chips(t: TpuTopology) -> int:
    return t.chips * t.hosts


def total_hbm_gib(t: TpuTopology) -> float:
    return total_chips(t) * t.hbm_gib_per_chip
