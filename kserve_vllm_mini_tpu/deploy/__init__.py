"""Deployment layer (L1): KServe InferenceService manifests for GKE TPU
node pools, per-backend deploy specs, and cluster preflight checks.

Replaces the reference's sed-patched isvc.yaml + per-backend deploy.sh
(/root/reference/deploy.sh:91-99, runners/backends/*/deploy.sh) with
structured manifest rendering and an injectable kubectl runner so the whole
layer is unit-testable without a cluster (SURVEY.md §4.3 mock-kubectl
pattern, §7.4 "no sed-based YAML patching").
"""

from kserve_vllm_mini_tpu.deploy.topology import TOPOLOGIES, TpuTopology, get_topology

__all__ = ["TOPOLOGIES", "TpuTopology", "get_topology"]
