"""Injectable kubectl runner for the deploy/compare/chaos layers.

The reference shells out to kubectl everywhere and its CI replaces the
binary with a stub script (SURVEY.md §4.3) — here the substitution point is
a Python callable instead, so tests inject a fake without touching PATH.
All real calls degrade gracefully: no kubectl / no cluster -> KubectlResult
with ok=False, never an exception (reference analyze.py:29-31 pattern).
"""

from __future__ import annotations

import shutil
import subprocess
import time
from dataclasses import dataclass
from typing import Callable, Optional, Sequence


@dataclass
class KubectlResult:
    ok: bool
    stdout: str = ""
    stderr: str = ""
    returncode: int = -1


# signature: (args, stdin_text, timeout_s) -> KubectlResult
KubectlFn = Callable[[Sequence[str], Optional[str], float], KubectlResult]


def real_kubectl(
    args: Sequence[str], stdin_text: Optional[str] = None, timeout_s: float = 60.0
) -> KubectlResult:
    if shutil.which("kubectl") is None:
        return KubectlResult(False, stderr="kubectl not found on PATH")
    try:
        proc = subprocess.run(
            ["kubectl", *args],
            input=stdin_text,
            capture_output=True,
            text=True,
            timeout=timeout_s,
        )
    except (subprocess.TimeoutExpired, OSError) as e:
        return KubectlResult(False, stderr=str(e))
    return KubectlResult(
        proc.returncode == 0, proc.stdout, proc.stderr, proc.returncode
    )


class Kubectl:
    """Thin stateful wrapper bound to one runner function."""

    def __init__(self, runner: KubectlFn = real_kubectl):
        self._run = runner

    def run(
        self,
        args: Sequence[str],
        stdin_text: Optional[str] = None,
        timeout_s: float = 60.0,
    ) -> KubectlResult:
        return self._run(args, stdin_text, timeout_s)

    def apply(self, manifest_yaml: str, namespace: Optional[str] = None) -> KubectlResult:
        args = ["apply", "-f", "-"]
        if namespace:
            args += ["-n", namespace]
        return self.run(args, stdin_text=manifest_yaml)

    def delete(
        self, kind: str, name: str, namespace: str, ignore_not_found: bool = True
    ) -> KubectlResult:
        args = ["delete", kind, name, "-n", namespace, "--wait=false"]
        if ignore_not_found:
            args.append("--ignore-not-found=true")
        return self.run(args)

    def ensure_namespace(self, namespace: str) -> KubectlResult:
        res = self.run(["get", "namespace", namespace])
        if res.ok:
            return res
        return self.run(["create", "namespace", namespace])

    def wait_ready(
        self, kind: str, name: str, namespace: str, timeout_s: float = 600.0
    ) -> KubectlResult:
        return self.run(
            [
                "wait",
                f"--for=condition=Ready",
                f"{kind}/{name}",
                "-n",
                namespace,
                f"--timeout={int(timeout_s)}s",
            ],
            timeout_s=timeout_s + 30.0,
        )

    def isvc_url(self, name: str, namespace: str) -> Optional[str]:
        res = self.run(
            [
                "get",
                "inferenceservice",
                name,
                "-n",
                namespace,
                "-o",
                "jsonpath={.status.url}",
            ]
        )
        url = res.stdout.strip()
        return url or None if res.ok else None

    def wait_ready_timed(
        self, kind: str, name: str, namespace: str, timeout_s: float = 600.0
    ) -> tuple[KubectlResult, float]:
        """wait_ready plus elapsed seconds — MTTR / deploy-time instrument
        (reference chaos_harness.sh:99-109 wall-clocks `kubectl wait`)."""
        t0 = time.time()
        res = self.wait_ready(kind, name, namespace, timeout_s)
        return res, time.time() - t0
