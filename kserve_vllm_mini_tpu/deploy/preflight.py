"""Pre-deploy environment checks.

Behavioral spec: /root/reference/scripts/preflight-checks.sh:25-53 (kubectl
present, context reachable, KServe CRDs, accelerator nodes, object-store
creds) with TPU substitutions: accelerator nodes are located by the
``cloud.google.com/gke-tpu-accelerator`` label and the local path checks
that JAX can enumerate devices (there is no nvidia-smi analog — the device
census IS the probe). Each check is data, so callers (bench stage 0, the
chaos harness) can gate on severity rather than parsing text.
"""

from __future__ import annotations

import argparse
import json
from dataclasses import dataclass
from typing import Optional

from kserve_vllm_mini_tpu.deploy.kubectl import Kubectl


@dataclass
class Check:
    name: str
    ok: bool
    required: bool
    detail: str = ""


def _cluster_checks(kc: Kubectl, namespace: str = "kvmini-tpu") -> list[Check]:
    checks: list[Check] = []
    ctx = kc.run(["config", "current-context"])
    checks.append(
        Check("kubectl-context", ctx.ok, True,
              ctx.stdout.strip() or ctx.stderr.strip())
    )
    if not ctx.ok:
        return checks

    api = kc.run(["get", "--raw", "/healthz"], timeout_s=15.0)
    checks.append(Check("cluster-reachable", api.ok, True, api.stderr.strip()))

    crd = kc.run(["get", "crd", "inferenceservices.serving.kserve.io"])
    checks.append(Check("kserve-crd", crd.ok, True, crd.stderr.strip()))

    nodes = kc.run(
        ["get", "nodes", "-l", "cloud.google.com/gke-tpu-accelerator",
         "-o", "jsonpath={.items[*].metadata.name}"]
    )
    tpu_nodes = nodes.stdout.split() if nodes.ok else []
    checks.append(
        Check("tpu-nodes", bool(tpu_nodes), False,
              f"{len(tpu_nodes)} TPU node(s)" if nodes.ok else nodes.stderr.strip())
    )

    secret = kc.run(["get", "secret", "storage-config", "-n", namespace])
    checks.append(
        Check("storage-credentials", secret.ok, False,
              "" if secret.ok else "no storage-config secret (ok for public models)")
    )
    return checks


def _local_checks() -> list[Check]:
    checks: list[Check] = []
    try:
        import jax

        devices = jax.devices()
        kinds = sorted({d.platform for d in devices})
        checks.append(
            Check("jax-devices", True, True,
                  f"{len(devices)} device(s): {', '.join(kinds)}")
        )
        has_tpu = any(d.platform == "tpu" for d in devices)
        checks.append(
            Check("tpu-present", has_tpu, False,
                  f"{sum(d.platform == 'tpu' for d in devices)} TPU device(s)"
                  if has_tpu
                  else "no TPU attached — runtime will run on " + ",".join(kinds))
        )
    except Exception as e:  # jax import or backend init failure
        checks.append(Check("jax-devices", False, True, f"{type(e).__name__}: {e}"))
    return checks


def preflight(
    mode: str = "cluster",
    kubectl: Optional[Kubectl] = None,
    namespace: str = "kvmini-tpu",
) -> list[Check]:
    """mode: cluster | local | all."""
    checks: list[Check] = []
    if mode in ("cluster", "all"):
        checks += _cluster_checks(kubectl or Kubectl(), namespace)
    if mode in ("local", "all"):
        checks += _local_checks()
    return checks


def passed(checks: list[Check]) -> bool:
    return all(c.ok for c in checks if c.required)


# -- CLI ---------------------------------------------------------------------

def register(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("--mode", default="cluster", choices=("cluster", "local", "all"))
    parser.add_argument("--namespace", default="kvmini-tpu")
    parser.add_argument("--json", action="store_true")


def run(args: argparse.Namespace) -> int:
    checks = preflight(args.mode, namespace=args.namespace)
    if args.json:
        print(json.dumps([c.__dict__ for c in checks], indent=2))
    else:
        for c in checks:
            flag = "PASS" if c.ok else ("FAIL" if c.required else "warn")
            print(f"[{flag:>4}] {c.name:<22} {c.detail}")
    return 0 if passed(checks) else 1
