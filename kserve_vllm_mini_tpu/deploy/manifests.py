"""InferenceService manifest rendering + deploy orchestration.

Replaces the reference's sed-patched template (deploy.sh:91-99, isvc.yaml) —
manifests are built as Python dicts and serialized once, so every knob is a
typed parameter and nothing depends on the template's line layout. TPU
scheduling follows GKE conventions: nodeSelector on
``cloud.google.com/gke-tpu-accelerator`` + ``gke-tpu-topology`` and a
``google.com/tpu`` chip resource (SURVEY.md §7.2.6).

Deploy flow mirrors reference deploy.sh:86-130: ensure namespace -> apply ->
wait Ready (timed: TPU pools cold-start in minutes, SURVEY.md §7.3.4) ->
resolve URL -> smoke request.
"""

from __future__ import annotations

import argparse
import json
import sys
from dataclasses import dataclass, field
from typing import Any, Optional

import yaml

from kserve_vllm_mini_tpu.deploy.backends import Backend, BackendConfig, get_backend
from kserve_vllm_mini_tpu.deploy.kubectl import Kubectl
from kserve_vllm_mini_tpu.deploy.topology import TpuTopology, get_topology


@dataclass
class DeploySpec:
    name: str
    namespace: str = "kvmini-tpu"
    backend: str = "jax-native"
    topology: str = "v5e-8"
    config: BackendConfig = field(default_factory=BackendConfig)
    # Knative autoscaling knobs — the autoscale sweep's dimensions
    # (reference sweeps/autoscale-sweep.sh:25-29)
    min_scale: int = 0
    max_scale: int = 3
    container_concurrency: int = 0
    scale_to_zero_grace: str = ""        # e.g. "30s"
    stable_window: str = ""              # e.g. "60s"
    panic_window_pct: str = ""           # e.g. "10.0"
    cpu: str = "8"
    memory: str = "32Gi"
    service_account: str = ""


def render_isvc(spec: DeploySpec) -> dict[str, Any]:
    backend = get_backend(spec.backend)
    topo = get_topology(spec.topology)
    annotations: dict[str, str] = {
        "autoscaling.knative.dev/min-scale": str(spec.min_scale),
        "autoscaling.knative.dev/max-scale": str(spec.max_scale),
    }
    if spec.scale_to_zero_grace:
        annotations["autoscaling.knative.dev/scale-to-zero-grace-period"] = (
            spec.scale_to_zero_grace
        )
    if spec.stable_window:
        annotations["autoscaling.knative.dev/window"] = spec.stable_window
    if spec.panic_window_pct:
        annotations["autoscaling.knative.dev/panic-window-percentage"] = (
            spec.panic_window_pct
        )

    env = backend.env_fn(spec.config, topo)
    args = backend.args_fn(spec.config, topo)
    container: dict[str, Any] = {
        "name": "kserve-container",
        "image": backend.image,
        "env": [{"name": k, "value": v} for k, v in sorted(env.items())],
        "ports": [{"containerPort": backend.port, "protocol": "TCP"}],
        "readinessProbe": {
            "httpGet": {"path": backend.readiness_path, "port": backend.port},
            "initialDelaySeconds": 30,
            "periodSeconds": 10,
            # model load + XLA compile can take minutes on a fresh pool
            "failureThreshold": 60,
        },
        "resources": {
            "requests": {
                "cpu": spec.cpu,
                "memory": spec.memory,
                "google.com/tpu": str(topo.chips),
            },
            "limits": {"google.com/tpu": str(topo.chips)},
        },
    }
    if args:
        container["args"] = args

    predictor: dict[str, Any] = {
        "containers": [container],
        "nodeSelector": {
            "cloud.google.com/gke-tpu-accelerator": topo.accelerator,
            "cloud.google.com/gke-tpu-topology": topo.topology,
        },
    }
    if spec.container_concurrency:
        predictor["containerConcurrency"] = spec.container_concurrency
    if spec.service_account:
        predictor["serviceAccountName"] = spec.service_account
    if topo.hosts > 1:
        # multi-host slice: KServe schedules the leader; workers run the
        # same image (the runtime elects roles from TPU_WORKER_ID injected
        # by the GKE device plugin) and must declare their own PodSpec —
        # a bare {size} renders worker pods with no containers.
        worker_container = {
            "name": "worker-container",
            "image": backend.image,
            "env": [{"name": k, "value": v} for k, v in sorted(env.items())],
            "resources": {
                # topo.chips is per-pod (per host), matching the leader's
                "requests": {
                    "cpu": spec.cpu,
                    "memory": spec.memory,
                    "google.com/tpu": str(topo.chips),
                },
                "limits": {"google.com/tpu": str(topo.chips)},
            },
        }
        predictor["workerSpec"] = {
            "size": topo.hosts - 1,
            "containers": [worker_container],
            "nodeSelector": dict(predictor["nodeSelector"]),
        }

    return {
        "apiVersion": "serving.kserve.io/v1beta1",
        "kind": "InferenceService",
        "metadata": {
            "name": spec.name,
            "namespace": spec.namespace,
            "annotations": annotations,
            "labels": {
                "app.kubernetes.io/managed-by": "kvmini-tpu",
                "kvmini-tpu/backend": spec.backend,
                "kvmini-tpu/topology": spec.topology,
            },
        },
        "spec": {"predictor": predictor},
    }


def render_yaml(spec: DeploySpec) -> str:
    return yaml.safe_dump(render_isvc(spec), sort_keys=False, default_flow_style=False)


@dataclass
class DeployOutcome:
    ok: bool
    url: Optional[str] = None
    deploy_seconds: float = 0.0
    error: str = ""


def deploy(
    spec: DeploySpec,
    kubectl: Optional[Kubectl] = None,
    wait_timeout_s: float = 900.0,
) -> DeployOutcome:
    kc = kubectl or Kubectl()
    ns = kc.ensure_namespace(spec.namespace)
    if not ns.ok:
        return DeployOutcome(False, error=f"namespace: {ns.stderr.strip()}")
    applied = kc.apply(render_yaml(spec), namespace=spec.namespace)
    if not applied.ok:
        return DeployOutcome(False, error=f"apply: {applied.stderr.strip()}")
    waited, elapsed = kc.wait_ready_timed(
        "inferenceservice", spec.name, spec.namespace, wait_timeout_s
    )
    if not waited.ok:
        return DeployOutcome(
            False, deploy_seconds=elapsed, error=f"wait: {waited.stderr.strip()}"
        )
    url = kc.isvc_url(spec.name, spec.namespace)
    return DeployOutcome(True, url=url, deploy_seconds=elapsed)


def teardown(spec: DeploySpec, kubectl: Optional[Kubectl] = None) -> bool:
    kc = kubectl or Kubectl()
    return kc.delete("inferenceservice", spec.name, spec.namespace).ok


def spec_from_args(args: argparse.Namespace) -> DeploySpec:
    cfg = BackendConfig(
        model_uri=args.model_uri or "",
        model_id=args.model_id,
        tensor_parallel=args.tensor_parallel,
        pipeline_parallel=args.pipeline_parallel,
        pp_microbatches=args.pp_microbatches,
        quantization=args.quantization,
        max_model_len=args.max_model_len,
        drafter_model_id=args.drafter or "",
    )
    return DeploySpec(
        name=args.name,
        namespace=args.namespace,
        backend=args.backend,
        topology=args.topology,
        config=cfg,
        min_scale=args.min_scale,
        max_scale=args.max_scale,
        container_concurrency=args.container_concurrency,
    )


# -- CLI ---------------------------------------------------------------------

def register(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("--name", default="kvmini-llm")
    parser.add_argument("--namespace", default="kvmini-tpu")
    parser.add_argument("--backend", default="jax-native",
                        help="jetstream | vllm-tpu | jax-native")
    parser.add_argument("--topology", default="v5e-8",
                        help="TPU slice (v5e-1/v5e-4/v5e-8/v5p-8/v5p-16/v6e-8)")
    parser.add_argument("--model-uri", default=None, help="gs:// or s3:// model store")
    parser.add_argument("--model-id", default="meta-llama/Llama-3.1-8B-Instruct")
    parser.add_argument("--pipeline-parallel", type=int, default=0,
                        help="Serving PP stages (layer-range; pure-pp mesh) "
                             "forwarded to the jax-native runtime as KVMINI_PP")
    parser.add_argument("--pp-microbatches", type=int, default=1,
                        help="GPipe slot groups per step with --pipeline-parallel "
                             "(jax-native; forwarded as KVMINI_PP_MICROBATCHES)")
    parser.add_argument("--tensor-parallel", type=int, default=0,
                        help="TP size (0 = all chips in the slice)")
    parser.add_argument("--quantization", default="none")
    parser.add_argument("--max-model-len", type=int, default=4096)
    parser.add_argument("--drafter", default=None, help="speculative-decoding draft model")
    parser.add_argument("--min-scale", type=int, default=0)
    parser.add_argument("--max-scale", type=int, default=3)
    parser.add_argument("--container-concurrency", type=int, default=0)
    parser.add_argument("--render-only", action="store_true",
                        help="print the manifest, do not touch a cluster")
    parser.add_argument("--teardown", action="store_true", help="delete the service")
    parser.add_argument("--wait-timeout", type=float, default=900.0)
    parser.add_argument("--json", action="store_true", help="machine-readable outcome")


def run(args: argparse.Namespace) -> int:
    spec = spec_from_args(args)
    if args.render_only:
        print(render_yaml(spec))
        return 0
    if args.teardown:
        ok = teardown(spec)
        print(f"deploy: teardown {'ok' if ok else 'FAILED'}")
        return 0 if ok else 1
    outcome = deploy(spec, wait_timeout_s=args.wait_timeout)
    if args.json:
        print(json.dumps(outcome.__dict__))
    elif outcome.ok:
        print(f"deploy: ready in {outcome.deploy_seconds:.1f}s at {outcome.url}")
    else:
        print(f"deploy: FAILED: {outcome.error}", file=sys.stderr)
    return 0 if outcome.ok else 1
