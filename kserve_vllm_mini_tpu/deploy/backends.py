"""Serving-backend registry: how each TPU runtime is containerized, flagged,
and spoken to.

The analog of the reference's runners/backends/{vllm,tgi,triton}/deploy.sh —
but as data + one renderer instead of three divergent shell scripts
(the drift between those scripts is called out in SURVEY.md §7.1). Each
backend declares its image, port, readiness path, loadgen protocol adapter,
and a function from BackendConfig -> container env, so tensor-parallel size,
quantization, and context length are explicit knobs the sweeps can drive
(reference vllm/deploy.sh:78-83 TENSOR_PARALLEL_SIZE / MAX_MODEL_LEN).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional

from kserve_vllm_mini_tpu.deploy.topology import TpuTopology, total_chips


@dataclass
class BackendConfig:
    model_uri: str = ""
    model_id: str = "meta-llama/Llama-3.1-8B-Instruct"
    tensor_parallel: int = 0          # 0 => all chips in the slice
    pipeline_parallel: int = 0        # 0/1 => off; >1 => layer-range stages
                                      # (jax-native: pure-pp mesh, serving_pp.py;
                                      # vllm-tpu: --pipeline-parallel-size)
    pp_microbatches: int = 1          # jax-native: GPipe slot groups per step
    quantization: str = "none"        # none | int8 | int4 (fp8: no kernel path)
    quant_mode: str = "dequant"       # jax-native only: dequant | w8a8
                                      # (int8 MXU contraction, ops/qmatmul.py)
    kv_cache_dtype: str = "auto"
    max_model_len: int = 4096
    max_batch_size: int = 64
    drafter_model_id: str = ""        # speculative decoding drafter
    extra_env: dict[str, str] = field(default_factory=dict)

    def effective_tp(self, topo: TpuTopology) -> int:
        """tp defaulting to the whole slice — divided by pp when a backend
        composes both (vllm-tpu), so tp x pp never exceeds the chips."""
        if self.tensor_parallel:
            return self.tensor_parallel
        return total_chips(topo) // max(self.pipeline_parallel, 1)


@dataclass(frozen=True)
class Backend:
    name: str
    image: str
    port: int
    protocol: str                     # loadgen adapter: openai | jetstream | kserve_v2
    readiness_path: str
    env_fn: Callable[[BackendConfig, TpuTopology], dict[str, str]]
    args_fn: Callable[[BackendConfig, TpuTopology], list[str]] = lambda c, t: []


def _require_no_pp(cfg: BackendConfig, backend: str) -> None:
    if cfg.pipeline_parallel > 1:
        raise ValueError(
            f"{backend} has no pipeline-parallel knob; drop "
            "--pipeline-parallel or use the jax-native/vllm-tpu backend"
        )


def _jetstream_env(cfg: BackendConfig, topo: TpuTopology) -> dict[str, str]:
    _require_no_pp(cfg, "jetstream")
    env = {
        "MODEL_ID": cfg.model_id,
        "TOKENIZER_PATH": cfg.model_uri or cfg.model_id,
        "TPU_CHIPS": str(total_chips(topo)),
        "ICI_TENSOR_PARALLELISM": str(cfg.effective_tp(topo)),
        "MAX_PREFILL_LENGTH": str(cfg.max_model_len // 2),
        "MAX_TARGET_LENGTH": str(cfg.max_model_len),
        "BATCH_SIZE": str(cfg.max_batch_size),
    }
    if cfg.quantization != "none":
        env["QUANTIZATION"] = cfg.quantization   # jetstream int8 weight/kv configs
    if cfg.kv_cache_dtype != "auto":
        # KV-cache quantization is independent of weight quantization
        env["QUANTIZE_KVCACHE"] = "true"
        env["KV_CACHE_DTYPE"] = cfg.kv_cache_dtype
    if cfg.drafter_model_id:
        env["DRAFTER_MODEL_ID"] = cfg.drafter_model_id
    env.update(cfg.extra_env)
    return env


def _vllm_tpu_env(cfg: BackendConfig, topo: TpuTopology) -> dict[str, str]:
    env = {
        "MODEL_ID": cfg.model_id,
        "VLLM_TENSOR_PARALLEL_SIZE": str(cfg.effective_tp(topo)),
        "MAX_MODEL_LEN": str(cfg.max_model_len),
        "VLLM_USE_V1": "1",
    }
    if cfg.model_uri:
        env["MODEL_URI"] = cfg.model_uri
    if cfg.quantization != "none":
        env["QUANTIZATION"] = cfg.quantization
    if cfg.kv_cache_dtype != "auto":
        env["KV_CACHE_DTYPE"] = cfg.kv_cache_dtype
    env.update(cfg.extra_env)
    return env


def _vllm_tpu_args(cfg: BackendConfig, topo: TpuTopology) -> list[str]:
    args = [
        f"--model={cfg.model_uri or cfg.model_id}",
        f"--tensor-parallel-size={cfg.effective_tp(topo)}",
        f"--max-model-len={cfg.max_model_len}",
        f"--max-num-seqs={cfg.max_batch_size}",
    ]
    if cfg.quantization != "none":
        args.append(f"--quantization={cfg.quantization}")
    if cfg.kv_cache_dtype != "auto":
        args.append(f"--kv-cache-dtype={cfg.kv_cache_dtype}")
    if cfg.drafter_model_id:
        args.append(f"--speculative-model={cfg.drafter_model_id}")
    if cfg.pipeline_parallel > 1:
        args.append(f"--pipeline-parallel-size={cfg.pipeline_parallel}")
    return args


def _jax_native_env(cfg: BackendConfig, topo: TpuTopology) -> dict[str, str]:
    """The in-repo runtime (runtime/server.py) packaged as a container."""
    if cfg.pipeline_parallel > 1 and total_chips(topo) != cfg.pipeline_parallel:
        # the runtime builds a pure-pp mesh of exactly pp devices; a bigger
        # slice would silently idle the rest (serving_pp.py rejects mixed
        # meshes, so tp cannot absorb them)
        raise ValueError(
            f"pipeline_parallel={cfg.pipeline_parallel} on a "
            f"{total_chips(topo)}-chip slice would idle "
            f"{total_chips(topo) - cfg.pipeline_parallel} chips — size the "
            "topology to exactly pp chips (or drop pp and use tp)"
        )
    if cfg.pipeline_parallel > 1 and cfg.drafter_model_id:
        # the engine rejects this combination at boot; fail at render time
        # instead of shipping a CrashLoop
        raise ValueError(
            "speculative decoding does not compose with serving pipeline "
            "parallelism — drop the drafter or pipeline_parallel"
        )
    if cfg.pipeline_parallel > 1 and topo.name.endswith("-longctx"):
        # the runtime's pp branch takes precedence over topology, so the
        # seq-sharded layout would be silently dropped — reject instead
        raise ValueError(
            f"pipeline_parallel does not compose with the {topo.name} "
            "layout (pure-pp mesh would drop the seq-sharded KV cache); "
            "pick one"
        )
    env = {
        "KVMINI_MODEL_ID": cfg.model_id,
        "KVMINI_MODEL_URI": cfg.model_uri or cfg.model_id,
        "KVMINI_TP": str(cfg.effective_tp(topo)),
        "KVMINI_MAX_MODEL_LEN": str(cfg.max_model_len),
        "KVMINI_MAX_BATCH": str(cfg.max_batch_size),
        "KVMINI_QUANTIZATION": cfg.quantization,
        **({"KVMINI_PP": str(cfg.pipeline_parallel),
            "KVMINI_PP_MICROBATCHES": str(max(cfg.pp_microbatches, 1))}
           if cfg.pipeline_parallel > 1 else {}),
        # layout-suffixed topologies (v5e-8-longctx: tp x sp with the KV
        # seq axis sharded) are a runtime MESH choice, not a pod shape —
        # hand the preset name through so serve builds the right mesh
        **({"KVMINI_TOPOLOGY": topo.name}
           if topo.name.endswith("-longctx") else {}),
    }
    if cfg.kv_cache_dtype != "auto":
        env["KVMINI_KV_CACHE_DTYPE"] = cfg.kv_cache_dtype
    if cfg.quant_mode != "dequant":
        env["KVMINI_QUANT_MODE"] = cfg.quant_mode
    if cfg.drafter_model_id:
        env["KVMINI_DRAFTER"] = cfg.drafter_model_id
    env.update(cfg.extra_env)
    return env


BACKENDS: dict[str, Backend] = {
    b.name: b
    for b in (
        Backend(
            "jetstream",
            image="us-docker.pkg.dev/cloud-tpu-images/inference/jetstream-maxtext:latest",
            port=9000,
            protocol="jetstream",
            readiness_path="/v1/health",
            env_fn=_jetstream_env,
        ),
        Backend(
            "vllm-tpu",
            image="vllm/vllm-tpu:latest",
            port=8000,
            protocol="openai",
            readiness_path="/health",
            env_fn=_vllm_tpu_env,
            args_fn=_vllm_tpu_args,
        ),
        Backend(
            "jax-native",
            image="kvmini-tpu/runtime:latest",
            port=8000,
            protocol="openai",
            readiness_path="/healthz",   # runtime/server.py registers GET /healthz
            env_fn=_jax_native_env,
        ),
    )
}


def get_backend(name: str) -> Backend:
    try:
        return BACKENDS[name]
    except KeyError:
        raise ValueError(
            f"unknown backend {name!r} (known: {', '.join(sorted(BACKENDS))})"
        ) from None
