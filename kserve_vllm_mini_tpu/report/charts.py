"""matplotlib chart helpers -> base64 <img> tags (self-contained HTML).

Reference pattern (/root/reference/report_generator.py:66-312): every chart
renders to a base64 PNG embedded inline so reports are single-file
artifacts. Degrades to a styled placeholder when matplotlib is absent.
"""

from __future__ import annotations

import base64
import io
from typing import Any, Optional

try:
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    HAVE_MPL = True
except ImportError:  # pragma: no cover
    HAVE_MPL = False

_PALETTE = {"primary": "#2563eb", "warm": "#f59e0b", "cold": "#60a5fa",
            "ok": "#16a34a", "bad": "#dc2626", "grid": "#e5e7eb"}


def _to_img(fig) -> str:
    buf = io.BytesIO()
    fig.savefig(buf, format="png", dpi=110, bbox_inches="tight")
    plt.close(fig)
    b64 = base64.b64encode(buf.getvalue()).decode()
    return f'<img src="data:image/png;base64,{b64}" style="max-width:100%"/>'


def _placeholder(title: str) -> str:
    return (
        f'<div style="border:1px dashed #aaa;padding:2em;text-align:center;'
        f'color:#888">chart unavailable (matplotlib not installed): {title}</div>'
    )


def latency_histogram_chart(results: dict[str, Any]) -> str:
    hist = results.get("latency_histogram") or {}
    if not HAVE_MPL or not hist.get("buckets"):
        return _placeholder("latency distribution")
    fig, ax = plt.subplots(figsize=(7, 3))
    buckets, counts = hist["buckets"], hist["counts"]
    width = (buckets[1] - buckets[0]) if len(buckets) > 1 else 1.0
    ax.bar(buckets, counts, width=width * 0.9, color=_PALETTE["primary"], alpha=0.85)
    for pct, key in ((50, "p50_ms"), (95, "p95_ms"), (99, "p99_ms")):
        v = results.get(key)
        if v is not None:
            ax.axvline(v, color=_PALETTE["bad"] if pct >= 95 else _PALETTE["ok"],
                       linestyle="--", linewidth=1)
            ax.text(v, max(counts) * 0.92, f"p{pct}", fontsize=8, rotation=90)
    ax.set_xlabel("latency (ms)")
    ax.set_ylabel("requests")
    ax.set_title("Latency distribution")
    ax.grid(color=_PALETTE["grid"], axis="y")
    return _to_img(fig)


def ttft_vs_latency_chart(results: dict[str, Any]) -> str:
    if not HAVE_MPL:
        return _placeholder("ttft vs latency")
    pairs = [
        ("TTFT p50", results.get("ttft_p50_ms")),
        ("TTFT p95", results.get("ttft_p95_ms")),
        ("latency p50", results.get("p50_ms")),
        ("latency p95", results.get("p95_ms")),
        ("latency p99", results.get("p99_ms")),
    ]
    pairs = [(k, v) for k, v in pairs if v is not None]
    if not pairs:
        return _placeholder("ttft vs latency")
    fig, ax = plt.subplots(figsize=(7, 3))
    names = [k for k, _ in pairs]
    vals = [v for _, v in pairs]
    colors = [_PALETTE["cold"] if "TTFT" in n else _PALETTE["primary"] for n in names]
    ax.barh(names, vals, color=colors)
    for i, v in enumerate(vals):
        ax.text(v, i, f" {v:.0f} ms", va="center", fontsize=9)
    ax.set_title("Latency percentiles")
    ax.grid(color=_PALETTE["grid"], axis="x")
    return _to_img(fig)


def autoscale_timeline_chart(decisions: list[dict[str, Any]]) -> str:
    """Replica count + duty/queue signals over the controller's decision
    log (autoscale/controller.py JSONL rows)."""
    rows = [d for d in decisions if "applied" in d and "ts" in d]
    if len(rows) < 2:
        return ""  # not enough decisions to plot — caller skips the section
    if not HAVE_MPL:
        return _placeholder("autoscale timeline")
    t0 = rows[0]["ts"]
    ts = [d["ts"] - t0 for d in rows]
    fig, ax = plt.subplots(figsize=(7, 3))
    ax.step(ts, [d["applied"] for d in rows], where="post",
            color=_PALETTE["primary"], linewidth=2, label="replicas")
    ax.set_xlabel("time (s)")
    ax.set_ylabel("replicas")
    ax.grid(color=_PALETTE["grid"], axis="y")
    ax2 = ax.twinx()
    duty_rows = [(t, d["duty"]) for t, d in zip(ts, rows) if "duty" in d]
    if duty_rows:
        ax2.plot([t for t, _ in duty_rows], [v for _, v in duty_rows],
                 color=_PALETTE["warm"], linewidth=1, label="duty")
    queue_rows = [(t, d["queue"]) for t, d in zip(ts, rows) if "queue" in d]
    if queue_rows:
        qmax = max((v for _, v in queue_rows), default=0) or 1
        ax2.plot([t for t, _ in queue_rows],
                 [v / qmax for _, v in queue_rows],
                 color=_PALETTE["cold"], linewidth=1,
                 label=f"queue (/{qmax:.0f})")
    ax2.set_ylabel("duty / queue (normalized)")
    ax2.set_ylim(0, 1.1)
    breaches = [t for t, d in zip(ts, rows) if d.get("slo_breached")]
    for b in breaches:
        ax.axvline(b, color=_PALETTE["bad"], linestyle=":", linewidth=1)
    lines1, labels1 = ax.get_legend_handles_labels()
    lines2, labels2 = ax2.get_legend_handles_labels()
    ax.legend(lines1 + lines2, labels1 + labels2, fontsize=8, loc="upper left")
    ax.set_title("Autoscale decisions")
    return _to_img(fig)


def run_timeline_chart(
    samples: list[dict[str, Any]], events: list[dict[str, Any]] | None = None
) -> str:
    """The monitor's 1 Hz timeline (docs/MONITORING.md) as three stacked
    lanes — completion throughput, windowed duty cycle, queue depth —
    with detected events as vertical markers. Mirrors the trace viewer's
    role: the trace explains ONE request, the timeline explains the RUN."""
    rows = [
        s for s in samples
        if isinstance(s.get("t"), (int, float))
    ]
    if len(rows) < 2:
        return ""  # a sub-2-sample run has no timeline to draw — skip
    if not HAVE_MPL:
        return _placeholder("run timeline")
    t0 = rows[0]["t"]
    ts = [s["t"] - t0 for s in rows]

    def series(block: str, key: str) -> list[tuple[float, float]]:
        return [
            (t, s[block][key])
            for t, s in zip(ts, rows)
            if isinstance(s.get(block), dict) and key in s[block]
        ]

    fig, axes = plt.subplots(3, 1, figsize=(7, 5), sharex=True)
    ax_thr, ax_duty, ax_q = axes

    thr = series("loadgen", "window_throughput_rps")
    if thr:
        ax_thr.plot([t for t, _ in thr], [v for _, v in thr],
                    color=_PALETTE["primary"], linewidth=1.5)
    ax_thr.set_ylabel("rps")
    ax_thr.set_title("Run timeline")

    # windowed duty from the busy-seconds counter, cumulative gauge as
    # fallback — the same derivation energy integration uses
    from kserve_vllm_mini_tpu.analysis.telemetry import windowed_duty_series

    duty_pts = [
        (t - t0, d)
        for t, d in windowed_duty_series([
            (s["t"], s["runtime"]) for s in rows
            if isinstance(s.get("runtime"), dict)
        ])
    ]
    if duty_pts:
        ax_duty.plot([t for t, _ in duty_pts], [v for _, v in duty_pts],
                     color=_PALETTE["warm"], linewidth=1.5)
    ax_duty.set_ylabel("duty")
    ax_duty.set_ylim(0, 1.05)

    q = series("runtime", "queue_depth")
    infl = series("loadgen", "inflight")
    if q:
        ax_q.plot([t for t, _ in q], [v for _, v in q],
                  color=_PALETTE["cold"], linewidth=1.5, label="queue depth")
    if infl:
        ax_q.plot([t for t, _ in infl], [v for _, v in infl],
                  color=_PALETTE["primary"], linewidth=1, linestyle="--",
                  label="in flight")
    if q or infl:
        ax_q.legend(fontsize=8, loc="upper left")
    ax_q.set_ylabel("requests")
    ax_q.set_xlabel("time (s)")

    for ax in axes:
        ax.grid(color=_PALETTE["grid"], axis="y")
        for e in events or []:
            et = e.get("t")
            if isinstance(et, (int, float)) and et >= t0:
                ax.axvline(et - t0, color=_PALETTE["bad"], linestyle=":",
                           linewidth=1)
    for e in events or []:
        et = e.get("t")
        if isinstance(et, (int, float)) and et >= t0:
            ax_thr.text(et - t0, ax_thr.get_ylim()[1] * 0.9,
                        str(e.get("type", "event")), fontsize=7, rotation=90,
                        color=_PALETTE["bad"], va="top")
    return _to_img(fig)


def kv_timeline_chart(
    samples: list[dict[str, Any]], events: list[dict[str, Any]] | None = None
) -> str:
    """KV-cache & memory over the run (docs/TROUBLESHOOTING.md "HBM
    pressure & KV thrash") as three stacked lanes: paged-pool occupancy,
    HBM watermark vs the device limit, and retained-eviction churn rate —
    with the kv_thrash / hbm_watermark_high markers where they fired.
    Lanes with no data stay empty rather than suppressing the chart, so
    a dense-layout run still gets its HBM lane."""
    rows = [
        s for s in samples
        if isinstance(s.get("t"), (int, float))
        and isinstance(s.get("runtime"), dict)
    ]
    kv_keys = ("kv_occupancy", "kv_free_blocks", "hbm_bytes_in_use",
               "kv_retained_evictions_total")
    rows = [s for s in rows if any(k in s["runtime"] for k in kv_keys)]
    if len(rows) < 2:
        return ""  # no KV/HBM series sampled — nothing to draw
    if not HAVE_MPL:
        return _placeholder("KV cache & memory timeline")
    t0 = rows[0]["t"]

    def series(key: str) -> list[tuple[float, float]]:
        return [
            (s["t"] - t0, s["runtime"][key])
            for s in rows if key in s["runtime"]
        ]

    fig, axes = plt.subplots(3, 1, figsize=(7, 5), sharex=True)
    ax_occ, ax_hbm, ax_churn = axes

    occ = series("kv_occupancy")
    if occ:
        ax_occ.plot([t for t, _ in occ], [v for _, v in occ],
                    color=_PALETTE["primary"], linewidth=1.5,
                    label="occupancy")
        ax_occ.set_ylim(0, 1.05)
    free = series("kv_free_blocks")
    if free:
        ax_free = ax_occ.twinx()
        ax_free.plot([t for t, _ in free], [v for _, v in free],
                     color=_PALETTE["cold"], linewidth=1, linestyle="--",
                     label="free blocks")
        ax_free.set_ylabel("free blocks", fontsize=8)
    ax_occ.set_ylabel("pool occupancy")
    ax_occ.set_title("KV cache & memory")

    in_use = series("hbm_bytes_in_use")
    limit = series("hbm_bytes_limit")
    if in_use:
        ax_hbm.plot([t for t, _ in in_use],
                    [v / 1e9 for _, v in in_use],
                    color=_PALETTE["warm"], linewidth=1.5, label="in use")
    if limit:
        ax_hbm.plot([t for t, _ in limit],
                    [v / 1e9 for _, v in limit],
                    color=_PALETTE["bad"], linewidth=1, linestyle=":",
                    label="limit")
    if in_use or limit:
        ax_hbm.legend(fontsize=8, loc="upper left")
    ax_hbm.set_ylabel("HBM (GB)")

    def rate(key: str) -> list[tuple[float, float]]:
        pts = series(key)
        return [
            (tb, max(vb - va, 0.0) / (tb - ta))
            for (ta, va), (tb, vb) in zip(pts, pts[1:]) if tb > ta
        ]

    # Eviction churn split: an eviction that lands in the host-RAM tier
    # (kv_tier_demotions_total) is recoverable; the remainder is a true
    # discard. Both derive from counters the timeline already samples.
    churn = rate("kv_retained_evictions_total")
    demo = dict(rate("kv_tier_demotions_total"))
    if churn:
        if demo:
            discard = [(t, max(v - demo.get(t, 0.0), 0.0)) for t, v in churn]
            ax_churn.plot([t for t, _ in discard], [v for _, v in discard],
                          color=_PALETTE["bad"], linewidth=1.5,
                          label="true discards")
            dpts = sorted(demo.items())
            ax_churn.plot([t for t, _ in dpts], [v for _, v in dpts],
                          color=_PALETTE["cold"], linewidth=1.5,
                          linestyle="--", label="demoted to tier")
            ax_churn.legend(fontsize=8, loc="upper left")
        else:
            ax_churn.plot([t for t, _ in churn], [v for _, v in churn],
                          color=_PALETTE["bad"], linewidth=1.5)
    ax_churn.set_ylabel("evictions/s")
    ax_churn.set_xlabel("time (s)")

    kv_events = [
        e for e in events or []
        if e.get("type") in ("kv_thrash", "hbm_watermark_high")
    ]
    for ax in axes:
        ax.grid(color=_PALETTE["grid"], axis="y")
        for e in kv_events:
            et = e.get("t")
            if isinstance(et, (int, float)) and et >= t0:
                ax.axvline(et - t0, color=_PALETTE["bad"], linestyle=":",
                           linewidth=1)
    for e in kv_events:
        et = e.get("t")
        if isinstance(et, (int, float)) and et >= t0:
            ax_occ.text(et - t0, ax_occ.get_ylim()[1] * 0.9,
                        str(e.get("type", "event")), fontsize=7, rotation=90,
                        color=_PALETTE["bad"], va="top")
    return _to_img(fig)


def econ_timeline_chart(
    samples: list[dict[str, Any]], events: list[dict[str, Any]] | None = None
) -> str:
    """The live economics rail (docs/ECONOMICS.md) over the run as two
    stacked lanes — $/1K-tok (fleet marginal-replica gauge dashed beside
    it when the router exported one) and Wh/1K-tok — with the
    cost_burn_exceeded / replica_unprofitable markers where they fired.
    Runs whose timeline carried no econ gauges (unpriced engine, CPU
    backend) draw nothing: absent, never a fabricated $0 lane."""
    rows = [
        s for s in samples
        if isinstance(s.get("t"), (int, float))
        and isinstance(s.get("runtime"), dict)
        and "econ_usd_per_1k_tokens" in s["runtime"]
    ]
    if len(rows) < 2:
        return ""  # rail never warmed up (or never existed) — skip
    if not HAVE_MPL:
        return _placeholder("cost & energy timeline")
    t0 = rows[0]["t"]

    def series(key: str) -> list[tuple[float, float]]:
        return [
            (s["t"] - t0, s["runtime"][key])
            for s in rows if key in s["runtime"]
        ]

    fig, (ax_usd, ax_wh) = plt.subplots(2, 1, figsize=(7, 4), sharex=True)

    usd = series("econ_usd_per_1k_tokens")
    ax_usd.plot([t for t, _ in usd], [v for _, v in usd],
                color=_PALETTE["primary"], linewidth=1.5, label="$/1K-tok")
    marginal = series("econ_marginal_replica_usd_per_1k_tokens")
    if marginal:
        ax_usd.plot([t for t, _ in marginal], [v for _, v in marginal],
                    color=_PALETTE["bad"], linewidth=1.2, linestyle="--",
                    label="marginal replica $/1K-tok")
    ax_usd.legend(fontsize=8, loc="upper right")
    ax_usd.set_ylabel("$ / 1K tok")
    ax_usd.set_title("Cost & energy")

    wh = series("econ_wh_per_1k_tokens")
    if wh:
        ax_wh.plot([t for t, _ in wh], [v for _, v in wh],
                   color=_PALETTE["warm"], linewidth=1.5)
    ax_wh.set_ylabel("Wh / 1K tok")
    ax_wh.set_xlabel("time (s)")

    econ_events = [
        e for e in events or []
        if e.get("type") in ("cost_burn_exceeded", "replica_unprofitable")
    ]
    for ax in (ax_usd, ax_wh):
        ax.grid(color=_PALETTE["grid"], axis="y")
        for e in econ_events:
            et = e.get("t")
            if isinstance(et, (int, float)) and et >= t0:
                ax.axvline(et - t0, color=_PALETTE["bad"], linestyle=":",
                           linewidth=1)
    for e in econ_events:
        et = e.get("t")
        if isinstance(et, (int, float)) and et >= t0:
            ax_usd.text(et - t0, ax_usd.get_ylim()[1] * 0.9,
                        str(e.get("type", "event")), fontsize=7, rotation=90,
                        color=_PALETTE["bad"], va="top")
    return _to_img(fig)


def cost_pareto_chart(rows: list[dict[str, Any]]) -> str:
    """Cost vs latency Pareto scatter over sweep cells: $/1K-tok (live
    economics when the cell carried the rail, post-hoc cost otherwise)
    against TTFT p95. The Pareto-efficient cells — no other cell both
    cheaper AND faster — are highlighted and connected; everything
    northeast of the frontier is paying for latency it isn't getting."""
    pts = []
    for r in rows:
        econ = r.get("economics") if isinstance(r.get("economics"), dict) else {}
        cost = econ.get("usd_per_1k_tokens", r.get("cost_per_1k_tokens"))
        ttft = r.get("ttft_p95_ms")
        # sweep CSV rows carry strings ("" for a cell that never priced)
        try:
            pts.append((
                float(ttft), float(cost),
                str(r.get("run_id") or r.get("concurrency") or "?"),
            ))
        except (TypeError, ValueError):
            continue
    if len(pts) < 2:
        return ""  # a frontier needs at least two priced cells
    if not HAVE_MPL:
        return _placeholder("cost vs TTFT Pareto")
    frontier = sorted(
        p for p in pts
        if not any(
            q[0] <= p[0] and q[1] <= p[1] and q != p for q in pts
        )
    )
    fig, ax = plt.subplots(figsize=(7, 3.6))
    dominated = [p for p in pts if p not in frontier]
    if dominated:
        ax.scatter([p[0] for p in dominated], [p[1] for p in dominated],
                   color=_PALETTE["cold"], s=36, label="dominated")
    ax.scatter([p[0] for p in frontier], [p[1] for p in frontier],
               color=_PALETTE["ok"], s=48, zorder=3, label="Pareto frontier")
    ax.plot([p[0] for p in frontier], [p[1] for p in frontier],
            color=_PALETTE["ok"], linewidth=1, linestyle="--", zorder=2)
    for t, c, name in pts:
        ax.annotate(name, (t, c), fontsize=7,
                    xytext=(4, 4), textcoords="offset points")
    ax.set_xlabel("TTFT p95 (ms)")
    ax.set_ylabel("$ / 1K tok")
    ax.set_title("Cost vs TTFT p95")
    ax.legend(fontsize=8, loc="upper left")
    ax.grid(color=_PALETTE["grid"])
    return _to_img(fig)


def perf_trajectory_chart(traj: dict[str, Any]) -> str:
    """The perf trajectory (analysis/trajectory.py) as two stacked lanes:
    device tokens/s/chip for REAL rounds, compile-time + step-ratio for
    PROXY rounds — separate axes because a proxy number must never read
    as a device measurement. Dark rounds show as shaded gaps so lost
    coverage stays visible."""
    rows = traj.get("rounds") or []
    if len(rows) < 2:
        return ""
    if not HAVE_MPL:
        return _placeholder("perf trajectory")
    xs = list(range(len(rows)))
    names = [r.get("name", "?") for r in rows]
    fig, (ax_real, ax_proxy) = plt.subplots(2, 1, figsize=(7, 4.6),
                                            sharex=True)
    real = [(x, r["tokens_per_sec_per_chip"]) for x, r in zip(xs, rows)
            if r.get("tokens_per_sec_per_chip")]
    if real:
        ax_real.plot([x for x, _ in real], [v for _, v in real],
                     marker="o", color=_PALETTE["primary"], linewidth=1.5,
                     label="real device")
        ax_real.legend(fontsize=8, loc="upper left")
    ax_real.set_ylabel("tok/s/chip")
    ax_real.set_title("Perf trajectory")
    compile_s = [(x, r["proxy"]["compile_wall_s"]) for x, r in zip(xs, rows)
                 if isinstance(r.get("proxy"), dict)
                 and "compile_wall_s" in r["proxy"]]
    ratio = [(x, r["proxy"]["step_count_ratio"]) for x, r in zip(xs, rows)
             if isinstance(r.get("proxy"), dict)
             and "step_count_ratio" in r["proxy"]]
    if compile_s:
        ax_proxy.plot([x for x, _ in compile_s], [v for _, v in compile_s],
                      marker="s", color=_PALETTE["warm"], linewidth=1.2,
                      label="proxy: compile s")
    if ratio:
        ax2 = ax_proxy.twinx()
        ax2.plot([x for x, _ in ratio], [v for _, v in ratio],
                 marker="^", color=_PALETTE["cold"], linewidth=1.2,
                 label="proxy: step ratio")
        ax2.set_ylabel("sync/chained")
        lines1, labels1 = ax_proxy.get_legend_handles_labels()
        lines2, labels2 = ax2.get_legend_handles_labels()
        ax_proxy.legend(lines1 + lines2, labels1 + labels2, fontsize=8,
                        loc="upper left")
    elif compile_s:
        ax_proxy.legend(fontsize=8, loc="upper left")
    ax_proxy.set_ylabel("compile (s)")
    for ax in (ax_real, ax_proxy):
        ax.grid(color=_PALETTE["grid"], axis="y")
        for x, r in zip(xs, rows):
            if r.get("series") == "dark":
                ax.axvspan(x - 0.35, x + 0.35, color=_PALETTE["grid"],
                           alpha=0.6)
    ax_proxy.set_xticks(xs, names, fontsize=8)
    ax_proxy.set_xlabel("bench round")
    return _to_img(fig)


def cold_warm_chart(results: dict[str, Any]) -> str:
    cold, warm = results.get("cold_p95_ms"), results.get("warm_p95_ms")
    if not HAVE_MPL or cold is None or warm is None:
        return ""
    fig, ax = plt.subplots(figsize=(5, 3))
    ax.bar(["warm p50", "warm p95", "cold p50", "cold p95"],
           [results.get("warm_p50_ms", 0), warm, results.get("cold_p50_ms", 0), cold],
           color=[_PALETTE["warm"], _PALETTE["warm"], _PALETTE["cold"], _PALETTE["cold"]])
    mult = results.get("cold_multiplier")
    ax.set_title(
        f"Cold vs warm latency (cold multiplier {mult:.2f}x)" if mult else
        "Cold vs warm latency"
    )
    ax.set_ylabel("ms")
    ax.grid(color=_PALETTE["grid"], axis="y")
    return _to_img(fig)


def cost_breakdown_chart(results: dict[str, Any]) -> str:
    bd = results.get("cost_breakdown") or {}
    bd = {k: v for k, v in bd.items() if v and v > 0}
    if not HAVE_MPL or not bd:
        return ""
    fig, ax = plt.subplots(figsize=(4.5, 3))
    ax.pie(list(bd.values()), labels=list(bd.keys()), autopct="%1.0f%%",
           colors=[_PALETTE["primary"], _PALETTE["warm"], _PALETTE["cold"], "#a78bfa"])
    ax.set_title(f"Cost breakdown (total ${results.get('cost_total', 0):.4f})")
    return _to_img(fig)


def heatmap_chart(
    rows: list[str], cols: list[str], values: list[list[Optional[float]]],
    title: str, fmt: str = "{:.0f}",
) -> str:
    if not HAVE_MPL:
        return _placeholder(title)
    import numpy as np

    arr = np.array([[v if v is not None else np.nan for v in row] for row in values],
                   dtype=float)
    fig, ax = plt.subplots(figsize=(1.2 + 0.9 * len(cols), 1.0 + 0.6 * len(rows)))
    im = ax.imshow(arr, cmap="viridis", aspect="auto")
    ax.set_xticks(range(len(cols)), cols, rotation=30, ha="right", fontsize=8)
    ax.set_yticks(range(len(rows)), rows, fontsize=8)
    for i in range(len(rows)):
        for j in range(len(cols)):
            if not np.isnan(arr[i, j]):
                ax.text(j, i, fmt.format(arr[i, j]), ha="center", va="center",
                        fontsize=8, color="white")
    ax.set_title(title)
    fig.colorbar(im, shrink=0.8)
    return _to_img(fig)
