"""Executive HTML reports: single run, grid sweep, topology matrix.

Reference surface (/root/reference/report_generator.py:398-827): metric
cards, embedded charts, cold/warm section, prewarm break-even, bottleneck
classification, recommendations, a zero-dependency trace viewer deep-linked
at the p95 request, sweep heatmaps, and the topology (née MIG) matrix.
Everything inlines into one .html file.
"""

from __future__ import annotations

import argparse
import csv
import html as html_mod
import json
from pathlib import Path
from typing import Any, Optional

from kserve_vllm_mini_tpu.report import charts
from kserve_vllm_mini_tpu.report.recommendations import (
    classify_bottleneck,
    generate_recommendations,
    prewarm_breakeven,
)

_CSS = """
body{font-family:system-ui,-apple-system,sans-serif;margin:2em auto;max-width:1100px;
     color:#111827;padding:0 1em}
h1{border-bottom:3px solid #2563eb;padding-bottom:.3em}
.cards{display:flex;flex-wrap:wrap;gap:12px;margin:1em 0}
.card{border:1px solid #e5e7eb;border-radius:10px;padding:14px 18px;min-width:150px;
      box-shadow:0 1px 3px rgba(0,0,0,.06)}
.card .v{font-size:1.6em;font-weight:700;color:#2563eb}
.card .l{font-size:.8em;color:#6b7280;text-transform:uppercase;letter-spacing:.05em}
.warn{color:#b45309}.bad{color:#dc2626}.ok{color:#16a34a}
section{margin:2em 0}
ul.recs li{margin:.5em 0}
pre.trace{background:#0b1020;color:#c9d4ff;padding:1em;border-radius:8px;
          overflow-x:auto;font-size:.85em}
table{border-collapse:collapse}td,th{border:1px solid #e5e7eb;padding:6px 10px}
"""


def _card(label: str, value: Any, unit: str = "") -> str:
    if value is None:
        return ""
    if isinstance(value, float):
        value = f"{value:,.2f}" if value >= 10 else f"{value:.4g}"
    return (
        f'<div class="card"><div class="v">{value}{unit}</div>'
        f'<div class="l">{html_mod.escape(label)}</div></div>'
    )


def _trace_viewer(run_dir: Optional[Path], results: dict[str, Any]) -> str:
    """Find the request closest to p95 and render its span tree
    (reference report_generator.py:423-491)."""
    if run_dir is None:
        return ""
    traces_path = run_dir / "traces" / "traces.json"
    requests_path = run_dir / "requests.csv"
    if not traces_path.exists() or not requests_path.exists():
        return ""
    p95 = results.get("p95_ms")
    if p95 is None:
        return ""
    best: Optional[dict] = None
    with requests_path.open(newline="") as f:
        for row in csv.DictReader(f):
            try:
                lat = float(row.get("latency_ms") or 0)
            except ValueError:
                continue
            if row.get("ok") != "1" or lat <= 0:
                continue
            if best is None or abs(lat - p95) < abs(float(best["latency_ms"]) - p95):
                best = row
    if not best:
        return ""
    trace_id = best.get("trace_id", "")
    doc = json.loads(traces_path.read_text())
    from kserve_vllm_mini_tpu.runtime.tracing import spans_from_otlp

    # up to three lanes: the loadgen's client spans; the router's
    # fleet.route/fleet.proxy spans when the run went through the fleet
    # router; and the server's phase spans, clock-corrected onto the
    # client timeline — per replica when the fleet merge estimated one
    # offset per lane (docs/TRACING.md "Fleet tracing"), by the single
    # merge estimate otherwise
    offset_ns = int(doc.get("clockOffsetNanosEstimate", 0) or 0)
    router_offset_ns = int(doc.get("clockOffsetNanosRouter", 0) or 0)
    replica_offsets = {
        str(k): int(v)
        for k, v in (doc.get("clockOffsetsNanosByReplica") or {}).items()
    }

    def _replica(s: dict) -> str:
        for a in s.get("attributes") or []:
            if a.get("key") == "replica":
                return str((a.get("value") or {}).get("stringValue", ""))
        return ""

    def _srv_shift(s: dict) -> int:
        return replica_offsets.get(_replica(s), offset_ns)

    client_spans, fleet_spans, server_spans = [], [], []
    for svc, s in spans_from_otlp(doc):
        if s.get("traceId") != trace_id:
            continue
        if str(s.get("name", "")).startswith("fleet."):
            fleet_spans.append(s)
        elif s.get("kind") == 2:
            server_spans.append(s)
        else:
            client_spans.append(s)
    if not client_spans and not fleet_spans and not server_spans:
        return ""

    def _ns(s: dict, key: str, shift: int = 0) -> int:
        return int(s.get(key, 0)) - shift

    all_starts = (
        [_ns(s, "startTimeUnixNano") for s in client_spans]
        + [_ns(s, "startTimeUnixNano", router_offset_ns) for s in fleet_spans]
        + [_ns(s, "startTimeUnixNano", _srv_shift(s)) for s in server_spans]
    )
    t0 = min(all_starts)
    lines = [f"trace {trace_id}  (request {best['request_id']}, "
             f"{float(best['latency_ms']):.1f} ms ~ p95)"]

    def _render(pairs: list[tuple[dict, int]], lane: str) -> None:
        for s, shift in sorted(pairs, key=lambda p: int(p[0]["startTimeUnixNano"])):
            start_ms = (_ns(s, "startTimeUnixNano", shift) - t0) / 1e6
            dur_ms = (int(s["endTimeUnixNano"]) - int(s["startTimeUnixNano"])) / 1e6
            indent = "  " if s.get("parentSpanId") else ""
            rid = _replica(s)
            name = s["name"] + (f" @{rid}" if rid else "")
            bar = "#" * max(
                int(dur_ms / max(float(best["latency_ms"]), 1e-9) * 40), 1
            )
            lines.append(f"{lane}{indent}{name:<24} +{start_ms:8.1f}ms "
                         f"{dur_ms:8.1f}ms  {bar}")

    _render([(s, 0) for s in client_spans], "")
    if fleet_spans:
        lines.append("")
        lines.append(
            f"fleet lane (router clock offset est {router_offset_ns / 1e6:+.2f} ms)"
        )
        _render([(s, router_offset_ns) for s in fleet_spans], "  ")
    if server_spans:
        lines.append("")
        if replica_offsets:
            offs = ", ".join(
                f"{rid} {off / 1e6:+.2f} ms"
                for rid, off in sorted(replica_offsets.items())
            )
            lines.append(f"server lane (per-replica clock offsets: {offs})")
        else:
            lines.append(
                f"server lane (clock offset est {offset_ns / 1e6:+.2f} ms)"
            )
        _render([(s, _srv_shift(s)) for s in server_spans], "  ")
    return (
        "<section><h2>p95 request trace</h2>"
        f"<pre class='trace'>{html_mod.escape(chr(10).join(lines))}</pre></section>"
    )


def _duty_pct(results: dict[str, Any]) -> Optional[float]:
    """Windowed average when a real window backed it; the instantaneous
    snapshot otherwise (tpu_metrics_source says which — see
    docs/MONITORING.md on the *_avg honesty rule)."""
    duty = results.get("tpu_duty_cycle_avg")
    if duty is None:
        duty = results.get("tpu_duty_cycle")
    return duty * 100 if duty is not None else None


def _timeline_section(
    run_dir: Optional[Path], results: dict[str, Any],
    samples: Optional[list[dict[str, Any]]] = None,
) -> str:
    """Monitor timeline lane (docs/MONITORING.md): throughput / duty /
    queue over the run with event markers, plus the burn-rate and abort
    summary from the results `monitor` block. Renders beside the trace
    viewer — the trace explains one request, this explains the run."""
    if run_dir is None:
        return ""
    if samples is None:
        from kserve_vllm_mini_tpu.core.rundir import RunDir

        samples = RunDir(run_dir).read_timeline()
    mon = results.get("monitor") or {}
    events = mon.get("events") or []
    chart = charts.run_timeline_chart(samples, events)
    if not chart and not mon:
        return ""
    parts = ["<section><h2>Run timeline</h2>"]
    facts = []
    if mon:
        facts.append(f"{mon.get('samples', 0)} samples "
                     f"@ {mon.get('interval_s', '?')}s")
        if mon.get("skipped_samples"):
            facts.append(f"{mon['skipped_samples']} skipped")
        for key, label in (("burn_rates", "burn"),
                           ("burn_rates_peak", "peak burn")):
            rates = mon.get(key) or {}
            if rates:
                facts.append(label + " " + ", ".join(
                    f"{k}={v:.2f}" for k, v in sorted(rates.items())
                ))
    if facts:
        parts.append(f"<p>{html_mod.escape(' · '.join(facts))}</p>")
    aborted = results.get("aborted_early") or mon.get("aborted")
    if aborted:
        parts.append(
            f"<p class='bad'>aborted early: {html_mod.escape(str(aborted))}</p>"
        )
    for e in events:
        parts.append(
            f"<p class='warn'>event @ {e.get('t', 0):.0f}: "
            f"{html_mod.escape(str(e.get('detail', e.get('type', '?'))))}</p>"
        )
    if chart:
        parts.append(chart)
    parts.append("</section>")
    return "".join(parts)


def trajectory_section(traj: dict[str, Any]) -> str:
    """The "Perf trajectory" section (analysis/trajectory.py): coverage
    line, real/proxy chart lanes, and the per-round trend table with
    same-series regression deltas. Proxy rounds are visibly labeled —
    their numbers are compile/cost-model metrics, never device
    throughput (docs/PROFILING.md)."""
    rows = traj.get("rounds") or []
    if not rows:
        return ""
    cov = traj.get("coverage") or {}
    parts = ["<section><h2>Perf trajectory</h2>"]
    parts.append(
        f"<p>{cov.get('total', len(rows))} rounds · "
        f"{cov.get('real', 0)} real · {cov.get('proxy', 0)} proxy · "
        f"{cov.get('dark', 0)} dark</p>"
    )
    chart = charts.perf_trajectory_chart(traj)
    if chart:
        parts.append(chart)
    body = []
    for r in rows:
        tok = r.get("tokens_per_sec_per_chip")
        delta = r.get("delta_vs_last_real_pct")
        px = r.get("proxy") or {}
        note = r.get("downshifted") or ""
        cls = {"real": "ok", "proxy": "warn", "dark": "bad"}.get(
            r.get("series", "dark"), "")
        body.append(
            "<tr>"
            f"<td>{html_mod.escape(str(r.get('name', '?')))}</td>"
            f"<td class='{cls}'>{html_mod.escape(str(r.get('series')))}</td>"
            f"<td>{html_mod.escape(str(r.get('status')))}</td>"
            f"<td>{tok if tok is not None else '—'}</td>"
            f"<td>{f'{delta:+.1f}%' if delta is not None else '—'}</td>"
            f"<td>{px.get('compile_wall_s', '—')}</td>"
            f"<td>{px.get('step_count_ratio', '—')}</td>"
            f"<td>{html_mod.escape(str(note))}</td></tr>"
        )
    parts.append(
        "<table><tr><th>round</th><th>series</th><th>status</th>"
        "<th>tok/s/chip</th><th>Δ vs last real</th>"
        "<th>proxy compile s</th><th>proxy step ratio</th><th>note</th></tr>"
        + "".join(body) + "</table>"
    )
    regs = traj.get("regressions") or []
    for reg in regs:
        parts.append(
            f"<p class='bad'>regression {html_mod.escape(str(reg['round']))}: "
            f"{html_mod.escape(str(reg['metric']))} {reg['value']} vs "
            f"{reg['anchor']} ({reg['delta_pct']:+.1f}%)</p>"
        )
    parts.append("</section>")
    return "".join(parts)


def generate_trajectory_html(traj: dict[str, Any]) -> str:
    """Standalone page for ``kvmini-tpu trajectory --html``."""
    return (
        "<html><head><meta charset='utf-8'>"
        "<title>kvmini-tpu perf trajectory</title>"
        f"<style>{_CSS}</style></head><body>"
        "<h1>Perf trajectory</h1>"
        + trajectory_section(traj)
        + "</body></html>"
    )


def _compile_stats_section(results: dict[str, Any]) -> str:
    """Compile-stats block (docs/PROFILING.md) when the run carried one:
    totals plus the per-executable table from the self-serve snapshot."""
    cs = results.get("compile_stats")
    if not isinstance(cs, dict) or not cs.get("compiles"):
        return ""
    parts = ["<section><h2>Compile stats</h2>"]
    facts = [f"{cs['compiles']} executables"]
    if cs.get("compile_wall_s") is not None:
        facts.append(f"{cs['compile_wall_s']:.2f}s compile wall")
    if cs.get("flops"):
        facts.append(f"{cs['flops']:.3g} cost-model FLOPs/step total")
    if cs.get("peak_bytes"):
        facts.append(f"{cs['peak_bytes'] / 1e9:.2f} GB peak buffer estimate")
    parts.append(f"<p>{html_mod.escape(' · '.join(facts))}</p>")
    exes = cs.get("executables") or []
    if exes:
        rows = "".join(
            "<tr>"
            f"<td>{html_mod.escape(str(e.get('label', '?')))}</td>"
            f"<td>{e.get('compile_wall_s', '—')}</td>"
            f"<td>{e.get('flops', '—')}</td>"
            f"<td>{e.get('bytes_accessed', '—')}</td>"
            f"<td>{e.get('peak_bytes', '—')}</td></tr>"
            for e in exes
        )
        parts.append(
            "<table><tr><th>executable</th><th>compile s</th><th>FLOPs</th>"
            "<th>bytes accessed</th><th>peak bytes</th></tr>"
            + rows + "</table>"
        )
    parts.append("</section>")
    return "".join(parts)


def _kv_cache_section(
    results: dict[str, Any], run_dir: Optional[Path] = None,
    samples: Optional[list[dict[str, Any]]] = None,
) -> str:
    """The "KV cache & memory" section (docs/TROUBLESHOOTING.md "HBM
    pressure & KV thrash"): prefix-cache attribution facts, paged-pool
    occupancy, HBM watermarks, the headroom-model verdict, and the
    occupancy/watermark/churn timeline lanes with kv_thrash /
    hbm_watermark_high markers. Rendered only when the run carried the
    observability rail (kv_cache block or KV timeline series) — an
    external engine's report simply has no section."""
    kv = results.get("kv_cache")
    kv = kv if isinstance(kv, dict) else {}
    chart = ""
    if run_dir is not None:
        if samples is None:
            from kserve_vllm_mini_tpu.core.rundir import RunDir

            samples = RunDir(run_dir).read_timeline()
        events = (results.get("monitor") or {}).get("events") or []
        chart = charts.kv_timeline_chart(samples, events)
    if not kv and not chart:
        return ""
    parts = ["<section><h2>KV cache & memory</h2>"]
    facts = []
    if kv.get("prefix_lookups"):
        hits = kv.get("prefix_hits", 0)
        facts.append(
            f"prefix hits {hits:.0f}/{kv['prefix_lookups']:.0f} lookups"
        )
    if kv.get("hit_depth_p95"):
        facts.append(
            f"hit depth p50/p95 {kv.get('hit_depth_p50', 0):.0f}/"
            f"{kv['hit_depth_p95']:.0f} tok"
        )
    if kv.get("reused_bytes"):
        facts.append(f"{kv['reused_bytes'] / 1e6:.1f} MB KV reused")
    if kv.get("blocks_allocated") is not None:
        facts.append(
            f"{kv['blocks_allocated']:.0f} blocks allocated · "
            f"{kv.get('retained_evictions', 0):.0f} retained evictions · "
            f"{kv.get('share_reclaims', 0):.0f} share reclaims"
        )
    if kv.get("occupancy") is not None:
        facts.append(
            f"pool occupancy {kv['occupancy']:.0%}"
            + (f" · fragmentation {kv['fragmentation']:.0%}"
               if kv.get("fragmentation") is not None else "")
            + (f" · retained {kv['retained_fraction']:.0%}"
               if kv.get("retained_fraction") is not None else "")
        )
    if kv.get("tier_demotions") or kv.get("tier_promotions"):
        tier = (
            f"host tier {kv.get('tier_demotions', 0):.0f} demotions · "
            f"{kv.get('tier_promotions', 0):.0f} promotions · "
            f"{kv.get('tier_hits', 0):.0f} hits"
        )
        if kv.get("tier_bytes"):
            tier += f" · {kv['tier_bytes'] / 1e6:.1f} MB resident"
        if kv.get("tier_disabled"):
            tier += " · DISABLED (thrash guard)"
        facts.append(tier)
    if kv.get("migrated_blocks"):
        facts.append(
            f"{kv['migrated_blocks']:.0f} blocks migrated in from "
            f"siblings ({kv.get('migrated_bytes', 0) / 1e6:.1f} MB)"
        )
    if kv.get("hbm_peak_bytes"):
        hbm = f"HBM peak {kv['hbm_peak_bytes'] / 1e9:.2f} GB"
        if kv.get("hbm_bytes_limit"):
            hbm += (f" of {kv['hbm_bytes_limit'] / 1e9:.2f} GB "
                    f"({kv['hbm_peak_bytes'] / kv['hbm_bytes_limit']:.0%})")
        facts.append(hbm)
    if facts:
        parts.append(f"<p>{html_mod.escape(' · '.join(facts))}</p>")
    err = results.get("headroom_error_pct")
    if err is not None:
        # negative = the analytic model UNDERESTIMATED the observed peak —
        # the direction that RESOURCE_EXHAUSTs a run the guard admitted
        cls = "bad" if err < 0 else ("warn" if err > 50 else "ok")
        verdict = ("UNDERESTIMATES the observed peak (OOM risk)" if err < 0
                   else "overestimates the observed peak")
        parts.append(
            f"<p class='{cls}'>headroom model {verdict}: "
            f"{err:+.1f}% vs observed HBM peak</p>"
        )
    if chart:
        parts.append(chart)
    parts.append("</section>")
    return "".join(parts)


def _resilience_section(results: dict[str, Any]) -> str:
    """The "Resilience" section (docs/RESILIENCE.md): shed/retry
    accounting from the per-request CSV, the runtime's watchdog/degrade
    rail, and the overload_shedding / engine_fault monitor events.
    Rendered only when the run saw resilience activity — a clean run's
    report simply has no section."""
    res = results.get("resilience")
    res = res if isinstance(res, dict) else {}
    shed = results.get("shed_requests") or 0
    retries = results.get("retries_total") or 0
    events = [
        e for e in ((results.get("monitor") or {}).get("events") or [])
        if isinstance(e, dict)
        and e.get("type") in ("overload_shedding", "engine_fault")
    ]
    if not res and not shed and not retries and not events:
        return ""
    parts = ["<section><h2>Resilience</h2>"]
    facts = []
    if shed:
        rate = results.get("shed_rate")
        facts.append(
            f"{shed} request(s) shed"
            + (f" ({rate:.1%} of the run)" if rate is not None else "")
            + " — counted separately from errors"
        )
    if retries:
        facts.append(f"{retries} 429 resend(s) absorbed by client backoff")
    if res.get("requests_shed"):
        facts.append(f"server shed {res['requests_shed']:.0f} at admission")
    if res.get("watchdog_trips"):
        facts.append(f"{res['watchdog_trips']:.0f} watchdog trip(s)")
    if res.get("engine_faults"):
        facts.append(f"{res['engine_faults']:.0f} engine fault(s) recovered")
    if res.get("faults_armed"):
        facts.append(
            f"{res['faults_armed']:.0f} injection point(s) armed (chaos run)"
        )
    if facts:
        parts.append(f"<p>{html_mod.escape(' · '.join(facts))}</p>")
    level = res.get("degrade_level")
    if level:
        ladder = {1: "sync pipeline", 2: "decode chunk 1", 3: "spec off",
                  4: "gave up"}
        parts.append(
            f"<p class='warn'>engine finished DEGRADED at level "
            f"{level:.0f} ({ladder.get(int(level), '?')}) — each watchdog "
            "trip/device fault gives up one optimization</p>"
        )
    for e in events:
        parts.append(
            f"<p>event @{e.get('t', 0):.0f}: "
            f"<b>{html_mod.escape(str(e.get('type')))}</b> — "
            f"{html_mod.escape(str(e.get('detail', '')))}</p>"
        )
    parts.append("</section>")
    return "".join(parts)


def _disagg_section(results: dict[str, Any]) -> str:
    """The "Disaggregated serving" section (docs/DISAGGREGATION.md):
    prefill-lane handoff volume, wait/busy accounting, drops and the
    degrade ladder, plus the handoff_stall monitor event. Rendered only
    for runs that actually handed off — a colocated run's report simply
    has no section."""
    dg = results.get("disagg")
    if not isinstance(dg, dict):
        return ""
    parts = ["<section><h2>Disaggregated serving</h2>"]
    facts = []
    handoffs = dg.get("handoffs") or 0
    if handoffs:
        facts.append(
            f"{handoffs:.0f} prefill(s) handed off "
            f"({dg.get('handoff_blocks', 0):.0f} KV blocks)"
        )
        wait = dg.get("handoff_wait_s")
        if wait is not None and handoffs:
            facts.append(
                f"mean handoff wait {wait / handoffs * 1000.0:.1f} ms"
            )
        copied = dg.get("handoff_bytes_copied")
        if copied:
            facts.append(f"{copied / 1e6:.1f} MB KV copied (dense v1 stripe)")
        elif copied == 0:
            facts.append("0 B KV copied (paged zero-copy handoff)")
    busy = dg.get("lane_busy_s")
    if busy:
        facts.append(f"prefill lane busy {busy:.2f} s")
    if dg.get("handoff_drops"):
        facts.append(f"{dg['handoff_drops']:.0f} handoff(s) dropped")
    if dg.get("colocated_fallbacks"):
        facts.append(
            f"{dg['colocated_fallbacks']:.0f} prefill(s) degraded to "
            "colocated"
        )
    if facts:
        parts.append(f"<p>{html_mod.escape(' · '.join(facts))}</p>")
    if dg.get("degraded"):
        parts.append(
            "<p class='warn'>engine finished with the prefill lane "
            "DEGRADED to colocated routing — repeated handoff drops or a "
            "dead lane (docs/DISAGGREGATION.md degrade ladder)</p>"
        )
    for e in ((results.get("monitor") or {}).get("events") or []):
        if isinstance(e, dict) and e.get("type") == "handoff_stall":
            parts.append(
                f"<p>event @{e.get('t', 0):.0f}: <b>handoff_stall</b> — "
                f"{html_mod.escape(str(e.get('detail', '')))}</p>"
            )
    parts.append("</section>")
    return "".join(parts)


def _economics_section(
    results: dict[str, Any], run_dir: Optional[Path] = None,
    samples: Optional[list[dict[str, Any]]] = None,
) -> str:
    """The "Economics" section (docs/ECONOMICS.md): the live rail's
    rolling $/1K-tok, Wh/1K-tok and hourly burn from the results
    ``economics`` block, the cost/energy timeline lanes, and the
    cost_burn_exceeded / replica_unprofitable monitor events. Rendered
    only when the run priced itself — an unpriced engine's report simply
    has no section; the post-hoc cost estimate keeps its own card."""
    econ = results.get("economics")
    econ = econ if isinstance(econ, dict) else {}
    chart = ""
    if samples is not None:
        events = (results.get("monitor") or {}).get("events") or []
        chart = charts.econ_timeline_chart(samples, events)
    if not econ and not chart:
        return ""
    parts = ["<section><h2>Economics</h2>"]
    facts = []
    if econ.get("usd_per_1k_tokens") is not None:
        facts.append(f"live ${econ['usd_per_1k_tokens']:.4f}/1K tok")
    if econ.get("wh_per_1k_tokens") is not None:
        facts.append(f"{econ['wh_per_1k_tokens']:.3f} Wh/1K tok")
    if econ.get("usd_per_hour") is not None:
        facts.append(f"${econ['usd_per_hour']:.2f}/h burn")
    if econ.get("tokens_per_sec") is not None:
        facts.append(f"{econ['tokens_per_sec']:.1f} tok/s priced")
    if econ.get("marginal_replica_usd_per_1k_tokens") is not None:
        facts.append(
            "marginal replica "
            f"${econ['marginal_replica_usd_per_1k_tokens']:.4f}/1K tok"
        )
    posthoc = results.get("cost_per_1k_tokens")
    if posthoc is not None and econ.get("usd_per_1k_tokens"):
        facts.append(f"post-hoc estimate ${posthoc:.4f}/1K tok")
    if facts:
        parts.append(f"<p>{html_mod.escape(' · '.join(facts))}</p>")
    if econ.get("source"):
        parts.append(
            f"<p class='l'>source: {html_mod.escape(str(econ['source']))}</p>"
        )
    for e in ((results.get("monitor") or {}).get("events") or []):
        if isinstance(e, dict) and e.get("type") in (
            "cost_burn_exceeded", "replica_unprofitable"
        ):
            parts.append(
                f"<p class='warn'>event @{e.get('t', 0):.0f}: "
                f"<b>{html_mod.escape(str(e.get('type')))}</b> — "
                f"{html_mod.escape(str(e.get('detail', '')))}</p>"
            )
    if chart:
        parts.append(chart)
    parts.append("</section>")
    return "".join(parts)


def _fleet_section(results: dict[str, Any]) -> str:
    """The "Serving fleet" section (docs/FLEET.md): replica counts,
    placement mix, re-placements the clients never saw, fleet-level
    sheds, self-healing restarts and scale-step cold starts. Rendered
    only for runs that went through the fleet router — a single-server
    run's report simply has no section."""
    fl = results.get("fleet")
    if not isinstance(fl, dict):
        return ""
    parts = ["<section><h2>Serving fleet</h2>"]
    facts = [
        f"{fl.get('replicas_live', 0):.0f}/{fl.get('replicas_desired', 0):.0f}"
        " replicas live"
    ]
    if fl.get("placements"):
        facts.append(f"{fl['placements']:.0f} placement(s)")
    if fl.get("reroutes"):
        facts.append(
            f"{fl['reroutes']:.0f} re-placement(s) absorbed before any "
            "client saw them"
        )
    if fl.get("sheds"):
        facts.append(f"{fl['sheds']:.0f} fleet-level shed(s)")
    if fl.get("stream_errors"):
        facts.append(
            f"{fl['stream_errors']:.0f} mid-stream replica loss(es) "
            "surfaced as honest terminal events"
        )
    if fl.get("replica_restarts"):
        facts.append(
            f"{fl['replica_restarts']:.0f} replica(s) self-healed"
        )
    scale_steps = (fl.get("scale_ups") or 0) + (fl.get("scale_downs") or 0)
    if scale_steps:
        facts.append(
            f"{fl.get('scale_ups', 0):.0f} scale-up(s) / "
            f"{fl.get('scale_downs', 0):.0f} scale-down(s)"
        )
    if fl.get("last_cold_start_s"):
        facts.append(
            f"last scale-up cold start {fl['last_cold_start_s']:.2f} s"
        )
    parts.append(f"<p>{html_mod.escape(' · '.join(facts))}</p>")
    outlier = results.get("routing_outlier")
    if isinstance(outlier, dict) and outlier.get("decisions"):
        # the analyzer joined the p99-latency request back to its router
        # decision(s) (docs/TRACING.md "Fleet tracing"): where it landed
        # and why — two placement rows mean the request was re-placed
        where = "; ".join(
            f"{d.get('chosen', '?')} ({d.get('reason', '?')}, "
            f"{len(d.get('candidates') or [])} candidate(s))"
            for d in outlier["decisions"]
        )
        parts.append(
            f"<p class='warn'>p99 outlier trace "
            f"{html_mod.escape(str(outlier.get('trace_id', '?')))} "
            f"({outlier.get('latency_ms', 0):.1f} ms) placed on: "
            f"{html_mod.escape(where)}</p>"
        )
    for e in ((results.get("monitor") or {}).get("events") or []):
        if isinstance(e, dict) and e.get("type") == "replica_down":
            parts.append(
                f"<p>event @{e.get('t', 0):.0f}: <b>replica_down</b> — "
                f"{html_mod.escape(str(e.get('detail', '')))}</p>"
            )
    parts.append("</section>")
    return "".join(parts)


def generate_single_run_html(
    results: dict[str, Any], run_dir: Optional[Path] = None
) -> str:
    label, why = classify_bottleneck(results)
    recs = generate_recommendations(results)
    breakeven = prewarm_breakeven(results)

    cards = "".join(
        [
            _card("p95 latency", results.get("p95_ms"), " ms"),
            _card("TTFT p50", results.get("ttft_p50_ms"), " ms"),
            _card("throughput", results.get("throughput_rps"), " rps"),
            _card("tokens/sec", results.get("tokens_per_sec")),
            _card("error rate", (results.get("error_rate") or 0) * 100, "%"),
            _card("$/1K tokens", results.get("cost_per_1k_tokens")),
            _card("Wh/1K tokens", results.get("energy_wh_per_1k_tokens")),
            _card("TPU duty", _duty_pct(results), "%"),
            _card("cold multiplier", results.get("cold_multiplier"), "x"),
            _card("quality", results.get("quality_score")),
        ]
    )

    sections = [
        f"<h1>Benchmark report — {html_mod.escape(str(results.get('model', 'run')))}</h1>",
        f"<p>{html_mod.escape(str(results.get('runtime', '')))} · "
        f"{html_mod.escape(str(results.get('accelerator', '') or ''))} · "
        f"pattern {html_mod.escape(str(results.get('pattern', '?')))} · "
        f"{results.get('requests', '?')} requests</p>",
        f'<div class="cards">{cards}</div>',
        f"<section><h2>Bottleneck: {label}</h2><p>{html_mod.escape(why)}</p></section>",
        "<section><h2>Latency</h2>",
        charts.latency_histogram_chart(results),
        charts.ttft_vs_latency_chart(results),
        "</section>",
    ]
    pm = results.get("per_model")
    if pm:
        # multi-LoRA runs: one row per adapter/model so a slow fine-tune
        # can't hide behind a fast base in the aggregates
        def _cell(m: dict, key: str) -> str:
            # an all-error adapter has NO latency keys (metrics.py omits
            # them on purpose) — absence must render as "—", never 0.0 ms,
            # or the broken adapter looks like the fastest row
            return f"{m[key]:.1f}" if key in m else "—"

        rows = "".join(
            f"<tr><td>{html_mod.escape(name)}</td>"
            f"<td>{m.get('requests', 0)}</td>"
            f"<td>{_cell(m, 'p50_ms')}</td>"
            f"<td>{_cell(m, 'p95_ms')}</td>"
            f"<td>{_cell(m, 'ttft_p95_ms')}</td>"
            f"<td>{_cell(m, 'tokens_per_sec')}</td>"
            f"<td>{100 * m.get('error_rate', 0):.1f}%</td></tr>"
            for name, m in pm.items()
        )
        sections.append(
            "<section><h2>Per model / adapter</h2><table>"
            "<tr><th>model</th><th>requests</th><th>p50 ms</th>"
            "<th>p95 ms</th><th>TTFT p95 ms</th><th>tok/s</th>"
            "<th>errors</th></tr>" + rows + "</table></section>"
        )

    if run_dir is not None:
        # convention: the autoscale controller's --decision-log written
        # into the run dir as autoscale_decisions.jsonl
        dec_path = run_dir / "autoscale_decisions.jsonl"
        if dec_path.exists():
            decisions = []
            for line in dec_path.read_text().splitlines():
                try:
                    decisions.append(json.loads(line))
                except ValueError:
                    continue  # a kill mid-append truncates the last line —
                              # degrade, don't abort the whole report
            chart = charts.autoscale_timeline_chart(decisions)
            if chart:
                sections.append(
                    f"<section><h2>Autoscale decisions</h2>{chart}</section>"
                )
        # the policy simulator's replay (kvmini-tpu autoscale-sim
        # --run-dir ...) writes the same decision shape plus a summary —
        # render it beside the live timeline so recorded traffic and its
        # simulated what-if share one report
        sim_path = run_dir / "autoscale_sim.json"
        if sim_path.exists():
            try:
                sim = json.loads(sim_path.read_text())
            except ValueError:
                sim = None
            if isinstance(sim, dict) and sim.get("decisions"):
                chart = charts.autoscale_timeline_chart(sim["decisions"])
                summ = sim.get("summary", {})
                facts = " · ".join(
                    f"{k.replace('_', ' ')}: {v}"
                    for k, v in summ.items()
                    if k in ("peak_replicas", "replica_seconds",
                             "wait_p95_s", "peak_queue", "unserved_at_end")
                )
                if chart:
                    sections.append(
                        "<section><h2>Autoscale policy simulation</h2>"
                        f"<p>{html_mod.escape(facts)}</p>{chart}</section>"
                    )

    cw = charts.cold_warm_chart(results)
    if cw:
        sections.append(f"<section><h2>Cold vs warm</h2>{cw}")
        if breakeven:
            sections.append(f"<p>{html_mod.escape(breakeven['explanation'])}</p>")
        sections.append("</section>")
    cb = charts.cost_breakdown_chart(results)
    if cb:
        sections.append(f"<section><h2>Cost</h2>{cb}</section>")
    sections.append(
        "<section><h2>Recommendations</h2><ul class='recs'>"
        + "".join(f"<li>{html_mod.escape(r)}</li>" for r in recs)
        + "</ul></section>"
    )
    sections.append(_compile_stats_section(results))
    # one timeline.jsonl parse shared by the KV/memory and run-timeline
    # sections (a long run's 1 Hz timeline is multi-MB)
    timeline_samples: Optional[list[dict[str, Any]]] = None
    if run_dir is not None:
        from kserve_vllm_mini_tpu.core.rundir import RunDir

        timeline_samples = RunDir(run_dir).read_timeline()
    sections.append(_kv_cache_section(results, run_dir, timeline_samples))
    sections.append(_economics_section(results, run_dir, timeline_samples))
    sections.append(_disagg_section(results))
    sections.append(_fleet_section(results))
    sections.append(_resilience_section(results))
    sections.append(_timeline_section(run_dir, results, timeline_samples))
    sections.append(_trace_viewer(run_dir, results))
    sections.append(
        "<section><h2>Raw results</h2><details><summary>results.json</summary>"
        f"<pre>{html_mod.escape(json.dumps(results, indent=2, sort_keys=True))}</pre>"
        "</details></section>"
    )
    return (
        f"<html><head><meta charset='utf-8'><title>kvmini-tpu report</title>"
        f"<style>{_CSS}</style></head><body>{''.join(sections)}</body></html>"
    )


def _read_sweep_csv(path: Path) -> list[dict[str, str]]:
    with path.open(newline="") as f:
        return list(csv.DictReader(f))


def generate_grid_sweep_html(csv_path: Path, metric: str = "p95_ms") -> str:
    """Heatmaps over concurrency x max_tokens per pattern
    (reference report_generator.py:597-771)."""
    rows = _read_sweep_csv(csv_path)
    patterns = sorted({r.get("pattern", "?") for r in rows})
    sections = [f"<h1>Grid sweep — {html_mod.escape(metric)}</h1>"]
    for pat in patterns:
        sub = [r for r in rows if r.get("pattern") == pat]
        concs = sorted({int(r["concurrency"]) for r in sub if r.get("concurrency")})
        toks = sorted({int(r["max_tokens"]) for r in sub if r.get("max_tokens")})
        grid: list[list[Optional[float]]] = []
        for c in concs:
            row_vals: list[Optional[float]] = []
            for t in toks:
                match = [
                    r for r in sub
                    if int(r.get("concurrency", -1)) == c and int(r.get("max_tokens", -1)) == t
                ]
                try:
                    row_vals.append(float(match[0][metric]) if match else None)
                except (KeyError, ValueError):
                    row_vals.append(None)
            grid.append(row_vals)
        sections.append(f"<section><h2>pattern: {html_mod.escape(pat)}</h2>")
        sections.append(
            charts.heatmap_chart(
                [f"conc {c}" for c in concs],
                [f"{t} tok" for t in toks],
                grid,
                f"{metric} ({pat})",
                fmt="{:.0f}",
            )
        )
        sections.append("</section>")
    # cost-vs-latency Pareto over the whole sweep (docs/ECONOMICS.md):
    # cells that carried neither a live nor a post-hoc price drop out of
    # the scatter; with fewer than two priced cells there is no frontier
    pareto = charts.cost_pareto_chart(rows)
    if pareto:
        sections.append(
            "<section><h2>Cost vs TTFT p95 (Pareto)</h2>"
            "<p>Cells northeast of the frontier pay for latency they "
            f"aren't getting.</p>{pareto}</section>"
        )
    return (
        f"<html><head><meta charset='utf-8'><style>{_CSS}</style></head>"
        f"<body>{''.join(sections)}</body></html>"
    )


def generate_topology_matrix_html(csv_path: Path) -> str:
    """Topology-slice matrix (v5e-1/-4/-8 ...), the MIG-matrix analog
    (reference report_generator.py:774-827)."""
    rows = _read_sweep_csv(csv_path)
    header = (
        "<tr><th>topology</th><th>chips</th><th>p95 ms</th><th>TTFT p50 ms</th>"
        "<th>tokens/s</th><th>tokens/s/chip</th><th>$/1K tok</th><th>verdict</th></tr>"
    )
    body = []
    best_eff: Optional[float] = None
    for r in rows:
        try:
            eff = float(r.get("tokens_per_sec_per_chip") or 0)
        except ValueError:
            eff = 0.0
        best_eff = max(best_eff or 0.0, eff)
    for r in rows:
        try:
            eff = float(r.get("tokens_per_sec_per_chip") or 0)
        except ValueError:
            eff = 0.0
        verdict = "most efficient" if best_eff and eff == best_eff else ""
        body.append(
            "<tr>"
            f"<td>{html_mod.escape(r.get('topology', '?'))}</td>"
            f"<td>{html_mod.escape(r.get('chips', '?'))}</td>"
            f"<td>{html_mod.escape(r.get('p95_ms', ''))}</td>"
            f"<td>{html_mod.escape(r.get('ttft_p50_ms', ''))}</td>"
            f"<td>{html_mod.escape(r.get('tokens_per_sec', ''))}</td>"
            f"<td>{eff:.1f}</td>"
            f"<td>{html_mod.escape(r.get('cost_per_1k_tokens', ''))}</td>"
            f"<td class='ok'>{verdict}</td></tr>"
        )
    return (
        f"<html><head><meta charset='utf-8'><style>{_CSS}</style></head><body>"
        "<h1>Topology matrix</h1><table>"
        + header + "".join(body) + "</table></body></html>"
    )


# -- CLI ---------------------------------------------------------------------

def register(parser: argparse.ArgumentParser) -> None:
    src = parser.add_mutually_exclusive_group(required=True)
    src.add_argument("--input", help="results.json or run dir")
    src.add_argument("--grid-sweep", help="Grid sweep CSV")
    src.add_argument("--topology-matrix", help="Topology matrix CSV")
    parser.add_argument("--metric", default="p95_ms", help="Sweep heatmap metric")
    parser.add_argument("--output", required=True, help="Output .html path")


def run(args: argparse.Namespace) -> int:
    if args.input:
        p = Path(args.input)
        run_dir = p if p.is_dir() else p.parent
        results_path = p / "results.json" if p.is_dir() else p
        with results_path.open() as f:
            results = json.load(f)
        html = generate_single_run_html(results, run_dir=run_dir)
    elif args.grid_sweep:
        html = generate_grid_sweep_html(Path(args.grid_sweep), metric=args.metric)
    else:
        html = generate_topology_matrix_html(Path(args.topology_matrix))
    Path(args.output).write_text(html)
    print(f"report: wrote {args.output} ({len(html)} bytes)")
    return 0
