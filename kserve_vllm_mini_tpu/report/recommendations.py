"""Analysis models behind the report: bottleneck classification, prewarm
break-even, recommendations.

Reference behavior: headroom/bottleneck heuristics
(report_generator.py:199-245), prewarm break-even RPS model (:131-196), and
the recommendations engine (:315-395) — recalibrated for TPU serving (cold
starts are minutes; the bottleneck taxonomy gains an HBM-bound class).
"""

from __future__ import annotations

from typing import Any, Optional

from kserve_vllm_mini_tpu.costs.planner import DEFAULT_COLD_START_S, HOURS_PER_MONTH


def classify_bottleneck(results: dict[str, Any]) -> tuple[str, str]:
    """(label, explanation). Heuristics over the measured signals."""
    # windowed average when a run carried one (Prometheus or the monitor
    # timeline); the instantaneous end-of-run snapshot is the fallback
    duty = results.get("tpu_duty_cycle_avg")
    if duty is None:
        duty = results.get("tpu_duty_cycle")
    rtt_p95 = results.get("network_rtt_p95_ms")
    p95 = results.get("p95_ms")
    ttft_p95 = results.get("ttft_p95_ms")
    tpot_p95 = results.get("tpot_p95_ms")

    if p95 is None:
        return "unknown", "no successful requests to classify"
    if rtt_p95 is not None and p95 and rtt_p95 > 0.3 * p95:
        return (
            "network-bound",
            f"endpoint RTT p95 ({rtt_p95:.0f} ms) is >30% of request p95 — "
            "move the load generator closer or check the ingress path",
        )
    if duty is not None and duty > 0.85:
        return (
            "compute-bound",
            f"TPU duty cycle {duty:.0%}: the chip is saturated — scale out "
            "(more chips / replicas) or quantize to int8",
        )
    if ttft_p95 is not None and p95 and ttft_p95 > 0.6 * p95:
        return (
            "scheduler-bound",
            f"TTFT p95 ({ttft_p95:.0f} ms) dominates request p95 — requests "
            "queue before prefill; raise engine slots or add replicas",
        )
    if duty is not None and duty < 0.3 and tpot_p95 is not None:
        return (
            "hbm-bound",
            f"duty cycle only {duty:.0%} with steady token cadence "
            f"({tpot_p95:.1f} ms/token p95): decode is HBM-bandwidth bound — "
            "batch more requests per step or shrink the KV cache (shorter "
            "max_seq, int8 KV)",
        )
    return "balanced", "no single dominant bottleneck detected"


def prewarm_breakeven(
    results: dict[str, Any],
    cold_start_s: float = DEFAULT_COLD_START_S,
    chip_hourly_usd: Optional[float] = None,
) -> Optional[dict[str, Any]]:
    """At what request rate does keeping a warm replica beat eating cold
    starts? (reference report_generator.py:131-196, TPU cold-start scale).

    Cold cost per event ~ extra latency cost proxy: (cold_p95 - warm_p95) x
    requests affected. Monetary comparison: warm replica $/h vs cold events/h
    x wasted chip-seconds."""
    cold_p95 = results.get("cold_p95_ms")
    warm_p95 = results.get("warm_p95_ms")
    chip_hourly = chip_hourly_usd or results.get("cost_chip_hourly")
    if cold_p95 is None or warm_p95 is None or not chip_hourly:
        return None
    from kserve_vllm_mini_tpu.costs.planner import breakeven_events_per_hour

    # each cold event wastes ~cold_start_s of one chip
    cold_event_usd = chip_hourly * cold_start_s / 3600.0
    warm_replica_usd_per_h = chip_hourly
    breakeven = breakeven_events_per_hour(cold_start_s)
    return {
        "cold_event_usd": round(cold_event_usd, 4),
        "warm_replica_usd_per_hour": round(warm_replica_usd_per_h, 4),
        "breakeven_cold_events_per_hour": round(breakeven, 2),
        "monthly_warm_cost_usd": round(warm_replica_usd_per_h * HOURS_PER_MONTH, 2),
        "explanation": (
            f"keep a warm replica when cold starts exceed "
            f"~{breakeven:.1f}/hour (each cold start wastes "
            f"~{cold_start_s:.0f}s of chip time)"
        ),
    }


def generate_recommendations(results: dict[str, Any]) -> list[str]:
    recs: list[str] = []
    label, why = classify_bottleneck(results)
    if label != "balanced" and label != "unknown":
        recs.append(f"[{label}] {why}")

    err = results.get("error_rate", 0.0)
    if err and err > 0.02:
        recs.append(
            f"error rate {err:.1%} exceeds 2%: inspect per-request errors in "
            "requests.csv before trusting latency numbers"
        )
    mult = results.get("cold_multiplier")
    if mult and mult > 3.0:
        recs.append(
            f"cold requests are {mult:.1f}x slower than warm: consider minScale>=1 "
            "or a warm pool (see prewarm break-even)"
        )
    cache = results.get("cache_hit_ratio")
    if cache is not None and cache < 0.2:
        recs.append(
            f"prompt-cache hit ratio {cache:.0%}: enable prefix caching or "
            "normalize system prompts across tenants"
        )
    cost = results.get("cost_per_1k_tokens")
    if cost and cost > 0.05:
        recs.append(
            f"cost ${cost:.4f}/1K tokens exceeds the $0.05 budget: try int8 "
            "quantization (2x density) or a smaller topology slice"
        )
    energy = results.get("energy_wh_per_1k_tokens")
    if energy and energy > 50:
        recs.append(
            f"energy {energy:.1f} Wh/1K tokens over budget: raise batch size "
            "(amortize weight streaming) or use a more efficient slice"
        )
    if results.get("power_provenance") == "modeled":
        recs.append(
            "energy figures are MODELED (duty-cycle x TDP), not measured — "
            "deploy the node telemetry agent for measured power"
        )
    trunc = results.get("truncated_requests")
    if trunc:
        recs.append(
            f"{trunc} request(s) had prompt HEADS dropped to fit the KV "
            f"window ({results.get('truncated_prompt_tokens', 0)} tokens cut "
            "from the beginnings - system prompts/examples go first): "
            "the measured workload is NOT the submitted workload — raise "
            "--max-seq-len or shorten prompts before comparing runs"
        )
    if not recs:
        recs.append("all signals within budgets; no action needed")
    return recs
