"""Weight-only int8 quantization for the serving path.

The reference treats quantization as an engine flag it sweeps and measures
(sweeps/quantization_sweep.py:40-234, runners/profiles/quantization/*.yaml);
the engines themselves do the work. Here the runtime is in-repo, so the knob
is real: per-output-channel symmetric int8 on every transformer matmul
weight, stored as ``{"q": int8 [..., in, out], "s": f32 [..., out]}``.

TPU-first shape of the trick: the int8 tensor halves HBM traffic vs bf16
(weights are the dominant stream during decode), and the dequantize —
``(x @ q) * s`` — is a trailing elementwise multiply XLA fuses into the
matmul's epilogue on the MXU. Activations stay bf16, so accuracy loss is the
weight rounding only (the usual "W8A16" recipe, cf. AWQ/GPTQ claims at
reference README.md:119-121).
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp

# A quantized linear leaf is a dict with exactly these keys; the AWQ
# variant (ops/awq.py) adds "a" — the per-INPUT-channel runtime multiplier
# (1/s of the calibration scaling), applied to activations before the
# matmul and folded back by dequantize_weight.
_QKEYS = frozenset({"q", "s"})
_QKEYS_AWQ = frozenset({"q", "s", "a"})


def is_quantized(leaf: Any) -> bool:
    return isinstance(leaf, dict) and set(leaf.keys()) in (_QKEYS, _QKEYS_AWQ)


def quantize_weight(w: jnp.ndarray, bits: int = 8) -> dict[str, jnp.ndarray]:
    """Per-output-channel symmetric int8/int4 over the input (second-to-last)
    axis. Works on [in, out] and layer-stacked [L, in, out] alike: the scale
    is computed over axis -2 and has shape [..., out].

    ``bits=4`` stores the nibbles PACKED two-per-``uint8`` along the output
    axis (``q`` shape [..., in, out//2]) rather than as native ``jnp.int4``
    leaves: an S4 array at a jit dispatch boundary triggers a relayout
    ``device_put`` that recurses into jit (measured on the v5e relay —
    RecursionError at dispatch), while a uint8 leaf crosses cleanly and is
    bitcast back to int4 *inside* the compiled program (``_unpack_int4``),
    where XLA's native two-nibbles-per-byte S4 representation takes over.
    HBM still streams half the int8 bytes — the W4A16 recipe; the quality
    cost is what the quantization sweep's fidelity axis measures.
    """
    if bits not in (8, 4):
        raise ValueError(f"bits must be 8 or 4, got {bits}")
    qmax = 127.0 if bits == 8 else 7.0
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=-2, keepdims=True)
    scale = jnp.where(amax > 0, amax / qmax, 1.0)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -qmax, qmax)
    if bits == 4:
        if w.shape[-1] % 2:
            raise ValueError(f"int4 packing needs an even output dim, got {w.shape}")
        n = q.astype(jnp.int8)
        # element 2i -> low nibble of byte i, 2i+1 -> high nibble: the order
        # _unpack_int4's mask/shift unpack restores (pinned by
        # tests/test_quant.py test_int4_unpack_traced_matches_eager, which
        # compares the jitted unpack against the eager one)
        lo = n[..., 0::2] & 0x0F
        hi = n[..., 1::2] & 0x0F
        packed = (lo | (hi << 4)).astype(jnp.uint8)
        return {"q": packed, "s": scale.squeeze(-2).astype(jnp.float32)}
    return {"q": q.astype(jnp.int8), "s": scale.squeeze(-2).astype(jnp.float32)}


def is_packed_int4(qw: dict[str, jnp.ndarray]) -> bool:
    """Packed-int4 leaves are discriminated by dtype: uint8 holds nibble
    pairs, int8 holds plain int8 channels."""
    return qw["q"].dtype == jnp.uint8


def _unpack_int4(packed: jnp.ndarray) -> jnp.ndarray:
    """uint8 [..., out//2] nibble pairs -> [..., out] int8 tensor.

    Arithmetic unpack (mask / shift / sign-extend), identical traced and
    eager. NOT a ``lax.bitcast_convert_type(..., int4)``: on this JAX line
    the sub-byte bitcast keeps the byte shape at abstract-eval time (no
    trailing nibble axis), so the following widen-to-[..., out] reshape is
    a width mismatch — and the lowering fails the MLIR verifier anyway
    (KVM063's sub-byte-bitcast rule pins this). An S4 intermediate at a
    dispatch boundary also recurses into relayout (see quantize_weight).
    The arithmetic form still streams only the packed bytes from HBM: XLA
    fuses the mask/shift into the consumer's producer epilogue."""
    lo = (packed & 0x0F).astype(jnp.int8)
    hi = (packed >> 4).astype(jnp.int8)
    lo = jnp.where(lo > 7, lo - 16, lo)
    hi = jnp.where(hi > 7, hi - 16, hi)
    out = jnp.stack([lo, hi], axis=-1)  # [..., out//2, 2]
    return out.reshape(*packed.shape[:-1], packed.shape[-1] * 2)


def unpacked_q(qw: dict[str, jnp.ndarray]) -> jnp.ndarray:
    """The quantized weight as its logical [..., in, out] integer tensor."""
    return _unpack_int4(qw["q"]) if is_packed_int4(qw) else qw["q"]


def dequantize_weight(qw: dict[str, jnp.ndarray], dtype=jnp.bfloat16) -> jnp.ndarray:
    q = unpacked_q(qw)
    deq = q.astype(jnp.float32) * qw["s"][..., None, :].astype(jnp.float32)
    if "a" in qw:
        # AWQ leaf: the stored integers encode W*s; fold the input scaling
        # back (a = 1/s) so this returns the effective weight
        deq = deq * qw["a"][..., :, None].astype(jnp.float32)
    return deq.astype(dtype)


def linear(x: jnp.ndarray, w: Any, mode: str = "dequant") -> jnp.ndarray:
    """``x @ w`` where ``w`` is a plain array or a quantized dict.

    ``mode`` is the quant_mode axis (ops/qmatmul.py QUANT_MODES):

    - ``"dequant"`` (default, W8A16/W4A16): the matmul runs with the int
      tensor cast to the activation dtype (one fused convert feeding the
      MXU) and the per-channel scale applied to the [..., out] result — an
      epilogue multiply, not a materialized dequantized weight. AWQ leaves
      additionally multiply the activations by the per-input-channel
      compensation (``a``) first — a producer-side elementwise op XLA
      fuses; HBM traffic is unchanged.
    - ``"w8a8"``: activations are quantized per token and the contraction
      runs int8 x int8 on the MXU with an int32 accumulator, scales folded
      post-accumulation (ops/qmatmul.py qdot). Plain (unquantized) weights
      are unaffected by the mode.
    """
    if is_quantized(w):
        if mode == "w8a8":
            from kserve_vllm_mini_tpu.ops.qmatmul import qdot

            return qdot(x, w)
        if "a" in w:
            x = x * w["a"].astype(x.dtype)
        y = x @ unpacked_q(w).astype(x.dtype)
        return y * w["s"].astype(x.dtype)
    return x @ w


# Names of the per-layer matmul weights that quantization applies to
# (models/llama.py init_params layout). Norms, embeddings, and the lm_head
# stay high-precision — standard practice, and the embed is a gather anyway.
QUANTIZABLE = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def quantize_params(params: dict[str, Any], bits: int = 8) -> dict[str, Any]:
    """Quantize every transformer matmul weight in a Llama param tree."""
    out = dict(params)
    out["layers"] = {
        k: (quantize_weight(v, bits=bits) if k in QUANTIZABLE else v)
        for k, v in params["layers"].items()
    }
    return out


def quantized_bytes(params: dict[str, Any]) -> int:
    """Total parameter bytes, honoring quantized leaves (for /metrics + logs).

    int4 counts as half a byte per element — XLA packs pairs in TPU HBM
    even though host-side ml_dtypes reports itemsize 1."""
    import jax
    import jax.numpy as jnp

    total = 0
    for leaf in jax.tree.leaves(params):
        if leaf.dtype == jnp.int4:
            total += (leaf.size + 1) // 2
        else:
            total += leaf.size * leaf.dtype.itemsize
    return total
