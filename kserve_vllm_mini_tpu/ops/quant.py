"""Weight-only int8 quantization for the serving path.

The reference treats quantization as an engine flag it sweeps and measures
(sweeps/quantization_sweep.py:40-234, runners/profiles/quantization/*.yaml);
the engines themselves do the work. Here the runtime is in-repo, so the knob
is real: per-output-channel symmetric int8 on every transformer matmul
weight, stored as ``{"q": int8 [..., in, out], "s": f32 [..., out]}``.

TPU-first shape of the trick: the int8 tensor halves HBM traffic vs bf16
(weights are the dominant stream during decode), and the dequantize —
``(x @ q) * s`` — is a trailing elementwise multiply XLA fuses into the
matmul's epilogue on the MXU. Activations stay bf16, so accuracy loss is the
weight rounding only (the usual "W8A16" recipe, cf. AWQ/GPTQ claims at
reference README.md:119-121).
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp

# A quantized linear leaf is a dict with exactly these keys.
_QKEYS = frozenset({"q", "s"})


def is_quantized(leaf: Any) -> bool:
    return isinstance(leaf, dict) and set(leaf.keys()) == _QKEYS


def quantize_weight(w: jnp.ndarray, bits: int = 8) -> dict[str, jnp.ndarray]:
    """Per-output-channel symmetric int8/int4 over the input (second-to-last)
    axis. Works on [in, out] and layer-stacked [L, in, out] alike: the scale
    is computed over axis -2 and has shape [..., out].

    ``bits=4`` stores ``jnp.int4`` leaves — XLA packs them two-per-byte in
    TPU HBM, quartering the dominant decode weight stream vs bf16 (the
    W4A16 recipe; the quality cost is what the quantization sweep's
    fidelity axis measures).
    """
    if bits not in (8, 4):
        raise ValueError(f"bits must be 8 or 4, got {bits}")
    qmax = 127.0 if bits == 8 else 7.0
    qdt = jnp.int8 if bits == 8 else jnp.int4
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=-2, keepdims=True)
    scale = jnp.where(amax > 0, amax / qmax, 1.0)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -qmax, qmax).astype(qdt)
    return {"q": q, "s": scale.squeeze(-2).astype(jnp.float32)}


def dequantize_weight(qw: dict[str, jnp.ndarray], dtype=jnp.bfloat16) -> jnp.ndarray:
    return (qw["q"].astype(jnp.float32) * qw["s"][..., None, :].astype(jnp.float32)).astype(dtype)


def linear(x: jnp.ndarray, w: Any) -> jnp.ndarray:
    """``x @ w`` where ``w`` is a plain array or a quantized dict.

    For int8 weights the matmul runs with the int8 tensor cast to the
    activation dtype (one fused convert feeding the MXU) and the per-channel
    scale applied to the [..., out] result — an epilogue multiply, not a
    materialized dequantized weight.
    """
    if is_quantized(w):
        y = x @ w["q"].astype(x.dtype)
        return y * w["s"].astype(x.dtype)
    return x @ w


# Names of the per-layer matmul weights that quantization applies to
# (models/llama.py init_params layout). Norms, embeddings, and the lm_head
# stay high-precision — standard practice, and the embed is a gather anyway.
QUANTIZABLE = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def quantize_params(params: dict[str, Any], bits: int = 8) -> dict[str, Any]:
    """Quantize every transformer matmul weight in a Llama param tree."""
    out = dict(params)
    out["layers"] = {
        k: (quantize_weight(v, bits=bits) if k in QUANTIZABLE else v)
        for k, v in params["layers"].items()
    }
    return out


def quantized_bytes(params: dict[str, Any]) -> int:
    """Total parameter bytes, honoring quantized leaves (for /metrics + logs).

    int4 counts as half a byte per element — XLA packs pairs in TPU HBM
    even though host-side ml_dtypes reports itemsize 1."""
    import jax
    import jax.numpy as jnp

    total = 0
    for leaf in jax.tree.leaves(params):
        if leaf.dtype == jnp.int4:
            total += (leaf.size + 1) // 2
        else:
            total += leaf.size * leaf.dtype.itemsize
    return total
