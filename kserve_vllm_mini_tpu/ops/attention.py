"""Scaled dot-product attention with GQA, in XLA-fusable jnp.

This is the reference implementation every optimized kernel (Pallas flash
attention for prefill, paged decode attention) must match bit-for-bit within
bf16 tolerance. Softmax runs in float32; the two matmuls stay bf16 for the
MXU. Shapes follow the [B, heads, T, head_dim] convention throughout the
framework.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from jax import nn


def repeat_kv(k: jnp.ndarray, n_rep: int) -> jnp.ndarray:
    """[B, KVH, S, D] -> [B, KVH*n_rep, S, D] by head-group broadcast."""
    if n_rep == 1:
        return k
    b, kvh, s, d = k.shape
    k = k[:, :, None, :, :]
    k = jnp.broadcast_to(k, (b, kvh, n_rep, s, d))
    return k.reshape(b, kvh * n_rep, s, d)


def attention(
    q: jnp.ndarray,                      # [B, H, T, D]
    k: jnp.ndarray,                      # [B, KVH, S, D]
    v: jnp.ndarray,                      # [B, KVH, S, D]
    mask: Optional[jnp.ndarray] = None,  # broadcastable to [B, 1|H, T, S]; True = attend
    scale: Optional[float] = None,
    softcap: Optional[float] = None,     # gemma-2: scores -> cap*tanh(s/cap)
) -> jnp.ndarray:
    """Returns [B, H, T, D] in q.dtype.

    GQA is computed grouped — q reshaped to [B, KVH, G, T, D] against
    unexpanded K/V — never via repeat_kv materialization: broadcasting the
    cache to H heads costs G× the KV bytes in HBM traffic per step, which
    made decode per-slot-bound instead of weight-streaming-bound
    (measured ~2× end-to-end decode throughput on llama-1b @ v5e).
    """
    h, kvh = q.shape[1], k.shape[1]
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    if h == kvh:
        logits = jnp.einsum("bhtd,bhsd->bhts", q, k).astype(jnp.float32) * scale
        if softcap is not None:
            logits = jnp.tanh(logits / softcap) * softcap
        if mask is not None:
            logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
        probs = nn.softmax(logits, axis=-1).astype(q.dtype)
        return jnp.einsum("bhts,bhsd->bhtd", probs, v)

    g = h // kvh
    b, _, t, d = q.shape
    s = k.shape[2]
    qg = q.reshape(b, kvh, g, t, d)
    logits = jnp.einsum("bkgtd,bksd->bkgts", qg, k).astype(jnp.float32) * scale
    if softcap is not None:
        logits = jnp.tanh(logits / softcap) * softcap
    if mask is not None:
        # normalize any broadcastable-to-[B, 1|H, T, S] mask to 4-D first
        m4 = mask if mask.ndim == 4 else mask.reshape((1,) * (4 - mask.ndim) + mask.shape)
        if m4.shape[1] == 1:
            m = m4[:, :, None, :, :]                        # [B|1, 1, 1, T, S]
        else:
            # per-head mask: expand to grouped layout (bool, cheap vs KV)
            m = jnp.broadcast_to(m4, (b, h, t, s)).reshape(b, kvh, g, t, s)
        logits = jnp.where(m, logits, jnp.finfo(jnp.float32).min)
    probs = nn.softmax(logits, axis=-1).astype(q.dtype)
    o = jnp.einsum("bkgts,bksd->bkgtd", probs, v)
    return o.reshape(b, h, t, d)


def causal_mask(t: int, s: int, offset: int = 0) -> jnp.ndarray:
    """[T, S] boolean mask: query i attends keys j where j <= i + offset.

    ``offset`` is the number of cached tokens preceding the query block
    (prefill: 0; chunked prefill/decode: cache length)."""
    qi = jnp.arange(t)[:, None] + offset
    kj = jnp.arange(s)[None, :]
    return kj <= qi


def length_mask(lengths: jnp.ndarray, s: int) -> jnp.ndarray:
    """[B, 1, 1, S] boolean: key j valid where j < lengths[b]. For decode
    against a static-size cache where each slot has its own fill level."""
    kj = jnp.arange(s)[None, :]
    return (kj < lengths[:, None])[:, None, None, :]
