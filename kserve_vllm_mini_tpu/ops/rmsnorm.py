"""RMSNorm with float32 accumulation.

bf16 inputs are normalized in f32 (TPU VPU does this cheaply; the MXU never
sees the norm) and cast back, the standard numerically-safe layout for
bf16-parameter models.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float = 1e-5) -> jnp.ndarray:
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    y = xf * jax.lax.rsqrt(var + eps)
    return (y * weight.astype(jnp.float32)).astype(dtype)


def layer_norm(
    x: jnp.ndarray, weight: jnp.ndarray, bias: jnp.ndarray, eps: float = 1e-5
) -> jnp.ndarray:
    """Mean-centered LayerNorm with bias (phi-family blocks), f32 accumulation."""
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    xc = xf - mean
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    y = xc * jax.lax.rsqrt(var + eps)
    return (y * weight.astype(jnp.float32) + bias.astype(jnp.float32)).astype(dtype)
