"""Native-quantized matmul: the W8A8 int8 contraction for the decode path.

``ops/quant.py``'s dequant path keeps HBM traffic at int8/int4 but converts
the weight to the activation dtype before the dot, so the MXU still
contracts in bf16. Decode is weight-stream-bound (ops/quant.py:10) and the
MXU's int8 path doubles its per-cycle multiply throughput vs bf16, so the
remaining lever is keeping int8 *in the contraction*:

- activations are quantized per token (symmetric absmax over the
  contraction axis -> int8 values + one f32 scale per row) right before
  the dot — "dynamic" quantization, no calibration state;
- the contraction is an int8 x int8 ``lax.dot_general`` with
  ``preferred_element_type=jnp.int32`` (the KVM064 accumulator
  convention: without it the accumulator inherits int8 and wraps);
- both scales fold AFTER accumulation:
  ``(x_q @ w_q) * x_s * w_s == (x_q x_s) @ (w_q w_s)`` exactly, because
  per-row/per-column scales commute with the contraction sum;
- packed-int4 weights unpack in the contraction prologue
  (``_unpack_int4``'s mask/shift arithmetic fuses into the dot's operand
  producer), so HBM streams the packed uint8 bytes and the int8 operand
  only ever exists in registers/VMEM;
- AWQ leaves fold their per-input-channel compensation (``a``) into the
  activation-quant pass — same one sweep over the activations, no extra
  op on the weight stream.

The numerics cost vs the dequant path is the activation rounding (<= 1/254
relative per element); ``quality/perplexity.py`` NLL and the sweep's
``quality_perplexity_delta_vs_baseline`` gate keep that honest.

Selected by ``quant_mode="w8a8"`` (ModelConfig/EngineConfig/
``--quant-mode``/``KVMINI_QUANT_MODE``); ``ops.quant.linear`` dispatches.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

# the documented quant_mode axis: "dequant" converts the weight to the
# activation dtype before the dot (W8A16/W4A16 — ops/quant.py), "w8a8"
# quantizes activations per token and contracts in int8 (this module)
QUANT_MODES = ("dequant", "w8a8")


def validate_quant_mode(mode: str) -> str:
    if mode not in QUANT_MODES:
        raise ValueError(
            f"unknown quant_mode {mode!r}; known: {', '.join(QUANT_MODES)}"
        )
    return mode


def quantize_activations(
    x: jnp.ndarray, pre_scale: Optional[jnp.ndarray] = None
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Per-token symmetric int8 over the contraction (last) axis.

    Returns ``(q int8 [..., K], s f32 [..., 1])`` with ``q * s ~= x``.
    ``pre_scale`` is the AWQ per-input-channel compensation ``a`` —
    applied inside the same f32 pass that computes the row amax, so an
    AWQ leaf costs no extra sweep. Zero rows get scale 1.0 (no NaNs,
    mirroring quantize_weight's zero-channel rule)."""
    xf = x.astype(jnp.float32)
    if pre_scale is not None:
        xf = xf * pre_scale.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(xf / scale), -127, 127).astype(jnp.int8)
    return q, scale


def int8_dot(
    xq: jnp.ndarray, wq: jnp.ndarray, batch_dims: int = 0
) -> jnp.ndarray:
    """int8 x int8 contraction with an int32 accumulator (KVM064).

    Contracts ``xq``'s last axis against ``wq``'s first post-batch axis;
    ``batch_dims`` leading axes are shared batch dims (the MoE expert
    axis). Shapes: [*B, ..., K] @ [*B, K, N] -> [*B, ..., N] int32."""
    b = tuple(range(batch_dims))
    return jax.lax.dot_general(
        xq, wq,
        (((xq.ndim - 1,), (batch_dims,)), (b, b)),
        preferred_element_type=jnp.int32,
    )


def qdot(x: jnp.ndarray, qw: dict[str, Any], batch_dims: int = 0) -> jnp.ndarray:
    """``x @ W_eff`` for a quantized leaf, contraction in int8.

    ``qw`` is an ops/quant.py leaf ({q, s[, a]}): int8, packed int4
    (unpacked in the prologue — HBM streams the packed bytes), or AWQ
    (``a`` folded into the activation quant). The int32 accumulator is
    rescaled once post-accumulation — f32 math, then cast to ``x.dtype``
    so downstream fusions see the model dtype."""
    from kserve_vllm_mini_tpu.ops.quant import unpacked_q

    wq = unpacked_q(qw)
    xq, xs = quantize_activations(x, pre_scale=qw.get("a"))
    acc = int8_dot(xq, wq, batch_dims=batch_dims)
    # w_s is per-output-channel [*batch, N]; insert the x-side axes so it
    # broadcasts against the accumulator ([*batch, ..., N]) — all in f32
    ws = qw["s"].astype(jnp.float32)
    extra = acc.ndim - ws.ndim
    if extra:
        ws = ws.reshape(ws.shape[:batch_dims] + (1,) * extra + ws.shape[batch_dims:])
    y = acc.astype(jnp.float32) * xs * ws
    return y.astype(x.dtype)
