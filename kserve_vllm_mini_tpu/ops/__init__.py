from kserve_vllm_mini_tpu.ops.rmsnorm import rms_norm
from kserve_vllm_mini_tpu.ops.rope import rope_frequencies, apply_rope
from kserve_vllm_mini_tpu.ops.attention import attention

__all__ = ["rms_norm", "rope_frequencies", "apply_rope", "attention"]
