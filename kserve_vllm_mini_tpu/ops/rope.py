"""Rotary position embeddings (RoPE), precomputed-table formulation.

Frequencies are computed once per model config and indexed by position ids,
so prefill (positions 0..T) and decode (arbitrary per-slot positions) share
one code path — important under jit where positions are traced values.
"""

from __future__ import annotations

import math
from typing import Optional

import jax.numpy as jnp


def _llama3_scale(inv_freq: jnp.ndarray, scaling: tuple[float, float, float, int]) -> jnp.ndarray:
    """Llama-3.1 'llama3' rope_scaling: long wavelengths divide by ``factor``,
    short ones stay, the band between interpolates smoothly (matches HF
    transformers' _compute_llama3_parameters)."""
    factor, low_freq_factor, high_freq_factor, orig_max_pos = scaling
    low_freq_wavelen = orig_max_pos / low_freq_factor
    high_freq_wavelen = orig_max_pos / high_freq_factor
    wavelen = 2.0 * math.pi / inv_freq
    scaled = inv_freq / factor
    smooth = (orig_max_pos / wavelen - low_freq_factor) / (
        high_freq_factor - low_freq_factor
    )
    mid = (1.0 - smooth) * scaled + smooth * inv_freq
    out = jnp.where(wavelen > low_freq_wavelen, scaled, inv_freq)
    is_mid = (wavelen <= low_freq_wavelen) & (wavelen >= high_freq_wavelen)
    return jnp.where(is_mid, mid, out)


def rope_frequencies(
    head_dim: int,
    max_seq_len: int,
    theta: float,
    rope_scaling: Optional[tuple[float, float, float, int]] = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Return (cos, sin) tables of shape [max_seq_len, head_dim//2], float32."""
    inv_freq = 1.0 / (
        theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )
    if rope_scaling is not None:
        inv_freq = _llama3_scale(inv_freq, rope_scaling)
    pos = jnp.arange(max_seq_len, dtype=jnp.float32)
    angles = jnp.outer(pos, inv_freq)  # [S, D/2]
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(
    x: jnp.ndarray,          # [B, H, T, D]
    positions: jnp.ndarray,  # [B, T] int32
    cos: jnp.ndarray,        # [S, D/2]
    sin: jnp.ndarray,        # [S, D/2]
) -> jnp.ndarray:
    """Rotate pairs (x[..., :D/2], x[..., D/2:]) — the 'split-half' convention
    used by HF Llama, so converted checkpoints are bit-compatible."""
    dtype = x.dtype
    c = cos[positions][:, None, :, :]  # [B, 1, T, D/2]
    s = sin[positions][:, None, :, :]
    d2 = x.shape[-1] // 2
    x1 = x[..., :d2].astype(jnp.float32)
    x2 = x[..., d2:].astype(jnp.float32)
    out = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    return out.astype(dtype)
