"""Activation-aware int4 quantization (AWQ-style) for the serving path.

The reference sweeps autoawq/gptq as first-class quant configs
(reference sweeps/quantization_sweep.py:179-214,
runners/profiles/quantization/autoawq.yaml) — the engines in its container
images do the calibration. Here the runtime is in-repo, so the calibration
loop is too, re-thought for this stack:

1. **Stats** (``collect_activation_stats``): run the model's own
   ``layer_forward`` eagerly, layer by layer, with the shared matmul entry
   point (``ops/lora.adapted_linear`` — every quantizable projection goes
   through it, carrying its target name) temporarily wrapped to record each
   matmul input's per-channel amax. No hook framework, no second model
   implementation: the real layer math produces the real activations.
2. **Scale rule** (``awq_scales``): AWQ's insight is that a few input
   channels with large activations carry most of the output error budget;
   scaling those channels UP before rounding (and compensating at runtime)
   shrinks their relative rounding error. The scales here are
   **exponent-only** (powers of two) and **protect-only** (never < 1):
   ``s_j = 2^max(0, round(alpha * log2(a_j / gmean(a))))``. Exponent-only
   matters because the serving path runs bf16 — multiplying an activation
   by a power of two is an exact exponent shift, so the runtime
   compensation ``x * (1/s)`` reproduces the calibration-time scaling
   bit-for-bit. A free-form f32 scale would round every activation it
   touches (~0.2% relative, per token), silently decorrelating serving
   from the calibration objective — the dtype-drift bug class kvmini-lint
   KVM061 exists for (docs/LINTING.md). Protect-only keeps unprotected
   channels' quantization grids identical to plain int4, so calibration
   can only refine, never perturb, the baseline rounding.
3. **Where and how much**: the serving path (``quantize_params_awq``)
   protects only the norm-fed projections ``AWQ_SERVING_TARGETS``
   (wq/wk/wv/w_gate/w_up) at the canonical ``AWQ_SERVING_ALPHAS =
   (0.5,)``. Those inputs are rmsnorm outputs: the norm weight
   multiplies channelwise, so their outlier pattern is structural —
   token-independent — which is exactly AWQ's premise that calibration
   saliency predicts serving saliency. ``wo``/``w_down`` inputs
   (attention-mixed values, silu-gated products) have data-dependent
   heavy tails; calibration amax there is token-specific, and protecting
   on it misallocates the int4 grid (measured on the outlier CI model:
   it degrades served log-likelihood). The activation-weighted
   weight-rounding error
   ``sum_j a_j^2 * sum_o (deq(Q(W s))_jo / s_j - W_jo)^2`` (the expected
   output MSE under a diagonal activation covariance) remains the scoring
   surface ``awq_scales`` grid-searches for explicit sweeps — but it is a
   weight-space proxy too coarse to rank exponent candidates per layer,
   so serving does not per-layer-search.
4. **Runtime**: the quantized leaf carries ``a = 1/s`` ([..., in]); the
   matmul path multiplies activations by it before the int4 matmul — one
   exact (power-of-two) elementwise op XLA fuses into the matmul's
   producer, so the HBM story (stream half the int8 bytes) is identical
   to plain int4.

Acceptance metric: the quantization sweep's likelihood/fidelity axis
(quality/evaluator.py) — calibrated int4 must beat plain int4 there at
equal speed, which tests/test_quant.py pins on the CPU-testable models.

Memory note: calibration needs the full-precision tree resident plus one
eager forward — fine on hosts and CPU CI; on a 16 GB v5e the 8B bf16 tree
itself does not fit, so calibrate 8B off-chip (CPU host) and ship the
quantized tree, or calibrate from an int8-resident model (stats shift is
second-order).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from kserve_vllm_mini_tpu.ops.quant import (
    QUANTIZABLE,
    dequantize_weight,
    quantize_weight,
)

DEFAULT_ALPHAS = (0.0, 0.25, 0.5, 0.75, 1.0)

# What the serving path applies (see the module docstring): uniform
# exponent protection on the norm-fed projections only, no per-layer
# search — the weight-space proxy mis-ranks exponent candidates, and
# alpha=0.5 is the measured sweet spot (one-to-two octaves of protection
# at ~8x outliers, s=1 on flat channels).
AWQ_SERVING_ALPHAS = (0.5,)

# The projections whose inputs are rmsnorm outputs: channelwise norm
# weights make their outlier pattern structural (stable across tokens),
# so calibration amax transfers to serving. wo/w_down inputs are
# data-dependent (attention mixing, silu gating) and stay plain-int4.
AWQ_SERVING_TARGETS = ("wq", "wk", "wv", "w_gate", "w_up")


def collect_activation_stats(
    params: dict[str, Any],
    cfg,
    tokens: jnp.ndarray,          # [B, T] int32 calibration prompt(s)
) -> dict[str, np.ndarray]:
    """Per-matmul-input channel amax from one eager cache-free forward.

    Returns ``{name: [L, d_in] float32}`` for every QUANTIZABLE target the
    model actually routes through ``adapted_linear`` (MoE expert mats are
    not captured — they fall back to plain quantization).

    Runs layer-by-layer in Python (not under jit/scan) so the recording
    wrapper sees concrete values; a few hundred calibration tokens take
    seconds, and the loop reuses ``layer_forward`` — the same math every
    execution path shares — so the stats are the serving activations.
    """
    from kserve_vllm_mini_tpu.models import llama
    from kserve_vllm_mini_tpu.ops import lora as lora_mod

    stats: dict[str, list[np.ndarray]] = {}
    real = lora_mod.adapted_linear

    def recording(x, w, lora_layer, name, ids, mode="dequant"):
        if name in QUANTIZABLE:
            a = np.max(
                np.abs(np.asarray(x, dtype=np.float32)),
                axis=tuple(range(x.ndim - 1)),
            )
            stats.setdefault(name, []).append(a)
        return real(x, w, lora_layer, name, ids, mode=mode)

    B, T = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
    lora_mod.adapted_linear = recording
    try:
        x = llama.embed_tokens(params, cfg, tokens)
        cos, sin = llama.rope_frequencies(
            cfg.rotary_dim, cfg.max_seq_len, cfg.rope_theta, cfg.rope_scaling
        )
        for layer in range(cfg.n_layers):
            p_l = jax.tree.map(lambda v: v[layer], params["layers"])
            x = llama.layer_forward(
                p_l, cfg, x, positions, cos, sin,
                layer_idx=jnp.int32(layer),
            )
    finally:
        lora_mod.adapted_linear = real
    return {k: np.stack(v).astype(np.float32) for k, v in stats.items()}


def awq_scales(
    w: jnp.ndarray,               # [L, in, out] or [in, out] full-precision
    act_amax: np.ndarray,         # [L, in] or [in]
    bits: int = 4,
    alphas: Sequence[float] = DEFAULT_ALPHAS,
) -> jnp.ndarray:
    """Per-input-channel AWQ scales ``s`` (same leading shape as act_amax):
    exponent-only, protect-only (see the module docstring). With more than
    one alpha candidate the grid is searched PER LAYER against the
    activation-weighted rounding error — alpha=0 is plain quantization
    (s=1), so the selected scales can never score worse than plain int4 on
    the search objective. The serving path passes the single canonical
    ``AWQ_SERVING_ALPHAS`` instead of searching (the proxy mis-ranks
    exponent candidates; see docstring point 3)."""
    w32 = jnp.asarray(w, jnp.float32)
    single = w32.ndim == 2
    if single:
        w32 = w32[None]
    a = jnp.asarray(act_amax, jnp.float32)
    if a.ndim == 1:
        a = a[None]
    a = jnp.maximum(a, 1e-8)
    # normalize by the geometric mean so s is scale-free in the activation
    # units (AWQ's formulation); log-space for stability
    gmean = jnp.exp(jnp.mean(jnp.log(a), axis=-1, keepdims=True))
    log_ratio = jnp.log2(a / gmean)                       # [L, in]
    w_sq_weight = (a * a)[..., None]                      # [L, in, 1]

    def pow2_scales(alpha: float) -> jnp.ndarray:
        return jnp.exp2(jnp.maximum(0.0, jnp.round(alpha * log_ratio)))

    if len(alphas) == 1:
        # no grid to search: the canonical serving path skips the scoring
        # round-trips entirely
        s = pow2_scales(alphas[0])
        return s[0] if single else s

    best_err: Optional[jnp.ndarray] = None
    best_alpha = jnp.zeros((w32.shape[0],), jnp.float32)
    for alpha in alphas:
        s = pow2_scales(alpha)                            # [L, in]
        qw = quantize_weight(w32 * s[..., :, None], bits=bits)
        deq = dequantize_weight(qw, dtype=jnp.float32) / s[..., :, None]
        err = jnp.sum((deq - w32) ** 2 * w_sq_weight, axis=(-2, -1))  # [L]
        if best_err is None:
            best_err, best_alpha = err, jnp.full_like(best_alpha, alpha)
        else:
            take = err < best_err
            best_err = jnp.where(take, err, best_err)
            best_alpha = jnp.where(take, alpha, best_alpha)
    s = jnp.exp2(jnp.maximum(0.0, jnp.round(best_alpha[:, None] * log_ratio)))
    return s[0] if single else s


def quantize_weight_awq(
    w: jnp.ndarray,
    act_amax: np.ndarray,
    bits: int = 4,
    alphas: Sequence[float] = AWQ_SERVING_ALPHAS,
) -> dict[str, jnp.ndarray]:
    """AWQ-calibrated quantized leaf: ``{"q", "s", "a"}`` where ``a = 1/s``
    is the runtime input-channel multiplier (ops/quant.linear applies it
    before the matmul; dequantize_weight folds it back). ``s`` is a power
    of two, so ``a`` is exactly representable in every float dtype and the
    runtime multiply is rounding-free in bf16."""
    s = awq_scales(w, act_amax, bits=bits, alphas=alphas)
    qw = quantize_weight(jnp.asarray(w, jnp.float32) * s[..., :, None], bits=bits)
    qw["a"] = (1.0 / s).astype(jnp.float32)
    return qw


def quantize_params_awq(
    params: dict[str, Any],
    cfg,
    tokens: Optional[jnp.ndarray] = None,
    stats: Optional[dict[str, np.ndarray]] = None,
    bits: int = 4,
    alphas: Sequence[float] = AWQ_SERVING_ALPHAS,
    targets: Sequence[str] = AWQ_SERVING_TARGETS,
) -> dict[str, Any]:
    """Quantize a full-precision Llama tree with activation-aware scales.

    Pass calibration ``tokens`` (stats are collected here) or precomputed
    ``stats``. Only ``targets`` (default: the norm-fed projections — see
    the module docstring) get AWQ scales; everything else QUANTIZABLE,
    and any target without stats (e.g. MoE experts), falls back to plain
    symmetric quantization, so the tree always comes out fully quantized.
    """
    if stats is None:
        if tokens is None:
            raise ValueError("need calibration tokens or precomputed stats")
        stats = collect_activation_stats(params, cfg, tokens)
    out = dict(params)
    layers = {}
    for name, leaf in params["layers"].items():
        if name in QUANTIZABLE:
            if name in targets and name in stats:
                layers[name] = quantize_weight_awq(
                    leaf, stats[name], bits=bits, alphas=alphas
                )
            else:
                layers[name] = quantize_weight(leaf, bits=bits)
        else:
            layers[name] = leaf
    out["layers"] = layers
    return out


def calibration_tokens(
    vocab_size: int,
    tokenizer=None,
    n_tokens: int = 512,
    seed: int = 0,
) -> jnp.ndarray:
    """Default calibration batch: the embedded perplexity corpus through
    the live tokenizer when one is available (real token statistics, no
    network — quality/texts.py exists for exactly this air-gap), else a
    seeded uniform sample (random-weight CI models have no meaningful
    token distribution anyway)."""
    ids: list[int] = []
    if tokenizer is not None:
        try:
            from kserve_vllm_mini_tpu.quality.texts import EVAL_TEXTS

            for text in EVAL_TEXTS:
                ids.extend(tokenizer.encode(text))
                if len(ids) >= n_tokens:
                    break
        except Exception:  # noqa: BLE001 — fall through to random ids
            ids = []
    if len(ids) >= 32:
        ids = [i for i in ids[:n_tokens] if 0 <= i < vocab_size]
    if len(ids) < 32:
        rng = np.random.default_rng(seed)
        ids = rng.integers(0, vocab_size, size=(n_tokens,)).tolist()
    return jnp.asarray(ids, jnp.int32)[None, :]
