"""Multi-LoRA serving: batched low-rank adapter deltas for the decode path.

The reference benchmarks vLLM servers, whose multi-LoRA mode serves many
fine-tunes behind one base model by routing each request to an adapter
(per-request ``model`` field). Here the runtime is in-repo, so the
mechanism is too: every transformer matmul target can carry a bank of N
adapters, and each slot in the continuous batch picks its adapter by
index — one jitted step serves heterogeneous adapters.

TPU shape of the trick: the bank is stacked [L, N, in, r] / [L, N, r, out]
(layer axis first so it rides the layer scan like the base weights); a
step gathers the batch's adapters ([B, in, r] — a few MB at serving ranks)
and the delta is two small einsums XLA fuses around the main matmul. The
``alpha/r`` scale is folded into the B factor at init/load time, so the
hot path has no per-adapter scalar bookkeeping.

Adapter index 0 is reserved as the BASE (zero) adapter: its A/B factors
are zeros, so un-adaptered requests run bit-identical to the base model
without a separate execution path.

PEFT checkpoint loading (the ``adapter_model.safetensors`` layout that HF
fine-tunes produce) lives in ``load_peft_adapter``; reference analog: the
``model`` routing surface of scripts/openai_parity_probe.py:71-116 and the
vLLM ``--enable-lora`` deployments the harness benchmarks.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp

# targets that may carry adapters (subset of ops.quant.QUANTIZABLE; the
# default mirrors common PEFT configs: attention projections only)
LORA_TARGETS_DEFAULT = ("wq", "wk", "wv", "wo")
LORA_TARGETS_ALL = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def _target_dims(cfg, name: str) -> tuple[int, int]:
    d, h = cfg.d_model, cfg.n_heads * cfg.head_dim
    kv = cfg.n_kv_heads * cfg.head_dim
    return {
        "wq": (d, h),
        "wk": (d, kv),
        "wv": (d, kv),
        "wo": (h, d),
        "w_gate": (d, cfg.d_ff),
        "w_up": (d, cfg.d_ff),
        "w_down": (cfg.d_ff, d),
    }[name]


def init_lora_bank(
    rng: jax.Array,
    cfg,
    n_adapters: int,
    rank: int = 8,
    alpha: float = 16.0,
    targets: Sequence[str] = LORA_TARGETS_DEFAULT,
    dtype=jnp.bfloat16,
) -> dict[str, Any]:
    """Random bank of ``n_adapters`` REAL adapters (+ the reserved zero
    adapter at index 0, so the bank's N axis is n_adapters + 1).

    A ~ N(0, 1/r) and B = 0 is the standard LoRA init (delta starts at 0);
    for testing heterogeneous batches a random-B variant is more useful,
    so B is also drawn and pre-scaled by alpha/rank.
    """
    layers: dict[str, jnp.ndarray] = {}
    n = n_adapters + 1
    keys = jax.random.split(rng, 2 * len(targets))
    for i, t in enumerate(targets):
        din, dout = _target_dims(cfg, t)
        # std 1/sqrt(r) => variance 1/r, the documented N(0, 1/r) scale
        # (was /rank, i.e. variance 1/r² — round-4 advisor finding)
        a = jax.random.normal(
            keys[2 * i], (cfg.n_layers, n, din, rank)
        ) / (rank ** 0.5)
        b = jax.random.normal(keys[2 * i + 1], (cfg.n_layers, n, rank, dout))
        b = b * (alpha / rank)
        # index 0 = base: zero delta
        a = a.at[:, 0].set(0.0)
        b = b.at[:, 0].set(0.0)
        layers[t + "_A"] = a.astype(dtype)
        layers[t + "_B"] = b.astype(dtype)
    return {"layers": layers, "rank": rank, "targets": tuple(targets)}


def zero_lora_bank(
    cfg,
    n_adapters: int,
    rank: int = 8,
    targets: Sequence[str] = LORA_TARGETS_DEFAULT,
    dtype=jnp.bfloat16,
) -> dict[str, Any]:
    """All-zero bank with slots for ``n_adapters`` adapters to be installed
    via ``install_adapter`` (index 0 stays the base adapter)."""
    layers: dict[str, jnp.ndarray] = {}
    n = n_adapters + 1
    for t in targets:
        din, dout = _target_dims(cfg, t)
        layers[t + "_A"] = jnp.zeros((cfg.n_layers, n, din, rank), dtype)
        layers[t + "_B"] = jnp.zeros((cfg.n_layers, n, rank, dout), dtype)
    return {"layers": layers, "rank": rank, "targets": tuple(targets)}


def grow_bank_rank(bank: dict[str, Any], new_rank: int) -> dict[str, Any]:
    """Zero-pad every factor's rank dimension to ``new_rank``. The delta
    ``A @ B`` is bit-unchanged for installed adapters (padded rank rows/
    columns contribute zero), so a live bank grows to accept higher-rank
    installs WITHOUT a restart — the only cost is one decode retrace on
    the next dispatch (jit keys on shapes)."""
    r = bank["rank"]
    if new_rank <= r:
        return bank
    layers: dict[str, Any] = {}
    for k, v in bank["layers"].items():
        if k.endswith("_A"):      # [L, N, in, r] — pad the last dim
            pad = [(0, 0)] * (v.ndim - 1) + [(0, new_rank - r)]
        else:                     # [L, N, r, out] — pad the rank dim
            pad = [(0, 0)] * (v.ndim - 2) + [(0, new_rank - r), (0, 0)]
        layers[k] = jnp.pad(v, pad)
    return {**bank, "layers": layers, "rank": new_rank}


def pad_adapter_rank(adapter: dict[str, Any], rank: int) -> dict[str, Any]:
    """Zero-pad a lower-rank adapter's factors up to the bank rank (exact:
    the padding contributes nothing to A @ B). Higher-than-bank ranks are
    the caller's problem (grow the bank first)."""
    out: dict[str, Any] = {}
    for t, (a, b) in adapter.items():
        r = a.shape[-1]
        if r > rank:
            raise ValueError(
                f"adapter rank {r} exceeds bank rank {rank}; grow the bank"
            )
        if r < rank:
            a = jnp.pad(a, [(0, 0)] * (a.ndim - 1) + [(0, rank - r)])
            b = jnp.pad(b, [(0, 0)] * (b.ndim - 2) + [(0, rank - r), (0, 0)])
        out[t] = (a, b)
    return out


def install_adapter(
    bank: dict[str, Any],
    index: int,
    adapter: dict[str, Any],
) -> dict[str, Any]:
    """Write one adapter's per-layer factors into bank slot ``index``
    (1-based for real adapters; 0 is reserved). ``adapter`` maps target ->
    (A [L, in, r], B [L, r, out]); B must already carry the alpha/r scale
    (load_peft_adapter does this)."""
    if index < 1:
        raise ValueError("adapter index 0 is reserved for the base model")
    layers = dict(bank["layers"])
    for t, (a, b) in adapter.items():
        ka, kb = t + "_A", t + "_B"
        if ka not in layers:
            raise ValueError(
                f"bank has no target {t!r} (targets: {bank['targets']})"
            )
        if a.shape[-1] != bank["rank"]:
            raise ValueError(
                f"adapter rank {a.shape[-1]} != bank rank {bank['rank']}"
            )
        layers[ka] = layers[ka].at[:, index].set(a.astype(layers[ka].dtype))
        layers[kb] = layers[kb].at[:, index].set(b.astype(layers[kb].dtype))
    return {**bank, "layers": layers}


def lora_delta(
    x: jnp.ndarray,          # [B, T, in]
    a_bank: jnp.ndarray,     # [N, in, r]   (one layer's slice)
    b_bank: jnp.ndarray,     # [N, r, out]
    ids: jnp.ndarray,        # [B] int32 adapter index per slot
) -> jnp.ndarray:
    """Per-slot adapter delta (x @ A_i) @ B_i -> [B, T, out] in f32. The
    gathers materialize only the BATCH's factors ([B, in, r] — MBs at
    serving ranks), never the bank.

    The side-path runs in f32 end to end: the rank-r intermediates are
    tiny (negligible HBM/FLOPs), and a bf16 mid would round BEFORE the
    cross-shard psum when the contraction axis is tp-sharded (e.g. the
    wo/w_down deltas on a mesh), compounding into logit drift ~the delta's
    own magnitude across layers. The caller casts the finished delta once."""
    a = a_bank[ids].astype(jnp.float32)            # [B, in, r]
    b = b_bank[ids].astype(jnp.float32)            # [B, r, out]
    mid = jnp.einsum("btd,bdr->btr", x.astype(jnp.float32), a)
    return jnp.einsum("btr,bro->bto", mid, b)


def adapted_linear(
    x: jnp.ndarray,
    w: Any,
    lora_layer: Optional[dict[str, jnp.ndarray]],
    name: str,
    ids: Optional[jnp.ndarray],
    mode: str = "dequant",
) -> jnp.ndarray:
    """ops.quant.linear plus this target's adapter delta when the layer
    bank carries it (targets not in the bank run the base matmul only).
    ``mode`` is the base matmul's quant_mode (cfg.quant_mode); the adapter
    delta itself stays in the bank dtype — it is rank-r noise-level FLOPs."""
    from kserve_vllm_mini_tpu.ops.quant import linear

    y = linear(x, w, mode=mode)
    if lora_layer is None or ids is None or name + "_A" not in lora_layer:
        return y
    d = lora_delta(x, lora_layer[name + "_A"], lora_layer[name + "_B"], ids)
    return y + d.astype(y.dtype)


def load_peft_adapter(
    path: str,
    cfg,
    targets: Sequence[str] = LORA_TARGETS_DEFAULT,
) -> dict[str, Any]:
    """Read a PEFT ``adapter_model.safetensors`` (+ ``adapter_config.json``)
    directory into the install_adapter format.

    PEFT names look like
    ``base_model.model.model.layers.{i}.self_attn.q_proj.lora_A.weight``
    with torch [out, in] orientation; they are transposed to this repo's
    [in, out] convention and stacked over layers. The config's
    ``lora_alpha / r`` scale is folded into B.
    """
    import json
    import os

    import numpy as np

    cfg_path = os.path.join(path, "adapter_config.json")
    with open(cfg_path) as f:
        acfg = json.load(f)
    rank = int(acfg["r"])
    scale = float(acfg.get("lora_alpha", rank)) / rank

    from safetensors.numpy import load_file

    tensors = load_file(os.path.join(path, "adapter_model.safetensors"))

    peft_name = {
        "wq": "self_attn.q_proj", "wk": "self_attn.k_proj",
        "wv": "self_attn.v_proj", "wo": "self_attn.o_proj",
        "w_gate": "mlp.gate_proj", "w_up": "mlp.up_proj",
        "w_down": "mlp.down_proj",
    }
    out: dict[str, Any] = {}
    for t in targets:
        frag = peft_name[t]
        a_layers, b_layers = [], []
        for li in range(cfg.n_layers):
            ka = kb = None
            for key in tensors:
                if f"layers.{li}.{frag}.lora_A" in key:
                    ka = key
                if f"layers.{li}.{frag}.lora_B" in key:
                    kb = key
            if ka is None or kb is None:
                break  # target absent from layer li onward
            # torch Linear stores [out, in]; transpose to [in, out] math
            a_layers.append(np.asarray(tensors[ka]).T)          # [in, r]
            b_layers.append(np.asarray(tensors[kb]).T * scale)  # [r, out]
        if len(a_layers) == cfg.n_layers:
            out[t] = (jnp.asarray(np.stack(a_layers)),
                      jnp.asarray(np.stack(b_layers)))
        elif a_layers:
            # partial coverage must fail LOUDLY: silently dropping the
            # target would serve the fine-tune with part of its weights
            # missing (e.g. a layers_to_transform adapter)
            raise ValueError(
                f"adapter at {path} covers target {t!r} for only "
                f"{len(a_layers)}/{cfg.n_layers} layers; per-layer-subset "
                "(layers_to_transform) adapters are not supported"
            )
    if not out:
        raise ValueError(
            f"no usable LoRA targets found in {path} "
            f"(looked for {[peft_name[t] for t in targets]})"
        )
    if rank != next(iter(out.values()))[0].shape[-1]:
        raise ValueError("adapter_config r does not match tensor shapes")
    return out
