"""Pallas TPU flash attention (prefill path).

Blocked causal attention with the online-softmax m/l/acc recurrence held in
VMEM scratch. Grid is (batch, q_heads, q_blocks, kv_blocks); TPU iterates the
last grid axis innermost, so scratch accumulators persist across the
kv-block sweep for one (b, h, q_block) output tile. GQA is handled in the
BlockSpec index map (query head h reads kv head h // n_rep) so kv blocks are
never materialized repeated.

The jnp reference (ops/attention.py) is the correctness oracle; tests compare
against it in interpret mode on CPU. The serving path reaches the kernel via
``prefill_attention`` below (models/llama.py ``forward(fresh_prefill=True)``,
called by runtime/engine.py's prefill step), which compiles the kernel on TPU
— the MXU sees [block_q, d] x [d, block_k] bf16 tiles — and falls back to the
jnp oracle on other backends. bench.py asserts the prefill executable
actually lowers to a tpu_custom_call.

``cached_prefill_attention`` below is the CONTINUATION-chunk variant: a
chunk's queries attend the slot's whole dense cache stripe (earlier
chunks' cached KV plus the chunk's own just-written rows) with positional
masking, reading int8-KV stripes with in-kernel dequant — the prefill
twin of ops/paged_attention.py ``dense_decode_attention``, sharing its
``k_scale``/``v_scale`` conventions. The eager ``_read_layer`` dequant
path (models/llama.py) stays the fallback/consistency oracle.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
_NEG_INF = -1e30


def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref,
    *, scale: float, block_q: int, block_k: int, causal: bool,
):
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # Causal: kv block strictly after the q block contributes nothing.
    run = (not causal) or (ki * block_k <= qi * block_q + block_q - 1)

    @pl.when(run)
    def _accumulate():
        q = q_ref[0, 0]                      # [BQ, D]
        k = k_ref[0, 0]                      # [BK, D]
        v = v_ref[0, 0]                      # [BK, D]
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                            # [BQ, BK]
        if causal:
            qpos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            kpos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            logits = jnp.where(kpos <= qpos, logits, _NEG_INF)
        m_prev = m_ref[:]                    # [BQ, 1]... stored as [BQ, 128] lanes
        m_cur = jnp.max(logits, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(logits - m_new)
        l_ref[:] = l_ref[:] * corr + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = m_new

    @pl.when(ki == nk - 1)
    def _finalize():
        o_ref[0, 0] = (acc_ref[:] / jnp.maximum(l_ref[:], 1e-30)).astype(o_ref.dtype)


def flash_attention(
    q: jnp.ndarray,   # [B, H, T, D]
    k: jnp.ndarray,   # [B, KVH, S, D]
    v: jnp.ndarray,   # [B, KVH, S, D]
    causal: bool = True,
    scale: Optional[float] = None,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Causal flash attention. T and S must be multiples of the block sizes
    (the runtime pads sequences to bucket boundaries anyway)."""
    B, H, T, D = q.shape
    _, KVH, S, _ = k.shape
    block_q = min(block_q, T)
    block_k = min(block_k, S)
    if T % block_q or S % block_k:
        raise ValueError(f"T={T}, S={S} must be multiples of blocks ({block_q},{block_k})")
    n_rep = H // KVH
    scale = scale if scale is not None else D ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    grid = (B, H, T // block_q, S // block_k)
    kernel = functools.partial(
        _flash_kernel, scale=scale, block_q=block_q, block_k=block_k, causal=causal
    )
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, qi, ki: (b, h, qi, 0)),
            pl.BlockSpec((1, 1, block_k, D), lambda b, h, qi, ki: (b, h // n_rep, ki, 0)),
            pl.BlockSpec((1, 1, block_k, D), lambda b, h, qi, ki: (b, h // n_rep, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, D), lambda b, h, qi, ki: (b, h, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((B, H, T, D), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, D), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)


def _cached_prefill_kernel(
    layer_ref,   # [1] int32 layer index (scalar prefetch; used in index maps)
    offset_ref,  # [B] int32 absolute position of each row's first query
    q_ref,       # [1, 1, BQ, D] this (b, h, qi) query tile
    k_ref,       # [1, 1, 1, BK, D] this grid step's cache stripe (int8 when
    v_ref,       #                  quantized)
    *rest,       # [k_s_ref, v_s_ref,] o_ref, m_ref, l_ref, acc_ref
    scale: float,
    block_q: int,
    block_k: int,
    quantized: bool,
):
    """One key-block step of the CACHED-prefill online-softmax recurrence:
    a chunk of queries at absolute positions offset..offset+T-1 attends the
    slot's whole cache stripe (earlier chunks' KV plus this chunk's own
    just-written rows) with positional masking. Same m/l/acc scratch
    persistence across the innermost grid axis — and the same per-position
    scale-dequant convention — as ``_decode_block_body``
    (ops/paged_attention.py): (q . k_j s_j) = (q . k_j) * s_j and
    p @ (v s) = (p * s) @ v, so the int8 stripes stream straight from HBM
    and the materialized bf16 KV tensor of the eager read never exists."""
    if quantized:
        k_s_ref, v_s_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        o_ref, m_ref, l_ref, acc_ref = rest
    b = pl.program_id(0)
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    nk = pl.num_programs(3)
    off = offset_ref[b]

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # key j of cache block ki sits at absolute position ki*BK + j; the
    # tile's LAST query sits at off + qi*BQ + BQ - 1 — a block starting
    # past it is all-masked, skip its FLOPs entirely
    run = ki * block_k <= off + qi * block_q + block_q - 1

    @pl.when(run)
    def _accumulate():
        q = q_ref[0, 0]                      # [BQ, D]
        k = k_ref[0, 0, 0]                   # [BK, D] (int8 when quantized)
        v = v_ref[0, 0, 0]
        logits = jax.lax.dot_general(
            q, k.astype(q.dtype), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                            # [BQ, BK]
        if quantized:
            logits = logits * k_s_ref[0, 0, 0][None, :]
        qpos = off + qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        kpos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        logits = jnp.where(kpos <= qpos, logits, _NEG_INF)
        m_prev = m_ref[:]
        m_cur = jnp.max(logits, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(logits - m_new)
        l_ref[:] = l_ref[:] * corr + jnp.sum(p, axis=1, keepdims=True)
        if quantized:
            pv = (p * v_s_ref[0, 0, 0][None, :]).astype(jnp.float32)
            vv = v.astype(jnp.float32)
        else:
            pv = p.astype(v.dtype)
            vv = v
        acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
            pv, vv, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = m_new

    @pl.when(ki == nk - 1)
    def _finalize():
        o_ref[0, 0] = (acc_ref[:] / jnp.maximum(l_ref[:], 1e-30)).astype(o_ref.dtype)


def cached_prefill_blocks(t: int, s: int) -> Optional[tuple[int, int]]:
    """(block_q, block_k) the cached-prefill kernel tiles (T, S) with, or
    None when either axis has no supported tiling (the caller keeps the
    eager read path). Same alignment contract as ``prefill_attention``:
    the chunk axis T is a power-of-two bucket >= 16 or a multiple of the
    full 128 block; the cache axis S must tile by a power of two >= 8
    (Pallas pads partial blocks with whatever HBM holds — the positional
    mask would zero the scores, but an unvalidated ragged block shape is
    not worth handing Mosaic)."""
    pow2 = t & (t - 1) == 0
    if t < 16 or not (pow2 or t % DEFAULT_BLOCK_Q == 0):
        return None
    bq = min(DEFAULT_BLOCK_Q, t)
    for bk in (DEFAULT_BLOCK_K, 64, 32, 16, 8):
        if s % bk == 0:
            return bq, bk
    return None


def cached_prefill_attention(
    q: jnp.ndarray,        # [B, H, T, D] chunk queries
    k_cache: jnp.ndarray,  # [L, B, KVH, S, D] layer-stacked dense cache
                           # (or [B, KVH, S, D] for a single layer)
    v_cache: jnp.ndarray,
    offsets: jnp.ndarray,  # [B] int32 absolute position of each row's
                           # first query (the chunk's cache offset)
    layer: jnp.ndarray | int = 0,  # which layer of the stacked cache
    scale: Optional[float] = None,
    k_scale: Optional[jnp.ndarray] = None,  # [L, B, KVH, S] f32: int8-KV
    v_scale: Optional[jnp.ndarray] = None,  # per-position dequant scales
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Flash prefill OVER THE CACHE: a continuation chunk's T queries
    attend the slot's whole dense cache stripe — earlier chunks' cached
    KV plus this chunk's own just-written rows — with positional masking,
    streaming int8 stripes and dequantizing in-kernel when
    ``k_scale``/``v_scale`` are given (the scaled-int8 KV layout,
    models/llama.py). The prefill-side twin of ``dense_decode_attention``:
    the eager read path (models/llama.py ``_read_layer``) materializes the
    dequantized bf16 [B, KVH, S, D] tensor before attention — 3x the live
    KV bytes in HBM traffic; here that tensor never exists. The layer
    index rides the index map so the caller never slices the stacked
    cache. GQA is handled in the BlockSpec index map (query head h reads
    kv head h // n_rep). The jnp gather/dequant path is the correctness
    oracle; tests compare in interpret mode on CPU."""
    if k_cache.ndim == 4:
        k_cache = k_cache[None]
        v_cache = v_cache[None]
        if k_scale is not None:
            k_scale, v_scale = k_scale[None], v_scale[None]
    quantized = k_scale is not None
    B, H, T, D = q.shape
    L, _, KVH, S, _ = k_cache.shape
    blocks = cached_prefill_blocks(T, S)
    if blocks is None:
        raise ValueError(
            f"cached prefill kernel needs tileable (T={T}, S={S}) — use "
            "the eager read path (cached_prefill_blocks)"
        )
    bq, bk = blocks
    n_rep = H // KVH
    scale = scale if scale is not None else D ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    offsets = offsets.astype(jnp.int32)
    layer_arr = jnp.asarray(layer, jnp.int32).reshape((1,))

    def _cache_spec():
        return pl.BlockSpec(
            (1, 1, 1, bk, D),
            lambda b, h, qi, ki, layer, off: (layer[0], b, h // n_rep, ki, 0),
        )

    def _scale_spec():
        return pl.BlockSpec(
            (1, 1, 1, bk),
            lambda b, h, qi, ki, layer, off: (layer[0], b, h // n_rep, ki),
        )

    in_specs = [
        pl.BlockSpec(
            (1, 1, bq, D), lambda b, h, qi, ki, layer, off: (b, h, qi, 0)
        ),
        _cache_spec(),
        _cache_spec(),
    ]
    operands = [q, k_cache, v_cache]
    if quantized:
        in_specs += [_scale_spec(), _scale_spec()]
        operands += [k_scale, v_scale]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, H, T // bq, S // bk),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, 1, bq, D), lambda b, h, qi, ki, layer, off: (b, h, qi, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, D), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _cached_prefill_kernel, scale=scale, block_q=bq, block_k=bk,
        quantized=quantized,
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, T, D), q.dtype),
        interpret=interpret,
    )(layer_arr, offsets, *operands)


def prefill_attention(
    q: jnp.ndarray,   # [B, H, T, D]
    k: jnp.ndarray,   # [B, KVH, T, D]
    v: jnp.ndarray,   # [B, KVH, T, D]
    use_flash: Optional[bool] = None,
) -> jnp.ndarray:
    """Serving-prefill attention over a freshly projected block.

    The engine's prefill writes a new request's whole prompt at cache offset
    0, so block-causal attention over (q, k, v) themselves is exact — no
    cache readback, and attention cost is T x T instead of T x max_seq.

    Dispatch: the compiled Pallas kernel on TPU (prompts pad to power-of-two
    buckets, so shapes are always block-aligned), the jnp oracle elsewhere.
    ``use_flash`` forces the choice for tests (interpret mode off-TPU).
    """
    T = q.shape[2]
    bq = min(DEFAULT_BLOCK_Q, T)
    bk = min(DEFAULT_BLOCK_K, T)
    # tile-aligned block shapes only: T a power of two >= 16 (the engine's
    # bucket sizes) or a multiple of the full 128 block — anything else
    # (e.g. a clamped 99-wide bucket) takes the jnp path rather than handing
    # Mosaic an unvalidated block shape
    pow2 = T & (T - 1) == 0
    aligned = (
        (T >= 16)
        and (pow2 or T % DEFAULT_BLOCK_Q == 0)
        and q.shape[1] % k.shape[1] == 0
    )
    if use_flash is None:
        use_flash = jax.default_backend() == "tpu" and aligned
    if use_flash:
        return flash_attention(q, k, v, causal=True, block_q=bq, block_k=bk)
    from kserve_vllm_mini_tpu.ops.attention import attention, causal_mask

    return attention(q, k, v, causal_mask(T, T)[None, None])
