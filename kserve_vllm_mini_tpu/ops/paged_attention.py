"""Pallas TPU paged-attention decode kernel.

The gather-based paged read (models/llama.py ``_read_layer``) materializes
the batch's blocks into a contiguous [B, KVH, S, D] tensor before the
attention matmuls — 3x the KV bytes in HBM traffic (pool read + gather
write + attention read). This kernel is the TPU-native version of the trick
vLLM's namesake PagedAttention kernel does on GPU: the BLOCK TABLE is a
scalar-prefetch argument whose values drive each grid step's BlockSpec
index map, so the pool block a slot needs is DMA'd straight from HBM into
VMEM — per slot, per kv head, per block — and attention runs on it in
place. No gathered copy exists, and HBM sees exactly one read of the live
KV prefix.

Layout: grid (slots, kv_heads, max_blocks); the online-softmax m/l/acc
recurrence lives in VMEM scratch and persists across the block sweep (the
innermost grid axis, same structure as ops/flash_attention.py). GQA comes
in pre-grouped: q is [S, KVH, G, D] so each grid step contracts a [G, D]
query tile against the [BLK, D] key block on the MXU.

Blocks past the slot's live length are skipped (``pl.when``) — their DMA
still happens (the grid is static), reading whatever block their table
entry names. The engine parks freed/unwritten table rows on its scratch
block (runtime/engine.py ``_paged_release``), which is what concentrates
the dead traffic; the ``jnp.clip`` below is only bounds safety for ids
outside [0, P).

The kernel takes the LAYER-STACKED pool ([L, P, KVH, BLK, D]) plus the
layer index as a scalar-prefetch value folded into the index map: slicing
one layer out before the call would hand XLA a dynamic-slice feeding a
custom call, which materializes the whole layer pool in HBM per step —
exactly the copy this kernel exists to avoid.

The jnp gather path is the correctness oracle; tests compare in interpret
mode on CPU (tests/test_paged_kernel.py). The serving path dispatches to
the kernel on TPU for plain-causal, bf16-KV configs and keeps the exact
gather path elsewhere (models/llama.py run_cached_layers).

``dense_decode_attention`` is the DENSE-cache twin for the int8-KV
layout: same shared online-softmax block body, same ``k_scale``/
``v_scale`` dequant-in-kernel convention, but the key-block sweep walks
the per-slot [L, B, KVH, S, D] cache stripes directly (no table) — so the
eager read path's materialized bf16 [B, KVH, S, D] dequantized tensor
never exists (models/llama.py ``_read_layer`` remains the fallback
oracle).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _decode_block_body(
    qpos,        # scalar int32: this slot's query position
    q_ref,       # [1, 1, G, D] this slot/head's query tile
    k_ref,       # [1, 1, 1, BLK, D] this grid step's key block
    v_ref,       # [1, 1, 1, BLK, D]
    rest,        # [k_s_ref, v_s_ref,] o_ref, m_ref, l_ref, acc_ref —
                 # int8-KV mode carries per-position scale blocks
    block_k: int,
    scale: float,
    quantized: bool,
):
    """One key-block step of the online-softmax decode recurrence — the
    body BOTH decode kernels share (paged: the block arrived via the table
    index map; dense: via the sequential S sweep). The m/l/acc scratch
    persists across the innermost grid axis."""
    if quantized:
        k_s_ref, v_s_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        o_ref, m_ref, l_ref, acc_ref = rest
    b = pl.program_id(2)
    nb = pl.num_programs(2)

    @pl.when(b == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # keys j of block b sit at positions b*BLK + j; the decode query at
    # position qpos attends j <= qpos, so a block starting past qpos is
    # all-masked — skip its FLOPs entirely
    run = b * block_k <= qpos

    @pl.when(run)
    def _accumulate():
        q = q_ref[0, 0]                      # [G, D]
        k = k_ref[0, 0, 0]                   # [BLK, D] (int8 when quantized)
        v = v_ref[0, 0, 0]
        logits = jax.lax.dot_general(
            q, k.astype(q.dtype), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                            # [G, BLK]
        if quantized:
            # per-position dequant folds into the [G, BLK] intermediates:
            # (q . k_j s_j) = (q . k_j) * s_j, and p @ (v s) = (p * s) @ v
            logits = logits * k_s_ref[0, 0, 0][None, :]
        kpos = b * block_k + jax.lax.broadcasted_iota(
            jnp.int32, logits.shape, 1
        )
        logits = jnp.where(kpos <= qpos, logits, _NEG_INF)
        m_prev = m_ref[:]
        m_cur = jnp.max(logits, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(logits - m_new)
        l_ref[:] = l_ref[:] * corr + jnp.sum(p, axis=1, keepdims=True)
        if quantized:
            pv = (p * v_s_ref[0, 0, 0][None, :]).astype(jnp.float32)
            vv = v.astype(jnp.float32)
        else:
            pv = p.astype(v.dtype)
            vv = v
        acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
            pv, vv, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = m_new

    @pl.when(b == nb - 1)
    def _finalize():
        o_ref[0, 0] = (acc_ref[:] / jnp.maximum(l_ref[:], 1e-30)).astype(o_ref.dtype)


def _paged_decode_kernel(
    layer_ref,   # [1] int32 layer index (scalar prefetch; used in index maps)
    table_ref,   # [S, MAXB] int32 (scalar prefetch)
    qpos_ref,    # [S] int32 query positions (scalar prefetch)
    q_ref,
    k_ref,       # the table-selected pool block
    v_ref,
    *rest,
    block_k: int,
    scale: float,
    quantized: bool,
):
    _decode_block_body(
        qpos_ref[pl.program_id(0)], q_ref, k_ref, v_ref, rest,
        block_k=block_k, scale=scale, quantized=quantized,
    )


def _dense_decode_kernel(
    layer_ref,   # [1] int32 layer index (scalar prefetch; used in index maps)
    qpos_ref,    # [B] int32 query positions (scalar prefetch)
    q_ref,
    k_ref,       # this slot's b-th BLK-position stripe of the dense cache
    v_ref,
    *rest,
    block_k: int,
    scale: float,
    quantized: bool,
):
    _decode_block_body(
        qpos_ref[pl.program_id(0)], q_ref, k_ref, v_ref, rest,
        block_k=block_k, scale=scale, quantized=quantized,
    )


def paged_decode_attention(
    q: jnp.ndarray,        # [S, KVH, G, D] decode queries, GQA pre-grouped
    k_pool: jnp.ndarray,   # [L, P, KVH, BLK, D] layer-stacked key pool
                           # (or [P, KVH, BLK, D] for a single layer)
    v_pool: jnp.ndarray,
    table: jnp.ndarray,    # [S, MAXB] int32 block ids (position order)
    qpos: jnp.ndarray,     # [S] int32 current query position per slot
    layer: jnp.ndarray | int = 0,  # which layer of the stacked pool
    scale: Optional[float] = None,
    k_scale: Optional[jnp.ndarray] = None,  # [L, P, KVH, BLK] f32: int8-KV
    v_scale: Optional[jnp.ndarray] = None,  # per-position dequant scales
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Returns [S, KVH, G, D] attention outputs, reading each slot's live
    blocks straight from the pool (table-driven DMA, no gather copy). The
    layer index rides the index map so the caller never slices the pool.
    With ``k_scale``/``v_scale`` the pools hold int8 values dequantized
    in-kernel (the scaled-int8 KV cache layout, models/llama.py)."""
    if k_pool.ndim == 4:
        k_pool = k_pool[None]
        v_pool = v_pool[None]
        if k_scale is not None:
            k_scale, v_scale = k_scale[None], v_scale[None]
    quantized = k_scale is not None
    S, KVH, G, D = q.shape
    L, P, _, BLK, _ = k_pool.shape
    MAXB = table.shape[1]
    scale = scale if scale is not None else D ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    # bounds safety only: dead-but-in-range ids DMA whatever they name
    # (the engine's scratch-row convention concentrates that traffic)
    safe_table = jnp.clip(table, 0, P - 1).astype(jnp.int32)
    qpos = qpos.astype(jnp.int32)
    layer_arr = jnp.asarray(layer, jnp.int32).reshape((1,))

    def _pool_spec():
        return pl.BlockSpec(
            (1, 1, 1, BLK, D),
            lambda s, h, b, layer, table, qpos: (
                layer[0], table[s, b], h, 0, 0
            ),
        )

    def _scale_spec():
        return pl.BlockSpec(
            (1, 1, 1, BLK),
            lambda s, h, b, layer, table, qpos: (layer[0], table[s, b], h, 0),
        )

    in_specs = [
        pl.BlockSpec(
            (1, 1, G, D),
            lambda s, h, b, layer, table, qpos: (s, h, 0, 0),
        ),
        _pool_spec(),
        _pool_spec(),
    ]
    operands = [q, k_pool, v_pool]
    if quantized:
        in_specs += [_scale_spec(), _scale_spec()]
        operands += [k_scale, v_scale]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(S, KVH, MAXB),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, 1, G, D), lambda s, h, b, layer, table, qpos: (s, h, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((G, 1), jnp.float32),
            pltpu.VMEM((G, 1), jnp.float32),
            pltpu.VMEM((G, D), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _paged_decode_kernel, block_k=BLK, scale=scale, quantized=quantized
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, KVH, G, D), q.dtype),
        interpret=interpret,
    )(layer_arr, safe_table, qpos, *operands)


def dense_decode_block(seq_len: int) -> Optional[int]:
    """Key-block size the dense decode kernel sweeps ``seq_len`` with, or
    None when no supported block divides it (the caller then keeps the
    eager read path). Powers of two down to 8: the sweep grid must tile
    the cache's S axis exactly — Pallas pads partial blocks with whatever
    HBM holds, and while the positional mask would zero those scores, a
    dense cache length that is not even 8-aligned is a test-only shape
    not worth the kernel."""
    for cand in (512, 256, 128, 64, 32, 16, 8):
        if seq_len % cand == 0:
            return cand
    return None


def dense_decode_attention(
    q: jnp.ndarray,        # [B, KVH, G, D] decode queries, GQA pre-grouped
    k_cache: jnp.ndarray,  # [L, B, KVH, S, D] layer-stacked dense cache
                           # (or [B, KVH, S, D] for a single layer)
    v_cache: jnp.ndarray,
    qpos: jnp.ndarray,     # [B] int32 current query position per slot
    layer: jnp.ndarray | int = 0,  # which layer of the stacked cache
    scale: Optional[float] = None,
    k_scale: Optional[jnp.ndarray] = None,  # [L, B, KVH, S] f32: int8-KV
    v_scale: Optional[jnp.ndarray] = None,  # per-position dequant scales
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Decode attention straight over the DENSE per-slot cache: the twin of
    ``paged_decode_attention`` for ``kv_layout="dense"``.

    The eager int8-KV read path (models/llama.py ``_read_layer``)
    dequantizes the whole [B, KVH, S, D] stripe into a materialized bf16
    tensor before attention — 3x the live-KV bytes in HBM traffic (int8
    read + bf16 write + attention read) plus a full dequantized copy in
    HBM. Here each BLK-position stripe is DMA'd int8 from HBM into VMEM
    and dequantized in-register inside the online-softmax sweep (the same
    shared block body as the paged kernel, same ``k_scale``/``v_scale``
    layout), so the bf16 KV tensor never exists. The layer index rides the
    index map so the caller never slices the stacked cache (a dynamic-
    slice operand feeding a custom call would materialize the whole layer
    in HBM — the copy this kernel exists to avoid).

    Blocks past a slot's live length are skipped by the block body's
    ``run`` guard; their DMA still happens (static grid) but reads the
    slot's own dead cache tail, never another slot's data."""
    if k_cache.ndim == 4:
        k_cache = k_cache[None]
        v_cache = v_cache[None]
        if k_scale is not None:
            k_scale, v_scale = k_scale[None], v_scale[None]
    quantized = k_scale is not None
    B, KVH, G, D = q.shape
    L, _, _, S, _ = k_cache.shape
    BLK = dense_decode_block(S)
    if BLK is None:
        raise ValueError(
            f"dense decode kernel needs a power-of-two-tileable seq axis "
            f"(>= 8); got S={S} — use the eager read path"
        )
    scale = scale if scale is not None else D ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    qpos = qpos.astype(jnp.int32)
    layer_arr = jnp.asarray(layer, jnp.int32).reshape((1,))
    nb = S // BLK

    def _cache_spec():
        return pl.BlockSpec(
            (1, 1, 1, BLK, D),
            lambda s, h, b, layer, qpos: (layer[0], s, h, b, 0),
        )

    def _scale_spec():
        return pl.BlockSpec(
            (1, 1, 1, BLK),
            lambda s, h, b, layer, qpos: (layer[0], s, h, b),
        )

    in_specs = [
        pl.BlockSpec(
            (1, 1, G, D),
            lambda s, h, b, layer, qpos: (s, h, 0, 0),
        ),
        _cache_spec(),
        _cache_spec(),
    ]
    operands = [q, k_cache, v_cache]
    if quantized:
        in_specs += [_scale_spec(), _scale_spec()]
        operands += [k_scale, v_scale]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, KVH, nb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, 1, G, D), lambda s, h, b, layer, qpos: (s, h, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((G, 1), jnp.float32),
            pltpu.VMEM((G, 1), jnp.float32),
            pltpu.VMEM((G, D), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _dense_decode_kernel, block_k=BLK, scale=scale, quantized=quantized
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, KVH, G, D), q.dtype),
        interpret=interpret,
    )(layer_arr, qpos, *operands)
