"""Orchestration: file discovery -> fact index -> checkers -> baseline gate."""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterable, Optional

from kserve_vllm_mini_tpu.lint import (
    async_flow,
    baseline as baseline_mod,
    buffer_lifecycle,
    concurrency,
    config_flow,
    contract_flow,
    dtype_flow,
    jit_purity,
    lockstep,
    mesh_flow,
    metrics_drift,
    protocol_flow,
    resource_paths,
    workload,
)
from kserve_vllm_mini_tpu.lint.diagnostics import (
    RULES,
    SUPPRESSION_TOKENS,
    Diagnostic,
)
from kserve_vllm_mini_tpu.lint.facts import FactIndex

EXCLUDED_DIR_NAMES = {"__pycache__", ".git", "node_modules", ".venv"}

# (family prefix, display name, checker, needs_docs) — `--family KVM05`
# selects by prefix match on the family column; needs_docs checkers take
# `(index, doc_texts)` because they join against the docs/dashboards
# surfaces. Tuple order IS family-code order: both the timing table and
# the parallel-run result concatenation follow it, so `--timing-out`
# artifacts diff cleanly across runs and parallel output is byte-
# identical to serial.
CHECKERS = (
    ("KVM01", "jit_purity", jit_purity.check, False),
    ("KVM02", "lockstep", lockstep.check, False),
    ("KVM03", "metrics_drift", metrics_drift.check, True),
    ("KVM04", "workload", workload.check, False),
    ("KVM05", "concurrency", concurrency.check, False),
    ("KVM06", "dtype_flow", dtype_flow.check, False),
    ("KVM07", "buffer_lifecycle", buffer_lifecycle.check, False),
    ("KVM08", "mesh_flow", mesh_flow.check, False),
    ("KVM09", "resource_paths", resource_paths.check, False),
    ("KVM10", "protocol_flow", protocol_flow.check, False),
    ("KVM11", "contract_flow", contract_flow.check, True),
    ("KVM12", "async_flow", async_flow.check, False),
    ("KVM13", "config_flow", config_flow.check, True),
)

# diagnostic code prefix -> the CHECKERS/timings display name, for the
# per-family finding counts the --timing-out report carries
FAMILY_NAMES = {family: name for family, name, _, _ in CHECKERS}
FAMILY_NAMES["KVM001"] = "stale_suppressions"


def counts_by_checker(diags: list[Diagnostic],
                      timings: dict[str, float]) -> dict[str, int]:
    """Finding counts keyed like the timing table (checkers that ran but
    found nothing report an explicit 0 — absence means 'did not run')."""
    out = {name: 0 for name in timings if name != "facts"}
    for d in diags:
        for prefix in sorted(FAMILY_NAMES, key=len, reverse=True):
            if d.code.startswith(prefix):
                name = FAMILY_NAMES[prefix]
                out[name] = out.get(name, 0) + 1
                break
    return out


def discover_py_files(paths: Iterable[Path]) -> list[Path]:
    out: list[Path] = []
    for p in paths:
        if p.is_dir():
            for f in sorted(p.rglob("*.py")):
                if not any(part in EXCLUDED_DIR_NAMES for part in f.parts):
                    out.append(f)
        elif p.suffix == ".py":
            out.append(p)
    return out


def discover_doc_files(paths: Iterable[Path]) -> list[Path]:
    out: list[Path] = []
    for p in paths:
        if p.is_dir():
            out += sorted(p.rglob("*.md")) + sorted(p.rglob("*.json"))
        elif p.suffix in {".md", ".json"}:
            out.append(p)
    return out


def normalize_families(families: Optional[Iterable[str]]) -> Optional[set[str]]:
    """CLI family args ("KVM05", "kvm051") -> validated prefix set.

    KVM001 (stale suppressions) is meta — it rides along with whatever
    rules run and cannot be selected on its own; accepting it would
    select zero checkers and report a green no-op."""
    if not families:
        return None
    out = set()
    selectable = set(RULES) - {"KVM001"}
    for f in families:
        norm = f.strip().upper()
        if not norm.startswith("KVM") or not any(
                code.startswith(norm) for code in selectable):
            raise ValueError(
                f"unknown rule family {f!r} (families: KVM01..KVM13, or a "
                "full code like KVM051; KVM001 always rides along and is "
                "not selectable)")
        out.add(norm)
    return out


def _family_selected(families: Optional[set[str]], prefix: str) -> bool:
    if families is None:
        return True
    return any(f.startswith(prefix) or prefix.startswith(f) for f in families)


def _active_suppression_tokens(families: Optional[set[str]]) -> Optional[set[str]]:
    """Tokens whose rules actually run under this family filter (None =
    everything runs; KVM001 staleness then checks all tokens)."""
    if families is None:
        return None
    return {
        r.suppression for code, r in RULES.items()
        if r.suppression and any(code.startswith(f) for f in families)
    }


def _code_selected(code: str, families: Optional[set[str]]) -> bool:
    """Does this diagnostic code fall under the family filter? Handles
    both directions: ``--family KVM05`` selects KVM051..055, and a full
    code ``--family KVM051`` selects exactly KVM051 (the checker still
    RUNS at family granularity, so sibling findings must be dropped
    after the fact — the help text promises one rule)."""
    if families is None:
        return True
    return any(code.startswith(f) or f.startswith(code) for f in families)


def _filter_baseline(baseline: dict[str, int],
                     families: Optional[set[str]],
                     active_tokens: Optional[set[str]]) -> dict[str, int]:
    """With a family filter, only that family's baseline entries are in
    play — entries for rules that didn't run this pass must not read as
    stale. Keys are ``path::code::context``; for KVM001 the context IS
    the suppression token list, so stale-suppression entries stay in
    play only when their tokens' rules ran."""
    if families is None:
        return baseline
    out = {}
    for key, n in baseline.items():
        parts = key.split("::")
        code = parts[1] if len(parts) > 1 else ""
        if code == "KVM001":
            tokens = set((parts[2] if len(parts) > 2 else "").split(","))
            if active_tokens is None or tokens & active_tokens:
                out[key] = n
        elif _code_selected(code, families):
            out[key] = n
    return out


@dataclass
class LintResult:
    diagnostics: list[Diagnostic] = field(default_factory=list)
    parse_errors: list[tuple[str, int, str]] = field(default_factory=list)
    baseline_diff: Optional[baseline_mod.BaselineDiff] = None
    # per-stage wall time (seconds): fact-index build + each checker that
    # ran — the `--timing` surface the <10s live-codebase pin uses to
    # attribute regressions to a specific checker
    timings: dict[str, float] = field(default_factory=dict)

    @property
    def exit_code(self) -> int:
        if self.parse_errors:
            return 2
        if self.baseline_diff is not None:
            return 0 if self.baseline_diff.clean else 1
        return 1 if self.diagnostics else 0

    @property
    def gating(self) -> list[Diagnostic]:
        """The findings that actually fail the run."""
        if self.baseline_diff is not None:
            return self.baseline_diff.new
        return self.diagnostics


def _rel(root: Path, p: Path) -> Path:
    try:
        return p.resolve().relative_to(root.resolve())
    except ValueError:
        return p


def changed_scan_paths(root: Path, paths: list[Path],
                       ref: str) -> tuple[list[Path], list[str]]:
    """The `--changed` file set: python files under ``paths`` that differ
    from ``ref`` (``git diff --name-only``) or are untracked (``git
    ls-files --others`` — a brand-new module must never make the scan
    silently green), plus their cross-file consumers via a reverse
    import map — a consumer's facts reference the changed module, so its
    findings can change too. Git prints paths relative to the repo
    TOPLEVEL, not the cwd, so they are resolved against it. Raises
    RuntimeError when git cannot produce the diff (loud, never a
    silently-empty scan).

    Returns ``(scan_paths, skipped)``: a deleted or renamed-away file
    shows up in the diff but no longer exists on disk — it has nothing
    to scan (its importers, which DO still exist, are picked up as
    consumers), so it is reported in ``skipped`` (toplevel-relative
    python paths) for the CLI's note instead of crashing the scan."""
    import subprocess

    def git(*args: str) -> str:
        proc = subprocess.run(["git", *args], cwd=root,
                              capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"git {' '.join(args)} failed: "
                f"{proc.stderr.strip() or proc.stdout.strip()}")
        return proc.stdout

    toplevel = Path(git("rev-parse", "--show-toplevel").strip())
    # diff prints toplevel-relative paths; ls-files prints CWD-relative
    # ones unless --full-name forces toplevel — without it, untracked
    # files are silently missed whenever the scan runs in a subdirectory
    listed = (git("diff", "--name-only", ref, "--")
              + git("ls-files", "--others", "--exclude-standard",
                    "--full-name"))
    listed_rel = [line.strip() for line in listed.splitlines()
                  if line.strip()]
    skipped = sorted({
        rel for rel in listed_rel
        if rel.endswith(".py") and not (toplevel / rel).exists()
    })
    diff = {(toplevel / rel).resolve() for rel in listed_rel
            if (toplevel / rel).exists()}
    scope = discover_py_files(paths)
    changed = [f for f in scope if f.resolve() in diff]
    if not changed:
        return [], skipped
    by_rel = {_rel(root, f).as_posix(): f for f in scope}
    changed_rel = {_rel(root, f).as_posix() for f in changed}
    consumer_rel = _reverse_import_deps(root, scope, changed_rel)
    return sorted(
        {by_rel[r] for r in (changed_rel | consumer_rel) if r in by_rel}
    ), skipped


def _reverse_import_deps(root: Path, scope: list[Path],
                         changed_rel: set[str]) -> set[str]:
    """Repo-relative paths of scope modules importing a changed module.
    A parse-imports-only pass (one ``ast.parse`` per file, no function
    walk) — building the full FactIndex here would cost the `--changed`
    mode most of the full-scan time it exists to avoid. Resolution
    mirrors FactIndex.module_for_dotted: exact dotted name, then suffix
    match inside the scanned package."""
    import ast

    by_dotted: dict[str, str] = {}
    for f in scope:
        rel = _rel(root, f).as_posix()
        dotted = rel[:-3].replace("/", ".") if rel.endswith(".py") else rel
        if dotted.endswith(".__init__"):
            dotted = dotted[: -len(".__init__")]
        by_dotted[dotted] = rel

    def resolve(dotted: str) -> Optional[str]:
        rel = by_dotted.get(dotted)
        if rel is None and dotted:
            for d, r in by_dotted.items():
                if d.endswith("." + dotted) or d == dotted:
                    return r
        return rel

    out: set[str] = set()
    for f in scope:
        rel = _rel(root, f).as_posix()
        if rel in changed_rel:
            continue
        try:
            tree = ast.parse(f.read_text(encoding="utf-8"))
        except (OSError, SyntaxError):
            continue  # the scan itself reports parse errors
        deps: set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    target = resolve(a.name)
                    if target:
                        deps.add(target)
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                target = resolve(mod)
                if target:
                    deps.add(target)
                for a in node.names:
                    # `from pkg import module` binds a submodule
                    sub = resolve(f"{mod}.{a.name}" if mod else a.name)
                    if sub:
                        deps.add(sub)
        if deps & changed_rel:
            out.add(rel)
    return out


def run_lint(
    paths: list[Path],
    doc_paths: Optional[list[Path]] = None,
    baseline_path: Optional[Path] = None,
    root: Optional[Path] = None,
    families: Optional[set[str]] = None,
    baseline_scope_to_paths: bool = False,
    jobs: Optional[int] = None,
) -> LintResult:
    """``baseline_scope_to_paths``: restrict the baseline gate to entries
    for the scanned files — a `--changed` subset scan must not call an
    unscanned file's grandfathered finding stale (the full scan still
    ratchets it). Ordinary single-file scans keep whole-baseline
    semantics: a fixed finding flags stale no matter which file you ran.

    ``jobs``: checker-family parallelism. ``1`` runs the families
    serially in tuple order; ``None`` (the default) sizes a thread pool
    to the selected family count. Every family is read-only over the one
    shared FactIndex (the only writes — the call-site cache and the
    used-suppression sets — are idempotent dict/set inserts, safe under
    the GIL), and results are concatenated in CHECKERS order before the
    final sort/dedup, so parallel output is byte-identical to serial."""
    root = (root or Path.cwd()).resolve()
    files = discover_py_files(paths)
    timings: dict[str, float] = {}
    t0 = time.perf_counter()
    index = FactIndex.build(root, [root / _rel(root, f) for f in files])
    timings["facts"] = time.perf_counter() - t0
    # absence-based rules (mesh scopes, axis vocabulary) stand down on
    # partial scans — the missing fact may live in an unscanned module
    index.full_scan = bool(paths) and all(p.is_dir() for p in paths)

    # cross-surface drift (KVM032 vs docs/dashboards, KVM13x vs docs)
    # asserts over the WHOLE emitter set, so it only runs for directory
    # scans — linting a single changed file must not fail on metrics or
    # knobs that other (unscanned) modules provide
    full_scan = index.full_scan
    doc_texts: dict[str, str] = {}
    if full_scan and any(_family_selected(families, family)
                         for family, _, _, needs_docs in CHECKERS
                         if needs_docs):
        for doc in discover_doc_files(doc_paths or []):
            try:
                doc_texts[_rel(root, doc).as_posix()] = doc.read_text(
                    encoding="utf-8")
            except OSError:
                continue

    # one timed thunk per selected family; run serially or in a thread
    # pool, then concatenate in tuple (= family-code) order — the
    # downstream sort/dedup sees the same stream either way
    selected = [(name, checker, needs_docs)
                for family, name, checker, needs_docs in CHECKERS
                if _family_selected(families, family)]

    def run_one(name: str, checker, needs_docs: bool
                ) -> tuple[list[Diagnostic], float]:
        t = time.perf_counter()
        found = (checker(index, doc_texts) if needs_docs
                 else checker(index))
        return found, time.perf_counter() - t

    if jobs is None:
        # one thread per family, capped at the core count — the checkers
        # are pure-Python CPU work, so threads beyond the cores only add
        # GIL contention (a single-core runner degrades ~20% with a full
        # 13-thread pool; it runs the serial path instead)
        import os

        jobs = min(len(selected), os.cpu_count() or 1)
    diags: list[Diagnostic] = []
    if jobs <= 1 or len(selected) <= 1:
        results = [run_one(*task) for task in selected]
    else:
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=jobs) as pool:
            futures = [pool.submit(run_one, *task) for task in selected]
            results = [f.result() for f in futures]
    for (name, _, _), (found, dt) in zip(selected, results):
        diags += found
        timings[name] = dt

    # stale `# kvmini:` comments — only after every rule had its chance,
    # and only for the suppression tokens whose rules ran this pass
    active_tokens = _active_suppression_tokens(families)
    if not index.full_scan:
        # the KVM10x/11x families reason from the ABSENCE of a fact on
        # the far side of a protocol and stand down entirely on subset
        # scans — a protocol-ok on the publish side would read as stale
        # whenever the follower module is out of scope. Likewise
        # async-ok (the loop-root registration may be unscanned) and
        # config-ok (the knob table/docs join is full-scan only). These
        # tokens can only be judged stale by a full scan.
        if active_tokens is None:
            active_tokens = set(SUPPRESSION_TOKENS)
        active_tokens -= {"protocol-ok", "contract-ok",
                          "async-ok", "config-ok"}
    for mod in index.modules.values():
        diags += mod.suppressions.stale(mod.path, active_tokens)

    # nested defs are visited both standalone and inside their enclosing
    # function's walk; report each site once. A full-code family filter
    # (--family KVM051) also drops sibling codes the family checker
    # emitted (KVM001 is already token-restricted above).
    seen: set[tuple[str, int, str, str]] = set()
    unique: list[Diagnostic] = []
    for d in sorted(diags, key=lambda d: (d.path, d.line, d.code)):
        if d.code != "KVM001" and not _code_selected(d.code, families):
            continue
        k = (d.path, d.line, d.code, d.message)
        if k not in seen:
            seen.add(k)
            unique.append(d)

    result = LintResult(diagnostics=unique, parse_errors=index.parse_errors,
                        timings={k: round(v, 4) for k, v in timings.items()})
    if baseline_path is not None and baseline_path.exists():
        base = _filter_baseline(baseline_mod.load(baseline_path),
                                families, active_tokens)
        if baseline_scope_to_paths:
            scanned = {_rel(root, f).as_posix() for f in files}
            base = {k: n for k, n in base.items()
                    if k.split("::", 1)[0] in scanned}
        result.baseline_diff = baseline_mod.diff(unique, base)
    return result
