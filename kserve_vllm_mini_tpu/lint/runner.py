"""Orchestration: file discovery -> fact index -> checkers -> baseline gate."""

from __future__ import annotations

from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterable, Optional

from kserve_vllm_mini_tpu.lint import (
    baseline as baseline_mod,
    jit_purity,
    lockstep,
    metrics_drift,
    workload,
)
from kserve_vllm_mini_tpu.lint.diagnostics import Diagnostic
from kserve_vllm_mini_tpu.lint.facts import FactIndex

EXCLUDED_DIR_NAMES = {"__pycache__", ".git", "node_modules", ".venv"}

CHECKERS = (
    jit_purity.check,
    lockstep.check,
    workload.check,
)


def discover_py_files(paths: Iterable[Path]) -> list[Path]:
    out: list[Path] = []
    for p in paths:
        if p.is_dir():
            for f in sorted(p.rglob("*.py")):
                if not any(part in EXCLUDED_DIR_NAMES for part in f.parts):
                    out.append(f)
        elif p.suffix == ".py":
            out.append(p)
    return out


def discover_doc_files(paths: Iterable[Path]) -> list[Path]:
    out: list[Path] = []
    for p in paths:
        if p.is_dir():
            out += sorted(p.rglob("*.md")) + sorted(p.rglob("*.json"))
        elif p.suffix in {".md", ".json"}:
            out.append(p)
    return out


@dataclass
class LintResult:
    diagnostics: list[Diagnostic] = field(default_factory=list)
    parse_errors: list[tuple[str, int, str]] = field(default_factory=list)
    baseline_diff: Optional[baseline_mod.BaselineDiff] = None

    @property
    def exit_code(self) -> int:
        if self.parse_errors:
            return 2
        if self.baseline_diff is not None:
            return 0 if self.baseline_diff.clean else 1
        return 1 if self.diagnostics else 0

    @property
    def gating(self) -> list[Diagnostic]:
        """The findings that actually fail the run."""
        if self.baseline_diff is not None:
            return self.baseline_diff.new
        return self.diagnostics


def _rel(root: Path, p: Path) -> Path:
    try:
        return p.resolve().relative_to(root.resolve())
    except ValueError:
        return p


def run_lint(
    paths: list[Path],
    doc_paths: Optional[list[Path]] = None,
    baseline_path: Optional[Path] = None,
    root: Optional[Path] = None,
) -> LintResult:
    root = (root or Path.cwd()).resolve()
    files = discover_py_files(paths)
    index = FactIndex.build(root, [root / _rel(root, f) for f in files])

    # cross-surface drift (KVM032 vs docs/dashboards) asserts over the
    # WHOLE emitter set, so it only runs for directory scans — linting a
    # single changed file must not fail on metrics that other (unscanned)
    # emitter modules provide
    full_scan = bool(paths) and all(p.is_dir() for p in paths)
    doc_texts: dict[str, str] = {}
    if full_scan:
        for doc in discover_doc_files(doc_paths or []):
            try:
                doc_texts[_rel(root, doc).as_posix()] = doc.read_text(
                    encoding="utf-8")
            except OSError:
                continue

    diags: list[Diagnostic] = []
    for checker in CHECKERS:
        diags += checker(index)
    diags += metrics_drift.check(index, doc_texts)

    # stale `# kvmini:` comments — only after every rule had its chance
    for mod in index.modules.values():
        diags += mod.suppressions.stale(mod.path)

    # nested defs are visited both standalone and inside their enclosing
    # function's walk; report each site once
    seen: set[tuple[str, int, str, str]] = set()
    unique: list[Diagnostic] = []
    for d in sorted(diags, key=lambda d: (d.path, d.line, d.code)):
        k = (d.path, d.line, d.code, d.message)
        if k not in seen:
            seen.add(k)
            unique.append(d)

    result = LintResult(diagnostics=unique, parse_errors=index.parse_errors)
    if baseline_path is not None and baseline_path.exists():
        result.baseline_diff = baseline_mod.diff(
            unique, baseline_mod.load(baseline_path))
    return result
