"""kvmini-lint — AST-based invariant checker for the repo's load-bearing
conventions (docs/LINTING.md "Conventions kvmini-lint enforces").

Thirteen checkers, all stdlib-``ast`` over one shared cross-file fact
index (run in a thread pool sized to the CPU count; ``--jobs 1`` forces
the byte-identical serial path) — deliberately JAX-free so the lint
gate runs anywhere the harness layers do (same contract as
loadgen/analysis: no ``runtime`` extra required):

- **jit purity / static shapes** (KVM011-KVM015): no data-dependent
  Python control flow, wall clocks, host randomness, or host syncs
  inside code traced by ``jax.jit``/``pjit``/``shard_map`` — or, for
  syncs, inside the host functions that dispatch jitted callables (the
  decode hot path, where an unannotated sync silently serializes the
  double-buffered pipeline, docs/DECODE_PIPELINE.md).
- **lockstep determinism** (KVM021-KVM022): scheduler paths replayed by
  runtime/multihost.py must route every state-advancing step through the
  ``on_decision`` publisher and stay free of host-local nondeterminism
  (wall-clock control flow, randomness, ``set`` iteration order).
- **metrics/schema drift** (KVM031-KVM033): every engine stats counter
  must reach ``/metrics``; every consumed/documented ``kvmini_tpu_*``
  name must be emitted (and vice versa); every results.json key written
  by the pipeline must exist in core/schema.py's ``Results``.
- **workload-change surfacing** (KVM041): truncation / silent drops /
  fallbacks in loadgen+runtime code must stamp a flag field the
  analyzer reads (LINTING.md "don't hide workload changes").
- **thread-safety / lock discipline** (KVM051-KVM055): thread-root
  discovery (Thread/executor/HTTP-handler spawn sites propagated through
  the call graph), guarded-by inference for cross-thread ``self._x``
  state, lock-order cycle detection, unbounded wait/join, and raw
  mutable-container publication across the thread boundary
  (lint/concurrency.py).
- **numerics / dtype flow** (KVM061-KVM065): an abstract interpretation
  over dtypes ("the dtype-flow lattice", docs/LINTING.md) flags silent
  bf16×f32 upcasts on jit hot paths, dequantization that drops the
  scale/zero-point compensation contract, sub-byte bitcasts and
  materialized int4 leaves, integer dots without an accumulator dtype,
  and low-precision accumulations (lint/dtype_flow.py).
- **buffer lifecycle** (KVM071-KVM074): donation discipline (donated
  args read after dispatch, cache carries that should donate) and
  paged-KV block lifecycle (double-free, use-after-free, retained-LRU
  claims without unpin) with suite-aware, exit-cancelling event
  ordering (lint/buffer_lifecycle.py).
- **mesh & sharding consistency** (KVM081-KVM084): a mesh-axis fact
  table from construction sites and shard_map scopes flags collectives
  over unbound axes, ``PartitionSpec`` arity/axis-name mismatches,
  hidden reshards (``device_put``/``with_sharding_constraint``) on
  jit-dispatch hot paths, and donated buffers whose sharding changes
  across the shard_map boundary (lint/mesh_flow.py).
- **exception-path resource safety** (KVM091-KVM093): learned
  acquire/release pairs (free-list pops, ``_release_slot``-style
  releasers, lock/arm toggles) walked over each function's CFG — a
  path leaking an acquire, a double release on one path, and a
  ``finally`` re-raising past a pending release all fail
  (lint/resource_paths.py).
- **wire-protocol conformance** (KVM101-KVM104): lockstep replay
  symmetry (every published decision type needs a replay arm and vice
  versa), host-only state reads on the replay path, handoff version
  negotiation, and degrade-ladder re-arm discipline
  (lint/protocol_flow.py).
- **absent-not-zero contract drift** (KVM111-KVM113): fabricated zeros
  on the metrics/results export path, event-taxonomy drift against
  ``EVENT_TYPES``, and HTTP surface drift between the real server, the
  mock, and docs/API.md (lint/contract_flow.py).
- **asyncio event-loop discipline** (KVM121-KVM124): an event-loop-root
  table (aiohttp handlers, lifecycle callbacks, task spawns,
  ``asyncio.run`` targets) propagated through the call graph flags
  blocking calls on the loop, fire-and-forget tasks, loop-affinity
  violations (loop state also mutated by thread-rooted code without
  ``call_soon_threadsafe`` routing), and read-modify-writes straddling
  an ``await`` (lint/async_flow.py).
- **config-surface drift** (KVM131-KVM134): the operator-visible knob
  surface joined across env reads, ``*_ENV_KNOBS`` tables, argparse
  flags, config dataclasses, and docs pages — undiscoverable knobs,
  dead table entries, unreachable config fields, and cross-layer
  default drift (lint/config_flow.py).

CLI: ``python -m kserve_vllm_mini_tpu.lint [paths...]`` — see __main__.py.
Suppressions: ``# kvmini: <token>`` line comments (diagnostics.RULES maps
each code to its token); a committed ``lint-baseline.json`` grandfathers
pre-existing findings while new ones (and stale baseline entries) fail.
"""

from kserve_vllm_mini_tpu.lint.diagnostics import RULES, Diagnostic
from kserve_vllm_mini_tpu.lint.runner import LintResult, run_lint

__all__ = ["Diagnostic", "LintResult", "RULES", "run_lint"]
