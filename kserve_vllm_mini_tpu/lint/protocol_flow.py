"""KVM101-KVM104 — replicated-state & wire-protocol conformance.

The multihost lockstep stream and the disagg KV-handoff wire are the
two protocol surfaces whose producer and consumer live in different
modules — exactly where a one-sided edit compiles, passes unit tests,
and diverges a pod hundreds of steps later. Four rules, all riding the
shared fact index:

- **KVM101 — publish/replay symmetry**: every decision tag published
  into the lockstep stream (a tuple literal handed to the
  ``on_decision`` callback or to a ``.publish(...)`` call) must have a
  matching dispatch arm in ``run_follower``'s replay loop (a string
  the follower compares the command opcode against), and vice versa.
  An unknown tag on either side fires — this is the day-one guardrail
  for ROADMAP item 1's ``("handoff",)`` decision.
- **KVM102 — host-only field discipline**: fields the primary strips
  from the replay payload (the module-level ``*_HOST_ONLY_FIELDS``
  set: ``deadline_s``, trace ids, ...) must never be read inside
  follower-replayed engine methods — followers see ``None`` and
  diverge. Reads gated on ``self._lockstep`` (or on a local derived
  from it) are the blessed split and exempt.
- **KVM103 — version-negotiation completeness**: every
  ``KVHandoff(version=...)`` construction must be covered by a
  consume-side version check (a function comparing ``.version``) —
  a new version constant with no consumer arm fires before the first
  tombstone does.
- **KVM104 — degrade-ladder soundness**: sticky degrade flags
  (``self.*_degraded`` / ``self.*_disabled``, written with bool
  literals) are terminal outside init/reset paths — a ``False``
  re-arm elsewhere fires, as does a flag that is read but never set
  (a ladder level with no entry edge).

Suppress a deliberate asymmetry with ``# kvmini: protocol-ok`` (e.g. a
decision tag published for stream-shape convention that lockstep never
reaches, or a host-local telemetry field both sides agree to drop).

All four rules reason from the ABSENCE of a fact on the far side of the
protocol, so they stand down on partial scans (``index.full_scan``) —
the missing arm may live in an unscanned module.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, Optional

from kserve_vllm_mini_tpu.lint.diagnostics import Diagnostic
from kserve_vllm_mini_tpu.lint.facts import (
    FactIndex,
    FunctionInfo,
    ModuleFacts,
    iter_scope,
)

PUBLISHER_PARAM = "on_decision"
FOLLOWER_PREFIXES = ("run_follower", "run_replica")
HOST_ONLY_SET = re.compile(r"HOST_ONLY_FIELDS$")
VERSION_CONST = re.compile(r"HANDOFF_VERSION")
STICKY_ATTR = re.compile(r"_(degraded|disabled)$")
RESET_FN = re.compile(r"^(__init__$|_?reset|_?clear)")
LOCKSTEP_ATTR = "_lockstep"


def _tuple_tag(call: ast.Call) -> Optional[tuple[str, ast.AST]]:
    """`cb(("retire", payload))` -> ("retire", <tuple node>)."""
    if call.args and isinstance(call.args[0], ast.Tuple):
        tup = call.args[0]
        if tup.elts and isinstance(tup.elts[0], ast.Constant) and isinstance(
                tup.elts[0].value, str):
            return tup.elts[0].value, tup
    return None


def _mentions_lockstep(node: ast.AST) -> bool:
    return any(
        isinstance(n, ast.Attribute) and LOCKSTEP_ATTR in n.attr
        for n in ast.walk(node))


def _mentions_names(node: ast.AST, names: set[str]) -> bool:
    return any(
        isinstance(n, ast.Name) and n.id in names for n in ast.walk(node))


class ProtocolChecker:
    def __init__(self, index: FactIndex):
        self.index = index
        self.diags: list[Diagnostic] = []

    def run(self) -> list[Diagnostic]:
        if not self.index.full_scan:
            return []
        self._check_symmetry()
        self._check_host_only_reads()
        self._check_version_negotiation()
        self._check_degrade_ladder()
        return self.diags

    def _emit(self, mod: ModuleFacts, line: int, code: str, msg: str,
              ctx: str) -> None:
        if mod.suppressions.is_suppressed(line, code):
            return
        self.diags.append(Diagnostic(mod.path, line, code, msg, context=ctx))

    # -- KVM101 -------------------------------------------------------------
    def _published_tags(self) -> list[tuple[ModuleFacts, int, str]]:
        """Tuple-literal decisions entering the stream: calls of the
        `on_decision` callback (inside publisher-threaded functions) and
        `.publish((...))` attribute calls (the wire side)."""
        out: list[tuple[ModuleFacts, int, str]] = []
        for mod in self.index.modules.values():
            for fn in mod.functions.values():
                takes_publisher = PUBLISHER_PARAM in fn.params
                for node in iter_scope(fn.node):
                    if not isinstance(node, ast.Call):
                        continue
                    f = node.func
                    is_cb = (takes_publisher and isinstance(f, ast.Name)
                             and f.id == PUBLISHER_PARAM)
                    is_wire = isinstance(f, ast.Attribute) and f.attr == "publish"
                    if not (is_cb or is_wire):
                        continue
                    tagged = _tuple_tag(node)
                    if tagged is not None:
                        out.append((mod, node.lineno, tagged[0]))
        return out

    def _replay_arms(self) -> list[tuple[ModuleFacts, FunctionInfo, int, str]]:
        """String opcodes the follower dispatch loop compares against:
        inside run_follower*/run_replica*, `op = cmd[0]` names compared
        (==, or `in (...)` membership) to string constants."""
        out: list[tuple[ModuleFacts, FunctionInfo, int, str]] = []
        for mod in self.index.modules.values():
            for fn in mod.functions.values():
                if not fn.name.startswith(FOLLOWER_PREFIXES):
                    continue
                op_names: set[str] = set()
                for node in iter_scope(fn.node):
                    if isinstance(node, ast.Assign) and isinstance(
                            node.value, ast.Subscript):
                        sl = node.value.slice
                        if isinstance(sl, ast.Constant) and sl.value == 0:
                            op_names |= {t.id for t in node.targets
                                         if isinstance(t, ast.Name)}
                for node in iter_scope(fn.node):
                    if not isinstance(node, ast.Compare):
                        continue
                    operands = [node.left, *node.comparators]
                    if not any(isinstance(o, ast.Name) and o.id in op_names
                               for o in operands):
                        continue
                    for o in operands:
                        for c in ast.walk(o):
                            if isinstance(c, ast.Constant) and isinstance(
                                    c.value, str):
                                out.append((mod, fn, node.lineno, c.value))
        return out

    def _check_symmetry(self) -> None:
        published = self._published_tags()
        arms = self._replay_arms()
        # both-sides gate: a scan that sees only one end of the stream
        # (a fixture with publishers but no follower) has nothing to
        # compare symmetry against
        if not published or not arms:
            return
        pub_tags = {t for _, _, t in published}
        arm_tags = {t for _, _, _, t in arms}
        seen: set[tuple[str, str]] = set()
        for mod, line, tag in published:
            if tag in arm_tags or (mod.path, tag) in seen:
                continue
            seen.add((mod.path, tag))
            self._emit(
                mod, line, "KVM101",
                f"decision tag '{tag}' is published into the lockstep "
                "stream but no run_follower replay loop has a dispatch arm "
                "for it — followers hit the unknown-command path and the "
                "pod diverges; add the arm or mark `# kvmini: protocol-ok`",
                tag)
        seen.clear()
        for mod, fn, line, tag in arms:
            if tag in pub_tags or (mod.path, tag) in seen:
                continue
            seen.add((mod.path, tag))
            self._emit(
                mod, line, "KVM101",
                f"replay arm '{tag}' in `{fn.name}` matches a decision tag "
                "nothing ever publishes — dead protocol surface or a "
                "producer-side rename; publish it, delete the arm, or mark "
                "`# kvmini: protocol-ok`",
                tag)

    # -- KVM102 -------------------------------------------------------------
    def _host_only_fields(self) -> set[str]:
        fields: set[str] = set()
        for mod in self.index.modules.values():
            for node in mod.walk():
                if not isinstance(node, ast.Assign):
                    continue
                if not any(isinstance(t, ast.Name) and HOST_ONLY_SET.search(t.id)
                           for t in node.targets):
                    continue
                if isinstance(node.value, (ast.Set, ast.Tuple, ast.List)):
                    fields |= {e.value for e in node.value.elts
                               if isinstance(e, ast.Constant)
                               and isinstance(e.value, str)}
        return fields

    def _replayed_closure(self) -> list[tuple[ModuleFacts, FunctionInfo]]:
        """Follower-replayed class methods + their same-module callees —
        the KVM022 scope: code both primary and followers execute."""
        replayed = self.index.follower_replayed_methods()
        out: list[tuple[ModuleFacts, FunctionInfo]] = []
        work: list[tuple[ModuleFacts, FunctionInfo]] = []
        for mod in self.index.modules.values():
            for fn in mod.functions.values():
                if fn.name in replayed and fn.class_name is not None:
                    work.append((mod, fn))
        seen: set[tuple[str, str]] = set()
        while work:
            mod, fn = work.pop()
            if fn.key() in seen:
                continue
            seen.add(fn.key())
            out.append((mod, fn))
            for cs in self.index.call_sites(mod, fn):
                for callee in cs.callees:
                    if callee.path == mod.path and callee.key() not in seen:
                        work.append((mod, callee))
        return out

    def _check_host_only_reads(self) -> None:
        fields = self._host_only_fields()
        if not fields:
            return
        for mod, fn in self._replayed_closure():
            gated: set[str] = set()
            for node in iter_scope(fn.node):
                if isinstance(node, ast.Assign) and _mentions_lockstep(
                        node.value):
                    gated |= {t.id for t in node.targets
                              if isinstance(t, ast.Name)}
            reported: set[str] = set()

            def flag_reads(node: ast.AST) -> None:
                for n in ast.walk(node):
                    if (isinstance(n, ast.Attribute)
                            and isinstance(n.ctx, ast.Load)
                            and n.attr in fields
                            and not (isinstance(n.value, ast.Name)
                                     and n.value.id == "self")
                            and n.attr not in reported):
                        reported.add(n.attr)
                        self._emit(
                            mod, n.lineno, "KVM102",
                            f"host-only field '{n.attr}' read in "
                            f"follower-replayed `{fn.name}` — the primary "
                            "strips it from the replay payload "
                            "(_HOST_ONLY_FIELDS), so followers see None "
                            "and diverge; gate on self._lockstep or mark "
                            "`# kvmini: protocol-ok`",
                            f"{fn.qualname}:{n.attr}")

            def scan(stmts: Iterable[ast.stmt]) -> None:
                for stmt in stmts:
                    if isinstance(stmt, (ast.FunctionDef,
                                         ast.AsyncFunctionDef, ast.ClassDef)):
                        continue  # nested defs are their own FunctionInfo
                    if isinstance(stmt, (ast.If, ast.While)):
                        # a branch deciding on the lockstep mode (directly
                        # or via a local derived from it) IS the blessed
                        # host/replica split — its whole subtree is exempt
                        if (_mentions_lockstep(stmt.test)
                                or _mentions_names(stmt.test, gated)):
                            continue
                        flag_reads(stmt.test)
                        scan(stmt.body)
                        scan(stmt.orelse)
                        continue
                    blocks: list[list[ast.stmt]] = []
                    exprs: list[ast.AST] = []
                    for _, value in ast.iter_fields(stmt):
                        if (isinstance(value, list) and value
                                and isinstance(value[0], ast.stmt)):
                            blocks.append(value)
                        elif isinstance(value, ast.AST):
                            exprs.append(value)
                        elif isinstance(value, list):
                            exprs += [v for v in value
                                      if isinstance(v, ast.AST)]
                    if not blocks and any(_mentions_lockstep(e)
                                          for e in exprs):
                        continue  # the statement itself handles the split
                    for e in exprs:
                        flag_reads(e)
                    for b in blocks:
                        scan(b)

            scan(getattr(fn.node, "body", []))

    # -- KVM103 -------------------------------------------------------------
    def _version_exprs(self, value: ast.AST) -> list[tuple[str, object]]:
        """Names/ints a `version=` kwarg can evaluate to, through IfExp."""
        if isinstance(value, ast.IfExp):
            return (self._version_exprs(value.body)
                    + self._version_exprs(value.orelse))
        if isinstance(value, ast.Name):
            return [(value.id, value.id)]
        if isinstance(value, ast.Attribute):
            return [(value.attr, value.attr)]
        if isinstance(value, ast.Constant) and isinstance(value.value, int):
            return [(str(value.value), value.value)]
        return []

    def _check_version_negotiation(self) -> None:
        producers: list[tuple[ModuleFacts, ast.Call, str]] = []
        for mod in self.index.modules.values():
            for node in mod.walk():
                if not isinstance(node, ast.Call):
                    continue
                callee = None
                if isinstance(node.func, ast.Name):
                    callee = node.func.id
                elif isinstance(node.func, ast.Attribute):
                    callee = node.func.attr
                if callee is None or not callee.endswith("Handoff"):
                    continue
                for kw in node.keywords:
                    if kw.arg == "version":
                        producers.append((mod, node, callee))
        if not producers:
            return
        # consumer coverage: any function that COMPARES a `.version`
        # attribute negotiates; every name/int referenced in its scope is
        # a covered arm (name-matching across modules — the producer's
        # constant and the consumer's import share the constant's name)
        covered_names: set[str] = set()
        covered_ints: set[int] = set()
        for mod in self.index.modules.values():
            for fn in mod.functions.values():
                negotiates = any(
                    isinstance(n, ast.Compare) and any(
                        isinstance(o, ast.Attribute) and o.attr == "version"
                        for o in [n.left, *n.comparators])
                    for n in iter_scope(fn.node))
                if not negotiates:
                    continue
                for n in iter_scope(fn.node):
                    if isinstance(n, ast.Name):
                        covered_names.add(n.id)
                    elif isinstance(n, ast.Attribute):
                        covered_names.add(n.attr)
                    elif isinstance(n, ast.Constant) and isinstance(
                            n.value, int):
                        covered_ints.add(n.value)
        for mod, call, callee in producers:
            kw = next(k for k in call.keywords if k.arg == "version")
            for label, val in self._version_exprs(kw.value):
                ok = (val in covered_names if isinstance(val, str)
                      else val in covered_ints)
                if ok:
                    continue
                self._emit(
                    mod, call.lineno, "KVM103",
                    f"`{callee}(version={label})` has no consume-side "
                    "version check covering it — a reader that never "
                    "negotiates this version tombstones or mis-parses the "
                    "handoff; add the consumer arm or mark "
                    "`# kvmini: protocol-ok`",
                    f"{callee}:{label}")

    # -- KVM104 -------------------------------------------------------------
    def _check_degrade_ladder(self) -> None:
        # sticky attr -> write/read sites, package-wide (self.<attr> only)
        writes: dict[str, list[tuple[ModuleFacts, FunctionInfo, int, object]]] = {}
        reads: dict[str, list[tuple[ModuleFacts, int]]] = {}
        for mod in self.index.modules.values():
            for fn in mod.functions.values():
                for node in iter_scope(fn.node):
                    if isinstance(node, ast.Assign):
                        for t in node.targets:
                            if (isinstance(t, ast.Attribute)
                                    and isinstance(t.value, ast.Name)
                                    and t.value.id == "self"
                                    and STICKY_ATTR.search(t.attr)):
                                val = (node.value.value
                                       if isinstance(node.value, ast.Constant)
                                       else node.value)
                                writes.setdefault(t.attr, []).append(
                                    (mod, fn, node.lineno, val))
                    elif (isinstance(node, ast.Attribute)
                          and isinstance(node.ctx, ast.Load)
                          and isinstance(node.value, ast.Name)
                          and node.value.id == "self"
                          and STICKY_ATTR.search(node.attr)):
                        reads.setdefault(node.attr, []).append(
                            (mod, node.lineno))
        for attr, sites in sorted(writes.items()):
            # only bool-literal-written attrs are the sticky-ladder idiom;
            # attrs holding richer state are out of scope
            if not any(isinstance(v, bool) for _, _, _, v in sites):
                continue
            for mod, fn, line, val in sites:
                if val is False and not RESET_FN.match(fn.name):
                    self._emit(
                        mod, line, "KVM104",
                        f"sticky degrade flag `self.{attr}` is re-armed "
                        f"(set False) in `{fn.name}` — degraded states are "
                        "documented-terminal for the process; reset only "
                        "on init/reset paths or mark `# kvmini: protocol-ok`",
                        f"{attr}:rearm")
            entered = any(
                (val is True) or not isinstance(val, bool)
                for _, _, _, val in sites)
            if not entered and attr in reads:
                mod, line = sorted(reads[attr],
                                   key=lambda r: (r[0].path, r[1]))[0]
                self._emit(
                    mod, line, "KVM104",
                    f"sticky degrade flag `self.{attr}` is read but no "
                    "code path ever sets it — the ladder level has no "
                    "entry edge (dead guard, or the degrade write was "
                    "lost in a refactor)",
                    f"{attr}:noentry")


def check(index: FactIndex) -> list[Diagnostic]:
    return ProtocolChecker(index).run()
