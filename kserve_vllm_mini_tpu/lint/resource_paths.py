"""KVM091-KVM093 — exception-path resource safety.

The engine's paired acquire/release state grew past what the donation-
focused KVM07x rules see: slots pop off ``self._free`` and must come
back (or transfer into the slot tables), paged block ids move between
the free list, block tables, and the retained LRU, fault-registry arms
must clear, and the watchdog/chunked-prefill work of PRs 10-11 added
cancellation branches to almost every one of those lifecycles. The
failure mode is always the same shape: an *exception path* (or an early
return, or a cancellation branch) exits the function while the happy
path still owed a release.

**Learning the pairs.** The checker learns the repo's conventions
instead of hard-coding method names:

- a *free-list pop* assigned to a name (``slot = self._free.pop()``,
  ``bid, _ = self._retained_lru.popitem(last=False)``) acquires that
  name, as does ``open(...)`` and a call to a learned *acquirer* (a
  function whose return value derives from a free-list pop — the
  engine's ``_pop_slot_for``);
- a function that appends one of its *parameters* to a free list is a
  *releaser* of that parameter; releasing is transitive through the
  call graph (``_finish_slot`` -> ``_release_slot`` ->
  ``self._free.append(slot)``), three rounds;
- *toggle pairs* on one receiver (``lock.acquire()``/``release()``,
  ``registry.arm()``/``disarm()``/``clear()``, ``f.close()``) are
  tracked only when BOTH halves appear in the same function — a
  lone ``arm`` is a deliberate persistent arm (the POST /faults
  handler), not a leak.

**Ownership transfer** ends a resource's tracked lifetime without a
release: returning/yielding the token, storing it into object state
(``self._slot_req[slot] = handle`` — the slot tables ARE the ownership
record), passing it to any call, ``del``, or rebinding the name. The
generous transfer rule is the misses-over-false-alarms contract: only
a path where the token provably goes *nowhere* is a leak.

**The CFG.** Each function gets a statement-level control-flow graph:
``if``/loops/``with``/``try`` with handler and ``finally`` routing,
``return``/``raise``/``break``/``continue`` threaded through enclosing
``finally`` blocks. Implicit exception edges exist only INSIDE ``try``
bodies (every statement there may jump to each handler, and to the
``finally``) — outside a ``try``, calls are assumed not to raise, so
ordinary straight-line code never manufactures phantom leak paths.

- **KVM091**: from each acquire, some CFG path reaches the function
  exit with no release/transfer of the token — the except branch that
  returns while the slot is still popped.
- **KVM092**: a second release of the same token is reachable from a
  first with no intervening re-acquire/rebind — the drain path that
  frees a slot another branch already freed. Plain free-list
  double-appends stay KVM073's (suite-lexical) job; this rule covers
  the learned releaser *calls* and toggle releases KVM073 cannot see.
- **KVM093**: a ``finally`` block CAN raise before a release later in
  the same block — whenever the raise fires (it needs no exceptional
  entry, and it replaces any in-flight exception) the release is
  skipped, on exactly the failure path that most needs the cleanup. A
  conditional raise counts: the engine's deliberate never-retain-
  poisoned-KV designs annotate ``resource-ok`` instead.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field, replace
from typing import Optional

from kserve_vllm_mini_tpu.lint.diagnostics import Diagnostic
from kserve_vllm_mini_tpu.lint.facts import (
    FactIndex,
    FunctionInfo,
    ModuleFacts,
    iter_scope,
)

FREELIST = re.compile(r"^_?free(_blocks|_list|_slots|list)?$")
RETAINED = re.compile(r"retained")
POP_METHODS = {"pop", "popleft", "popitem"}
# toggle pairs: acquire method -> release methods on the SAME receiver
TOGGLES = {
    "acquire": {"release"},
    "arm": {"disarm", "clear"},
    "open": {"close"},  # via the open() builtin, receiver = bound name
}

TRY_TYPES = (ast.Try,) + ((ast.TryStar,) if hasattr(ast, "TryStar") else ())
EXIT = 0  # the one virtual exit node every leak path ends at


@dataclass(frozen=True)
class _Ctx:
    """Where abrupt control flow lands from the current position."""

    on_return: int = EXIT
    on_raise: tuple[int, ...] = (EXIT,)
    on_break: Optional[int] = None
    on_continue: Optional[int] = None
    exc: tuple[int, ...] = ()  # implicit-exception targets (try bodies only)


class CFG:
    """Statement-level control-flow graph of one function body."""

    def __init__(self, fn_node: ast.AST):
        self.succ: dict[int, set[int]] = {EXIT: set()}
        self.exc_succ: dict[int, set[int]] = {}
        self.stmt_of: dict[int, ast.stmt] = {}
        self._next = 1
        entry = self._seq(list(fn_node.body), EXIT, _Ctx())
        self.entry = entry

    def _new(self, stmt: Optional[ast.stmt]) -> int:
        nid = self._next
        self._next += 1
        if stmt is not None:
            self.stmt_of[nid] = stmt
        self.succ[nid] = set()
        return nid

    def _seq(self, stmts: list[ast.stmt], follow: int, ctx: _Ctx) -> int:
        entry = follow
        for stmt in reversed(stmts):
            entry = self._stmt(stmt, entry, ctx)
        return entry

    def _stmt(self, stmt: ast.stmt, follow: int, ctx: _Ctx) -> int:
        nid = self._new(stmt)
        if isinstance(stmt, ast.Return):
            self.succ[nid] = {ctx.on_return}
        elif isinstance(stmt, ast.Raise):
            self.succ[nid] = set(ctx.on_raise)
        elif isinstance(stmt, ast.Break):
            self.succ[nid] = {ctx.on_break if ctx.on_break is not None
                              else ctx.on_return}
        elif isinstance(stmt, ast.Continue):
            self.succ[nid] = {ctx.on_continue if ctx.on_continue is not None
                              else ctx.on_return}
        elif isinstance(stmt, ast.If):
            body = self._seq(stmt.body, follow, ctx)
            orelse = self._seq(stmt.orelse, follow, ctx) if stmt.orelse else follow
            self.succ[nid] = {body, orelse}
        elif isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            loop_ctx = replace(ctx, on_break=follow, on_continue=nid)
            body = self._seq(stmt.body, nid, loop_ctx)
            after = self._seq(stmt.orelse, follow, ctx) if stmt.orelse else follow
            self.succ[nid] = {body, after}
            if (isinstance(stmt, ast.While)
                    and isinstance(stmt.test, ast.Constant) and stmt.test.value):
                # `while True:` only exits through break (routed above) —
                # a phantom fall-through edge would manufacture leak paths
                self.succ[nid] = {body}
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            self.succ[nid] = {self._seq(stmt.body, follow, ctx)}
        elif isinstance(stmt, TRY_TYPES):
            self.succ[nid] = {self._try(stmt, follow, ctx)}
        else:
            self.succ[nid] = {follow}
        if ctx.exc and not isinstance(
                stmt, (ast.Return, ast.Raise, ast.Break, ast.Continue)):
            self.exc_succ[nid] = set(ctx.exc)
        return nid

    def _try(self, stmt: ast.stmt, follow: int, ctx: _Ctx) -> int:
        has_fin = bool(stmt.finalbody)
        if has_fin:
            fin_join = self._new(None)
            conts: set[int] = set()
            fin_entry = self._seq(stmt.finalbody, fin_join, ctx)

            def route(t: Optional[int]) -> Optional[int]:
                if t is None:
                    return None
                conts.add(t)
                return fin_entry

            def route_many(ts: tuple[int, ...]) -> tuple[int, ...]:
                conts.update(ts)
                return (fin_entry,)
        else:
            def route(t: Optional[int]) -> Optional[int]:
                return t

            def route_many(ts: tuple[int, ...]) -> tuple[int, ...]:
                return ts

        after = route(follow)
        out_ctx = replace(
            ctx,
            on_return=route(ctx.on_return),
            on_break=route(ctx.on_break),
            on_continue=route(ctx.on_continue),
            on_raise=route_many(ctx.on_raise),
            exc=route_many(ctx.exc) if ctx.exc else
                (((fin_entry,) if has_fin else ())),
        )
        handler_entries = tuple(
            self._seq(h.body, after, out_ctx) for h in stmt.handlers)
        # implicit exceptions in the body reach each handler, and (with a
        # finally but no handlers) run the finally then propagate out
        body_exc = handler_entries
        if has_fin:
            body_exc = body_exc + route_many(ctx.on_raise)
        body_ctx = replace(
            out_ctx,
            on_raise=handler_entries + out_ctx.on_raise,
            exc=body_exc,
        )
        body_follow = (self._seq(stmt.orelse, after, out_ctx)
                       if stmt.orelse else after)
        entry = self._seq(stmt.body, body_follow, body_ctx)
        if has_fin:
            self.succ[fin_join] = conts or {follow}
        return entry

    def all_succ(self, nid: int) -> set[int]:
        return self.succ.get(nid, set()) | self.exc_succ.get(nid, set())


def _own_nodes(stmt: ast.stmt):
    """Walk a statement's own expressions (headers included) without
    descending into nested statements or nested defs — those are their
    own CFG nodes / scopes."""
    yield stmt
    stack = [c for c in ast.iter_child_nodes(stmt)
             if not isinstance(c, (ast.stmt, ast.FunctionDef,
                                   ast.AsyncFunctionDef, ast.ClassDef))]
    while stack:
        n = stack.pop()
        yield n
        stack.extend(c for c in ast.iter_child_nodes(n)
                     if not isinstance(c, ast.stmt))


def _base_name(node: ast.AST) -> str:
    """`self._free.append` -> "_free"; `free_list.append` -> "free_list"."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _first_target_name(target: ast.AST) -> Optional[str]:
    if isinstance(target, ast.Name):
        return target.id
    if isinstance(target, (ast.Tuple, ast.List)) and target.elts:
        return _first_target_name(target.elts[0])
    return None


def _receiver_str(node: ast.AST) -> Optional[str]:
    """Stable text for a toggle receiver: `self._lock`, `reg`."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
        return f"{node.value.id}.{node.attr}"
    return None


@dataclass
class _Events:
    """What one CFG statement does to tracked tokens."""

    acquires: list[tuple[str, ast.AST]] = field(default_factory=list)
    releases: list[tuple[str, ast.AST, str]] = field(default_factory=list)
    transfers: set[str] = field(default_factory=set)
    rebinds: set[str] = field(default_factory=set)


class ResourcePathChecker:
    def __init__(self, index: FactIndex):
        self.index = index
        self.diags: list[Diagnostic] = []
        # fn key -> param indices it releases (to a free list, transitively)
        self.releasers: dict[tuple[str, str], set[int]] = {}
        # fn key -> True when the return value derives from a pop
        self.acquirers: set[tuple[str, str]] = set()
        # per-function scan results (one walk, _scan)
        self._uncond_calls: dict[tuple[str, str], set[int]] = {}
        self._interesting: set[tuple[str, str]] = set()

    # -- learning ------------------------------------------------------------
    @staticmethod
    def _freelist_pop(node: ast.AST) -> bool:
        return (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in POP_METHODS
                and (FREELIST.match(_base_name(node.func.value))
                     or RETAINED.search(_base_name(node.func.value))))

    @staticmethod
    def _freelist_append(node: ast.AST) -> Optional[str]:
        """The freed bare name of a `<freelist>.append(x)` call."""
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in {"append", "appendleft"}
                and len(node.args) == 1
                and isinstance(node.args[0], ast.Name)
                and FREELIST.match(_base_name(node.func.value))):
            return node.args[0].id
        return None

    @staticmethod
    def _unconditional_nodes(fn_node: ast.AST):
        """Nodes in the function body's top-level straight-line suite — a
        releaser must free its param UNCONDITIONALLY: `_emit_token`
        finishing a slot only when it hits EOS is not a releaser, or every
        per-token call would read as a double release."""
        for stmt in fn_node.body:
            for node in ast.walk(stmt):
                if isinstance(node, (ast.If, ast.For, ast.While, ast.With,
                                     ast.AsyncWith, ast.AsyncFor,
                                     ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef)) or isinstance(
                                         node, TRY_TYPES):
                    break
                yield node

    # attr names that make a node worth a closer look (gate before regex);
    # release-only toggle halves (release/disarm/clear) create no events
    # without their acquire half, so they do not mark a function
    _MARKER_ATTRS = POP_METHODS | {"append", "appendleft", "close",
                                   "acquire", "arm"}

    def _scan(self) -> None:
        """ONE walk per function: seed releasers/acquirers, remember which
        callsites sit in unconditional position, and mark the (few)
        functions that touch a tracked resource at all."""
        for mod in self.index.modules.values():
            for fn in mod.functions.values():
                uncond = {id(n) for n in self._unconditional_nodes(fn.node)}
                interesting = False
                for node in iter_scope(fn.node):
                    if isinstance(node, TRY_TYPES) and node.finalbody:
                        interesting = True
                    if not isinstance(node, ast.Call):
                        continue
                    f = node.func
                    if isinstance(f, ast.Name) and f.id == "open":
                        interesting = True
                        continue
                    if not (isinstance(f, ast.Attribute)
                            and f.attr in self._MARKER_ATTRS):
                        continue
                    if f.attr in ("append", "appendleft"):
                        freed = self._freelist_append(node)
                        if freed is None:
                            continue  # an ordinary list append
                        interesting = True
                        if id(node) in uncond and freed in fn.params:
                            self.releasers.setdefault(fn.key(), set()).add(
                                fn.params.index(freed))
                    elif f.attr in POP_METHODS:
                        if self._freelist_pop(node):
                            interesting = True
                    else:  # close / acquire / arm
                        interesting = True
                for node in iter_scope(fn.node) if interesting else ():
                    if (isinstance(node, ast.Return)
                            and node.value is not None
                            and any(self._freelist_pop(n)
                                    for n in ast.walk(node.value))):
                        self.acquirers.add(fn.key())
                self._uncond_calls[fn.key()] = uncond
                if interesting:
                    self._interesting.add(fn.key())

    def _learn(self) -> None:
        self._scan()
        # transitive closure over the call graph (3 rounds bound the
        # engine's _finish_slot -> _release_slot -> append chain); the
        # forwarding call must itself sit in unconditional position
        for _ in range(3):
            changed = False
            for mod in self.index.modules.values():
                for fn in mod.functions.values():
                    uncond = self._uncond_calls.get(fn.key(), set())
                    if not uncond:
                        continue
                    for cs in self.index.call_sites(mod, fn):
                        if id(cs.node) not in uncond:
                            continue
                        for callee in cs.callees:
                            rel = self.releasers.get(callee.key())
                            if not rel:
                                continue
                            offset = 1 if callee.params[:1] in (
                                ["self"], ["cls"]) and isinstance(
                                cs.node.func, ast.Attribute) else 0
                            for ri in rel:
                                ai = ri - offset
                                if not (0 <= ai < len(cs.node.args)):
                                    continue
                                arg = cs.node.args[ai]
                                if (isinstance(arg, ast.Name)
                                        and arg.id in fn.params):
                                    k = fn.key()
                                    pi = fn.params.index(arg.id)
                                    if pi not in self.releasers.setdefault(
                                            k, set()):
                                        self.releasers[k].add(pi)
                                        changed = True
            if not changed:
                break

    # -- event extraction ----------------------------------------------------
    def _toggle_receivers(self, fn: FunctionInfo) -> dict[str, set[str]]:
        """receiver -> acquire methods tracked (both halves must appear)."""
        seen: dict[str, set[str]] = {}
        for node in iter_scope(fn.node):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                continue
            recv = _receiver_str(node.func.value)
            if recv is None:
                continue
            seen.setdefault(recv, set()).add(node.func.attr)
        out: dict[str, set[str]] = {}
        for recv, methods in seen.items():
            for acq, rels in TOGGLES.items():
                if acq != "open" and acq in methods and methods & rels:
                    out.setdefault(recv, set()).add(acq)
        return out

    def _stmt_events(self, mod: ModuleFacts, fn: FunctionInfo,
                     stmt: ast.stmt, callees_of: dict[int, list[FunctionInfo]],
                     toggles: dict[str, set[str]]) -> _Events:
        ev = _Events()
        for node in _own_nodes(stmt):
            # rebinds (incl. for-targets): a stored name starts a fresh
            # lifetime for whatever it previously held
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
                ev.rebinds.add(node.id)
            if isinstance(node, ast.Delete):
                ev.transfers |= {t.id for t in node.targets
                                 if isinstance(t, ast.Name)}
            # acquires: assigned pops / open() / learned acquirer calls
            if isinstance(node, ast.Assign) and node.targets:
                tok = _first_target_name(node.targets[0])
                val = node.value
                if tok is not None and isinstance(val, ast.Call):
                    if self._freelist_pop(val):
                        ev.acquires.append((tok, val))
                    elif (isinstance(val.func, ast.Name)
                          and val.func.id == "open"):
                        ev.acquires.append((tok, val))
                    elif any(c.key() in self.acquirers
                             for c in callees_of.get(id(val), [])):
                        ev.acquires.append((tok, val))
            if not isinstance(node, ast.Call):
                continue
            # releases: free-list appends, learned releaser calls, toggles
            freed = self._freelist_append(node)
            if freed is not None:
                ev.releases.append((freed, node, "append"))
                continue
            released_here = False
            for callee in callees_of.get(id(node), []):
                rel = self.releasers.get(callee.key())
                if not rel:
                    continue
                offset = 1 if callee.params[:1] in (["self"], ["cls"]) and (
                    isinstance(node.func, ast.Attribute)) else 0
                for ri in rel:
                    ai = ri - offset
                    if (0 <= ai < len(node.args)
                            and isinstance(node.args[ai], ast.Name)):
                        ev.releases.append(
                            (node.args[ai].id, node, callee.name))
                        released_here = True
            if released_here:
                continue
            if isinstance(node.func, ast.Attribute):
                recv = _receiver_str(node.func.value)
                meth = node.func.attr
                if recv is not None and recv in toggles:
                    if meth in toggles[recv]:
                        ev.acquires.append((f"{recv}.{meth}()", node))
                        continue
                    for acq in toggles[recv]:
                        if meth in TOGGLES[acq]:
                            ev.releases.append(
                                (f"{recv}.{acq}()", node, meth))
                    if any(meth in TOGGLES[a] for a in toggles[recv]):
                        continue
                if meth == "close" and recv is not None:
                    ev.releases.append((recv, node, "close"))
                    continue
            # any other call a token rides into transfers ownership
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                for n in ast.walk(arg):
                    if isinstance(n, ast.Name):
                        ev.transfers.add(n.id)
        # stores into object state / subscripts transfer both the value
        # names and the index names (the slot tables ARE the ownership
        # record); return/yield transfers whatever rides out
        for node in _own_nodes(stmt):
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, (ast.Attribute, ast.Subscript)):
                        ev.transfers |= {
                            n.id for n in ast.walk(node.value)
                            if isinstance(n, ast.Name)}
                    if isinstance(tgt, ast.Subscript):
                        ev.transfers |= {
                            n.id for n in ast.walk(tgt.slice)
                            if isinstance(n, ast.Name)}
            if isinstance(node, (ast.Return, ast.Yield, ast.YieldFrom)):
                val = node.value
                if val is not None:
                    ev.transfers |= {n.id for n in ast.walk(val)
                                     if isinstance(n, ast.Name)}
        return ev

    # -- analysis ------------------------------------------------------------
    def run(self) -> list[Diagnostic]:
        self._learn()
        for mod in self.index.modules.values():
            for fn in mod.functions.values():
                self._check_fn(mod, fn)
        return self.diags

    def _emit(self, mod: ModuleFacts, node: ast.AST, code: str, msg: str,
              context: str) -> None:
        line = getattr(node, "lineno", 0)
        if mod.suppressions.is_suppressed(line, code):
            return
        self.diags.append(Diagnostic(mod.path, line, code, msg,
                                     context=context))

    def _worth_checking(self, fn: FunctionInfo) -> bool:
        """Cheap gate: almost no function touches a tracked resource."""
        if fn.key() in self._interesting:
            return True
        # learned releaser/acquirer callsites make a function interesting
        # even without its own markers (dict lookups on the cached sites)
        mod = self.index.modules[fn.path]
        return any(
            c.key() in self.releasers or c.key() in self.acquirers
            for cs in self.index.call_sites(mod, fn) for c in cs.callees)

    def _check_fn(self, mod: ModuleFacts, fn: FunctionInfo) -> None:
        if not self._worth_checking(fn):
            return
        callees_of = {id(cs.node): cs.callees
                      for cs in self.index.call_sites(mod, fn)}
        toggles = self._toggle_receivers(fn)
        cfg = CFG(fn.node)
        events = {nid: self._stmt_events(mod, fn, stmt, callees_of, toggles)
                  for nid, stmt in cfg.stmt_of.items()}
        self._check_leaks(mod, fn, cfg, events)
        self._check_double_release(mod, fn, cfg, events)
        self._check_finally_reraise(mod, fn, callees_of)

    @staticmethod
    def _node_settles(ev: _Events, token: str) -> bool:
        return (token in ev.transfers or token in ev.rebinds
                or any(t == token for t, _, _ in ev.releases)
                or any(t == token for t, _ in ev.acquires))

    # -- KVM091 --------------------------------------------------------------
    def _check_leaks(self, mod: ModuleFacts, fn: FunctionInfo, cfg: CFG,
                     events: dict[int, _Events]) -> None:
        for nid, ev in events.items():
            for token, node in ev.acquires:
                # start from NORMAL successors only: if the acquiring
                # statement itself raises, nothing was acquired
                escape = self._find_escape(cfg, events, cfg.succ.get(nid, set()),
                                           token)
                if escape is None:
                    continue
                where = (f"the path through line {escape}"
                         if escape > 0 else "a fall-through path")
                self._emit(
                    mod, node, "KVM091",
                    f"`{token}` acquired here can escape `{fn.name}` via "
                    f"{where} without a release or ownership transfer — "
                    "an exception/cancellation branch leaks the resource; "
                    "release it in a `finally`/except path, transfer "
                    "ownership, or mark `# kvmini: resource-ok`",
                    fn.qualname)

    def _find_escape(self, cfg: CFG, events: dict[int, _Events],
                     start: set[int], token: str) -> Optional[int]:
        """Line of the statement from which EXIT is reached while the
        token is still live; None when every path settles it."""
        seen: set[int] = set()
        # (node, line of the last real statement on the path so far)
        work: list[tuple[int, int]] = [(n, 0) for n in start]
        while work:
            nid, via = work.pop()
            if nid == EXIT:
                return via
            if nid in seen:
                continue
            seen.add(nid)
            ev = events.get(nid)
            if ev is not None and self._node_settles(ev, token):
                continue
            stmt = cfg.stmt_of.get(nid)
            line = getattr(stmt, "lineno", 0) if stmt is not None else via
            for s in cfg.all_succ(nid):
                work.append((s, line or via))
        return None

    # -- KVM092 --------------------------------------------------------------
    def _check_double_release(self, mod: ModuleFacts, fn: FunctionInfo,
                              cfg: CFG, events: dict[int, _Events]) -> None:
        for nid, ev in events.items():
            for token, node, kind in ev.releases:
                if kind == "append":
                    continue  # plain double-appends are KVM073's job
                second = self._find_second_release(cfg, events, nid, token)
                if second is None:
                    continue
                tok2, node2, _ = second
                self._emit(
                    mod, node2, "KVM092",
                    f"`{tok2}` is released here but a release on line "
                    f"{node.lineno} is reachable on the same path with no "
                    "re-acquire between — the second release frees a "
                    "handle another owner may already hold; make the "
                    "paths exclusive, or mark `# kvmini: resource-ok`",
                    fn.qualname)

    def _find_second_release(self, cfg: CFG, events: dict[int, _Events],
                             start_nid: int, token: str):
        seen: set[int] = set()
        # NORMAL successors only: if the releasing statement itself raises
        # (a socket close failing into the cleanup handler), the release
        # may not have happened — that handler's close is not a double one
        work = list(cfg.succ.get(start_nid, set()))
        while work:
            nid = work.pop()
            if nid in seen or nid == EXIT:
                continue
            seen.add(nid)
            ev = events.get(nid)
            if ev is not None:
                hit = next(((t, n, k) for t, n, k in ev.releases
                            if t == token and k != "append"), None)
                if hit is not None:
                    return hit
                if (token in ev.rebinds
                        or any(t == token for t, _ in ev.acquires)):
                    continue
            work.extend(cfg.all_succ(nid))
        return None

    # -- KVM093 --------------------------------------------------------------
    def _check_finally_reraise(self, mod: ModuleFacts, fn: FunctionInfo,
                               callees_of: dict) -> None:
        for node in iter_scope(fn.node):
            if not (isinstance(node, TRY_TYPES) and node.finalbody):
                continue
            fin_lines: list[tuple[int, str, ast.AST]] = []
            for stmt in node.finalbody:
                for sub in ast.walk(stmt):
                    if isinstance(sub, ast.Raise):
                        fin_lines.append((sub.lineno, "raise", sub))
                    elif isinstance(sub, ast.Call):
                        freed = self._freelist_append(sub)
                        if freed is not None:
                            fin_lines.append((sub.lineno, "release", sub))
                            continue
                        if any(self.releasers.get(c.key())
                               for c in callees_of.get(id(sub), [])):
                            fin_lines.append((sub.lineno, "release", sub))
            fin_lines.sort(key=lambda t: t[0])
            pending_raise: Optional[ast.AST] = None
            for _line, kind, sub in fin_lines:
                if kind == "raise":
                    pending_raise = pending_raise or sub
                elif pending_raise is not None:
                    self._emit(
                        mod, pending_raise, "KVM093",
                        f"this `finally` can raise before the release on "
                        f"line {sub.lineno} — whenever the raise fires "
                        "(normal OR exceptional entry, and it replaces "
                        "any in-flight exception) the release is "
                        "skipped, exactly on the failure path that most "
                        "needs the cleanup; release first, or mark a "
                        "deliberate leak-on-poison `# kvmini: resource-ok`",
                        fn.qualname)
                    break


def check(index: FactIndex) -> list[Diagnostic]:
    return ResourcePathChecker(index).run()
