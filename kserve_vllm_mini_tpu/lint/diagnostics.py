"""Diagnostic records, the rule table, and suppression-comment handling.

A diagnostic renders as ``path:line: KVM0xx message`` (the format the
Makefile/CI gate greps). Baseline identity deliberately excludes the line
number — findings keyed ``path::code::context`` survive unrelated edits
above them, so the committed lint-baseline.json doesn't churn.

Suppressions are ``# kvmini: <token>`` comments on the flagged line or
the line directly above it. Tokens are per-rule-family (RULES); a
comment that never matched a firing rule is itself a finding (KVM001),
so stale annotations can't accumulate — the same hygiene the baseline
gets from its stale-entry check.
"""

from __future__ import annotations

import io
import re
import tokenize
from dataclasses import dataclass, field
from typing import Optional


@dataclass(frozen=True)
class Rule:
    code: str
    name: str
    suppression: str  # the `# kvmini: <token>` that silences it
    summary: str


RULES: dict[str, Rule] = {
    r.code: r
    for r in [
        Rule("KVM001", "stale-suppression", "",
             "a `# kvmini:` suppression comment that silences nothing"),
        Rule("KVM011", "jit-data-dependent-if", "static-shape",
             "data-dependent Python `if` on a traced value inside jitted code"),
        Rule("KVM012", "jit-data-dependent-loop", "static-shape",
             "data-dependent Python loop over a traced value inside jitted code"),
        Rule("KVM013", "jit-wall-clock", "sync-ok",
             "wall-clock read inside jitted code (baked in at trace time)"),
        Rule("KVM014", "jit-host-randomness", "sync-ok",
             "host randomness / nondeterministically-seeded PRNGKey in jitted code"),
        Rule("KVM015", "host-sync", "sync-ok",
             "host sync (.item()/float()/np.asarray/device_get) in jitted code "
             "or a jit-dispatch hot path"),
        Rule("KVM021", "lockstep-unpublished-mutation", "lockstep-ok",
             "state-advancing call in a lockstep scheduler path not routed "
             "through the on_decision publisher"),
        Rule("KVM022", "lockstep-nondeterminism", "lockstep-ok",
             "nondeterminism source (wall-clock control flow, randomness, "
             "set iteration) in lockstep-replayed code"),
        Rule("KVM031", "stats-key-unexposed", "metrics-ok",
             "engine stats counter never exported on /metrics"),
        Rule("KVM032", "metric-name-drift", "metrics-ok",
             "kvmini_tpu_* name consumed/documented but never emitted, or "
             "emitted but never documented"),
        Rule("KVM033", "results-key-not-in-schema", "metrics-ok",
             "results.json key written that core/schema.py Results doesn't declare"),
        Rule("KVM041", "workload-change-unsurfaced", "workload-ok",
             "truncation/drop/fallback that doesn't stamp a flag field the "
             "analyzer reads"),
        Rule("KVM051", "unguarded-cross-thread-mutation", "thread-ok",
             "attribute mutated and shared across thread roots with no lock "
             "guarding any access"),
        Rule("KVM052", "inconsistent-lock-guard", "lock-ok",
             "attribute guarded by a lock on some accesses but touched bare "
             "on others (read under lock here, written bare there)"),
        Rule("KVM053", "lock-order-cycle", "lock-ok",
             "cycle in the acquires-while-holding graph (potential deadlock)"),
        Rule("KVM054", "unbounded-wait", "thread-ok",
             "Event/Condition wait() without a timeout, or Thread.join() "
             "without a bound in stop/teardown code"),
        Rule("KVM055", "shared-mutable-publication", "thread-ok",
             "mutable container handed across the thread boundary without "
             "snapshot (list()/dict() copy) — iteration races mutation"),
        Rule("KVM061", "mixed-precision-arith", "dtype-ok",
             "arithmetic silently mixing bf16/f16 with f32/f64 on a jit "
             "hot path (implicit upcast doubles the operand's HBM cost)"),
        Rule("KVM062", "dequant-drops-compensation", "dtype-ok",
             "dequantization applies the scale but never reads, tests, or "
             "writes the leaf's compensation key (zero-point 'z' / AWQ 'a')"),
        Rule("KVM063", "sub-byte-bitcast", "dtype-ok",
             "sub-byte dtype (int4/uint4) via bitcast_convert_type or as a "
             "materialized leaf — byte-shaped at abstract eval, relayout "
             "recursion at dispatch; unpack arithmetically instead"),
        Rule("KVM064", "int-dot-accum-dtype", "dtype-ok",
             "integer-dtype dot/matmul without preferred_element_type — "
             "the accumulator inherits the narrow input dtype and wraps"),
        Rule("KVM065", "low-precision-accumulation", "dtype-ok",
             "softmax/mean/variance family reduction over a bf16/f16 value "
             "— accumulate in f32 (astype before, astype back after)"),
        Rule("KVM071", "donated-buffer-read", "buffer-ok",
             "argument donated to a jitted call is read after dispatch "
             "(the buffer was surrendered to XLA; contents undefined)"),
        Rule("KVM072", "undonated-buffer-carry", "buffer-ok",
             "jit root threads a cache/KV buffer through (param in, "
             "updated value out) without donating it — both copies stay "
             "resident and HBM doubles"),
        Rule("KVM073", "kv-block-lifecycle", "buffer-ok",
             "KV block id freed twice, or used after it went back to the "
             "free list (another request may already own it)"),
        Rule("KVM074", "retained-claim-no-unpin", "buffer-ok",
             "retained-LRU block claimed (refcount bumped) without popping "
             "it from the LRU — eviction can reap a block in active use"),
        Rule("KVM081", "collective-unbound-axis", "mesh-ok",
             "collective (psum/ppermute/all_gather/...) names a mesh axis "
             "no enclosing shard_map scope binds — XLA fails late or "
             "resolves against the wrong mesh"),
        Rule("KVM082", "partition-spec-mismatch", "mesh-ok",
             "PartitionSpec arity disagrees with the annotated array shape "
             "/ the shard_map'd function's parameters, or names an axis no "
             "mesh in the package declares"),
        Rule("KVM083", "resharding-in-dispatch", "mesh-ok",
             "device_put / with_sharding_constraint in a jit-dispatch hot "
             "path — a hidden reshard (silent all-gather) on every decode "
             "step; place data once at setup, or annotate the intent"),
        Rule("KVM084", "donation-resharded", "mesh-ok",
             "buffer donated by the enclosing jit changes sharding across "
             "the shard_map boundary — the donation cannot alias and XLA "
             "silently copies (HBM doubles exactly where donation was "
             "meant to prevent it)"),
        Rule("KVM091", "acquire-leaks-on-path", "resource-ok",
             "a path (exception, early return, cancellation branch) exits "
             "the function with an acquired resource (slot, KV block, "
             "lock, file) neither released nor ownership-transferred"),
        Rule("KVM092", "double-release-path", "resource-ok",
             "one control-flow path reaches two releases of the same "
             "resource — the second release frees another owner's handle"),
        Rule("KVM093", "finally-reraise-skips-release", "resource-ok",
             "a `finally` block can raise before a pending release in "
             "the same block — whenever the raise fires, the release is "
             "skipped on exactly the failure path that needed it"),
        Rule("KVM101", "lockstep-publish-replay-asymmetry", "protocol-ok",
             "decision tag published into the lockstep stream with no "
             "run_follower replay arm, or a replay arm nothing publishes"),
        Rule("KVM102", "host-only-field-read", "protocol-ok",
             "field stripped from the replay payload (_HOST_ONLY_FIELDS) "
             "read inside a follower-replayed method — followers see None"),
        Rule("KVM103", "handoff-version-unconsumed", "protocol-ok",
             "KVHandoff(version=...) construction with no consume-side "
             "version check covering that version"),
        Rule("KVM104", "degrade-ladder-unsound", "protocol-ok",
             "sticky degrade flag re-armed outside init/reset, or read "
             "with no entry edge that ever sets it"),
        Rule("KVM111", "fabricated-zero-export", "contract-ok",
             ".get(key, 0) / `or 0` default flowing into a /metrics "
             "exposition or results block — absent-not-zero violated"),
        Rule("KVM112", "event-taxonomy-drift", "contract-ok",
             "EVENT_TYPES vs detector emits vs report/chart consumers vs "
             "docs/MONITORING.md rows out of sync"),
        Rule("KVM113", "http-surface-drift", "contract-ok",
             "server/router routes vs tests/mock_server.py vs docs/API.md "
             "vs in-repo client call sites out of sync (incl. the "
             "_shed_response 429 + Retry-After shape)"),
        Rule("KVM121", "blocking-call-on-event-loop", "async-ok",
             "blocking call (time.sleep, sync subprocess/HTTP, un-timed "
             "Lock.acquire, sync file IO) reachable from code running on "
             "the asyncio event loop — stalls every request on the loop"),
        Rule("KVM122", "fire-and-forget-task", "async-ok",
             "create_task/ensure_future handle neither stored, awaited, "
             "nor given a done-callback — task exceptions vanish silently"),
        Rule("KVM123", "loop-affinity-violation", "async-ok",
             "state mutated by both event-loop code and thread-rooted code "
             "without call_soon_threadsafe routing or a common lock"),
        Rule("KVM124", "await-straddled-rmw", "async-ok",
             "read-modify-write of loop state straddling an await (read "
             "before the await, written after) — stale by interleaving"),
        Rule("KVM131", "unregistered-env-knob", "config-ok",
             "os.environ read of a KVMINI_* key registered in no knob "
             "table and mentioned in no docs page"),
        Rule("KVM132", "stale-knob-entry", "config-ok",
             "knob-table entry whose env key no read site consumes"),
        Rule("KVM133", "unsurfaced-config-field", "config-ok",
             "EngineConfig/MonitorConfig/PolicyConfig field with no CLI "
             "flag, env knob, or docs surface (no operator can set it) — "
             "or a config flag undocumented in the docs"),
        Rule("KVM134", "knob-default-drift", "config-ok",
             "default-value drift between argparse default=, env-parse "
             "fallback, and config-dataclass default for the same knob"),
    ]
}

SUPPRESSION_TOKENS = sorted({r.suppression for r in RULES.values() if r.suppression})

# `kvmini:` may share the comment with other markers (`# noqa: ... kvmini: ...`)
_KVMINI_COMMENT = re.compile(r"#.*?kvmini:\s*([\w, -]+)")


@dataclass(frozen=True)
class Diagnostic:
    path: str  # repo-relative, forward slashes
    line: int
    code: str
    message: str
    context: str = ""  # enclosing qualname / key name — the baseline anchor

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.code} {self.message}"

    def baseline_key(self) -> str:
        return f"{self.path}::{self.code}::{self.context or self.message}"


@dataclass
class Suppressions:
    """Per-file `# kvmini:` comment map, with usage tracking."""

    by_line: dict[int, set[str]] = field(default_factory=dict)
    used: set[int] = field(default_factory=set)

    @classmethod
    def scan(cls, source: str) -> "Suppressions":
        sup = cls()
        try:
            tokens = tokenize.generate_tokens(io.StringIO(source).readline)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                m = _KVMINI_COMMENT.search(tok.string)
                if not m:
                    continue
                toks = {t.strip() for t in m.group(1).split(",") if t.strip()}
                sup.by_line.setdefault(tok.start[0], set()).update(toks)
        except tokenize.TokenError:
            pass  # syntax-broken file; the parse error is reported elsewhere
        return sup

    def is_suppressed(self, line: int, code: str) -> bool:
        token = RULES[code].suppression
        if not token:
            return False
        for cand in (line, line - 1):
            if token in self.by_line.get(cand, set()):
                self.used.add(cand)
                return True
        return False

    def stale(self, path: str,
              active_tokens: Optional[set[str]] = None) -> list[Diagnostic]:
        """KVM001 for comments that suppressed nothing in this run.

        ``active_tokens`` restricts the check to the suppression tokens
        whose rules actually ran — a ``--family KVM05`` scan must not
        flag a ``sync-ok`` comment as stale just because the jit checker
        was filtered out this run. The CONTEXT (= baseline key) is still
        built from every known token on the line, so a family-filtered
        run produces the same key a full run baselined (a multi-token
        comment must not flap between 'thread-ok' and
        'lock-ok,thread-ok' depending on the filter)."""
        active = set(SUPPRESSION_TOKENS)
        if active_tokens is not None:
            active &= active_tokens
        out = []
        for line, toks in sorted(self.by_line.items()):
            known = toks & set(SUPPRESSION_TOKENS)
            if known and (known & active) and line not in self.used:
                out.append(Diagnostic(
                    path, line, "KVM001",
                    f"stale suppression `# kvmini: {', '.join(sorted(known))}` "
                    "— no rule fires here; delete it",
                    # token-only context: line numbers would churn the
                    # baseline key (same-token stale comments share a key,
                    # disambiguated by the per-key count)
                    context=",".join(sorted(known)),
                ))
        return out
