"""KVM041 — workload changes must be surfaced, not absorbed.

docs/LINTING.md: "anything that alters what a load test measures
(truncation, drops, fallbacks) must be flagged in the request record and
surfaced by the analyzer." The engine's prompt-cap truncation does this
right (``req.truncated = True`` + ``truncated_tokens``); this rule keeps
every future shortcut honest.

Scope: loadgen/**, runtime/**, and bench_pipeline — the modules that
stand between the configured workload and the measured one. Two
patterns are flagged when the enclosing function stamps no flag:

- **silent except-fallback**: a handler that swallows the exception and
  degrades (``pass``/``continue``/return of a bare default) without a
  surfacing write. Returning an error response / recording ``.error``
  counts as surfaced.
- **unflagged truncation**: rebinding a prompt/token-ish value to a
  slice of itself (``toks = toks[:cap]``) with no truncation flag
  written anywhere in the function.
- **swallowed shed/retry** (docs/RESILIENCE.md): a branch that handles
  a 429/shed/retry condition (``status == 429``, a shed/retry-named
  guard) by silently continuing/passing/returning a bare default, in a
  function that stamps NO flag at all — shed and retried requests count
  as surfaced only when the CSV/results carry them (``rec.retries``,
  ``rec.shed``), never when the client quietly re-sends and the run
  reports the resend as a fresh healthy request.

"Surfacing" = assigning an attribute/key matching the flag vocabulary
(truncated/dropped/fallback/error/skipped/shed/retries...), bumping a
stats counter, or calling a record/mark/warn/fail-style function. A
deliberate absorb (e.g. best-effort cache warmup) takes
``# kvmini: workload-ok``.
"""

from __future__ import annotations

import ast
import re

from kserve_vllm_mini_tpu.lint.diagnostics import Diagnostic
from kserve_vllm_mini_tpu.lint.facts import (
    FactIndex,
    FunctionInfo,
    ModuleFacts,
    iter_scope,
)

SCOPE_PATH = re.compile(r"(^|/)(loadgen|runtime)/|(^|/)bench_pipeline\.py$")
FLAG_NAME = re.compile(
    r"truncat|dropp?ed|drop_|fallback|flag|error|fail|skip|ok\b|warn"
    r"|shed|retri|retry|degrad", re.I
)
# shed/retry condition vocabulary for the swallowed-429 rule
SHED_TEST = re.compile(r"shed|retry|retries|too_many|overload", re.I)
SURFACING_CALL = re.compile(
    r"record|mark|stamp|flag|warn|fail|abort|print|log", re.I
)
TRUNCATABLE_NAME = re.compile(r"tok|prompt|text|input|request|batch", re.I)
# pure control-flow exceptions: catching one drops nothing from the workload
CONTROL_FLOW_EXC = {
    "Empty", "QueueEmpty", "Full", "StopIteration", "StopAsyncIteration",
}
# teardown runs outside the measured window; best-effort absorbs are fine
TEARDOWN_FN = re.compile(r"^(close|aclose|stop|shutdown|__del__|__exit__|__aexit__)$")


def _writes_flag(node: ast.AST) -> bool:
    """Does this subtree surface a workload change?"""
    for n in ast.walk(node):
        if isinstance(n, (ast.Assign, ast.AugAssign)):
            targets = n.targets if isinstance(n, ast.Assign) else [n.target]
            for t in targets:
                if isinstance(t, ast.Attribute) and FLAG_NAME.search(t.attr):
                    return True
                if isinstance(t, ast.Subscript):
                    base = t.value
                    if isinstance(base, ast.Attribute) and base.attr == "stats":
                        return True
                    sl = t.slice
                    if (isinstance(sl, ast.Constant)
                            and isinstance(sl.value, str)
                            and FLAG_NAME.search(sl.value)):
                        return True
        elif isinstance(n, ast.Call):
            f = n.func
            name = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else "")
            if name and SURFACING_CALL.search(name):
                return True
        elif isinstance(n, ast.Raise):
            return True
    return False


def _is_bare_default_return(stmt: ast.Return) -> bool:
    v = stmt.value
    if v is None or isinstance(v, ast.Constant):
        return True
    if isinstance(v, (ast.Dict, ast.List, ast.Tuple, ast.Set)) and not (
            getattr(v, "keys", None) or getattr(v, "elts", None)):
        return True
    return isinstance(v, ast.Name)


def _exc_type_names(handler: ast.ExceptHandler) -> list[str]:
    t = handler.type
    parts = t.elts if isinstance(t, ast.Tuple) else ([t] if t else [])
    out = []
    for p in parts:
        if isinstance(p, ast.Attribute):
            out.append(p.attr)
        elif isinstance(p, ast.Name):
            out.append(p.id)
    return out


def _is_shed_test(test: ast.AST) -> bool:
    """Does this branch condition look at a 429/shed/retry outcome?"""
    for n in ast.walk(test):
        if isinstance(n, ast.Constant) and n.value == 429:
            return True
        if isinstance(n, ast.Attribute) and SHED_TEST.search(n.attr):
            return True
        if isinstance(n, ast.Name) and SHED_TEST.search(n.id):
            return True
    return False


def _branch_degrades(body: list) -> bool:
    """Branch body that silently absorbs: pass/continue/break or a bare
    default return."""
    for stmt in body:
        if isinstance(stmt, (ast.Pass, ast.Continue, ast.Break)):
            return True
        if isinstance(stmt, ast.Return) and _is_bare_default_return(stmt):
            return True
    return False


def _handler_degrades(handler: ast.ExceptHandler) -> bool:
    """Swallows the exception AND changes what gets measured."""
    names = _exc_type_names(handler)
    if names and all(n in CONTROL_FLOW_EXC for n in names):
        return False  # `except queue.Empty: break` — a drain idiom
    for n in ast.walk(handler):
        if isinstance(n, ast.Raise):
            return False
        # forwarding the caught exception anywhere (fut.set_exception(e),
        # rec.error = str(e)) surfaces it
        if (handler.name and isinstance(n, ast.Name) and n.id == handler.name
                and isinstance(n.ctx, ast.Load)):
            return False
    for stmt in handler.body:
        if isinstance(stmt, (ast.Pass, ast.Continue, ast.Break)):
            return True
        if isinstance(stmt, ast.Return) and _is_bare_default_return(stmt):
            return True
    return False


def _check_function(mod: ModuleFacts, fn: FunctionInfo,
                    diags: list[Diagnostic]) -> None:
    if TEARDOWN_FN.match(fn.name):
        return
    fn_surfaces = _writes_flag(fn.node)

    def emit(node: ast.AST, msg: str) -> None:
        line = getattr(node, "lineno", 0)
        if mod.suppressions.is_suppressed(line, "KVM041"):
            return
        diags.append(Diagnostic(mod.path, line, "KVM041", msg,
                                context=fn.qualname))

    for node in iter_scope(fn.node):
        if isinstance(node, ast.ExceptHandler):
            if _handler_degrades(node) and not _writes_flag(node):
                emit(node,
                     f"silent except-fallback in `{fn.name}` changes the "
                     "measured workload without stamping a flag the "
                     "analyzer reads — record it (rec.error / stats "
                     "counter / flag field) or mark `# kvmini: workload-ok`")
        elif (isinstance(node, ast.If) and not fn_surfaces
                and _is_shed_test(node.test)
                and _branch_degrades(node.body)):
            emit(node,
                 f"`{fn.name}` handles a 429/shed/retry outcome by "
                 "silently absorbing it — shed/retried requests count as "
                 "surfaced only when the CSV/results carry them "
                 "(rec.retries / rec.shed / a stats counter), or mark "
                 "`# kvmini: workload-ok`")
        elif isinstance(node, ast.Assign) and not fn_surfaces:
            v = node.value
            if (isinstance(v, ast.Subscript) and isinstance(v.slice, ast.Slice)
                    and v.slice.upper is not None
                    and isinstance(v.value, ast.Name)
                    and TRUNCATABLE_NAME.search(v.value.id)):
                for t in node.targets:
                    tname = t.id if isinstance(t, ast.Name) else (
                        t.attr if isinstance(t, ast.Attribute) else "")
                    if tname and TRUNCATABLE_NAME.search(tname):
                        emit(node,
                             f"`{tname}` is truncated by slicing in "
                             f"`{fn.name}` but no truncation flag is "
                             "stamped — the run measures a different "
                             "workload than configured; set the flag "
                             "field or mark `# kvmini: workload-ok`")
                        break


def check(index: FactIndex) -> list[Diagnostic]:
    diags: list[Diagnostic] = []
    for mod in index.modules.values():
        if not SCOPE_PATH.search(mod.path):
            continue
        for fn in mod.functions.values():
            _check_function(mod, fn, diags)
    return diags
