"""CLI: ``python -m kserve_vllm_mini_tpu.lint [paths...]``.

Defaults follow the repo layout so the CI/Makefile invocation stays one
line: scan ``kserve_vllm_mini_tpu/``, read cross-surface docs from
``./docs`` + ``./dashboards`` when present, gate against
``./lint-baseline.json`` when present.

Exit codes: 0 clean (vs baseline if one is in play); 1 new findings or
stale baseline entries; 2 parse/usage errors.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

from kserve_vllm_mini_tpu.lint import baseline as baseline_mod
from kserve_vllm_mini_tpu.lint import sarif as sarif_mod
from kserve_vllm_mini_tpu.lint.runner import (
    changed_scan_paths,
    counts_by_checker,
    normalize_families,
    run_lint,
)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m kserve_vllm_mini_tpu.lint",
        description="kvmini-lint: AST invariant checker (jit purity, "
                    "lockstep determinism, metrics/schema drift, workload "
                    "surfacing, thread-safety/lock discipline, dtype-flow "
                    "numerics, buffer lifecycle, mesh/sharding consistency, "
                    "exception-path resource safety, wire-protocol "
                    "conformance, absent-not-zero contract drift, asyncio "
                    "event-loop discipline, config-surface drift). See "
                    "docs/LINTING.md for the rule table.",
    )
    ap.add_argument("paths", nargs="*", type=Path,
                    help="files/dirs to lint (default: kserve_vllm_mini_tpu/)")
    ap.add_argument("--changed", default=None, metavar="REF",
                    help="scan only files that differ from git REF (plus "
                         "their cross-file importers via the fact index) — "
                         "the fast pre-commit loop (`make lint-changed`). "
                         "Directory-scan-only surfaces (KVM032 docs drift) "
                         "are skipped, same as any single-file scan; the "
                         "baseline gate is restricted to the scanned files.")
    ap.add_argument("--family", action="append", default=None,
                    metavar="KVM0x[,KVM0y]",
                    help="run only these rule families (repeatable AND "
                         "comma-separable; e.g. `--family KVM05,KVM12` for "
                         "the two concurrency families, or a full code "
                         "like KVM051). The baseline gate and the KVM001 "
                         "stale-suppression check are filtered to match.")
    ap.add_argument("--jobs", type=int, default=None, metavar="N",
                    help="checker-family parallelism (default: one thread "
                         "per selected family; `--jobs 1` forces the "
                         "serial path — output is byte-identical either "
                         "way, a test pins it)")
    ap.add_argument("--timing", action="store_true",
                    help="print per-checker wall time (the <10s budget "
                         "attribution surface; JSON output always carries "
                         "a 'timings' object)")
    ap.add_argument("--timing-out", type=Path, default=None, metavar="FILE",
                    help="also write the timing report as JSON to FILE — "
                         "lets CI upload the artifact from the SAME run "
                         "that gated, instead of linting twice")
    ap.add_argument("--sarif", type=Path, default=None, metavar="FILE",
                    help="also write findings as SARIF 2.1.0 to FILE "
                         "(GitHub code-scanning annotations; severity "
                         "mapped from the rule family, suppressed "
                         "findings omitted)")
    ap.add_argument("--docs", type=Path, action="append", default=None,
                    help="extra docs/dashboards surfaces for the drift "
                         "checker (default: ./docs, ./dashboards if present)")
    ap.add_argument("--baseline", type=Path, default=None,
                    help="baseline file (default: ./lint-baseline.json if "
                         "present)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore any baseline; report every finding")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline from current findings and exit 0")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    args = ap.parse_args(argv)

    paths = args.paths or [Path("kserve_vllm_mini_tpu")]
    missing = [p for p in paths if not p.exists()]
    if missing:
        print(f"kvmini-lint: no such path: {missing[0]}", file=sys.stderr)
        return 2

    family_args = None
    if args.family is not None:
        # `--family KVM05,KVM12` and `--family KVM05 --family KVM12` are
        # the same request; split commas before validation
        family_args = [part for f in args.family for part in f.split(",")
                       if part.strip()]
    try:
        families = normalize_families(family_args)
    except ValueError as e:
        print(f"kvmini-lint: {e}", file=sys.stderr)
        return 2
    if families is not None and args.write_baseline:
        # a family-filtered run only sees a slice of the findings; writing
        # it out would silently drop every other family from the ratchet
        print("kvmini-lint: --write-baseline cannot be combined with "
              "--family (the baseline must cover every rule)",
              file=sys.stderr)
        return 2

    docs = args.docs
    if docs is None:
        docs = [p for p in (Path("docs"), Path("dashboards")) if p.is_dir()]

    baseline_path = None
    if not args.no_baseline and not args.write_baseline:
        baseline_path = args.baseline or Path("lint-baseline.json")

    if args.changed is not None:
        if args.write_baseline:
            print("kvmini-lint: --write-baseline cannot be combined with "
                  "--changed (the baseline must come from a full scan)",
                  file=sys.stderr)
            return 2
        try:
            subset, skipped = changed_scan_paths(Path.cwd(), paths,
                                                 args.changed)
        except RuntimeError as e:
            print(f"kvmini-lint: --changed: {e}", file=sys.stderr)
            return 2
        if skipped:
            print(f"kvmini-lint: --changed: skipping {len(skipped)} "
                  f"deleted/renamed file(s): {', '.join(skipped)}")
        if not subset:
            print(f"kvmini-lint: no python files changed vs {args.changed} "
                  "— nothing to lint")
            return 0
        paths = subset

    t0 = time.monotonic()
    result = run_lint(paths, doc_paths=docs, baseline_path=baseline_path,
                      families=families,
                      baseline_scope_to_paths=args.changed is not None,
                      jobs=args.jobs)
    dt = time.monotonic() - t0

    if args.sarif is not None:
        sarif_mod.save(args.sarif, result.diagnostics)

    if args.timing_out is not None:
        args.timing_out.write_text(json.dumps({
            "elapsed_s": round(dt, 3),
            # what the same run would have cost serially (sum of the
            # per-family stage timings) — CI tracks serial-vs-parallel
            # drift from one artifact instead of linting twice
            "serial_equivalent_s": round(sum(result.timings.values()), 3),
            "timings": result.timings,
            "findings": len(result.diagnostics),
            # ms alone can't tell "fast because clean" from "fast because
            # broken": the per-family counts ride along so the uploaded
            # artifact shows what each checker actually produced
            "findings_by_checker": counts_by_checker(
                result.diagnostics, result.timings),
        }, indent=2) + "\n", encoding="utf-8")

    if args.write_baseline:
        if result.parse_errors:
            # a baseline written over unparsable files would be silently
            # missing their findings — refuse and surface the errors
            for path, line, msg in result.parse_errors:
                print(f"{path}:{line}: KVM000 parse error: {msg}",
                      file=sys.stderr)
            print("kvmini-lint: refusing to write a baseline with parse "
                  "errors", file=sys.stderr)
            return 2
        out = args.baseline or Path("lint-baseline.json")
        baseline_mod.save(out, result.diagnostics)
        print(f"kvmini-lint: wrote {out} "
              f"({len(result.diagnostics)} findings, {dt:.2f}s)")
        return 0

    if args.format == "json":
        print(json.dumps({
            "findings": [
                {"path": d.path, "line": d.line, "code": d.code,
                 "message": d.message, "context": d.context}
                for d in result.diagnostics
            ],
            "gating": [d.render() for d in result.gating],
            "stale_baseline": (result.baseline_diff.stale
                               if result.baseline_diff else []),
            "parse_errors": [list(e) for e in result.parse_errors],
            "elapsed_s": round(dt, 3),
            "timings": result.timings,
        }, indent=2))
        return result.exit_code

    for path, line, msg in result.parse_errors:
        print(f"{path}:{line}: KVM000 parse error: {msg}")
    for d in result.gating:
        print(d.render())
    if result.baseline_diff is not None:
        bd = result.baseline_diff
        for key in bd.stale:
            print(f"stale baseline entry (fixed — shrink lint-baseline.json "
                  f"with --write-baseline): {key}")
        status = "clean" if bd.clean else (
            f"{len(bd.new)} new, {len(bd.stale)} stale")
        print(f"kvmini-lint: {status} vs baseline "
              f"({bd.suppressed} grandfathered, {dt:.2f}s)")
    else:
        print(f"kvmini-lint: {len(result.diagnostics)} findings ({dt:.2f}s)")
    if args.timing:
        width = max((len(k) for k in result.timings), default=0)
        for name, secs in sorted(result.timings.items(),
                                 key=lambda kv: -kv[1]):
            print(f"kvmini-lint timing: {name:<{width}} {secs * 1000:8.1f} ms")
    return result.exit_code


if __name__ == "__main__":
    sys.exit(main())
