"""KVM081-KVM084 — mesh & sharding consistency.

Disaggregated prefill/decode (ROADMAP item 1) multiplies the number of
``shard_map``/``pjit`` roots, named axes, and cross-mesh transfers — and
every one of them can fail *silently*: a collective over an axis the
enclosing mesh never bound, a ``PartitionSpec`` one entry short of the
array's rank, or a ``device_put`` inside the decode dispatch path each
lower to a wrong-but-running program whose only symptom is an all-gather
in the profile. These rules make the mesh contract loud at lint time.

The checker builds a **mesh-axis fact table** and never guesses:

- **Construction sites**: ``Mesh(devices, ("dp", "tp"))`` and the repo's
  ``make_mesh``/``mesh_for_topology`` factories. Axis tuples resolve from
  literals or module-level constants (``AXES`` in parallel/mesh.py),
  through ``from``-imports. Functions *returning* a constructed mesh are
  mesh sources themselves (small fixpoint, like returns_jitted).
- **Mesh-typed params** (name ``mesh`` or a ``Mesh`` annotation) join the
  axis sets their resolved callsites feed in — union over resolved
  sites; a site the resolver cannot evaluate leaves the set *partial*
  rather than poisoning it (all of this repo's meshes share one axis
  vocabulary, so a partial set still catches axis typos).
- **shard_map scopes**: decorator (``@partial(shard_map, mesh=...)``)
  and wrap (``shard_map(f, mesh=...)``) sites anchor a scope at the
  wrapped function; everything reachable from its body through the call
  graph runs under that scope's axes.

Rules (misses over false alarms, like every kvmini-lint family):

- **KVM081**: a collective (``psum``/``pmean``/``ppermute``/
  ``all_gather``/``pvary``/...) whose *literal* axis name is not bound
  by any reaching scope. Complete scopes flag any unbound axis; partial
  scopes flag only axes absent from the package-wide construction table
  (the typo class). A collective whose axis is a runtime parameter, or
  whose scope never resolved, is skipped.
- **KVM082**: ``PartitionSpec`` consistency — a literal axis name no
  mesh in the package declares; a spec whose arity disagrees with the
  ``# [L, B, KVH, S, D]``-style shape annotation on its line; an
  ``in_specs`` tuple whose length cannot match the shard_map'd
  function's callable parameters (``partial``-bound args subtracted).
- **KVM083**: ``device_put``/``with_sharding_constraint`` inside a
  jit-DISPATCH hot path (a host function that invokes compiled work,
  jit_purity's dispatch notion) — a hidden reshard serializes the
  decode pipeline on every step. Setup/loading code (not a dispatch
  path) is exempt; intended placements carry ``# kvmini: mesh-ok``.
- **KVM084**: a buffer donated by the enclosing jit root whose
  ``in_specs`` entry at the shard_map boundary matches no ``out_specs``
  entry — the donation cannot alias across a sharding change, so XLA
  silently copies (composes with KVM072's donation facts).
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass, field
from typing import Optional

from kserve_vllm_mini_tpu.lint.diagnostics import Diagnostic
from kserve_vllm_mini_tpu.lint.facts import (
    FactIndex,
    FunctionInfo,
    ModuleFacts,
    _last_attr,
    iter_scope,
)

# collectives whose axis argument sits at position 1 (after the operand)
COLLECTIVES = {
    "psum", "pmean", "pmax", "pmin", "ppermute", "pshuffle", "all_gather",
    "all_to_all", "psum_scatter", "pbroadcast", "pvary",
}
# ... and the axis-only ones (axis name is argument 0)
AXIS_ARG0 = {"axis_index", "axis_size"}

SHAPE_COMMENT = re.compile(
    r"\[\s*([A-Za-z_][\w*]*(?:\s*,\s*[A-Za-z_][\w*]*)+)\s*\]"
)


def _comment_map(source: str) -> dict[int, str]:
    """line -> comment text (tokenize-accurate: a '#' in a string is not
    a comment)."""
    out: dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = tok.string
    except tokenize.TokenError:
        pass
    return out


def _literal_axes(node: ast.AST) -> Optional[frozenset[str]]:
    """A literal axis spec: "tp", ("dp", "tp"), ["dp"]."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return frozenset({node.value})
    if isinstance(node, (ast.Tuple, ast.List)):
        vals = []
        for e in node.elts:
            if not (isinstance(e, ast.Constant) and isinstance(e.value, str)):
                return None
            vals.append(e.value)
        return frozenset(vals)
    return None


@dataclass
class AxesInfo:
    """What we know about one mesh value's axis names."""

    axes: frozenset[str] = frozenset()
    complete: bool = True  # False: some contributing site didn't resolve

    def join(self, other: "AxesInfo") -> "AxesInfo":
        return AxesInfo(self.axes | other.axes,
                        self.complete and other.complete)


PARTIAL_UNKNOWN = AxesInfo(frozenset(), False)


def _is_partition_spec_name(mod: ModuleFacts, func: ast.AST) -> bool:
    name = _last_attr(func)
    if name == "PartitionSpec":
        return True
    if isinstance(func, ast.Name):
        fi = mod.from_imports.get(func.id)
        return fi is not None and fi[1] == "PartitionSpec"
    return False


def _is_shard_map_func(node: ast.AST) -> bool:
    return _last_attr(node) == "shard_map"


@dataclass
class SmapSite:
    """One shard_map application: wrap call or decorator."""

    mod: ModuleFacts
    enclosing: Optional[FunctionInfo]
    node: ast.AST  # the shard_map/partial call (diagnostics anchor)
    targets: list[FunctionInfo]
    mesh_expr: Optional[ast.AST]
    in_specs: Optional[ast.AST] = None
    out_specs: Optional[ast.AST] = None
    partial_bound: int = 0  # partial()-bound leading positionals
    partial_kwargs: set[str] = field(default_factory=set)


class MeshFlowChecker:
    def __init__(self, index: FactIndex):
        self.index = index
        self.diags: list[Diagnostic] = []
        # functions that RETURN a mesh -> what we know of its axes
        self.mesh_returns: dict[tuple[str, str], AxesInfo] = {}
        # (fn key, param name) -> joined axes info from resolved callsites
        self.param_axes: dict[tuple[tuple[str, str], str], AxesInfo] = {}
        # every axis any construction site in the scanned set declares
        self.global_axes: set[str] = set()
        self.smap_sites: list[SmapSite] = []
        self.smap_targets: set[tuple[str, str]] = set()
        # fn key -> joined scope info (absent = unreached); None = reached
        # but some reaching scope's mesh never resolved (never flag)
        self.scope: dict[tuple[str, str], Optional[AxesInfo]] = {}
        # candidate sites from the one shared package walk (_scan)
        self._ret_cands: list[tuple[ModuleFacts, FunctionInfo, ast.AST]] = []
        self._collective_sites: list[tuple[ModuleFacts,
                                           Optional[FunctionInfo],
                                           ast.Call]] = []
        self._pspec_sites: list[tuple[ModuleFacts, Optional[FunctionInfo],
                                      ast.Call]] = []
        self._smap_wraps: list[tuple[ModuleFacts, Optional[FunctionInfo],
                                     ast.Call]] = []

    # -- resolution (facts + two mesh-specific fallbacks) --------------------
    def _callees_with_offset(
            self, mod: ModuleFacts, fn: Optional[FunctionInfo],
            call: ast.Call) -> list[tuple[FunctionInfo, int]]:
        """Resolved callees with their self-offset. Beyond the FactIndex:
        `dist.global_mesh(...)` through a from-imported MODULE alias, and
        `Engine(...)` constructor calls onto `Engine.__init__` — both are
        how meshes actually travel from builder to engine in this repo."""
        out = [
            (c, 1 if c.params[:1] in (["self"], ["cls"])
             and isinstance(call.func, ast.Attribute) else 0)
            for c in self.index._resolve_expr(mod, fn, call.func)
        ]
        if out:
            return out
        f = call.func
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
            fi = mod.from_imports.get(f.value.id)
            if fi is not None:
                dotted = f"{fi[0]}.{fi[1]}" if fi[0] else fi[1]
                target = self.index.module_for_dotted(dotted)
                if target is not None and f.attr in target.functions:
                    return [(target.functions[f.attr], 0)]
        if isinstance(f, ast.Name):
            ctor = mod.functions.get(f"{f.id}.__init__")
            if ctor is not None:
                return [(ctor, 1)]
            fi = mod.from_imports.get(f.id)
            if fi is not None:
                target = self.index.module_for_dotted(fi[0])
                if target is not None:
                    ctor = target.functions.get(f"{fi[1]}.__init__")
                    if ctor is not None:
                        return [(ctor, 1)]
        return []

    # -- the mesh-axis fact table -------------------------------------------
    def _module_const_axes(self, mod: ModuleFacts,
                           name: str) -> Optional[frozenset[str]]:
        """A module-level `AXES = ("dp", ...)` constant, via from-imports."""
        fi = mod.from_imports.get(name)
        if fi is not None:
            target = self.index.module_for_dotted(fi[0])
            if target is not None:
                return self._module_const_axes(target, fi[1])
            return None
        for stmt in mod.tree.body:
            if isinstance(stmt, ast.Assign):
                for tgt in stmt.targets:
                    if isinstance(tgt, ast.Name) and tgt.id == name:
                        return _literal_axes(stmt.value)
        return None

    def _axes_spec_of(self, mod: ModuleFacts,
                      node: ast.AST) -> Optional[frozenset[str]]:
        axes = _literal_axes(node)
        if axes is not None:
            return axes
        if isinstance(node, ast.Name):
            return self._module_const_axes(mod, node.id)
        return None

    def _mesh_construction_axes(self, mod: ModuleFacts,
                                call: ast.Call) -> Optional[frozenset[str]]:
        """`Mesh(devices, <axes>)` / `Mesh(devices, axis_names=<axes>)`."""
        if _last_attr(call.func) != "Mesh":
            return None
        spec: Optional[ast.AST] = None
        if len(call.args) >= 2:
            spec = call.args[1]
        for kw in call.keywords:
            if kw.arg == "axis_names":
                spec = kw.value
        if spec is None:
            return None
        return self._axes_spec_of(mod, spec)

    def _axes_of_expr(self, mod: ModuleFacts, fn: Optional[FunctionInfo],
                      expr: ast.AST, _depth: int = 0) -> Optional[AxesInfo]:
        """What axes does this mesh-valued expression carry? None when the
        expression is not recognizably a mesh (or recursion bottoms out)."""
        if _depth > 4:
            return None
        if isinstance(expr, ast.Call):
            cons = self._mesh_construction_axes(mod, expr)
            if cons is not None:
                return AxesInfo(cons, True)
            if _last_attr(expr.func) == "Mesh":
                return PARTIAL_UNKNOWN  # a mesh, axes not resolvable
            out: Optional[AxesInfo] = None
            for callee, _off in self._callees_with_offset(mod, fn, expr):
                info = self.mesh_returns.get(callee.key())
                if info is not None:
                    out = info if out is None else out.join(info)
            return out
        if isinstance(expr, ast.Name):
            fi = fn
            while fi is not None:
                if expr.id in fi.params:
                    return self.param_axes.get((fi.key(), expr.id))
                for aliased in fi.local_aliases.get(expr.id, []):
                    got = self._axes_of_expr(mod, fi, aliased, _depth + 1)
                    if got is not None:
                        return got
                if expr.id in fi.local_aliases:
                    return None
                fi = fi.parent
        return None

    def _looks_mesh_param(self, fn: FunctionInfo, param: str) -> bool:
        if param == "mesh" or param.endswith("_mesh"):
            return True
        node = fn.node
        for a in node.args.posonlyargs + node.args.args + node.args.kwonlyargs:
            if a.arg == param and a.annotation is not None:
                return any(
                    isinstance(n, (ast.Name, ast.Attribute))
                    and _last_attr(n) == "Mesh"
                    for n in ast.walk(a.annotation))
        return False

    def _scan(self) -> None:
        """ONE walk over every scope, collecting all candidate sites the
        stages below consume — the package walk dominates checker time, so
        it must not repeat per rule."""
        for mod in self.index.modules.values():
            scopes: list[tuple[Optional[FunctionInfo], object]] = [
                (fn, iter_scope(fn.node)) for fn in mod.functions.values()
            ]
            # module-level statements (constructions/specs outside defs)
            scopes.append((None, (
                n for stmt in mod.tree.body
                if not isinstance(stmt, (ast.FunctionDef,
                                         ast.AsyncFunctionDef, ast.ClassDef))
                for n in ast.walk(stmt))))
            for fn, nodes in scopes:
                for node in nodes:
                    if (fn is not None and isinstance(node, ast.Return)
                            and isinstance(node.value, (ast.Call, ast.Name))):
                        self._ret_cands.append((mod, fn, node.value))
                    if not isinstance(node, ast.Call):
                        continue
                    axes = self._mesh_construction_axes(mod, node)
                    if axes is not None:
                        self.global_axes |= axes
                    name = _last_attr(node.func)
                    if name in COLLECTIVES or name in AXIS_ARG0:
                        self._collective_sites.append((mod, fn, node))
                    if _is_partition_spec_name(mod, node.func):
                        self._pspec_sites.append((mod, fn, node))
                    if _is_shard_map_func(node.func):
                        self._smap_wraps.append((mod, fn, node))

    def _build_fact_table(self) -> None:
        # callsite args feeding mesh-looking params (return candidates
        # come from the shared scan)
        ret_cands = self._ret_cands
        feed_cands: list[tuple[ModuleFacts, FunctionInfo, FunctionInfo,
                               str, ast.AST]] = []
        for mod in self.index.modules.values():
            for fn in mod.functions.values():
                for cs in self.index.call_sites(mod, fn):
                    for callee, offset in self._callees_with_offset(
                            mod, fn, cs.node):
                        params = callee.params
                        pairs: list[tuple[str, ast.AST]] = []
                        for i, arg in enumerate(cs.node.args):
                            pi = i + offset
                            if (not isinstance(arg, ast.Starred)
                                    and pi < len(params)):
                                pairs.append((params[pi], arg))
                        for kw in cs.node.keywords:
                            if kw.arg and kw.arg in params:
                                pairs.append((kw.arg, kw.value))
                        for pname, arg in pairs:
                            if self._looks_mesh_param(callee, pname):
                                feed_cands.append((mod, fn, callee, pname, arg))
        # Jacobi rounds: each recomputes BOTH maps from scratch against the
        # previous round's facts, so an early evaluation that missed a
        # not-yet-known mesh source cannot poison the joined set for good
        for _ in range(5):
            new_ret: dict[tuple[str, str], AxesInfo] = {}
            for mod, fn, expr in ret_cands:
                info = self._axes_of_expr(mod, fn, expr)
                if info is None:
                    continue
                prev = new_ret.get(fn.key())
                new_ret[fn.key()] = info if prev is None else prev.join(info)
            new_par: dict[tuple[tuple[str, str], str], AxesInfo] = {}
            for mod, fn, callee, pname, arg in feed_cands:
                info = self._axes_of_expr(mod, fn, arg)
                if info is None:
                    # an unresolvable feed leaves the joined set PARTIAL
                    # (typo-only strictness) instead of poisoning it
                    info = PARTIAL_UNKNOWN
                key = (callee.key(), pname)
                prev = new_par.get(key)
                new_par[key] = info if prev is None else prev.join(info)
            if new_ret == self.mesh_returns and new_par == self.param_axes:
                break
            self.mesh_returns, self.param_axes = new_ret, new_par

    # -- shard_map scope discovery ------------------------------------------
    def _resolve_smap_target(self, mod: ModuleFacts,
                             fn: Optional[FunctionInfo],
                             expr: ast.AST) -> tuple[list[FunctionInfo], int,
                                                     set[str]]:
        """The wrapped callable (through partial), with bound-arg counts."""
        if isinstance(expr, ast.Call) and _last_attr(expr.func) == "partial":
            if expr.args:
                inner, _, _ = self._resolve_smap_target(mod, fn, expr.args[0])
                return (inner, len(expr.args) - 1,
                        {kw.arg for kw in expr.keywords if kw.arg})
            return [], 0, set()
        return list(self.index._resolve_expr(mod, fn, expr)), 0, set()

    def _smap_call_site(self, mod: ModuleFacts, fn: Optional[FunctionInfo],
                        call: ast.Call,
                        target_fn: Optional[FunctionInfo] = None) -> None:
        """Record one shard_map(...) call. ``target_fn`` is the decorated
        function when the call is a decorator; else the wrapped callable is
        the first argument."""
        mesh_expr = None
        in_specs = out_specs = None
        args = list(call.args)
        if target_fn is None and args:
            args = args[1:]  # wrap form: args[0] is the callable
        elif target_fn is not None and args and _is_shard_map_func(args[0]):
            args = args[1:]  # @partial(shard_map, ...): args[0] is shard_map
        for i, pos_name in enumerate(("mesh", "in_specs", "out_specs")):
            if i < len(args):
                val = args[i]
                if pos_name == "mesh":
                    mesh_expr = val
                elif pos_name == "in_specs":
                    in_specs = val
                else:
                    out_specs = val
        for kw in call.keywords:
            if kw.arg == "mesh":
                mesh_expr = kw.value
            elif kw.arg == "in_specs":
                in_specs = kw.value
            elif kw.arg == "out_specs":
                out_specs = kw.value
        bound_n, bound_kw = 0, set()
        if target_fn is not None:
            targets = [target_fn]
        else:
            targets, bound_n, bound_kw = self._resolve_smap_target(
                mod, fn, call.args[0]) if call.args else ([], 0, set())
        self.smap_sites.append(SmapSite(
            mod=mod, enclosing=fn, node=call, targets=targets,
            mesh_expr=mesh_expr, in_specs=in_specs, out_specs=out_specs,
            partial_bound=bound_n, partial_kwargs=bound_kw))
        for t in targets:
            self.smap_targets.add(t.key())

    def _collect_smap_sites(self) -> None:
        # decorator forms: @partial(shard_map, mesh=...) and @shard_map(...)
        # — the partial's extra args bind nothing (the decorated fn IS the
        # callable). Wrap-form calls come from the shared scan.
        decorated: set[int] = set()
        for mod in self.index.modules.values():
            for fn in mod.functions.values():
                for dec in fn.node.decorator_list:
                    if not isinstance(dec, ast.Call):
                        continue
                    if _is_shard_map_func(dec.func) or (
                            _last_attr(dec.func) == "partial" and dec.args
                            and _is_shard_map_func(dec.args[0])):
                        self._smap_call_site(mod, fn.parent, dec,
                                             target_fn=fn)
                        decorated.add(id(dec))
        for mod, fn, node in self._smap_wraps:
            if id(node) not in decorated:
                self._smap_call_site(mod, fn, node)

    def _propagate_scopes(self) -> None:
        """BFS the call graph from each shard_map body: reached functions
        run under that scope's axes; multiple scopes join (union axes,
        unknown mesh poisons to never-flag)."""
        work: list[tuple[tuple[str, str], Optional[AxesInfo]]] = []
        for site in self.smap_sites:
            info: Optional[AxesInfo] = None
            if site.mesh_expr is not None:
                info = self._axes_of_expr(site.mod, site.enclosing,
                                          site.mesh_expr)
            for t in site.targets:
                work.append((t.key(), info))
        while work:
            key, info = work.pop()
            prev = self.scope.get(key, _UNSET)
            if prev is _UNSET:
                new = info
            elif prev is None or info is None:
                new = None
            else:
                new = prev.join(info)
            if prev is not _UNSET and new == prev:
                continue
            self.scope[key] = new
            path, qual = key
            mod = self.index.modules.get(path)
            fn = mod.functions.get(qual) if mod else None
            if fn is None:
                continue
            for cs in self.index.call_sites(mod, fn):
                for callee in cs.callees:
                    work.append((callee.key(), new))

    # -- checks --------------------------------------------------------------
    def run(self) -> list[Diagnostic]:
        self._scan()
        self._build_fact_table()
        self._collect_smap_sites()
        self._propagate_scopes()
        self._check_collectives()
        self._check_partition_specs()
        self._check_dispatch_resharding()
        self._check_donation_across_boundary()
        return self.diags

    def _emit(self, mod: ModuleFacts, node: ast.AST, code: str, msg: str,
              context: str) -> None:
        line = getattr(node, "lineno", 0)
        if mod.suppressions.is_suppressed(line, code):
            return
        self.diags.append(Diagnostic(mod.path, line, code, msg,
                                     context=context))

    # -- KVM081 --------------------------------------------------------------
    def _collective_axes(self, mod: ModuleFacts,
                         call: ast.Call) -> Optional[frozenset[str]]:
        name = _last_attr(call.func)
        spec: Optional[ast.AST] = None
        if name in AXIS_ARG0:
            if call.args:
                spec = call.args[0]
        elif len(call.args) >= 2:
            spec = call.args[1]
        for kw in call.keywords:
            if kw.arg in ("axis_name", "axis_names", "axes"):
                spec = kw.value
        if spec is None:
            return None
        return self._axes_spec_of(mod, spec)

    def _check_collectives(self) -> None:
        if not self.index.full_scan:
            # every KVM081 verdict reasons from ABSENCE ("no scanned
            # scope binds this axis") — on a single-file/--changed scan
            # the binding shard_map site may simply be unscanned, so the
            # rule stands down (the full scan still gates it), same as
            # the KVM032 docs-drift full-scan rule
            return
        for mod, fn, node in self._collective_sites:
            if fn is None:
                continue  # module-level collective: no scope to judge
            scope = self.scope.get(fn.key(), _UNSET)
            axes = self._collective_axes(mod, node)
            if not axes:
                continue  # runtime-parameter axis: not checkable
            if scope is _UNSET:
                # never reached from a shard_map body: only a plain-jit
                # root is provably scope-free (a helper may run under a
                # caller's mesh we cannot see)
                if fn.jit_root and fn.key() not in self.smap_targets:
                    for ax in sorted(axes):
                        self._emit(
                            mod, node, "KVM081",
                            f"collective over axis {ax!r} in jitted "
                            f"`{fn.name}`, which no shard_map scope "
                            "reaches — there is no mesh binding the "
                            "axis here; wrap the call in shard_map, "
                            "or mark `# kvmini: mesh-ok`",
                            fn.qualname)
                continue
            if scope is None:
                continue  # scope's mesh never resolved
            for ax in sorted(axes):
                if ax in scope.axes:
                    continue
                if not scope.complete and ax in self.global_axes:
                    continue  # partial scope: typo-only strictness
                known = ", ".join(sorted(scope.axes)) or "none"
                self._emit(
                    mod, node, "KVM081",
                    f"collective over axis {ax!r} in `{fn.name}`, "
                    "but the enclosing shard_map scope binds only "
                    f"[{known}] — the axis does not exist on this "
                    "mesh; fix the axis name or the mesh spec, or "
                    "mark `# kvmini: mesh-ok`",
                    fn.qualname)

    # -- KVM082 --------------------------------------------------------------
    def _check_partition_specs(self) -> None:
        comment_cache: dict[str, dict[int, str]] = {}
        for mod, _fn, node in self._pspec_sites:
            ctx = mod.path
            # literal axis names must exist on SOME package mesh — an
            # absence claim, so only a full scan (whole axis vocabulary
            # in view) may make it; arity checks below are local facts
            if self.global_axes and self.index.full_scan:
                for arg in node.args:
                    for s in self._spec_entry_strings(arg):
                        if s not in self.global_axes:
                            self._emit(
                                mod, node, "KVM082",
                                f"PartitionSpec names axis {s!r}, "
                                "which no mesh constructed in the "
                                "scanned set declares (known: "
                                f"[{', '.join(sorted(self.global_axes))}]) "
                                "— an axis typo shards nothing; fix "
                                "it or mark `# kvmini: mesh-ok`",
                                ctx)
            # arity vs the shape comment on the spec's line
            comments = comment_cache.get(mod.path)
            if comments is None:
                comments = comment_cache[mod.path] = _comment_map(mod.source)
            self._check_spec_arity(mod, node, comments, ctx)
        self._check_in_specs_arity()

    @staticmethod
    def _spec_entry_strings(arg: ast.AST):
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            yield arg.value
        elif isinstance(arg, (ast.Tuple, ast.List)):
            for e in arg.elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    yield e.value

    def _check_spec_arity(self, mod: ModuleFacts, node: ast.Call,
                          comments: dict[int, str], ctx: str) -> None:
        if any(isinstance(a, ast.Starred) for a in node.args) or not node.args:
            return
        for line in (getattr(node, "end_lineno", node.lineno),
                     node.lineno, node.lineno - 1):
            comment = comments.get(line)
            if comment is None:
                continue
            m = SHAPE_COMMENT.search(comment)
            if m is None:
                continue
            dims = [d.strip() for d in m.group(1).split(",")]
            if len(dims) != len(node.args):
                self._emit(
                    mod, node, "KVM082",
                    f"PartitionSpec has {len(node.args)} entries but the "
                    f"shape annotation `[{', '.join(dims)}]` declares "
                    f"{len(dims)} dims — a short spec silently replicates "
                    "the trailing axes; align them or mark "
                    "`# kvmini: mesh-ok`",
                    ctx)
            return

    def _check_in_specs_arity(self) -> None:
        for site in self.smap_sites:
            if not isinstance(site.in_specs, ast.Tuple):
                continue
            if any(isinstance(e, ast.Starred) for e in site.in_specs.elts):
                continue
            n_specs = len(site.in_specs.elts)
            for target in site.targets:
                params = [p for p in target.params if p not in ("self", "cls")]
                a = target.node.args
                n_defaults = len(a.defaults)
                avail = [p for p in params
                         if p not in site.partial_kwargs][site.partial_bound:]
                required = max(len(avail) - n_defaults, 0)
                if not (required <= n_specs <= len(avail)):
                    self._emit(
                        site.mod, site.node, "KVM082",
                        f"shard_map in_specs has {n_specs} entries but "
                        f"`{target.name}` takes {len(avail)} arguments"
                        + (f" (>= {required} required)" if n_defaults else "")
                        + " — the spec tuple must mirror the call "
                        "arguments one-to-one; fix the arity or mark "
                        "`# kvmini: mesh-ok`",
                        target.qualname)

    # -- KVM083 --------------------------------------------------------------
    def _jit_reachable(self) -> set[tuple[str, str]]:
        seen: set[tuple[str, str]] = set()
        work = [fn for fn in self.index.functions() if fn.jit_root]
        seen |= {fn.key() for fn in work}
        while work:
            fn = work.pop()
            mod = self.index.modules[fn.path]
            for cs in self.index.call_sites(mod, fn):
                for callee in cs.callees:
                    if callee.key() not in seen:
                        seen.add(callee.key())
                        work.append(callee)
        return seen

    def _check_dispatch_resharding(self) -> None:
        traced = self._jit_reachable()
        for mod in self.index.modules.values():
            if not (mod.jitted_names or mod.jitted_attrs or any(
                    f.jit_root or f.returns_jitted
                    for f in mod.functions.values())):
                continue
            for fn in mod.functions.values():
                if fn.key() in traced:
                    continue  # traced code: constraints belong there
                if fn.name == "__init__":
                    # constructors dispatch compiled warmup but run once —
                    # placement there IS the "once at setup" the rule asks for
                    continue
                if not any(
                        isinstance(n, ast.Call)
                        and self.index.calls_jitted_value(mod, fn, n)
                        for n in iter_scope(fn.node)):
                    continue
                for node in iter_scope(fn.node):
                    if not isinstance(node, ast.Call):
                        continue
                    name = _last_attr(node.func)
                    if name not in {"device_put", "with_sharding_constraint"}:
                        continue
                    self._emit(
                        mod, node, "KVM083",
                        f"`{name}` in jit-dispatch function `{fn.name}` — "
                        "a reshard/transfer on the hot path is a silent "
                        "all-gather every step (place data once at setup); "
                        "if this placement is intended here, mark "
                        "`# kvmini: mesh-ok`",
                        fn.qualname)

    # -- KVM084 --------------------------------------------------------------
    def _check_donation_across_boundary(self) -> None:
        sites_by_target: dict[tuple[str, str], SmapSite] = {}
        for site in self.smap_sites:
            for t in site.targets:
                sites_by_target[t.key()] = site
        for fn in self.index.functions():
            if not (fn.jit_root and (fn.donated_argnums or fn.donated_argnames)):
                continue
            mod = self.index.modules[fn.path]
            donated_names = {fn.params[i] for i in fn.donated_argnums
                             if i < len(fn.params)} | set(fn.donated_argnames)
            for cs in self.index.call_sites(mod, fn):
                for callee in cs.callees:
                    site = sites_by_target.get(callee.key())
                    if site is None or not isinstance(site.in_specs, ast.Tuple):
                        continue
                    outs = self._out_spec_strings(site)
                    if outs is None:
                        continue
                    for i, arg in enumerate(cs.node.args):
                        if not (isinstance(arg, ast.Name)
                                and arg.id in donated_names):
                            continue
                        if i >= len(site.in_specs.elts):
                            continue
                        in_str = ast.unparse(site.in_specs.elts[i])
                        if in_str not in outs:
                            self._emit(
                                mod, cs.node, "KVM084",
                                f"`{arg.id}` is donated by jit root "
                                f"`{fn.name}` but crosses the shard_map "
                                f"boundary as `{in_str}` with no matching "
                                "out_spec — the donation cannot alias "
                                "across a sharding change and XLA silently "
                                "copies; thread the buffer out with the "
                                "same spec, or mark `# kvmini: mesh-ok`",
                                fn.qualname)

    @staticmethod
    def _out_spec_strings(site: SmapSite) -> Optional[set[str]]:
        if site.out_specs is None:
            return None
        if isinstance(site.out_specs, ast.Tuple):
            return {ast.unparse(e) for e in site.out_specs.elts}
        return {ast.unparse(site.out_specs)}


_UNSET = object()


def check(index: FactIndex) -> list[Diagnostic]:
    return MeshFlowChecker(index).run()
