"""SARIF 2.1.0 rendering for GitHub code-scanning annotations.

One static document shape, no dependencies: the CLI's ``--sarif PATH``
writes ``render(result.diagnostics)`` so findings show up inline on PRs
via ``github/codeql-action/upload-sarif``. Suppressed findings never
reach this layer (suppression comments stop the Diagnostic at emit time,
diagnostics.py), so the uploaded document only carries live findings —
the same set the baseline ratchet gates on.

Severity maps from the rule family, not per finding: correctness-of-
served-bytes families (lockstep determinism, thread safety, numerics,
buffer lifecycle) annotate as ``error``; convention/drift families (jit
purity, metrics drift, workload surfacing) as ``warning``; the
suppression-hygiene rule KVM001 as ``note``.
"""

from __future__ import annotations

import json
from pathlib import Path

from kserve_vllm_mini_tpu.lint.diagnostics import RULES, Diagnostic

SCHEMA_URI = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)
INFO_URI = "https://github.com/kserve-vllm-mini-tpu"  # docs/LINTING.md

# family prefix -> SARIF level; longest (most specific) prefix wins so
# KVM001 can diverge from the rest of a hypothetical KVM00x family
FAMILY_LEVELS = {
    "KVM001": "note",     # stale-suppression hygiene
    "KVM01": "warning",   # jit purity / static shapes
    "KVM02": "error",     # lockstep determinism
    "KVM03": "warning",   # metrics/schema drift
    "KVM04": "warning",   # workload-change surfacing
    "KVM05": "error",     # thread safety / lock discipline
    "KVM06": "error",     # numerics / dtype flow
    "KVM07": "error",     # buffer lifecycle
    "KVM08": "error",     # mesh/sharding consistency (perf-silent wrongness)
    "KVM09": "error",     # exception-path resource safety
    "KVM10": "error",     # wire-protocol conformance (divergence = corruption)
    "KVM11": "warning",   # absent-not-zero contract drift
    "KVM12": "error",     # asyncio event-loop discipline (a blocked loop
    #                       stalls every in-flight request at once)
    "KVM13": "warning",   # config-surface drift (operability, not bytes)
}


def level_for(code: str) -> str:
    for prefix in sorted(FAMILY_LEVELS, key=len, reverse=True):
        if code.startswith(prefix):
            return FAMILY_LEVELS[prefix]
    return "warning"


def render(diagnostics: list[Diagnostic]) -> dict:
    """The SARIF run document for one lint invocation."""
    results = []
    used_rules = set()
    for d in diagnostics:
        used_rules.add(d.code)
        results.append({
            "ruleId": d.code,
            "level": level_for(d.code),
            "message": {"text": d.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": Path(d.path).as_posix(),
                        "uriBaseId": "%SRCROOT%",
                    },
                    "region": {"startLine": max(1, d.line)},
                },
            }],
        })
    rules = [
        {
            "id": r.code,
            "name": r.name,
            "shortDescription": {"text": r.summary},
            "helpUri": INFO_URI,
            "defaultConfiguration": {"level": level_for(r.code)},
        }
        # the full table rides along (GitHub needs the rule metadata for
        # every ruleId referenced; shipping all of RULES keeps the doc
        # stable whether or not a family fired this run)
        for r in RULES.values()
    ]
    assert used_rules <= set(RULES), used_rules - set(RULES)
    return {
        "$schema": SCHEMA_URI,
        "version": "2.1.0",
        "runs": [{
            "tool": {
                "driver": {
                    "name": "kvmini-lint",
                    "informationUri": INFO_URI,
                    "rules": rules,
                },
            },
            "results": results,
        }],
    }


def save(path: Path, diagnostics: list[Diagnostic]) -> None:
    path.write_text(json.dumps(render(diagnostics), indent=2) + "\n",
                    encoding="utf-8")
