"""KVM111-KVM113 — the absent-not-zero drift family.

Three repo-wide contracts that are prose in docs/ECONOMICS.md,
docs/MONITORING.md, and docs/API.md, mechanized:

- **KVM111 — fabricated-zero exports**: a ``.get(key, 0)`` / ``or 0``
  default flowing into a ``/metrics`` exposition f-string or a
  ``merge_into_results`` block fabricates a measurement. The
  absent-not-zero rule ("never a $0/1K-tok on unpriced engines"):
  an unmeasured surface must be absent — no line at all — not zero.
  Enumerated counters genuinely at zero (a fixed label vocabulary
  where 0 means "observed zero times", not "unknown") are the
  legitimate exception: mark them ``# kvmini: contract-ok``.
- **KVM112 — event-taxonomy drift**: the monitor's ``EVENT_TYPES``
  tuple vs the detector ``Event(t, "<type>", ...)`` emit sites vs the
  ``e.get("type") == ...`` consumers in report/charts vs the
  docs/MONITORING.md rows — the KVM032 analog for events. An emit or
  consumer naming a type outside the taxonomy fires, as does a
  taxonomy member nothing emits or nothing documents.
- **KVM113 — HTTP-surface drift**: server/router route registrations
  (``add_get``/``add_post``) vs ``tests/mock_server.py``'s routes vs
  the docs/API.md endpoint table vs in-repo client call sites
  (fleet/chaos/analysis/...). A route a client calls that the mock
  can't serve fires — the mock fleet must stay a faithful JAX-free
  twin. Every ``_shed_response`` must keep the 429 + Retry-After
  shape clients and the mock agree on.

Suppress a deliberate divergence with ``# kvmini: contract-ok``.

The cross-surface checks reason from absence, so they stand down on
partial scans (``index.full_scan``) — the emitter/consumer may live in
an unscanned module. The per-site checks (KVM111 zero defaults, the
KVM113 shed shape) hold on any scan.
"""

from __future__ import annotations

import ast
import re
from typing import Optional

from kserve_vllm_mini_tpu.lint.diagnostics import Diagnostic, Suppressions
from kserve_vllm_mini_tpu.lint.facts import FactIndex, ModuleFacts, iter_scope
from kserve_vllm_mini_tpu.lint.metrics_drift import (
    EXPOSITION_PREFIX,
    _docstring_nodes,
    _first_const,
)

EVENT_TYPES_NAME = re.compile(r"EVENT_TYPES$")
# event consumers filter `e.get("type")` in the monitor itself and the
# report/chart layer; a generic dict "type" key elsewhere (JSON schema
# specs, OpenAI tool payloads) is not an event read
EVENT_CONSUMER_PATH = re.compile(r"(^|/)(monitor|report)/")
ROUTE_REGISTRARS = {"add_get", "add_post"}
SERVER_PATH = re.compile(r"(^|/)runtime/")
ROUTER_PATH = re.compile(r"(^|/)fleet/")
CLIENT_PATH = re.compile(r"(^|/)(fleet|chaos|analysis|loadgen|probes)/")
MOCK_PATH = re.compile(r"(^|/)mock_server\.py$")
SHED_FN = "_shed_response"


def _is_zero(node: ast.AST) -> bool:
    return (isinstance(node, ast.Constant)
            and isinstance(node.value, (int, float))
            and not isinstance(node.value, bool) and node.value == 0)


def _zero_default(node: ast.AST) -> Optional[str]:
    """`x.get(k, 0)` -> "get-default"; `x or 0` -> "or-zero"; else None."""
    if (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)
            and node.func.attr == "get" and len(node.args) >= 2
            and _is_zero(node.args[1])):
        return "get-default"
    if (isinstance(node, ast.BoolOp) and isinstance(node.op, ast.Or)
            and node.values and _is_zero(node.values[-1])):
        return "or-zero"
    return None


class ContractChecker:
    def __init__(self, index: FactIndex,
                 doc_texts: Optional[dict[str, str]] = None):
        self.index = index
        self.doc_texts = doc_texts or {}
        self.diags: list[Diagnostic] = []

    def run(self) -> list[Diagnostic]:
        self._check_fabricated_zero()
        self._check_shed_shape()
        if self.index.full_scan:
            self._check_event_taxonomy()
            self._check_http_surfaces()
        return self.diags

    def _emit(self, mod: ModuleFacts, line: int, code: str, msg: str,
              ctx: str) -> None:
        if mod.suppressions.is_suppressed(line, code):
            return
        self.diags.append(Diagnostic(mod.path, line, code, msg, context=ctx))

    # -- KVM111 -------------------------------------------------------------
    def _check_fabricated_zero(self) -> None:
        for mod in self.index.modules.values():
            for node in mod.walk():
                if isinstance(node, ast.JoinedStr):
                    head = _first_const(node)
                    m = EXPOSITION_PREFIX.match(head or "")
                    if not m:
                        continue
                    for sub in ast.walk(node):
                        kind = _zero_default(sub)
                        if kind is not None:
                            self._emit(
                                mod, sub.lineno, "KVM111",
                                f"'{m.group(1)}' is exported with a "
                                f"fabricated zero ({kind}) — absent-not-"
                                "zero (docs/ECONOMICS.md): an unmeasured "
                                "surface must be absent, never 0; gate on "
                                "key presence, or mark a genuinely-zero "
                                "enumerated counter `# kvmini: contract-ok`",
                                m.group(1))
                elif (isinstance(node, ast.Call)
                      and isinstance(node.func, ast.Attribute)
                      and node.func.attr == "merge_into_results"
                      and node.args and isinstance(node.args[0], ast.Dict)):
                    for k, v in zip(node.args[0].keys, node.args[0].values):
                        kind = _zero_default(v)
                        if kind is None or not (
                                isinstance(k, ast.Constant)
                                and isinstance(k.value, str)):
                            continue
                        self._emit(
                            mod, v.lineno, "KVM111",
                            f"results key '{k.value}' is written with a "
                            f"fabricated zero ({kind}) — absent-not-zero: "
                            "omit the key when the measurement is missing "
                            "(gates/reports must see absence), or mark "
                            "`# kvmini: contract-ok`",
                            k.value)

    # -- KVM112 -------------------------------------------------------------
    def _check_event_taxonomy(self) -> None:
        taxonomy: dict[str, tuple[ModuleFacts, int]] = {}
        emits: dict[str, tuple[ModuleFacts, int]] = {}
        consumers: dict[str, tuple[ModuleFacts, int]] = {}
        for mod in self.index.modules.values():
            is_consumer = bool(EVENT_CONSUMER_PATH.search(mod.path))
            docstrings = _docstring_nodes(mod.tree)
            for node in mod.walk():
                if node in docstrings:
                    continue
                if isinstance(node, ast.Assign):
                    if any(isinstance(t, ast.Name)
                           and EVENT_TYPES_NAME.search(t.id)
                           for t in node.targets) and isinstance(
                               node.value, (ast.Tuple, ast.List)):
                        for e in node.value.elts:
                            if isinstance(e, ast.Constant) and isinstance(
                                    e.value, str):
                                taxonomy.setdefault(e.value, (mod, e.lineno))
                elif isinstance(node, ast.Call):
                    callee = (node.func.id if isinstance(node.func, ast.Name)
                              else node.func.attr
                              if isinstance(node.func, ast.Attribute)
                              else None)
                    # detector emit: Event(t, "<type>", ...) — arity
                    # excludes threading/asyncio Event() construction
                    if (callee == "Event" and len(node.args) >= 2
                            and isinstance(node.args[1], ast.Constant)
                            and isinstance(node.args[1].value, str)):
                        emits.setdefault(node.args[1].value,
                                         (mod, node.lineno))
                elif isinstance(node, ast.Compare) and is_consumer:
                    operands = [node.left, *node.comparators]
                    if not any(self._is_type_read(o) for o in operands):
                        continue
                    for o in operands:
                        if self._is_type_read(o):
                            continue
                        for c in ast.walk(o):
                            if isinstance(c, ast.Constant) and isinstance(
                                    c.value, str):
                                consumers.setdefault(c.value,
                                                     (mod, c.lineno))
        if not taxonomy:
            return
        for tag, (mod, line) in sorted(emits.items()):
            if tag not in taxonomy:
                self._emit(
                    mod, line, "KVM112",
                    f"event type '{tag}' is emitted but missing from "
                    "EVENT_TYPES — the monitor's taxonomy is the contract "
                    "report/chart consumers filter on; add it to the tuple "
                    "or mark `# kvmini: contract-ok`",
                    tag)
        for tag, (mod, line) in sorted(consumers.items()):
            if tag not in taxonomy:
                self._emit(
                    mod, line, "KVM112",
                    f"event type '{tag}' is consumed here but is not in "
                    "EVENT_TYPES — no detector can ever emit it, so this "
                    "branch is silently dead; fix the name or mark "
                    "`# kvmini: contract-ok`",
                    tag)
        md_texts = {p: t for p, t in self.doc_texts.items()
                    if p.endswith(".md")}
        for tag, (mod, line) in sorted(taxonomy.items()):
            if emits and tag not in emits:
                self._emit(
                    mod, line, "KVM112",
                    f"event type '{tag}' is declared in EVENT_TYPES but no "
                    "detector ever emits it — dead taxonomy row (or the "
                    "emit site drifted); remove it or mark "
                    "`# kvmini: contract-ok`",
                    tag)
            if md_texts and not any(
                    re.search(rf"\b{re.escape(tag)}\b", text)
                    for text in md_texts.values()):
                self._emit(
                    mod, line, "KVM112",
                    f"event type '{tag}' is undocumented — add its row to "
                    "the docs/MONITORING.md event table",
                    tag)

    @staticmethod
    def _is_type_read(node: ast.AST) -> bool:
        """`e.get("type")` / `e.get("type", d)` / `e["type"]`."""
        if (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)
                and node.func.attr == "get" and node.args
                and isinstance(node.args[0], ast.Constant)
                and node.args[0].value == "type"):
            return True
        return (isinstance(node, ast.Subscript)
                and isinstance(node.slice, ast.Constant)
                and node.slice.value == "type")

    # -- KVM113 -------------------------------------------------------------
    def _mock_module(self) -> Optional[ModuleFacts]:
        """The mock surface: an in-index mock_server module (fixture
        scans), else the repo's tests/mock_server.py parsed standalone —
        the package scan never includes tests/, but the twin contract is
        exactly about that file."""
        for mod in self.index.modules.values():
            if MOCK_PATH.search(mod.path):
                return mod
        cand = self.index.root / "tests" / "mock_server.py"
        try:
            source = cand.read_text(encoding="utf-8")
            tree = ast.parse(source)
        except (OSError, SyntaxError):
            return None
        return ModuleFacts(
            path="tests/mock_server.py", source=source, tree=tree,
            suppressions=Suppressions.scan(source))

    @staticmethod
    def _routes(mod: ModuleFacts) -> tuple[dict[str, int], set[int]]:
        """path -> first registration line, plus the registered-path
        Constant node ids (so client-literal scans skip them)."""
        out: dict[str, int] = {}
        reg_nodes: set[int] = set()
        for node in mod.walk():
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ROUTE_REGISTRARS
                    and node.args and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                out.setdefault(node.args[0].value, node.lineno)
                reg_nodes.add(id(node.args[0]))
        return out, reg_nodes

    def _check_http_surfaces(self) -> None:
        server_routes: dict[str, tuple[ModuleFacts, int]] = {}
        router_routes: dict[str, tuple[ModuleFacts, int]] = {}
        reg_node_ids: set[int] = set()
        for mod in self.index.modules.values():
            if MOCK_PATH.search(mod.path):
                continue
            routes, reg_nodes = self._routes(mod)
            reg_node_ids |= reg_nodes
            target = (server_routes if SERVER_PATH.search(mod.path)
                      else router_routes if ROUTER_PATH.search(mod.path)
                      else None)
            if target is None:
                continue
            for path, line in routes.items():
                target.setdefault(path, (mod, line))
        mock = self._mock_module()
        mock_routes = self._routes(mock)[0] if mock is not None else {}

        # a route a client calls that the mock can't serve — the mock
        # fleet silently 404s where the real fleet works
        if mock is not None and server_routes:
            seen: set[tuple[str, str]] = set()
            for mod in self.index.modules.values():
                if not CLIENT_PATH.search(mod.path):
                    continue
                docstrings = _docstring_nodes(mod.tree)
                for node in mod.walk():
                    if (not isinstance(node, ast.Constant)
                            or not isinstance(node.value, str)
                            or node in docstrings
                            or id(node) in reg_node_ids):
                        continue
                    path = node.value
                    if (path in server_routes and path not in mock_routes
                            and (mod.path, path) not in seen):
                        seen.add((mod.path, path))
                        self._emit(
                            mod, node.lineno, "KVM113",
                            f"client calls '{path}' but tests/"
                            "mock_server.py never registers it — the mock "
                            "fleet 404s where the real server works, so "
                            "the JAX-free suites can't cover this path; "
                            "add the mock route or mark "
                            "`# kvmini: contract-ok`",
                            path)

        # every registered endpoint belongs in the docs/API.md table
        api_docs = {p: t for p, t in self.doc_texts.items()
                    if p.endswith("API.md")}
        if api_docs:
            for path, (mod, line) in sorted({**router_routes,
                                             **server_routes}.items()):
                if not any(path in text for text in api_docs.values()):
                    self._emit(
                        mod, line, "KVM113",
                        f"endpoint '{path}' is registered but missing from "
                        "the docs/API.md endpoint table",
                        path)

        # a mock route no real server registers is a phantom surface —
        # tests would pass against an API that doesn't exist
        if mock is not None and (server_routes or router_routes):
            for path, line in sorted(mock_routes.items()):
                if path not in server_routes and path not in router_routes:
                    self._emit(
                        mock, line, "KVM113",
                        f"mock route '{path}' has no real server/router "
                        "registration — the twin serves an endpoint the "
                        "fleet doesn't; remove it or mark "
                        "`# kvmini: contract-ok`",
                        path)

    def _check_shed_shape(self) -> None:
        """Every `_shed_response` keeps the 429 + Retry-After shape the
        clients, the router, and the mock agree on (per-site — holds on
        any scan)."""
        for mod in self.index.modules.values():
            for fn in mod.functions.values():
                if fn.name != SHED_FN:
                    continue
                consts = {n.value for n in iter_scope(fn.node)
                          if isinstance(n, ast.Constant)}
                missing = [what for what, ok in
                           (("status 429", 429 in consts),
                            ("a Retry-After header", "Retry-After" in consts))
                           if not ok]
                if missing:
                    line = getattr(fn.node, "lineno", 0)
                    self._emit(
                        mod, line, "KVM113",
                        f"`{fn.qualname}` lacks {' and '.join(missing)} — "
                        "the shed contract (docs/API.md) is a 429 with "
                        "Retry-After so clients and the autoscaler "
                        "back off instead of hammering",
                        fn.qualname)


def check(index: FactIndex,
          doc_texts: Optional[dict[str, str]] = None) -> list[Diagnostic]:
    return ContractChecker(index, doc_texts).run()
