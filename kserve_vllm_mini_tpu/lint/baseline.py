"""Committed-baseline support (ratchet semantics).

``lint-baseline.json`` maps stable finding keys (``path::code::context``
— no line numbers, so unrelated edits don't churn it) to occurrence
counts. The gate:

- a finding whose key count exceeds the baseline → **new**, fails;
- a baseline entry with no matching finding anymore → **stale**, also
  fails (the fix landed; shrink the baseline — that's the ratchet
  pushing toward empty, ISSUE satellite #1).

``--write-baseline`` regenerates the file from the current findings.
"""

from __future__ import annotations

import json
from collections import Counter
from dataclasses import dataclass, field
from pathlib import Path

from kserve_vllm_mini_tpu.lint.diagnostics import Diagnostic

BASELINE_VERSION = 1


def counts(diags: list[Diagnostic]) -> dict[str, int]:
    return dict(Counter(d.baseline_key() for d in diags))


def load(path: Path) -> dict[str, int]:
    doc = json.loads(path.read_text(encoding="utf-8"))
    if doc.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"{path}: unsupported baseline version {doc.get('version')!r}")
    findings = doc.get("findings", {})
    if not isinstance(findings, dict):
        raise ValueError(f"{path}: 'findings' must be an object")
    return {str(k): int(v) for k, v in findings.items()}


def save(path: Path, diags: list[Diagnostic]) -> None:
    doc = {
        "version": BASELINE_VERSION,
        "tool": "kvmini-lint",
        "findings": dict(sorted(counts(diags).items())),
    }
    path.write_text(json.dumps(doc, indent=2, sort_keys=False) + "\n",
                    encoding="utf-8")


@dataclass
class BaselineDiff:
    new: list[Diagnostic] = field(default_factory=list)
    stale: list[str] = field(default_factory=list)      # baseline keys gone
    suppressed: int = 0                                  # grandfathered count

    @property
    def clean(self) -> bool:
        return not self.new and not self.stale


def diff(diags: list[Diagnostic], baseline: dict[str, int]) -> BaselineDiff:
    out = BaselineDiff()
    cur = counts(diags)
    for key, n in sorted(baseline.items()):
        if cur.get(key, 0) < n:
            # fully fixed or partially shrunk: either way the committed
            # count is stale and must be re-recorded (ratchet down)
            out.stale.append(key)
    # grandfather up to the recorded count per key (first occurrences in
    # file/line order); only the EXCESS is new — a third same-key finding
    # must not repaint the two pre-existing ones as regressions
    budget = dict(baseline)
    for d in sorted(diags, key=lambda d: (d.path, d.line, d.code)):
        key = d.baseline_key()
        if budget.get(key, 0) > 0:
            budget[key] -= 1
            out.suppressed += 1
        else:
            out.new.append(d)
    return out
