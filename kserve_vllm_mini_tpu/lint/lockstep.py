"""KVM021-KVM022 — lockstep determinism for multihost decision replay.

runtime/multihost.py's contract: the primary runs the scheduler
(`_schedule_once(on_decision=publish)`) and **publishes every
state-advancing decision before executing it**; followers replay the
identical stream. Two statically checkable hazards follow (the MLPerf
pod-scale failure mode — divergence discovered hundreds of steps later):

- **KVM021**: inside any function that takes an ``on_decision``
  parameter (the publisher-threaded scheduler paths), a call to a
  state-advancing engine method — the set the follower replays, learned
  from the fact index's ``run_follower`` scan, plus the conventional
  ``_admit*/_dispatch*/_retire*/_finish*/_cancel*`` prefixes — must be
  *routed*: the same statement block must reference ``on_decision``
  (publishing the decision, or forwarding the callback down).
- **KVM022**: in the replayed methods themselves (what both primary and
  followers execute) plus the publisher-threaded paths: no
  wall-clock-derived control flow, no host randomness, no bare ``set``
  iteration (arbitrary order ⇒ divergent slot choices). ``sorted(...)``
  over a set is the blessed fix and is exempt.

Suppress a deliberate host-local step with ``# kvmini: lockstep-ok``
(e.g. stats bookkeeping that followers intentionally skip).
"""

from __future__ import annotations

import ast
import re
from typing import Optional

from kserve_vllm_mini_tpu.lint.diagnostics import Diagnostic
from kserve_vllm_mini_tpu.lint.facts import (
    FactIndex,
    FunctionInfo,
    ModuleFacts,
    iter_scope,
)
from kserve_vllm_mini_tpu.lint.jit_purity import (
    _is_host_random_call,
    _is_wall_clock_call,
)

STATE_ADVANCING_PREFIX = re.compile(
    r"^_(admit|dispatch|retire|finish|cancel|decode_sweep|replay|fail)"
)
PUBLISHER_PARAM = "on_decision"


def _references_name(node: ast.AST, name: str) -> bool:
    return any(
        isinstance(n, ast.Name) and n.id == name for n in ast.walk(node)
    )


class _BlockMap(ast.NodeVisitor):
    """Maps every statement to the statement list (block) containing it."""

    def __init__(self) -> None:
        self.block_of: dict[ast.AST, list[ast.stmt]] = {}
        self.stmt_of: dict[ast.AST, ast.stmt] = {}

    def index(self, fn_node: ast.AST) -> None:
        # ast.walk is breadth-first, so deeper blocks are visited later:
        # plain assignment (not setdefault) leaves each node mapped to its
        # INNERMOST enclosing statement — with setdefault every node maps
        # to its outermost top-level statement and the "same block" check
        # degenerates to the whole function body (vacuously routed)
        for node in ast.walk(fn_node):
            for fname in ("body", "orelse", "finalbody"):
                block = getattr(node, fname, None)
                if isinstance(block, list) and block and isinstance(
                        block[0], ast.stmt):
                    for stmt in block:
                        self.block_of[stmt] = block
                        for sub in ast.walk(stmt):
                            self.stmt_of[sub] = stmt


class LockstepChecker:
    def __init__(self, index: FactIndex):
        self.index = index
        self.diags: list[Diagnostic] = []
        self.replayed = index.follower_replayed_methods()

    def run(self) -> list[Diagnostic]:
        publisher_fns = [
            (mod, fn)
            for mod in self.index.modules.values()
            for fn in mod.functions.values()
            if PUBLISHER_PARAM in fn.params
        ]
        for mod, fn in publisher_fns:
            self._check_routing(mod, fn)
            self._check_determinism(mod, fn)
        for mod, fn in self._replayed_scope(publisher_fns):
            self._check_determinism(mod, fn)
        return self.diags

    def _replayed_scope(self, publisher_fns) -> list[tuple[ModuleFacts, FunctionInfo]]:
        """Replayed methods + their same-module callees (both sides run
        them), excluding the publisher fns already checked."""
        done = {fn.key() for _, fn in publisher_fns}
        out: list[tuple[ModuleFacts, FunctionInfo]] = []
        work: list[tuple[ModuleFacts, FunctionInfo]] = []
        for mod in self.index.modules.values():
            for fn in mod.functions.values():
                if fn.name in self.replayed and fn.class_name is not None:
                    work.append((mod, fn))
        seen = set(done)
        while work:
            mod, fn = work.pop()
            if fn.key() in seen:
                continue
            seen.add(fn.key())
            out.append((mod, fn))
            for cs in self.index.call_sites(mod, fn):
                for callee in cs.callees:
                    if callee.path == mod.path and callee.key() not in seen:
                        work.append((mod, callee))
        return out

    def _emit(self, mod: ModuleFacts, node: ast.AST, code: str, msg: str,
              ctx: str) -> None:
        line = getattr(node, "lineno", 0)
        if mod.suppressions.is_suppressed(line, code):
            return
        self.diags.append(Diagnostic(mod.path, line, code, msg, context=ctx))

    # -- KVM021 -------------------------------------------------------------
    def _is_state_advancing(self, call: ast.Call) -> Optional[str]:
        f = call.func
        if (isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name)
                and f.value.id == "self"):
            if f.attr in self.replayed or STATE_ADVANCING_PREFIX.match(f.attr):
                return f.attr
        return None

    def _check_routing(self, mod: ModuleFacts, fn: FunctionInfo) -> None:
        blocks = _BlockMap()
        blocks.index(fn.node)
        for node in iter_scope(fn.node):
            if not isinstance(node, ast.Call):
                continue
            method = self._is_state_advancing(node)
            if method is None:
                continue
            if _references_name(node, PUBLISHER_PARAM):
                continue  # forwards the callback down — routed
            stmt = blocks.stmt_of.get(node)
            block = blocks.block_of.get(stmt, [])
            if any(_references_name(s, PUBLISHER_PARAM) for s in block):
                continue  # a publish lives in the same decision block
            self._emit(
                mod, node, "KVM021",
                f"`self.{method}(...)` advances scheduler state in "
                f"`{fn.name}` without publishing through {PUBLISHER_PARAM} "
                "— followers replaying the decision stream will diverge; "
                "publish in the same block or mark `# kvmini: lockstep-ok`",
                fn.qualname)

    # -- KVM022 -------------------------------------------------------------
    def _check_determinism(self, mod: ModuleFacts, fn: FunctionInfo) -> None:
        clock_names: set[str] = set()
        set_names: set[str] = set()
        for node in iter_scope(fn.node):
            if isinstance(node, ast.Assign):
                v = node.value
                names = [t.id for t in node.targets if isinstance(t, ast.Name)]
                if isinstance(v, ast.Call) and _is_wall_clock_call(mod, v):
                    clock_names.update(names)
                if (isinstance(v, (ast.Set, ast.SetComp))
                        or (isinstance(v, ast.Call)
                            and isinstance(v.func, ast.Name)
                            and v.func.id in {"set", "frozenset"})):
                    set_names.update(names)
        for node in iter_scope(fn.node):
            if isinstance(node, ast.Call) and _is_host_random_call(mod, node):
                self._emit(
                    mod, node, "KVM022",
                    f"host randomness in lockstep-replayed `{fn.name}` — "
                    "primary and followers draw different values; derive "
                    "from the shared engine seed or mark "
                    "`# kvmini: lockstep-ok`",
                    fn.qualname)
            elif isinstance(node, (ast.If, ast.While)):
                # only clock values COMPARED in the test steer control flow;
                # a timestamp passed through as a call argument (stats,
                # span bookkeeping) is host-local and harmless
                hits = [
                    n
                    for cmp_node in ast.walk(node.test)
                    if isinstance(cmp_node, ast.Compare)
                    for n in ast.walk(cmp_node)
                    if (isinstance(n, ast.Name) and n.id in clock_names)
                    or (isinstance(n, ast.Call) and _is_wall_clock_call(mod, n))
                ]
                if hits:
                    self._emit(
                        mod, node, "KVM022",
                        f"wall-clock control flow in lockstep-replayed "
                        f"`{fn.name}` — hosts read different clocks, so "
                        "branches diverge; decide on the primary and "
                        "publish, or mark `# kvmini: lockstep-ok`",
                        fn.qualname)
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                it = node.iter
                bare_set = (
                    isinstance(it, (ast.Set, ast.SetComp))
                    or (isinstance(it, ast.Call)
                        and isinstance(it.func, ast.Name)
                        and it.func.id in {"set", "frozenset"})
                    or (isinstance(it, ast.Name) and it.id in set_names)
                )
                if bare_set:
                    self._emit(
                        mod, node, "KVM022",
                        f"iteration over a `set` in lockstep-replayed "
                        f"`{fn.name}` — arbitrary order diverges across "
                        "hosts; wrap in sorted(...) or mark "
                        "`# kvmini: lockstep-ok`",
                        fn.qualname)


def check(index: FactIndex) -> list[Diagnostic]:
    return LockstepChecker(index).run()
