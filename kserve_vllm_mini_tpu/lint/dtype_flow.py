"""KVM061-KVM065 — numerics / dtype-flow analysis.

A small abstract interpretation over dtypes ("the dtype-flow lattice",
docs/LINTING.md): every expression is mapped to an abstract dtype —
``bf16/f16/f32/f64``, the integer widths, ``bool``, the *weak* Python
literal kinds (which adapt to the other operand and never widen, JAX's
weak-type rule), or **unknown** (the lattice top). Facts only ever come
from places the programmer wrote a dtype down:

- ``x.astype(D)``, ``jnp.asarray(x, D)``, ``dtype=D`` keyword/positional
  slots on the array constructors (``zeros/ones/full/arange/*_like``);
- the quant-leaf key contract (ops/quant.py): ``leaf["s"]``/``leaf["a"]``
  and the int8-KV ``"k_s"``/``"v_s"`` scales are f32 per-channel arrays;
- dtype-preserving ops propagate their input (elementwise math, reshapes,
  reductions, ``where``/``maximum`` join their branches);
- cross-function rounds: a param's dtype is the join of every resolved
  callsite's argument dtype (conflicts join to unknown), and a call
  expression takes the callee's joined return dtype.

**Unknown never fires a rule** — every diagnostic requires the operands'
dtypes to be *provable* from the source, so the checker under-approximates
(misses) rather than guesses (false alarms).

Rules:

- **KVM061**: arithmetic mixing two different known float widths on a jit
  hot path (a jit root or anything reachable from one through the call
  graph). ``bf16_act * f32_scale`` silently upcasts the whole activation
  tensor to f32 — 2x the bytes on the MXU path, and the op no longer
  computes what the bf16 serving contract promises. Cast the narrow side
  up explicitly (KVM065's accumulation rule) or the wide side down.
- **KVM062**: a consumer that reads both ``"q"`` and ``"s"`` from a quant
  leaf but never reads, membership-tests, or writes a compensation key
  (``"z"`` zero-point / ``"a"`` AWQ input-scale) — dequantization that
  applies the scale and silently drops the offset term. Builders (functions
  that *write* quant keys) are exempt.
- **KVM063**: sub-byte dtypes (int4/uint4) via ``lax.bitcast_convert_type``
  or materialized as array leaves. The sub-byte bitcast keeps the byte
  shape at abstract eval (no trailing nibble axis — the downstream widen
  reshape is a width mismatch), and an S4 leaf at a dispatch boundary
  recurses into relayout (ops/quant.py). Unpack arithmetically.
- **KVM064**: a dot/matmul whose operand is a known narrow integer dtype
  without ``preferred_element_type`` — the accumulator inherits int8 and
  wraps. The ``@`` operator cannot pass it; use ``lax.dot_general``.
- **KVM065**: softmax-family / mean / variance reductions over a value
  proven bf16/f16 — accumulate in f32 (``x.astype(jnp.float32)`` in,
  cast back out), the logits/rmsnorm convention models/llama.py follows.
"""

from __future__ import annotations

import ast
from typing import Optional

from kserve_vllm_mini_tpu.lint.diagnostics import Diagnostic
from kserve_vllm_mini_tpu.lint.facts import (
    FactIndex,
    FunctionInfo,
    ModuleFacts,
    _last_attr,
    iter_scope,
)

# -- lattice values -----------------------------------------------------------
BF16, F16, F32, F64 = "bf16", "f16", "f32", "f64"
I4, U4, I8, U8 = "int4", "uint4", "int8", "uint8"
I16, I32, I64 = "int16", "int32", "int64"
BOOL = "bool"
WEAK_F, WEAK_I = "weak_float", "weak_int"

FLOAT_RANK = {F16: 1, BF16: 1, F32: 2, F64: 3}
INT_RANK = {I4: 0, U4: 0, U8: 1, I8: 1, I16: 2, I32: 3, I64: 4}
SUB_BYTE = {I4, U4}
NARROW_INT = {I4, U4, I8, U8}

DTYPE_TOKENS = {
    "bfloat16": BF16, "float16": F16, "half": F16,
    "float32": F32, "single": F32, "float64": F64, "double": F64,
    "int4": I4, "uint4": U4, "int8": I8, "uint8": U8,
    "int16": I16, "int32": I32, "int64": I64, "bool_": BOOL,
}

# quant-leaf / int8-KV key contract (ops/quant.py, models/llama.py):
# per-channel scales are f32 arrays wherever they appear
SCALE_KEY_DTYPES = {"s": F32, "a": F32, "k_s": F32, "v_s": F32}

QUANT_COMPENSATION_KEYS = {"z", "a"}

# dtype-preserving ops: result carries the first array argument's dtype
PRESERVE_FIRST = {
    "exp", "exp2", "log", "log2", "sqrt", "rsqrt", "abs", "square",
    "negative", "transpose", "reshape", "squeeze", "ravel", "expand_dims",
    "broadcast_to", "roll", "flip", "tile", "pad", "swapaxes", "moveaxis",
    "copy", "sum", "mean", "max", "min", "prod", "cumsum", "var", "std",
    "round", "floor", "ceil", "clip", "tanh", "sigmoid", "relu", "gelu",
    "silu", "softmax", "log_softmax", "logsumexp", "take",
    "take_along_axis", "sort", "flatten", "at",
}
# ops joining several array args (branch/elementwise merge)
JOIN_ARGS = {"where", "maximum", "minimum", "stack", "concatenate", "add",
             "subtract", "multiply", "divide", "dot", "matmul"}

ARITH_OPS = (ast.Add, ast.Sub, ast.Mult, ast.Div, ast.FloorDiv, ast.Mod,
             ast.Pow)
DOT_CALL_NAMES = {"dot", "matmul", "tensordot", "dot_general", "einsum"}
ACCUM_CALL_NAMES = {"softmax", "log_softmax", "logsumexp", "mean", "var",
                    "std"}


def promote(a: Optional[str], b: Optional[str]) -> Optional[str]:
    """Binary-op result dtype; None (unknown) is absorbing."""
    if a is None or b is None:
        return None
    if a == b:
        return a
    if WEAK_F in (a, b):
        other = b if a == WEAK_F else a
        if other in FLOAT_RANK or other == WEAK_F:
            return other
        if other == WEAK_I:
            return WEAK_F
        return None  # weak float with an int array: backend default float
    if WEAK_I in (a, b):
        return b if a == WEAK_I else a
    if a == BOOL:
        return b
    if b == BOOL:
        return a
    if a in FLOAT_RANK and b in FLOAT_RANK:
        return a if FLOAT_RANK[a] >= FLOAT_RANK[b] else b
    if a in INT_RANK and b in INT_RANK:
        return a if INT_RANK[a] >= INT_RANK[b] else b
    if a in FLOAT_RANK and b in INT_RANK:
        return a
    if b in FLOAT_RANK and a in INT_RANK:
        return b
    return None


def join(a: Optional[str], b: Optional[str]) -> Optional[str]:
    """Path/callsite merge: agree or give up (no promotion — a param fed
    bf16 at one site and f32 at another has no single provable dtype)."""
    return a if a == b else None


def dtype_literal(node: ast.AST) -> Optional[str]:
    """`jnp.bfloat16` / `np.float32` / `"bfloat16"` -> lattice value."""
    if isinstance(node, ast.Attribute):
        return DTYPE_TOKENS.get(node.attr)
    if isinstance(node, ast.Name):
        return DTYPE_TOKENS.get(node.id)
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return DTYPE_TOKENS.get(node.value)
    return None


def _dtype_arg(call: ast.Call, pos: Optional[int]) -> Optional[ast.AST]:
    """The expression in a constructor's dtype slot (kw wins, then pos)."""
    for kw in call.keywords:
        if kw.arg == "dtype":
            return kw.value
    if pos is not None and len(call.args) > pos:
        return call.args[pos]
    return None


def _has_kwarg(call: ast.Call, name: str) -> bool:
    return any(kw.arg == name for kw in call.keywords)


class _ScopeNodes:
    """One iter_scope walk per function, bucketed by what the passes need
    (env.run is re-run every propagation round — re-walking the AST each
    time dominated the checker's wall time)."""

    __slots__ = ("stmts", "returns", "checks")

    def __init__(self, fn_node: ast.AST):
        self.stmts: list[ast.AST] = []
        self.returns: list[ast.Return] = []
        self.checks: list[ast.AST] = []
        for node in iter_scope(fn_node):
            if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign,
                                 ast.For, ast.AsyncFor)):
                self.stmts.append(node)
            elif isinstance(node, ast.Return):
                self.returns.append(node)
            if isinstance(node, (ast.BinOp, ast.Call)):
                self.checks.append(node)


class _DtypeEnv:
    """Per-function name -> abstract dtype, seeded from param dtypes."""

    def __init__(self, checker: "DtypeFlowChecker", mod: ModuleFacts,
                 fn: FunctionInfo):
        self.c = checker
        self.mod = mod
        self.fn = fn
        self.scope = checker.scope_nodes(fn)
        self.names: dict[str, Optional[str]] = dict(
            checker.param_dtypes.get(fn.key(), {}))

    # -- expression transfer function ------------------------------------
    def expr(self, node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Name):
            return self.names.get(node.id)
        if isinstance(node, ast.Constant):
            if isinstance(node.value, bool):
                return BOOL
            if isinstance(node.value, float):
                return WEAK_F
            if isinstance(node.value, int):
                return WEAK_I
            return None
        if isinstance(node, (ast.Compare, ast.BoolOp)):
            return BOOL
        if isinstance(node, ast.UnaryOp):
            return BOOL if isinstance(node.op, ast.Not) else self.expr(node.operand)
        if isinstance(node, ast.BinOp):
            return promote(self.expr(node.left), self.expr(node.right))
        if isinstance(node, ast.IfExp):
            return join(self.expr(node.body), self.expr(node.orelse))
        if isinstance(node, ast.Subscript):
            key = node.slice
            if isinstance(key, ast.Constant) and isinstance(key.value, str):
                d = SCALE_KEY_DTYPES.get(key.value)
                if d is not None:
                    return d
                return None  # "q" may be int8 or packed uint8 — unknown
            return self.expr(node.value)  # indexing preserves dtype
        if isinstance(node, ast.Call):
            return self._call(node)
        if isinstance(node, ast.Attribute):
            # x.T / x.at[...] style accessors preserve; anything else unknown
            if node.attr in {"T", "mT", "real"}:
                return self.expr(node.value)
            return None
        return None

    def _call(self, node: ast.Call) -> Optional[str]:
        f = node.func
        name = _last_attr(f)
        # x.astype(D) / x.view(D)
        if isinstance(f, ast.Attribute) and name in {"astype", "view"}:
            return self._resolve_dtype_expr(node.args[0]) if node.args else None
        # np.float32(x) / jnp.bfloat16(x) constructor spellings
        if name in DTYPE_TOKENS and name not in {"bool_"}:
            return DTYPE_TOKENS[name]
        if name in {"asarray", "array"}:
            d = _dtype_arg(node, 1)
            if d is not None:
                return self._resolve_dtype_expr(d)
            return self.expr(node.args[0]) if node.args else None
        if name in {"zeros", "ones", "empty"}:
            d = _dtype_arg(node, 1)
            return self._resolve_dtype_expr(d) if d is not None else None
        if name == "full":
            d = _dtype_arg(node, 2)
            return self._resolve_dtype_expr(d) if d is not None else None
        if name in {"zeros_like", "ones_like", "full_like", "empty_like"}:
            d = _dtype_arg(node, None)
            if d is not None:
                return self._resolve_dtype_expr(d)
            return self.expr(node.args[0]) if node.args else None
        if name == "arange":
            d = _dtype_arg(node, None)
            return self._resolve_dtype_expr(d) if d is not None else None
        if name in PRESERVE_FIRST:
            return self.expr(node.args[0]) if node.args else None
        if name in JOIN_ARGS:
            arr_args = node.args[1:] if name == "where" else node.args
            out: Optional[str] = None
            first = True
            for a in arr_args:
                d = self.expr(a)
                out, first = (d, False) if first else (promote(out, d), False)
            return out
        # resolved callee: its joined return dtype
        for callee in self.c.resolve_call(self.mod, self.fn, node):
            rd = self.c.return_dtypes.get(callee.key())
            if rd is not None:
                return rd
        return None

    def _resolve_dtype_expr(self, node: ast.AST) -> Optional[str]:
        d = dtype_literal(node)
        if d is not None:
            return d
        # y.astype(x.dtype): inherit x's inferred dtype
        if (isinstance(node, ast.Attribute) and node.attr == "dtype"):
            return self.expr(node.value)
        return None

    # -- statement walk ---------------------------------------------------
    def run(self) -> None:
        # two passes so late assignments reach loop-carried early reads
        for _ in range(2):
            for node in self.scope.stmts:
                if isinstance(node, ast.Assign):
                    d = self.expr(node.value)
                    for tgt in node.targets:
                        self._assign(tgt, node.value, d)
                elif isinstance(node, ast.AnnAssign) and node.value is not None:
                    self._assign(node.target, node.value, self.expr(node.value))
                elif isinstance(node, ast.AugAssign):
                    if isinstance(node.target, ast.Name):
                        self.names[node.target.id] = promote(
                            self.names.get(node.target.id),
                            self.expr(node.value))
                elif isinstance(node, (ast.For, ast.AsyncFor)):
                    if isinstance(node.target, ast.Name):
                        # iterating an array yields same-dtype rows
                        self.names[node.target.id] = self.expr(node.iter)

    def _assign(self, tgt: ast.AST, value: ast.AST, d: Optional[str]) -> None:
        if isinstance(tgt, ast.Name):
            self.names[tgt.id] = d
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            elts = (value.elts if isinstance(value, (ast.Tuple, ast.List))
                    and len(value.elts) == len(tgt.elts) else None)
            for i, t in enumerate(tgt.elts):
                if isinstance(t, ast.Name):
                    self.names[t.id] = (self.expr(elts[i])
                                        if elts is not None else None)


class DtypeFlowChecker:
    def __init__(self, index: FactIndex):
        self.index = index
        self.diags: list[Diagnostic] = []
        # (path, qualname) -> {param: dtype-or-None}; absent param = bottom
        self.param_dtypes: dict[tuple[str, str], dict[str, Optional[str]]] = {}
        self.return_dtypes: dict[tuple[str, str], Optional[str]] = {}
        self.jit_scope: set[tuple[str, str]] = set()
        self._scopes: dict[tuple[str, str], _ScopeNodes] = {}
        self._call_memo: dict[int, list[FunctionInfo]] = {}

    def scope_nodes(self, fn: FunctionInfo) -> _ScopeNodes:
        sc = self._scopes.get(fn.key())
        if sc is None:
            sc = self._scopes[fn.key()] = _ScopeNodes(fn.node)
        return sc

    def resolve_call(self, mod: ModuleFacts, fn: FunctionInfo,
                     call: ast.Call) -> list[FunctionInfo]:
        """index.resolve_call memoized by callsite node — env.run re-reads
        the same call expressions every propagation round."""
        out = self._call_memo.get(id(call))
        if out is None:
            out = self._call_memo[id(call)] = self.index.resolve_call(
                mod, fn, call)
        return out

    # -- scope + cross-function rounds -----------------------------------
    def _seed_jit_scope(self) -> None:
        frontier = [fn for fn in self.index.functions() if fn.jit_root]
        self.jit_scope = {fn.key() for fn in frontier}
        while frontier:
            fn = frontier.pop()
            mod = self.index.modules[fn.path]
            for cs in self.index.call_sites(mod, fn):
                for callee in cs.callees:
                    if callee.key() not in self.jit_scope:
                        self.jit_scope.add(callee.key())
                        frontier.append(callee)

    def _propagate(self) -> None:
        """Cross-function rounds: callsite arg dtypes -> callee params,
        return expressions -> call expressions. Three rounds bound the
        getter-chain depth this package actually has."""
        for _ in range(3):
            changed = False
            for mod in self.index.modules.values():
                for fn in mod.functions.values():
                    env = _DtypeEnv(self, mod, fn)
                    env.run()
                    rd: Optional[str] = None
                    first = True
                    for node in env.scope.returns:
                        if node.value is not None:
                            d = env.expr(node.value)
                            rd, first = (d, False) if first else (join(rd, d), False)
                    if not first and self.return_dtypes.get(fn.key(), "⊥") != rd:
                        # the round cap bounds any oscillation
                        self.return_dtypes[fn.key()] = rd
                        changed = True
                    for cs in self.index.call_sites(mod, fn):
                        for callee in cs.callees:
                            if self._bind_args(env, cs.node, callee):
                                changed = True
            if not changed:
                return

    def _bind_args(self, env: _DtypeEnv, call: ast.Call,
                   callee: FunctionInfo) -> bool:
        params = callee.params
        offset = 1 if params[:1] in (["self"], ["cls"]) and isinstance(
            call.func, ast.Attribute) else 0
        slots = self.param_dtypes.setdefault(callee.key(), {})
        changed = False
        for i, arg in enumerate(call.args):
            if isinstance(arg, ast.Starred):
                continue
            pi = i + offset
            if pi >= len(params):
                break
            changed |= self._join_slot(slots, params[pi], env.expr(arg))
        for kw in call.keywords:
            if kw.arg and kw.arg in params:
                changed |= self._join_slot(slots, kw.arg, env.expr(kw.value))
        return changed

    @staticmethod
    def _join_slot(slots: dict[str, Optional[str]], param: str,
                   d: Optional[str]) -> bool:
        if param not in slots:
            slots[param] = d
            return d is not None
        if slots[param] != d and slots[param] is not None:
            slots[param] = None
            return True
        return False

    # -- checks -----------------------------------------------------------
    def run(self) -> list[Diagnostic]:
        self._seed_jit_scope()
        self._propagate()
        for mod in self.index.modules.values():
            for fn in mod.functions.values():
                env = _DtypeEnv(self, mod, fn)
                env.run()
                self._check_fn(mod, fn, env)
                self._check_quant_contract(mod, fn)
        return self.diags

    def _emit(self, mod: ModuleFacts, node: ast.AST, code: str, msg: str,
              context: str) -> None:
        line = getattr(node, "lineno", 0)
        if mod.suppressions.is_suppressed(line, code):
            return
        self.diags.append(Diagnostic(mod.path, line, code, msg, context=context))

    def _check_fn(self, mod: ModuleFacts, fn: FunctionInfo,
                  env: _DtypeEnv) -> None:
        ctx = fn.qualname
        on_hot_path = fn.key() in self.jit_scope
        for node in env.scope.checks:
            if isinstance(node, ast.BinOp):
                ld, rd = env.expr(node.left), env.expr(node.right)
                if isinstance(node.op, ast.MatMult):
                    narrow = {d for d in (ld, rd) if d in NARROW_INT}
                    if narrow:
                        self._emit(
                            mod, node, "KVM064",
                            f"`@` over a {'/'.join(sorted(narrow))} operand "
                            f"in `{fn.name}` accumulates in the narrow int "
                            "dtype — use lax.dot_general(..., "
                            "preferred_element_type=jnp.int32), or mark "
                            "`# kvmini: dtype-ok`", ctx)
                elif (on_hot_path and isinstance(node.op, ARITH_OPS)
                        and ld in FLOAT_RANK and rd in FLOAT_RANK
                        and FLOAT_RANK[ld] != FLOAT_RANK[rd]):
                    lo, hi = sorted((ld, rd), key=FLOAT_RANK.get)
                    self._emit(
                        mod, node, "KVM061",
                        f"{lo} x {hi} arithmetic in jit-hot `{fn.name}` "
                        f"silently upcasts the {lo} operand to {hi} — cast "
                        "one side explicitly (accumulations: astype(f32) "
                        "in, astype back out), or mark `# kvmini: dtype-ok`",
                        ctx)
            elif isinstance(node, ast.Call):
                self._check_call(mod, fn, env, node, ctx)

    def _check_call(self, mod: ModuleFacts, fn: FunctionInfo, env: _DtypeEnv,
                    node: ast.Call, ctx: str) -> None:
        name = _last_attr(node.func)
        if name == "bitcast_convert_type":
            d = (self._sub_byte_literal(node.args[1])
                 if len(node.args) > 1 else None)
            for kw in node.keywords:
                if kw.arg == "new_dtype":
                    d = d or self._sub_byte_literal(kw.value)
            if d:
                self._emit(
                    mod, node, "KVM063",
                    f"bitcast_convert_type to {d} in `{fn.name}`: sub-byte "
                    "bitcast keeps the byte shape at abstract eval (the "
                    "widening reshape downstream is a width mismatch) — "
                    "unpack with mask/shift arithmetic, or mark "
                    "`# kvmini: dtype-ok`", ctx)
            return
        if name in {"astype", "asarray", "array", "zeros", "ones", "full",
                    "empty", "arange", "zeros_like", "ones_like", "full_like"}:
            for sub in list(node.args) + [kw.value for kw in node.keywords]:
                d = self._sub_byte_literal(sub)
                if d:
                    self._emit(
                        mod, node, "KVM063",
                        f"materialized {d} leaf in `{fn.name}` recurses "
                        "into dispatch relayout (ops/quant.py) — store "
                        "packed nibble pairs in uint8 and unpack "
                        "arithmetically, or mark `# kvmini: dtype-ok`", ctx)
                    return
        if name in DOT_CALL_NAMES and not _has_kwarg(
                node, "preferred_element_type"):
            narrow = {env.expr(a) for a in node.args} & NARROW_INT
            if narrow:
                self._emit(
                    mod, node, "KVM064",
                    f"{name}() over a {'/'.join(sorted(narrow))} operand "
                    f"in `{fn.name}` without preferred_element_type — the "
                    "accumulator inherits the narrow int dtype and wraps; "
                    "pass preferred_element_type=jnp.int32, or mark "
                    "`# kvmini: dtype-ok`", ctx)
            return
        if name in ACCUM_CALL_NAMES and node.args:
            d = env.expr(node.args[0])
            if d in {BF16, F16}:
                self._emit(
                    mod, node, "KVM065",
                    f"{name}() accumulates over a {d} value in `{fn.name}` "
                    "— sum/normalizer precision collapses at long axes; "
                    "compute in f32 (x.astype(jnp.float32)) and cast the "
                    "result back, or mark `# kvmini: dtype-ok`", ctx)

    @staticmethod
    def _sub_byte_literal(node: ast.AST) -> Optional[str]:
        d = dtype_literal(node)
        return d if d in SUB_BYTE else None

    # -- KVM062: quant-leaf contract --------------------------------------
    def _check_quant_contract(self, mod: ModuleFacts, fn: FunctionInfo) -> None:
        reads: dict[str, dict[str, ast.AST]] = {}
        handled: dict[str, set[str]] = {}
        writes: dict[str, set[str]] = {}
        for node in iter_scope(fn.node):
            if (isinstance(node, ast.Subscript)
                    and isinstance(node.value, ast.Name)
                    and isinstance(node.slice, ast.Constant)
                    and isinstance(node.slice.value, str)):
                base, key = node.value.id, node.slice.value
                if isinstance(node.ctx, ast.Store):
                    writes.setdefault(base, set()).add(key)
                else:
                    reads.setdefault(base, {}).setdefault(key, node)
            elif (isinstance(node, ast.Compare)
                    and isinstance(node.left, ast.Constant)
                    and isinstance(node.left.value, str)
                    and all(isinstance(op, (ast.In, ast.NotIn))
                            for op in node.ops)):
                for comp in node.comparators:
                    if isinstance(comp, ast.Name):
                        handled.setdefault(comp.id, set()).add(node.left.value)
            elif (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "get"
                    and isinstance(node.func.value, ast.Name)
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                # leaf.get("a") reads the key just as leaf["a"] does (the
                # ops/qmatmul.py qdot convention: pre_scale=qw.get("a"))
                handled.setdefault(node.func.value.id, set()).add(
                    node.args[0].value
                )
        for base, keymap in reads.items():
            if not {"q", "s"} <= set(keymap):
                continue
            if writes.get(base):
                continue  # builder: it produces the leaf, contract N/A
            seen = set(keymap) | handled.get(base, set())
            if seen & QUANT_COMPENSATION_KEYS:
                continue
            self._emit(
                mod, keymap["s"], "KVM062",
                f"`{base}` is dequantized (reads 'q' and 's') in "
                f"`{fn.name}` without reading, testing, or writing a "
                "compensation key ('z'/'a') — an AWQ/asymmetric leaf "
                "would silently drop its offset term; handle it "
                "(`if \"a\" in ...`), or mark `# kvmini: dtype-ok`",
                fn.qualname)


def check(index: FactIndex) -> list[Diagnostic]:
    return DtypeFlowChecker(index).run()
