"""KVM131-KVM134 — config-surface drift.

The operator config surface spans five layers that nothing joins
mechanically: ``*_ENV_KNOBS`` registration tables, ``KVMINI_*``
``os.environ`` read sites, argparse flags, the config dataclasses
(``EngineConfig``/``MonitorConfig``/``PolicyConfig``), and the docs
pages. Every PR note promises "validated loudly, documented in
_ENV_KNOBS" — this family turns that promise into checked facts:

- **KVM131 — unregistered env knob.** An ``os.environ`` read of a
  ``KVMINI_*`` key that no knob table registers and no docs page
  mentions: the knob works but no operator can discover it.
- **KVM132 — stale knob entry.** A knob-table key no read site
  consumes and whose string literal appears nowhere outside the table
  itself: the table documents a knob the code no longer honors.
- **KVM133 — unsurfaced config field.** A config-dataclass field with
  no CLI flag, no env knob, no profile-key/string plumbing, and no docs
  mention — the field exists but no operator can set it. The dual
  failure is also flagged: a field surfaced via CLI flag whose flag the
  docs never mention.
- **KVM134 — knob-default drift.** The same knob declared with
  different defaults across argparse ``default=``, the env-parse
  fallback, and the dataclass field default. Values are compared after
  normalization (``"256"`` == ``256``, ``"true"`` == ``True``), so only
  genuine drift fires.

Join semantics follow the KVM032 full-scan contract: KVM131/132/133 are
absence-based (their registration surface — tables, flags, docs — may
live in an unscanned module), so they run only on full package scans
where ``doc_texts`` is populated; KVM134 is presence-based (every
compared default is in the scanned set) and runs on any scan. Suppress
deliberate gaps with ``# kvmini: config-ok`` plus a one-line
justification.
"""

from __future__ import annotations

import ast
import re
from collections import Counter
from dataclasses import dataclass, field
from typing import Optional

from kserve_vllm_mini_tpu.lint.diagnostics import Diagnostic
from kserve_vllm_mini_tpu.lint.facts import FactIndex, ModuleFacts

ENV_PREFIX = "KVMINI_"
CONFIG_CLASSES = {"EngineConfig", "MonitorConfig", "PolicyConfig"}


@dataclass
class EnvRead:
    mod: ModuleFacts
    line: int
    key: str
    fallback: object = None  # constant second arg of .get/getenv, if any
    has_fallback: bool = False


@dataclass
class KnobTable:
    mod: ModuleFacts
    name: str
    node: ast.Assign
    keys: dict[str, int] = field(default_factory=dict)  # key -> line


@dataclass
class CliFlag:
    mod: ModuleFacts
    line: int
    flag: str          # e.g. "--max-batch"
    knob: str          # normalized: "max_batch"
    default: object = None
    has_default: bool = False


@dataclass
class ConfigField:
    mod: ModuleFacts
    cls: str
    name: str
    line: int
    default: object = None
    has_default: bool = False


def _env_receiver(node: ast.AST) -> bool:
    """True for the expression ``os.environ``."""
    return (isinstance(node, ast.Attribute) and node.attr == "environ"
            and isinstance(node.value, ast.Name) and node.value.id == "os")


def _const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _norm_default(v: object) -> object:
    """Collapse representation differences so only real drift compares
    unequal: booleans and numeric strings to float, truthy/falsy words
    to 1.0/0.0, other strings case-folded."""
    if isinstance(v, bool):
        return 1.0 if v else 0.0
    if isinstance(v, (int, float)):
        return float(v)
    if isinstance(v, str):
        s = v.strip().lower()
        if s in ("true", "yes", "on", "1"):
            return 1.0
        # "" is the conventional unset/falsy env fallback
        # (`os.environ.get(k, "") == "1"`), not a drifted default
        if s in ("false", "no", "off", "0", ""):
            return 0.0
        try:
            return float(s)
        except ValueError:
            return s
    return v


def _knob_of_env(key: str) -> str:
    k = key
    for prefix in (ENV_PREFIX, "BENCH_"):
        if k.startswith(prefix):
            k = k[len(prefix):]
    return k.lower()


class ConfigFlowChecker:
    def __init__(self, index: FactIndex, doc_texts: dict[str, str]):
        self.index = index
        self.doc_text = "\n".join(doc_texts.values())
        self.diags: list[Diagnostic] = []
        self.env_reads: list[EnvRead] = []
        self.tables: list[KnobTable] = []
        self.flags: list[CliFlag] = []
        self.fields: list[ConfigField] = []
        self.str_constants: Counter[str] = Counter()  # across all modules

    def _emit(self, mod: ModuleFacts, line: int, code: str, msg: str,
              ctx: str) -> None:
        if mod.suppressions.is_suppressed(line, code):
            return
        self.diags.append(Diagnostic(mod.path, line, code, msg, context=ctx))

    # -- collection -----------------------------------------------------------

    def _collect(self) -> None:
        # a flat type-dispatch over every node in the package: the inner
        # loop is hot (it sees ~every AST node once), so the common case
        # (a constant, or nothing of interest) stays branch-one/branch-two
        counts = self.str_constants
        for mod in self.index.modules.values():
            for node in mod.walk():
                t = node.__class__
                if t is ast.Constant:
                    if node.value.__class__ is str:
                        counts[node.value] += 1
                elif t is ast.Call:
                    self._collect_call(mod, node)
                elif t is ast.Subscript:
                    if _env_receiver(node.value):
                        key = _const_str(node.slice)
                        if key is not None:
                            self.env_reads.append(
                                EnvRead(mod, node.lineno, key))
                elif t is ast.Compare:
                    # "KEY" in os.environ
                    if (len(node.ops) == 1
                            and isinstance(node.ops[0], (ast.In, ast.NotIn))
                            and _env_receiver(node.comparators[0])):
                        key = _const_str(node.left)
                        if key is not None:
                            self.env_reads.append(
                                EnvRead(mod, node.lineno, key))
                elif t is ast.Assign:
                    self._collect_table(mod, node)
                elif t is ast.ClassDef:
                    if node.name in CONFIG_CLASSES:
                        self._collect_config_class(mod, node)

    def _collect_call(self, mod: ModuleFacts, node: ast.Call) -> None:
        f = node.func
        if not isinstance(f, ast.Attribute):
            return
        is_get = f.attr == "get" and _env_receiver(f.value)
        is_getenv = (f.attr == "getenv" and isinstance(f.value, ast.Name)
                     and f.value.id == "os")
        if is_get or is_getenv:
            if not node.args:
                return
            key = _const_str(node.args[0])
            if key is None:
                return
            rec = EnvRead(mod, node.lineno, key)
            if len(node.args) > 1 and isinstance(node.args[1], ast.Constant):
                rec.fallback = node.args[1].value
                # `.get(k, "")` is the unset sentinel for membership-test
                # parses (`.get(k, "").lower() in ("0", "false")`) — it
                # is not the knob's default, so it never enters the
                # KVM134 cross-layer join
                rec.has_fallback = rec.fallback != ""
            self.env_reads.append(rec)
            return
        if f.attr == "add_argument":
            flags = [v for a in node.args
                     if (v := _const_str(a)) is not None
                     and v.startswith("--")]
            default = None
            has_default = False
            for kw in node.keywords:
                if kw.arg == "default" and isinstance(kw.value, ast.Constant):
                    default = kw.value.value
                    has_default = default is not None
            for flag in flags:
                self.flags.append(CliFlag(
                    mod, node.lineno, flag,
                    flag.lstrip("-").replace("-", "_"),
                    default, has_default))

    def _collect_table(self, mod: ModuleFacts, node: ast.Assign) -> None:
        if not (len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id.endswith("ENV_KNOBS")
                and isinstance(node.value, ast.Dict)):
            return
        table = KnobTable(mod, node.targets[0].id, node)
        for k in node.value.keys:
            key = _const_str(k) if k is not None else None
            if key is not None:
                table.keys[key] = k.lineno
        self.tables.append(table)

    def _collect_config_class(self, mod: ModuleFacts,
                              node: ast.ClassDef) -> None:
        for stmt in node.body:
            if isinstance(stmt, ast.AnnAssign) and isinstance(
                    stmt.target, ast.Name):
                name, value = stmt.target.id, stmt.value
            elif (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)):
                name, value = stmt.targets[0].id, stmt.value
            else:
                continue
            if name.startswith("_"):
                continue
            f = ConfigField(mod, node.name, name, stmt.lineno)
            if isinstance(value, ast.Constant) and value.value is not None:
                f.default = value.value
                f.has_default = True
            self.fields.append(f)

    # -- KVM131 ---------------------------------------------------------------

    def _check_unregistered(self) -> None:
        registered = set()
        for t in self.tables:
            registered |= set(t.keys)
        seen: set[tuple[str, str]] = set()
        for r in sorted(self.env_reads,
                        key=lambda r: (r.mod.path, r.line)):
            if not r.key.startswith(ENV_PREFIX) or r.key in registered:
                continue
            if r.key in self.doc_text:
                continue
            if (r.mod.path, r.key) in seen:
                continue  # one finding per (module, key)
            seen.add((r.mod.path, r.key))
            self._emit(
                r.mod, r.line, "KVM131",
                f"env knob `{r.key}` is read here but registered in no "
                "`*_ENV_KNOBS` table and mentioned on no docs page — the "
                "knob works but no operator can discover it; register "
                "it (or document it in docs/API.md), or mark "
                "`# kvmini: config-ok`",
                r.key)

    # -- KVM132 ---------------------------------------------------------------

    def _check_stale_entries(self) -> None:
        read_keys = {r.key for r in self.env_reads}
        for t in self.tables:
            in_table = Counter(
                v for n in ast.walk(t.node)
                if (v := _const_str(n)) is not None)
            for key, line in sorted(t.keys.items()):
                if key in read_keys:
                    continue
                # consumed indirectly (helper call, f-string join) if the
                # literal appears anywhere outside the table assignment
                if self.str_constants[key] > in_table[key]:
                    continue
                self._emit(
                    t.mod, line, "KVM132",
                    f"knob-table entry `{key}` in `{t.name}` has no read "
                    "site — the table documents a knob the code no "
                    "longer honors; delete the entry (or wire the read "
                    "back up), or mark `# kvmini: config-ok`",
                    key)

    # -- KVM133 ---------------------------------------------------------------

    def _mentioned_in_docs(self, *terms: str) -> bool:
        for t in terms:
            if re.search(rf"(?<![\w-]){re.escape(t)}(?![\w-])",
                         self.doc_text):
                return True
        return False

    def _check_unsurfaced(self) -> None:
        env_knobs = {_knob_of_env(r.key) for r in self.env_reads
                     if r.key.startswith((ENV_PREFIX, "BENCH_"))}
        flag_knobs = {f.knob for f in self.flags}
        for f in sorted(self.fields,
                        key=lambda f: (f.mod.path, f.line)):
            dashed = f.name.replace("_", "-")
            via_cli = f.name in flag_knobs
            via_env = f.name in env_knobs
            # profile keys and dict-based plumbing surface the field as a
            # string literal (beyond the dataclass declaration itself)
            via_string = self.str_constants[f.name] > 0
            in_docs = self._mentioned_in_docs(f.name, dashed)
            if not (via_cli or via_env or via_string or in_docs):
                self._emit(
                    f.mod, f.line, "KVM133",
                    f"`{f.cls}.{f.name}` has no CLI flag, env knob, "
                    "profile key, or docs mention — the field exists but "
                    "no operator can set it; surface it (or document "
                    "why it is internal-only), or mark "
                    "`# kvmini: config-ok`",
                    f"{f.cls}.{f.name}")
            elif via_cli and not self._mentioned_in_docs(
                    f.name, dashed, f"--{dashed}"):
                self._emit(
                    f.mod, f.line, "KVM133",
                    f"`{f.cls}.{f.name}` is settable via `--{dashed}` "
                    "but the flag appears on no docs page — document it "
                    "in docs/API.md, or mark `# kvmini: config-ok`",
                    f"{f.cls}.{f.name}")

    # -- KVM134 ---------------------------------------------------------------

    def _check_default_drift(self) -> None:
        # knob name -> list of (source-desc, raw value, mod, line)
        sources: dict[str, list[tuple[str, object, ModuleFacts, int]]] = {}

        def add(knob: str, desc: str, value: object, mod: ModuleFacts,
                line: int) -> None:
            sources.setdefault(knob, []).append((desc, value, mod, line))

        for f in self.fields:
            if f.has_default:
                add(f.name, f"{f.cls} default", f.default, f.mod, f.line)
        for fl in self.flags:
            if fl.has_default:
                add(fl.knob, f"argparse {fl.flag} default=", fl.default,
                    fl.mod, fl.line)
        for r in self.env_reads:
            if r.has_fallback and r.key.startswith((ENV_PREFIX, "BENCH_")):
                add(_knob_of_env(r.key), f"{r.key} fallback", r.fallback,
                    r.mod, r.line)

        for knob in sorted(sources):
            entries = sources[knob]
            # per-LAYER value sets: several tools may declare the same
            # flag with tool-appropriate defaults (bench --seed 42 vs
            # engine seed 0), so drift is judged between layers, and only
            # when two layers share NO value at all — a name collision
            # within one layer is not cross-layer drift
            by_kind: dict[str, set[str]] = {}
            for d, v, *_ in entries:
                by_kind.setdefault(d.split(" ", 1)[0], set()).add(
                    repr(_norm_default(v)))
            kinds = sorted(by_kind)
            if len(kinds) < 2:
                continue  # drift needs two DIFFERENT declaration layers
            if not any(by_kind[a].isdisjoint(by_kind[b])
                       for i, a in enumerate(kinds) for b in kinds[i + 1:]):
                continue
            desc = "; ".join(f"{d} is {v!r}" for d, v, *_ in entries)
            # anchor at the last-declared surface (the one most likely
            # to have drifted from the canonical dataclass default)
            _, _, mod, line = entries[-1]
            self._emit(
                mod, line, "KVM134",
                f"knob `{knob}` declares different defaults across "
                f"layers ({desc}) — which one wins depends on call "
                "path; align them, or mark `# kvmini: config-ok`",
                knob)

    # -- driver ---------------------------------------------------------------

    def run(self) -> list[Diagnostic]:
        self._collect()
        if self.index.full_scan:
            self._check_unregistered()
            self._check_stale_entries()
            self._check_unsurfaced()
        self._check_default_drift()
        return self.diags


def check(index: FactIndex, doc_texts: dict[str, str]) -> list[Diagnostic]:
    return ConfigFlowChecker(index, doc_texts).run()
