"""KVM051-KVM055 — thread-safety and lock discipline.

PRs 1-4 made the toolkit genuinely concurrent: the engine scheduler loop,
the 1 Hz monitor sampler, loadgen workers sharing ``LiveStats``, the
multihost drivers, and per-request server threads all touch shared state.
This family checks the lock discipline those subsystems rely on, in four
layers:

- **Thread-root discovery.** ``threading.Thread(target=...)`` /
  ``Timer`` spawn sites, ``executor.submit`` / ``run_in_executor`` /
  ``asyncio.to_thread`` targets, and the event-loop-root table: aiohttp
  handler registrations (``router.add_get/add_post``), lifecycle
  callbacks (``app.on_startup.append``), created tasks
  (``create_task``/``ensure_future``), ``asyncio.run`` /
  ``run_until_complete`` targets, and ``call_soon(_threadsafe)``
  callbacks — all coalesced into ONE ``event-loop`` root (aiohttp runs
  them on the server's loop thread; one loop per process is the repo
  convention, so loop-vs-loop access is never concurrent).
  Reachability through the cross-file call graph labels every function
  with the roots that can execute it; unreached functions carry the
  implicit ``main`` root. Roots that reach a follower-replayed engine
  method (the fact index's ``run_follower`` scan) coalesce into ONE
  ``lockstep-driver`` root: exactly one driver — the engine's own loop,
  ``run_primary``, or a follower's replay — owns a given engine
  instance, so driver-vs-driver access is never concurrent.
- **Guarded-by inference (KVM051/KVM052).** For each ``self._x``
  touched from >= 2 roots with at least one mutation, infer the lock
  that consistently guards it: ``with self._lock:`` spans, plus
  helper-method indirection (a private method called ONLY from under a
  lock inherits that lock as held-at-entry). No lock anywhere ->
  KVM051; some accesses guarded, others bare (or a different lock) ->
  KVM052. One diagnostic per attribute, anchored where the annotation
  belongs: the foreign access when a single root owns all mutations
  (the benign-snapshot read), else the first mutation.
- **Lock-order analysis (KVM053).** The acquires-while-holding digraph
  across the package (lexical nesting + locks a callee acquires while
  the caller holds one); any cycle — including a non-reentrant
  self-acquire — is a potential deadlock.
- **Primitive misuse (KVM054/KVM055).** ``Event.wait()`` /
  ``Condition.wait()`` with no timeout (a wedged setter hangs the
  waiter forever — awaited asyncio waits are exempt, their timeout is
  ``wait_for``), ``Thread.join()`` with no bound in stop/teardown code
  or ``finally`` blocks, and bare ``return self._x`` of a mutable
  container that another thread mutates (the /traces deque-snapshot bug
  class: iteration races mutation even when every mutation is locked,
  because the raw reference outlives the lock).

Known approximations (under-, never over-reported): only ``self.<attr>``
accesses are attributed (cross-object reads are seen inside the owning
class only); callbacks stored and invoked through untyped fields don't
create call edges; receiver types come from ``self._x = ClassName(...)``
bindings and parameter/attribute annotations (string annotations
included), so an ``Any``-typed receiver contributes no edges.

Suppress intentional single-writer or benign-snapshot designs with
``# kvmini: thread-ok`` (KVM051/054/055) and deliberate asymmetric
guarding with ``# kvmini: lock-ok`` (KVM052/053) — with a one-line
justification, per docs/LINTING.md.
"""

from __future__ import annotations

import ast
import re
import threading
from dataclasses import dataclass, field
from typing import Iterable, Optional

from kserve_vllm_mini_tpu.lint.diagnostics import Diagnostic
from kserve_vllm_mini_tpu.lint.facts import (
    FactIndex,
    FunctionInfo,
    ModuleFacts,
    _last_attr,
    iter_scope,
)

LOCK_CTORS = {"Lock", "RLock"}
WAITABLE_CTORS = {"Event", "Condition", "Barrier"}
# attrs holding these are thread-safe by construction: their methods
# synchronize internally, so KVM051/052 never fire on them
THREADSAFE_CTORS = LOCK_CTORS | WAITABLE_CTORS | {
    "Semaphore", "BoundedSemaphore", "Queue", "SimpleQueue", "LifoQueue",
    "PriorityQueue", "local",
}
THREAD_CTORS = {"Thread", "Timer"}
CONTAINER_CTORS = {"list", "dict", "set", "deque", "defaultdict",
                   "OrderedDict", "Counter"}
MUTATOR_METHODS = {
    "append", "appendleft", "extend", "extendleft", "insert", "add",
    "discard", "remove", "pop", "popleft", "popitem", "clear", "update",
    "setdefault", "sort", "reverse", "rotate",
}
HANDLER_REGISTRARS = {"add_get", "add_post", "add_put", "add_delete",
                      "add_patch", "add_head"}
# aiohttp lifecycle hooks: `app.on_startup.append(fn)` — fn runs ON the
# server's event loop, same execution context as the handlers
LIFECYCLE_HOOKS = {"on_startup", "on_cleanup", "on_shutdown"}
# spawn sites whose target coroutine/callback runs on the calling loop:
# the task factories, plus the blessed thread->loop handoff primitives
TASK_SPAWNERS = {"create_task", "ensure_future"}
LOOP_CALLBACK_METHODS = {"call_soon_threadsafe", "call_soon"}
TEARDOWN_NAME = re.compile(
    r"(^|_)(stop|shutdown|close|teardown|finalize|cleanup|exit)", re.I)
# word-boundary match for a not-statically-typed lock name: a bare
# substring test would classify `self._block` (KV pool!) as a lock and
# both invent KVM052s and mask real KVM051s
_LOCKISH_NAME = re.compile(r"(^|_)(lock|mutex)($|_)", re.I)
MAIN_ROOT = "main"
DRIVER_ROOT = "lockstep-driver"
# ONE coalesced label for everything asyncio runs on a loop: aiohttp
# handlers, lifecycle callbacks, created tasks, run_until_complete/
# asyncio.run targets, and call_soon(_threadsafe) callbacks. The repo
# convention is one loop per process (fleet/router.py's dedicated loop
# thread), so loop-vs-loop access is never concurrent — a two-loop
# design would be under-reported, the checker's stated direction.
LOOP_ROOT = "event-loop"
# functions named like this ARE replay drivers even though nothing spawns
# them as threads in-package (the follower's main thread runs them) —
# treat as pseudo-roots so they never pick up the generic `main` label
REPLAY_DRIVER_PREFIXES = ("run_follower", "run_replica", "run_primary")
# engine convention (runtime/engine.py _run_admin): a callable handed to an
# admin-op executor runs ON the scheduler thread, between sweeps — the
# single-writer discipline bank/registry swaps rely on. Label those
# callables as the driver so their mutations aren't misattributed to the
# submitting thread.
ADMIN_EXECUTOR_METHODS = {"_run_admin"}

_INIT_NAMES = {"__init__", "__post_init__"}


def _self_attr(node: ast.AST) -> Optional[str]:
    """``self.x`` -> "x", else None."""
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _annotation_class_tokens(ann: ast.AST) -> set[str]:
    """Every Name/Attribute token in an annotation, including ones inside
    string annotations ("Optional[LiveStats]")."""
    out: set[str] = set()
    for n in ast.walk(ann):
        if isinstance(n, ast.Name):
            out.add(n.id)
        elif isinstance(n, ast.Attribute):
            out.add(n.attr)
        elif isinstance(n, ast.Constant) and isinstance(n.value, str):
            out.update(re.findall(r"[A-Za-z_]\w*", n.value))
    return out


@dataclass
class ClassInfo:
    """Per-(module, class) attribute kinds, from __init__/method scans."""

    lock_attrs: dict[str, str] = field(default_factory=dict)  # attr -> ctor
    waitable_attrs: set[str] = field(default_factory=set)
    threadsafe_attrs: set[str] = field(default_factory=set)
    thread_attrs: set[str] = field(default_factory=set)
    container_attrs: set[str] = field(default_factory=set)
    instance_types: dict[str, str] = field(default_factory=dict)


@dataclass
class Access:
    mod: ModuleFacts
    fn: FunctionInfo
    attr: str
    line: int
    mutation: bool
    held: frozenset[str]  # lexical with-lock spans at the access site


@dataclass
class CallRecord:
    mod: ModuleFacts
    fn: FunctionInfo
    node: ast.Call
    held: frozenset[str]
    in_finally: bool
    awaited: bool


@dataclass
class AcquireRecord:
    mod: ModuleFacts
    fn: FunctionInfo
    node: ast.AST
    lock: str
    held: frozenset[str]  # locks lexically held when this one is taken


class _FnScanner:
    """One recursive walk of a function's own scope (nested defs excluded,
    lambdas included) tracking held with-locks / finally depth, recording
    attribute accesses, call sites, and lock acquisitions."""

    def __init__(self, checker: "ConcurrencyChecker", mod: ModuleFacts,
                 fn: FunctionInfo) -> None:
        self.c = checker
        self.mod = mod
        self.fn = fn
        self.held: list[str] = []
        self.finally_depth = 0
        self.local_locks: set[str] = set()
        self.local_threads: set[str] = set()
        self.local_waitables: set[str] = set()
        # params annotated with a thread type count as thread-ish receivers
        args = fn.node.args
        for p in args.posonlyargs + args.args + args.kwonlyargs:
            if p.annotation is None:
                continue
            toks = _annotation_class_tokens(p.annotation)
            if "threading" in toks:  # `threading.Thread`, not any `Thread`
                if toks & THREAD_CTORS:
                    self.local_threads.add(p.arg)
                if toks & WAITABLE_CTORS:
                    self.local_waitables.add(p.arg)

    # -- helpers ------------------------------------------------------------

    def _lock_id(self, expr: ast.AST) -> Optional[str]:
        attr = _self_attr(expr)
        if attr is not None and self.fn.class_name:
            ci = self.c.class_info(self.mod.path, self.fn.class_name)
            if attr in ci.lock_attrs or _LOCKISH_NAME.search(attr):
                return f"{self.fn.class_name}.{attr}"
            return None
        if isinstance(expr, ast.Name):
            if expr.id in self.local_locks or _LOCKISH_NAME.search(expr.id):
                return f"{self.mod.path}::{expr.id}"
        return None

    def _record_access(self, attr: str, node: ast.AST, mutation: bool) -> None:
        if self.fn.class_name is None:
            return
        cls = self.fn.class_name
        # method/function-alias/jitted attrs are code, not shared data —
        # but the facts layer records EVERY `self.x = <name>` binding as a
        # potential alias, so only skip when some alias actually resolves
        # to a function (`self._reason = reason` must stay shared data)
        if f"{cls}.{attr}" in self.mod.functions:
            return
        if any(
            self.c.index._resolve_name(self.mod, None, n)
            for n in self.mod.class_attr_fn_aliases.get((cls, attr), ())
        ):
            return
        if (cls, attr) in self.mod.jitted_attrs:
            return
        self.c.accesses.setdefault((self.mod.path, cls, attr), []).append(
            Access(self.mod, self.fn, attr, getattr(node, "lineno", 0),
                   mutation, frozenset(self.held))
        )

    # -- the walk -----------------------------------------------------------

    def scan(self) -> None:
        for stmt in self.fn.node.body:
            self._visit(stmt)

    def _visit_all(self, nodes: Iterable[Optional[ast.AST]]) -> None:
        for n in nodes:
            if n is not None:
                self._visit(n)

    def _visit(self, node: ast.AST, awaited: bool = False) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # nested defs are scanned as their own functions
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired: list[str] = []
            for item in node.items:
                lock = self._lock_id(item.context_expr)
                if lock is not None:
                    self.c.acquires.append(AcquireRecord(
                        self.mod, self.fn, item.context_expr, lock,
                        frozenset(self.held)))
                    acquired.append(lock)
                else:
                    self._visit(item.context_expr)
                if item.optional_vars is not None:
                    self._visit(item.optional_vars)
            self.held.extend(acquired)
            self._visit_all(node.body)
            del self.held[len(self.held) - len(acquired):len(self.held)]
            return
        if isinstance(node, ast.Try):
            self._visit_all(node.body)
            for h in node.handlers:
                self._visit_all(h.body)
            self._visit_all(node.orelse)
            self.finally_depth += 1
            self._visit_all(node.finalbody)
            self.finally_depth -= 1
            return
        if isinstance(node, ast.Await):
            self._visit(node.value, awaited=True)
            return
        if isinstance(node, ast.Assign):
            self._track_locals(node)
            for t in node.targets:
                self._visit_target(t)
            self._visit(node.value)
            return
        if isinstance(node, ast.AugAssign):
            self._visit_target(node.target)
            self._visit(node.value)
            return
        if isinstance(node, ast.AnnAssign):
            self._visit_target(node.target)
            if node.value is not None:
                self._visit(node.value)
            return
        if isinstance(node, ast.Delete):
            for t in node.targets:
                self._visit_target(t)
            return
        if isinstance(node, ast.Call):
            self._visit_call(node, awaited)
            return
        attr = _self_attr(node)
        if attr is not None:
            self._record_access(attr, node, mutation=False)
            return
        self._visit_all(ast.iter_child_nodes(node))

    def _visit_target(self, t: ast.AST) -> None:
        attr = _self_attr(t)
        if attr is not None:
            self._record_access(attr, t, mutation=True)
            return
        if isinstance(t, ast.Subscript):
            base = _self_attr(t.value)
            if base is not None:
                # self.x[k] = v mutates the container behind self.x
                self._record_access(base, t, mutation=True)
            else:
                self._visit(t.value)
            self._visit(t.slice)
            return
        if isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self._visit_target(e)
            return
        if isinstance(t, ast.Starred):
            self._visit_target(t.value)
            return
        self._visit(t)

    def _track_locals(self, node: ast.Assign) -> None:
        v = node.value
        names = [t.id for t in node.targets if isinstance(t, ast.Name)]
        if not names or not isinstance(v, ast.Call):
            return
        ctor = _last_attr(v.func)
        if ctor in LOCK_CTORS:
            self.local_locks.update(names)
        elif ctor in THREAD_CTORS:
            self.local_threads.update(names)
        elif ctor in WAITABLE_CTORS:
            self.local_waitables.update(names)

    def _visit_call(self, node: ast.Call, awaited: bool) -> None:
        self.c.call_records.append(CallRecord(
            self.mod, self.fn, node, frozenset(self.held),
            self.finally_depth > 0, awaited))
        f = node.func
        if isinstance(f, ast.Attribute):
            base = f.value
            if f.attr in MUTATOR_METHODS:
                attr = _self_attr(base)
                if attr is None and isinstance(base, ast.Subscript):
                    # self.x[i].append(...) mutates self.x's contents
                    attr = _self_attr(base.value)
                if attr is not None:
                    self._record_access(attr, node, mutation=True)
        self._visit_all([f] if not isinstance(f, ast.Attribute)
                        else [f.value])
        self._visit_all(node.args)
        self._visit_all(kw.value for kw in node.keywords)


class ConcurrencyChecker:
    def __init__(self, index: FactIndex):
        self.index = index
        self.diags: list[Diagnostic] = []
        self._class_info: dict[tuple[str, str], ClassInfo] = {}
        # class name -> modules defining it (for typed method resolution)
        self._class_defs: dict[str, list[str]] = {}
        self.accesses: dict[tuple[str, str, str], list[Access]] = {}
        self.call_records: list[CallRecord] = []
        self.acquires: list[AcquireRecord] = []
        self._callee_cache: dict[tuple[str, str], list[FunctionInfo]] = {}
        # per-callsite resolution is re-requested by the held-propagation
        # fixpoint and the lock-order pass; memoize on node identity
        self._site_cache: dict[int, list[FunctionInfo]] = {}
        self._param_types: dict[tuple[str, str], dict[str, str]] = {}
        self.labels: dict[tuple[str, str], set[str]] = {}
        self.root_targets: set[tuple[str, str]] = set()
        # pre-coalescing (fn, label) spawn facts — the KVM12x checker
        # layers its event-loop analysis on these (lint/async_flow.py)
        self.raw_roots: list[tuple[FunctionInfo, str]] = []
        self.entry_held: dict[tuple[str, str], Optional[frozenset[str]]] = {}

    # -- phase 0: class facts ------------------------------------------------

    def class_info(self, path: str, cls: str) -> ClassInfo:
        return self._class_info.setdefault((path, cls), ClassInfo())

    def _collect_class_facts(self) -> None:
        # pass 1: register every class first — annotations/ctors in module A
        # may reference classes defined in module B (scanned later)
        for mod in self.index.modules.values():
            for node in mod.walk():
                if isinstance(node, ast.ClassDef):
                    paths = self._class_defs.setdefault(node.name, [])
                    if mod.path not in paths:
                        paths.append(mod.path)
        # pass 2: classify attribute kinds
        for mod in self.index.modules.values():
            # class-body annotations (dataclass fields):
            # `done: threading.Event = field(...)`
            for node in mod.walk():
                if not isinstance(node, ast.ClassDef):
                    continue
                ci = self.class_info(mod.path, node.name)
                for stmt in node.body:
                    if (isinstance(stmt, ast.AnnAssign)
                            and isinstance(stmt.target, ast.Name)):
                        self._classify_annotation(
                            ci, stmt.target.id, stmt.annotation)
            for fn in mod.functions.values():
                if fn.class_name is None:
                    continue
                ci = self.class_info(mod.path, fn.class_name)
                for node in iter_scope(fn.node):
                    if isinstance(node, ast.Assign):
                        for t in node.targets:
                            attr = _self_attr(t)
                            if attr is not None:
                                self._classify_value(ci, attr, node.value,
                                                     in_init=fn.name in _INIT_NAMES)
                                # `self.abort = abort` with an annotated
                                # ctor param carries the param's type
                                if isinstance(node.value, ast.Name):
                                    self._classify_from_param(
                                        ci, attr, node.value.id, fn)
                    elif isinstance(node, ast.AnnAssign):
                        attr = _self_attr(node.target)
                        if attr is not None:
                            self._classify_annotation(ci, attr, node.annotation)
                            if node.value is not None:
                                self._classify_value(ci, attr, node.value,
                                                     in_init=fn.name in _INIT_NAMES)
        # instance types only resolve to classes that actually exist in the
        # scanned tree — a token matching nothing contributes no edges
        for ci in self._class_info.values():
            ci.instance_types = {
                a: c for a, c in ci.instance_types.items()
                if c in self._class_defs
            }

    def _classify_from_param(self, ci: ClassInfo, attr: str, name: str,
                             fn: FunctionInfo) -> None:
        args = fn.node.args
        for p in args.posonlyargs + args.args + args.kwonlyargs:
            if p.arg == name and p.annotation is not None:
                self._classify_annotation(ci, attr, p.annotation)
                return

    def _classify_annotation(self, ci: ClassInfo, attr: str,
                             ann: ast.AST) -> None:
        toks = _annotation_class_tokens(ann)
        # `threading.Thread` / `Optional[threading.Event]` only: a bare
        # `Event` token may be ANY class named Event (the monitor's own
        # Event dataclass) — misclassifying it as a threading primitive
        # would silently exempt real shared state from KVM051/052
        if "threading" in toks:
            if toks & THREAD_CTORS:
                ci.thread_attrs.add(attr)
            if toks & WAITABLE_CTORS:
                ci.waitable_attrs.add(attr)
            if toks & THREADSAFE_CTORS:
                ci.threadsafe_attrs.add(attr)
        for t in sorted(toks):
            if t in self._class_defs:
                ci.instance_types.setdefault(attr, t)
                break

    def _classify_value(self, ci: ClassInfo, attr: str, value: ast.AST,
                        in_init: bool) -> None:
        if isinstance(value, (ast.List, ast.ListComp, ast.Dict, ast.DictComp,
                              ast.Set, ast.SetComp)):
            ci.container_attrs.add(attr)
            return
        if not isinstance(value, ast.Call):
            return
        ctor = _last_attr(value.func)
        if ctor is None:
            return
        if ctor in LOCK_CTORS:
            ci.lock_attrs[attr] = ctor
            ci.threadsafe_attrs.add(attr)
        elif ctor == "Condition":
            ci.lock_attrs[attr] = "Condition"
            ci.waitable_attrs.add(attr)
            ci.threadsafe_attrs.add(attr)
        elif ctor in WAITABLE_CTORS:
            ci.waitable_attrs.add(attr)
            ci.threadsafe_attrs.add(attr)
        elif ctor in THREADSAFE_CTORS:
            ci.threadsafe_attrs.add(attr)
        elif ctor in THREAD_CTORS:
            ci.thread_attrs.add(attr)
        elif ctor in CONTAINER_CTORS:
            ci.container_attrs.add(attr)
        elif ctor in self._class_defs:
            ci.instance_types.setdefault(attr, ctor)

    # -- typed call resolution ----------------------------------------------

    def _methods_of(self, cls: str, name: str) -> list[FunctionInfo]:
        out = []
        for path in self._class_defs.get(cls, []):
            cand = self.index.modules[path].functions.get(f"{cls}.{name}")
            if cand is not None:
                out.append(cand)
        return out

    def _fn_param_types(self, mod: ModuleFacts,
                        fn: FunctionInfo) -> dict[str, str]:
        key = fn.key()
        cached = self._param_types.get(key)
        if cached is not None:
            return cached
        types: dict[str, str] = {}
        args = fn.node.args
        for p in args.posonlyargs + args.args + args.kwonlyargs:
            if p.annotation is None:
                continue
            for t in _annotation_class_tokens(p.annotation):
                if t in self._class_defs:
                    types[p.arg] = t
                    break
        # local `x = ClassName(...)` bindings
        for node in iter_scope(fn.node):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                ctor = _last_attr(node.value.func)
                if ctor in self._class_defs:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            types[t.id] = ctor
        self._param_types[key] = types
        return types

    def _callees(self, mod: ModuleFacts, fn: FunctionInfo,
                 call: ast.Call) -> list[FunctionInfo]:
        cached = self._site_cache.get(id(call))
        if cached is not None:
            return cached
        out = self._callees_uncached(mod, fn, call)
        self._site_cache[id(call)] = out
        return out

    def _callees_uncached(self, mod: ModuleFacts, fn: FunctionInfo,
                          call: ast.Call) -> list[FunctionInfo]:
        resolved = self.index.resolve_call(mod, fn, call)
        if resolved:
            return resolved
        f = call.func
        if not isinstance(f, ast.Attribute):
            return []
        recv = f.value
        cls: Optional[str] = None
        attr = _self_attr(recv)
        if attr is not None and fn.class_name:
            ci = self.class_info(mod.path, fn.class_name)
            cls = ci.instance_types.get(attr)
        elif isinstance(recv, ast.Name):
            cls = self._fn_param_types(mod, fn).get(recv.id)
        if cls is None:
            return []
        return self._methods_of(cls, f.attr)

    def _fn_callees(self, mod: ModuleFacts,
                    fn: FunctionInfo) -> list[FunctionInfo]:
        key = fn.key()
        cached = self._callee_cache.get(key)
        if cached is not None:
            return cached
        out: list[FunctionInfo] = []
        seen: set[tuple[str, str]] = set()
        for cs in self.index.call_sites(mod, fn):
            for callee in self._callees(mod, fn, cs.node):
                if callee.key() not in seen:
                    seen.add(callee.key())
                    out.append(callee)
        self._callee_cache[key] = out
        return out

    # -- phase 1: thread roots + reachability labels ------------------------

    def _resolve_target(self, mod: ModuleFacts, fn: FunctionInfo,
                        expr: ast.AST) -> list[FunctionInfo]:
        if isinstance(expr, ast.Call) and _last_attr(expr.func) == "partial":
            if expr.args:
                return self._resolve_target(mod, fn, expr.args[0])
            return []
        return self.index._resolve_expr(mod, fn, expr)

    def _resolve_coro(self, mod: ModuleFacts, fn: FunctionInfo,
                      expr: ast.AST) -> list[FunctionInfo]:
        """A coroutine OBJECT argument (`create_task(self._scoreboard())`,
        `loop.run_until_complete(boot())`) resolves through the inner
        call's func — the called coroutine function is what the loop
        runs. A bare name (an already-created coro bound locally) falls
        back to plain target resolution."""
        if isinstance(expr, ast.Call):
            return self._resolve_target(mod, fn, expr.func)
        return self._resolve_target(mod, fn, expr)

    def _discover_roots(self) -> list[tuple[FunctionInfo, str]]:
        roots: list[tuple[FunctionInfo, str]] = []
        for mod in self.index.modules.values():
            for fn in mod.functions.values():
                if fn.name.startswith(REPLAY_DRIVER_PREFIXES):
                    roots.append((fn, DRIVER_ROOT))
                for node in iter_scope(fn.node):
                    if not isinstance(node, ast.Call):
                        continue
                    roots.extend(self._roots_from_call(mod, fn, node))
        return roots

    def _roots_from_call(self, mod: ModuleFacts, fn: FunctionInfo,
                         node: ast.Call) -> list[tuple[FunctionInfo, str]]:
        out: list[tuple[FunctionInfo, str]] = []
        ctor = _last_attr(node.func)
        if ctor in THREAD_CTORS:
            target = None
            label = None
            for kw in node.keywords:
                if kw.arg == "target":
                    target = kw.value
                if kw.arg == "name" and isinstance(kw.value, ast.Constant) \
                        and isinstance(kw.value.value, str):
                    label = kw.value.value
            if target is None and ctor == "Timer" and len(node.args) > 1:
                target = node.args[1]
            if target is not None:
                for t in self._resolve_target(mod, fn, target):
                    out.append((t, label or f"thread:{t.name}"))
            return out
        f = node.func
        if isinstance(f, ast.Attribute):
            if f.attr == "submit" and node.args:
                for t in self._resolve_target(mod, fn, node.args[0]):
                    out.append((t, f"pool:{t.name}"))
            elif f.attr == "run_in_executor" and len(node.args) > 1:
                for t in self._resolve_target(mod, fn, node.args[1]):
                    out.append((t, f"pool:{t.name}"))
            elif f.attr in HANDLER_REGISTRARS and len(node.args) > 1:
                for t in self._resolve_target(mod, fn, node.args[1]):
                    out.append((t, LOOP_ROOT))
            elif f.attr == "add_route" and len(node.args) > 2:
                for t in self._resolve_target(mod, fn, node.args[2]):
                    out.append((t, LOOP_ROOT))
            elif (f.attr == "append" and node.args
                  and isinstance(f.value, ast.Attribute)
                  and f.value.attr in LIFECYCLE_HOOKS):
                # app.on_startup.append(boot_cb): runs on the server loop
                for t in self._resolve_target(mod, fn, node.args[0]):
                    out.append((t, LOOP_ROOT))
            elif f.attr in TASK_SPAWNERS and node.args:
                for t in self._resolve_coro(mod, fn, node.args[0]):
                    out.append((t, LOOP_ROOT))
            elif f.attr in LOOP_CALLBACK_METHODS and node.args:
                for t in self._resolve_target(mod, fn, node.args[0]):
                    out.append((t, LOOP_ROOT))
            elif f.attr == "run_until_complete" and node.args:
                for t in self._resolve_coro(mod, fn, node.args[0]):
                    out.append((t, LOOP_ROOT))
            elif (f.attr == "run" and node.args
                  and isinstance(f.value, ast.Name)
                  and f.value.id == "asyncio"):
                # asyncio.run(main()) — NOT subprocess.run, hence the
                # explicit receiver check
                for t in self._resolve_coro(mod, fn, node.args[0]):
                    out.append((t, LOOP_ROOT))
            elif f.attr in ADMIN_EXECUTOR_METHODS and node.args:
                for t in self._resolve_target(mod, fn, node.args[0]):
                    out.append((t, DRIVER_ROOT))
        if _last_attr(node.func) == "to_thread" and node.args:
            for t in self._resolve_target(mod, fn, node.args[0]):
                out.append((t, f"pool:{t.name}"))
        if (isinstance(node.func, ast.Name)
                and node.func.id in TASK_SPAWNERS and node.args):
            # from asyncio import create_task — the bare-name spelling
            for t in self._resolve_coro(mod, fn, node.args[0]):
                out.append((t, LOOP_ROOT))
        return out

    def _reach(self, start: FunctionInfo) -> set[tuple[str, str]]:
        seen = {start.key()}
        work = [start]
        while work:
            fn = work.pop()
            mod = self.index.modules.get(fn.path)
            if mod is None:
                continue
            for callee in self._fn_callees(mod, fn):
                ck = callee.key()
                # a root target's execution context is its own root, not
                # the caller's — don't propagate through the boundary
                if ck in seen or ck in self.root_targets:
                    continue
                seen.add(ck)
                work.append(callee)
        return seen

    def _label_functions(self) -> None:
        raw_roots = self._discover_roots()
        self.raw_roots = raw_roots
        self.root_targets = {fn.key() for fn, _ in raw_roots}
        replayed = self.index.follower_replayed_methods()
        reach_cache: dict[tuple[str, str], set[tuple[str, str]]] = {}
        for fn, label in raw_roots:
            if fn.key() not in reach_cache:
                reach_cache[fn.key()] = self._reach(fn)
            reached = reach_cache[fn.key()]
            # driver coalescing: one engine instance has exactly one driver
            if any(
                self.index.modules[p].functions[q].name in replayed
                and self.index.modules[p].functions[q].class_name is not None
                for p, q in reached
            ):
                label = DRIVER_ROOT
            for key in reached:
                self.labels.setdefault(key, set()).add(label)
        # implicit main: everything no spawned root reaches
        main_seeds = [
            fn for fn in self.index.functions()
            if not self.labels.get(fn.key())
            and fn.key() not in self.root_targets
        ]
        seen: set[tuple[str, str]] = set()
        work = list(main_seeds)
        for fn in main_seeds:
            seen.add(fn.key())
        while work:
            fn = work.pop()
            self.labels.setdefault(fn.key(), set()).add(MAIN_ROOT)
            mod = self.index.modules.get(fn.path)
            if mod is None:
                continue
            for callee in self._fn_callees(mod, fn):
                ck = callee.key()
                if ck in seen or ck in self.root_targets:
                    continue
                seen.add(ck)
                work.append(callee)

    def _fn_labels(self, fn: FunctionInfo) -> frozenset[str]:
        return frozenset(self.labels.get(fn.key(), {MAIN_ROOT}))

    # -- phase 2: scan function bodies --------------------------------------

    def _scan_functions(self) -> None:
        for mod in self.index.modules.values():
            for fn in mod.functions.values():
                if fn.name in _INIT_NAMES:
                    continue  # pre-publication: the object isn't shared yet
                _FnScanner(self, mod, fn).scan()

    # -- phase 3: held-at-entry propagation (helper-method indirection) -----

    def _propagate_held(self) -> None:
        for _ in range(4):
            changed = False
            for rec in self.call_records:
                eff = rec.held | (self.entry_held.get(rec.fn.key())
                                  or frozenset())
                for callee in self._callees(rec.mod, rec.fn, rec.node):
                    # only private same-class helpers: a public method is
                    # callable from anywhere, including lock-free paths the
                    # index never sees
                    if (callee.class_name is None
                            or callee.class_name != rec.fn.class_name
                            or not callee.name.startswith("_")
                            or callee.key() in self.root_targets):
                        continue
                    prev = self.entry_held.get(callee.key())
                    new = eff if prev is None else (prev & eff)
                    if new != prev:
                        self.entry_held[callee.key()] = new
                        changed = True
            if not changed:
                return

    def _guards(self, a: Access) -> frozenset[str]:
        return a.held | (self.entry_held.get(a.fn.key()) or frozenset())

    # -- emission helpers ---------------------------------------------------

    def _emit(self, mod: ModuleFacts, line: int, code: str, msg: str,
              ctx: str) -> None:
        if mod.suppressions.is_suppressed(line, code):
            return
        self.diags.append(Diagnostic(mod.path, line, code, msg, context=ctx))

    # -- KVM051 / KVM052 ----------------------------------------------------

    def _check_guarded_by(self) -> None:
        for (path, cls, attr), accs in sorted(self.accesses.items()):
            ci = self.class_info(path, cls)
            if attr in ci.threadsafe_attrs or attr in ci.thread_attrs:
                continue
            muts = [a for a in accs if a.mutation]
            if not muts:
                continue
            roots: set[str] = set()
            for a in accs:
                roots |= self._fn_labels(a.fn)
            if len(roots) < 2:
                continue
            if LOOP_ROOT in roots and any(
                    r.startswith(("thread:", "pool:")) or r == DRIVER_ROOT
                    for r in roots):
                # loop-vs-thread sharing is KVM123's jurisdiction
                # (lint/async_flow.py): the right fix there is
                # call_soon_threadsafe routing, not "add a lock", so a
                # KVM051 here would prescribe the wrong remedy
                continue
            if roots <= {LOOP_ROOT, MAIN_ROOT}:
                # event-loop + main are temporally exclusive: main-rooted
                # code only coexists with a running loop by blocking in
                # asyncio.run()/run_until_complete() (a loop run on a
                # spawned thread carries a thread:/pool: root instead),
                # so the CLI's read-after-run pattern cannot race
                continue
            guard_sets = [self._guards(a) for a in accs]
            common = frozenset.intersection(*guard_sets)
            if common:
                continue  # one lock consistently guards every access
            accs_sorted = sorted(accs, key=lambda a: (a.mod.path, a.line))
            ctx = f"{cls}.{attr}"
            rootlist = ", ".join(sorted(roots))
            if not any(guard_sets):
                # no lock anywhere: anchor where the annotation belongs —
                # the foreign access when one root owns every mutation (the
                # benign-snapshot read), else the MINORITY root's mutation
                # (the unusual thread's write, e.g. a gauge updated from the
                # submit path while the scheduler owns everything else)
                mut_labels = {self._fn_labels(a.fn) for a in muts}
                if len(mut_labels) == 1:
                    anchor = min(
                        (a for a in accs_sorted
                         if self._fn_labels(a.fn) != next(iter(mut_labels))),
                        key=lambda a: (a.mod.path, a.line),
                        default=min(muts, key=lambda a: (a.mod.path, a.line)),
                    )
                else:
                    groups: dict[frozenset[str], list[Access]] = {}
                    for a in muts:
                        groups.setdefault(self._fn_labels(a.fn), []).append(a)
                    _, minority = min(
                        groups.items(),
                        key=lambda kv: (len(kv[1]), tuple(sorted(kv[0]))),
                    )
                    anchor = min(minority,
                                 key=lambda a: (a.mod.path, a.line))
                self._emit(
                    anchor.mod, anchor.line, "KVM051",
                    f"`self.{attr}` is mutated and shared across threads "
                    f"({rootlist}) with no lock guarding any access — a "
                    "torn read/lost update is a matter of timing; guard "
                    "every access with one lock or mark the intentional "
                    "single-writer design `# kvmini: thread-ok`",
                    ctx)
            else:
                # deterministic tiebreak on the lock name: set iteration
                # order is hash-randomized, and a flapping `best` would
                # move the anchored line between runs
                best = max(
                    sorted({g for gs in guard_sets for g in gs}),
                    key=lambda lk: sum(1 for gs in guard_sets if lk in gs),
                )
                bare = min(
                    (a for a, gs in zip(accs_sorted,
                                        [self._guards(a) for a in accs_sorted])
                     if best not in gs),
                    key=lambda a: (a.mod.path, a.line),
                )
                kind = "written" if bare.mutation else "read"
                self._emit(
                    bare.mod, bare.line, "KVM052",
                    f"`self.{attr}` is guarded by `{best}` elsewhere but "
                    f"{kind} bare here (threads: {rootlist}) — inconsistent "
                    "guarding protects nothing; take the same lock or mark "
                    "`# kvmini: lock-ok`",
                    ctx)

    # -- KVM053 -------------------------------------------------------------

    def _acquired_transitive(self) -> dict[tuple[str, str], set[str]]:
        """Locks each function may acquire, directly or via callees."""
        direct: dict[tuple[str, str], set[str]] = {}
        for rec in self.acquires:
            direct.setdefault(rec.fn.key(), set()).add(rec.lock)
        trans = {k: set(v) for k, v in direct.items()}
        for _ in range(6):
            changed = False
            for mod in self.index.modules.values():
                for fn in mod.functions.values():
                    mine = trans.setdefault(fn.key(), set())
                    for callee in self._fn_callees(mod, fn):
                        extra = trans.get(callee.key())
                        if extra and not extra <= mine:
                            mine |= extra
                            changed = True
            if not changed:
                break
        return trans

    def _check_lock_order(self) -> None:
        edges: dict[tuple[str, str], tuple[ModuleFacts, int]] = {}

        def add_edge(a: str, b: str, mod: ModuleFacts, line: int) -> None:
            if (a, b) not in edges:
                edges[(a, b)] = (mod, line)

        rlocks = {
            f"{cls}.{attr}"
            for (_p, cls), ci in self._class_info.items()
            for attr, ctor in ci.lock_attrs.items() if ctor == "RLock"
        }
        for rec in self.acquires:
            held = rec.held | (self.entry_held.get(rec.fn.key())
                               or frozenset())
            for h in held:
                if h == rec.lock and h in rlocks:
                    continue  # re-entrant self-acquire is legal
                add_edge(h, rec.lock, rec.mod,
                         getattr(rec.node, "lineno", 0))
        trans = self._acquired_transitive()
        for rec in self.call_records:
            held = rec.held | (self.entry_held.get(rec.fn.key())
                               or frozenset())
            if not held:
                continue
            for callee in self._callees(rec.mod, rec.fn, rec.node):
                for lk in trans.get(callee.key(), ()):
                    for h in held:
                        if h == lk and h in rlocks:
                            continue
                        add_edge(h, lk, rec.mod,
                                 getattr(rec.node, "lineno", 0))
        # cycle detection over the digraph; one diagnostic per cycle
        adj: dict[str, set[str]] = {}
        for (a, b) in edges:
            adj.setdefault(a, set()).add(b)
        seen_cycles: set[frozenset[str]] = set()
        for start in sorted(adj):
            stack = [(start, [start])]
            while stack:
                node, pathway = stack.pop()
                for nxt in sorted(adj.get(node, ())):
                    if nxt == start:
                        cyc = frozenset(pathway)
                        if cyc in seen_cycles:
                            continue
                        seen_cycles.add(cyc)
                        cycle_edges = list(zip(pathway,
                                               pathway[1:] + [start]))
                        mod, line = min(
                            (edges[e] for e in cycle_edges if e in edges),
                            key=lambda ml: (ml[0].path, ml[1]),
                        )
                        order = " -> ".join(pathway + [start])
                        self._emit(
                            mod, line, "KVM053",
                            f"lock-order cycle {order}: two threads taking "
                            "these locks in opposite order deadlock; pick "
                            "one global order or mark `# kvmini: lock-ok`",
                            "->".join(sorted(cyc)))
                    elif nxt not in pathway and len(pathway) < 6:
                        stack.append((nxt, pathway + [nxt]))

    # -- KVM054 -------------------------------------------------------------

    def _check_primitives(self) -> None:
        for rec in self.call_records:
            f = rec.node.func
            if not isinstance(f, ast.Attribute):
                continue
            has_bound = bool(rec.node.args) or any(
                kw.arg == "timeout" for kw in rec.node.keywords)
            if f.attr == "wait" and not has_bound and not rec.awaited:
                if self._is_waitable(rec):
                    self._emit(
                        rec.mod, rec.node.lineno, "KVM054",
                        f"`{ast.unparse(f.value)}.wait()` without a timeout "
                        f"in `{rec.fn.name}` — if the setter dies this "
                        "blocks forever; pass a timeout and handle the "
                        "False return, or mark `# kvmini: thread-ok`",
                        rec.fn.qualname)
            elif f.attr == "join" and not has_bound:
                if not self._is_threadish(rec):
                    continue
                if TEARDOWN_NAME.search(rec.fn.name) or rec.in_finally:
                    self._emit(
                        rec.mod, rec.node.lineno, "KVM054",
                        f"unbounded `{ast.unparse(f.value)}.join()` in "
                        f"teardown path `{rec.fn.name}` — a wedged worker "
                        "hangs shutdown; join with a timeout (and surface "
                        "a still-alive thread), or mark "
                        "`# kvmini: thread-ok`",
                        rec.fn.qualname)

    def _is_waitable(self, rec: CallRecord) -> bool:
        recv = rec.node.func.value  # type: ignore[union-attr]
        attr = _self_attr(recv)
        if attr is not None and rec.fn.class_name:
            ci = self.class_info(rec.mod.path, rec.fn.class_name)
            return attr in ci.waitable_attrs
        if isinstance(recv, ast.Name):
            # conservatively: locally-created Events/Conditions only
            return any(
                isinstance(n, ast.Assign)
                and isinstance(n.value, ast.Call)
                and _last_attr(n.value.func) in WAITABLE_CTORS
                and any(isinstance(t, ast.Name) and t.id == recv.id
                        for t in n.targets)
                for n in iter_scope(rec.fn.node)
            )
        return False

    def _is_threadish(self, rec: CallRecord) -> bool:
        recv = rec.node.func.value  # type: ignore[union-attr]
        attr = _self_attr(recv)
        if attr is not None and rec.fn.class_name:
            ci = self.class_info(rec.mod.path, rec.fn.class_name)
            return attr in ci.thread_attrs
        if isinstance(recv, ast.Name):
            scanner_types = _FnScanner(self, rec.mod, rec.fn)
            if recv.id in scanner_types.local_threads:
                return True
            return any(
                isinstance(n, ast.Assign)
                and isinstance(n.value, ast.Call)
                and _last_attr(n.value.func) in THREAD_CTORS
                and any(isinstance(t, ast.Name) and t.id == recv.id
                        for t in n.targets)
                for n in iter_scope(rec.fn.node)
            )
        return False

    # -- KVM055 -------------------------------------------------------------

    def _check_publication(self) -> None:
        for mod in self.index.modules.values():
            for fn in mod.functions.values():
                if fn.class_name is None or fn.name in _INIT_NAMES:
                    continue
                ci = self.class_info(mod.path, fn.class_name)
                for node in iter_scope(fn.node):
                    if not isinstance(node, ast.Return) or node.value is None:
                        continue
                    attr = _self_attr(node.value)
                    if attr is None or attr not in ci.container_attrs:
                        continue
                    accs = self.accesses.get(
                        (mod.path, fn.class_name, attr), [])
                    if not any(a.mutation for a in accs):
                        continue
                    roots: set[str] = set()
                    for a in accs:
                        roots |= self._fn_labels(a.fn)
                    roots |= self._fn_labels(fn)
                    if len(roots) < 2:
                        continue
                    self._emit(
                        mod, node.lineno, "KVM055",
                        f"`{fn.name}` returns `self.{attr}` — a live "
                        "mutable container another thread mutates "
                        f"({', '.join(sorted(roots))}); the raw reference "
                        "outlives any lock and iteration races mutation "
                        "(the /traces deque bug class); return a snapshot "
                        "(`list(...)`) or mark `# kvmini: thread-ok`",
                        f"{fn.class_name}.{attr}")

    # -- driver --------------------------------------------------------------

    def run_facts(self) -> "ConcurrencyChecker":
        self._collect_class_facts()
        self._label_functions()
        self._scan_functions()
        self._propagate_held()
        return self

    def run(self) -> list[Diagnostic]:
        self.run_facts()
        self._check_guarded_by()
        self._check_lock_order()
        self._check_primitives()
        self._check_publication()
        return self.diags


_FACTS_LOCK = threading.Lock()


def shared_facts(index: FactIndex) -> ConcurrencyChecker:
    """The fact phases (class kinds, root labels incl. the event-loop
    table, per-access records, held-lock propagation) memoized per index:
    KVM05x and the KVM12x async-flow family both reason from these facts,
    and on a full-package scan the phases cost more than either family's
    checks. The lock makes the build once-only when the two families run
    on concurrent checker threads; after it, every consumer is
    read-only (the label/guard caches are idempotent inserts)."""
    with _FACTS_LOCK:
        cached = getattr(index, "_kvmini_concurrency_facts", None)
        if cached is None:
            cached = ConcurrencyChecker(index).run_facts()
            index._kvmini_concurrency_facts = cached
        return cached


def check(index: FactIndex) -> list[Diagnostic]:
    c = shared_facts(index)
    c._check_guarded_by()
    c._check_lock_order()
    c._check_primitives()
    c._check_publication()
    return c.diags
