"""The cross-file fact index kvmini-lint's checkers share.

One ``ast.parse`` per file, then cheap linear walks that record:

- every function/method (qualname, params, decorators, nesting),
- import aliases (``np`` -> ``numpy``, ``rt_tracing`` -> ``...tracing``),
- which functions are **jit roots** (decorated with / wrapped by
  ``jax.jit``/``pjit``/``shard_map``, including the repo's dominant
  ``@partial(jax.jit, ...)`` inner-def idiom) plus their static args,
- which bindings *hold* jitted callables (``self._prefill_fns[key] =
  prefill``, ``self._cache = jax.jit(...)``) and which functions
  *return* them — so checkers can tell "this host function dispatches
  compiled work" (the decode hot path) from ordinary host code,
- a name-resolution-lite call graph: callee candidates per callsite with
  positional/keyword argument mapping, enough for the jit-purity
  checker's cross-function taint propagation,
- which engine methods a multihost follower replays (``engine.<m>(...)``
  inside ``run_follower``-named functions), anchoring the lockstep rules.

Resolution is deliberately approximate (no full type inference): a call
resolves to same-scope defs, same-class methods via ``self.``, class
attribute aliases (``self._fwd = forward``), ``from``-imports, and
module-alias attributes. Unresolved calls simply contribute no edges —
checkers under-approximate rather than guess.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterable, Optional

from kserve_vllm_mini_tpu.lint.diagnostics import Suppressions

JIT_WRAPPER_NAMES = {"jit", "pjit", "shard_map"}


def iter_scope(fn_node: ast.AST):
    """Walk a function's own scope: every descendant EXCEPT the bodies of
    nested function/class definitions (each nested def is analyzed as its
    own FunctionInfo, so descending here would double-report and leak
    the outer scope's taint into the inner one). Lambdas are NOT excluded:
    they get no FunctionInfo of their own, so their (expression-only)
    bodies are checked as part of the enclosing scope — a `.item()` inside
    an inline lambda is still a host sync at this site.

    The walk is memoized on the node: with fourteen checker families each
    re-walking every function, generator re-walks were the single largest
    cost in the full-package profile. The cached tuple preserves the
    exact historical yield order (DFS, reversed child order), so findings
    are byte-identical; the store is an idempotent single attribute
    write, safe under concurrent checker threads."""
    cached = getattr(fn_node, "_kvmini_scope", None)
    if cached is None:
        out = []
        stack = list(ast.iter_child_nodes(fn_node))
        while stack:
            n = stack.pop()
            out.append(n)
            if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                stack.extend(ast.iter_child_nodes(n))
        cached = tuple(out)
        fn_node._kvmini_scope = cached
    return cached


def _last_attr(node: ast.AST) -> Optional[str]:
    """`jax.jit` -> "jit", `jit` -> "jit", anything else -> None."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _is_jit_wrapper(node: ast.AST) -> bool:
    return _last_attr(node) in JIT_WRAPPER_NAMES


def _argnum_kwargs(call: ast.Call, num_key: str,
                   name_key: str) -> tuple[set[int], set[str]]:
    nums: set[int] = set()
    names: set[str] = set()
    for kw in call.keywords:
        if kw.arg == num_key and isinstance(kw.value, (ast.Tuple, ast.List)):
            nums |= {e.value for e in kw.value.elts
                     if isinstance(e, ast.Constant) and isinstance(e.value, int)}
        if kw.arg == name_key and isinstance(kw.value, (ast.Tuple, ast.List)):
            names |= {e.value for e in kw.value.elts
                      if isinstance(e, ast.Constant) and isinstance(e.value, str)}
    return nums, names


def _static_args_from_call(call: ast.Call) -> tuple[set[int], set[str]]:
    return _argnum_kwargs(call, "static_argnums", "static_argnames")


def _donate_args_from_call(call: ast.Call) -> tuple[set[int], set[str]]:
    return _argnum_kwargs(call, "donate_argnums", "donate_argnames")


# type-annotation tokens that can carry traced array data; a param whose
# annotation mentions NONE of these is host-static config (ModelConfig,
# Mesh, int, bool, str...) and never carries a tracer
ARRAYISH_ANNOTATION_TOKENS = {
    "ndarray", "Array", "ArrayLike", "Params", "Any", "dict", "Dict",
    "Mapping", "list", "List", "tuple", "Tuple", "Sequence", "PyTree",
    "object", "Tracer",
}


def _annotation_is_static(ann: Optional[ast.AST]) -> bool:
    if ann is None:
        return False
    for n in ast.walk(ann):
        if isinstance(n, ast.Name) and n.id in ARRAYISH_ANNOTATION_TOKENS:
            return False
        if isinstance(n, ast.Attribute) and n.attr in ARRAYISH_ANNOTATION_TOKENS:
            return False
        if isinstance(n, ast.Constant) and isinstance(n.value, str) and any(
                tok in n.value for tok in ARRAYISH_ANNOTATION_TOKENS):
            return False
    return True


@dataclass
class FunctionInfo:
    path: str
    qualname: str
    node: ast.AST  # FunctionDef | AsyncFunctionDef
    class_name: Optional[str]
    parent: Optional["FunctionInfo"]
    params: list[str] = field(default_factory=list)
    annotated_static: set[str] = field(default_factory=set)
    jit_root: bool = False
    static_argnums: set[int] = field(default_factory=set)
    static_argnames: set[str] = field(default_factory=set)
    donated_argnums: set[int] = field(default_factory=set)
    donated_argnames: set[str] = field(default_factory=set)
    returns_jitted: bool = False
    # the specific jit-root FunctionInfos a jitted-returning getter hands
    # out (so donation-aware checkers can map a `fn = self._get_step()`
    # binding back to the root's donate_argnums)
    returned_jit_roots: list["FunctionInfo"] = field(default_factory=list)
    # local names / `self.<attr>`s this function binds to other functions
    # (one level of alias, enclosing scopes chained at lookup time)
    local_aliases: dict[str, list[ast.AST]] = field(default_factory=dict)

    @property
    def name(self) -> str:
        return self.qualname.rsplit(".", 1)[-1]

    def key(self) -> tuple[str, str]:
        return (self.path, self.qualname)


@dataclass
class ModuleFacts:
    path: str  # repo-relative, forward slashes
    source: str
    tree: ast.Module
    suppressions: Suppressions
    functions: dict[str, FunctionInfo] = field(default_factory=dict)
    import_aliases: dict[str, str] = field(default_factory=dict)   # np -> numpy
    from_imports: dict[str, tuple[str, str]] = field(default_factory=dict)
    # (class, attr) -> names of functions it aliases (self._fwd = forward)
    class_attr_fn_aliases: dict[tuple[str, str], list[str]] = field(default_factory=dict)
    # bindings that hold jit-compiled callables: local/module names,
    # (class, attr) pairs, and (class, attr) dicts subscript-assigned
    jitted_names: set[str] = field(default_factory=set)
    jitted_attrs: set[tuple[str, str]] = field(default_factory=set)
    # memoized full-tree walk — several families scan every module node;
    # one materialized tuple replaces a dozen generator re-walks (same
    # ast.walk BFS order, so findings are byte-identical). Idempotent
    # single-attribute store: safe under concurrent checker threads.
    _walk_cache: Optional[tuple] = field(default=None, repr=False,
                                         compare=False)

    def walk(self) -> tuple:
        if self._walk_cache is None:
            self._walk_cache = tuple(ast.walk(self.tree))
        return self._walk_cache


class _ModuleWalker(ast.NodeVisitor):
    def __init__(self, facts: ModuleFacts):
        self.f = facts
        self.class_stack: list[str] = []
        self.fn_stack: list[FunctionInfo] = []

    # -- imports ------------------------------------------------------------
    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            self.f.import_aliases[a.asname or a.name.split(".")[0]] = a.name

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        mod = node.module or ""
        for a in node.names:
            self.f.from_imports[a.asname or a.name] = (mod, a.name)
        self.generic_visit(node)

    # -- classes / functions ------------------------------------------------
    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.class_stack.append(node.name)
        self.generic_visit(node)
        self.class_stack.pop()

    def _qualname(self, name: str) -> str:
        parts = []
        if self.fn_stack:
            parts.append(self.fn_stack[-1].qualname + ".<locals>")
        elif self.class_stack:
            parts.append(".".join(self.class_stack))
        parts.append(name)
        return ".".join(parts)

    def _handle_def(self, node) -> None:
        info = FunctionInfo(
            path=self.f.path,
            qualname=self._qualname(node.name),
            node=node,
            class_name=self.class_stack[-1] if self.class_stack else None,
            parent=self.fn_stack[-1] if self.fn_stack else None,
        )
        a = node.args
        all_args = a.posonlyargs + a.args + a.kwonlyargs
        info.params = [p.arg for p in all_args]
        info.annotated_static = {
            p.arg for p in all_args if _annotation_is_static(p.annotation)
        }
        for dec in node.decorator_list:
            if _is_jit_wrapper(dec):
                info.jit_root = True
            elif isinstance(dec, ast.Call):
                if _is_jit_wrapper(dec.func) or (
                    _last_attr(dec.func) == "partial"
                    and any(_is_jit_wrapper(x) for x in dec.args)
                ):
                    info.jit_root = True
                    nums, names = _static_args_from_call(dec)
                    info.static_argnums |= nums
                    info.static_argnames |= names
                    dnums, dnames = _donate_args_from_call(dec)
                    info.donated_argnums |= dnums
                    info.donated_argnames |= dnames
        self.f.functions[info.qualname] = info
        self.fn_stack.append(info)
        self.generic_visit(node)
        self.fn_stack.pop()

    visit_FunctionDef = _handle_def
    visit_AsyncFunctionDef = _handle_def

    # -- bindings -----------------------------------------------------------
    def _alias_candidates(self, value: ast.AST) -> list[ast.AST]:
        """Expressions a binding may refer to, through IfExp/BoolOp."""
        if isinstance(value, ast.IfExp):
            return self._alias_candidates(value.body) + self._alias_candidates(value.orelse)
        if isinstance(value, ast.BoolOp):
            out: list[ast.AST] = []
            for v in value.values:
                out += self._alias_candidates(v)
            return out
        if isinstance(value, (ast.Name, ast.Attribute, ast.Call)):
            return [value]
        return []

    def _value_is_jitted(self, value: ast.AST) -> bool:
        if isinstance(value, ast.Call) and _is_jit_wrapper(value.func):
            return True
        if isinstance(value, ast.Name):
            fn = self._lookup_fn(value.id)
            if fn is not None and fn.jit_root:
                return True
            return value.id in self.f.jitted_names
        return False

    def _lookup_fn(self, name: str) -> Optional[FunctionInfo]:
        # nested defs of the current function chain, then module scope
        for fi in reversed(self.fn_stack):
            cand = self.f.functions.get(fi.qualname + ".<locals>." + name)
            if cand is not None:
                return cand
        return self.f.functions.get(name)

    def visit_Assign(self, node: ast.Assign) -> None:
        cands = self._alias_candidates(node.value)
        jitted = self._value_is_jitted(node.value)
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                if self.fn_stack:
                    self.fn_stack[-1].local_aliases.setdefault(tgt.id, []).extend(cands)
                if jitted:
                    self.f.jitted_names.add(tgt.id)
            elif (isinstance(tgt, ast.Attribute)
                  and isinstance(tgt.value, ast.Name) and tgt.value.id == "self"
                  and self.class_stack):
                cls = self.class_stack[-1]
                for c in cands:
                    if isinstance(c, ast.Name):
                        self.f.class_attr_fn_aliases.setdefault(
                            (cls, tgt.attr), []).append(c.id)
                    elif (isinstance(c, ast.Call) and _is_jit_wrapper(c.func)
                          and c.args and isinstance(c.args[0], ast.Name)):
                        # self._step = jax.jit(step, ...): the attr aliases
                        # the wrapped function (donation facts resolvable)
                        self.f.class_attr_fn_aliases.setdefault(
                            (cls, tgt.attr), []).append(c.args[0].id)
                if jitted:
                    self.f.jitted_attrs.add((cls, tgt.attr))
            elif (isinstance(tgt, ast.Subscript)
                  and isinstance(tgt.value, ast.Attribute)
                  and isinstance(tgt.value.value, ast.Name)
                  and tgt.value.value.id == "self"
                  and self.class_stack and jitted):
                # self._prefill_fns[key] = <jit-decorated def>
                self.f.jitted_attrs.add((self.class_stack[-1], tgt.value.attr))
        # jax.jit(fn) marks fn itself a root even when the wrapper is bound
        if isinstance(node.value, ast.Call) and _is_jit_wrapper(node.value.func):
            self._mark_wrapped_root(node.value)
        self.generic_visit(node)

    def _mark_wrapped_root(self, call: ast.Call) -> None:
        for arg in call.args[:1]:
            if isinstance(arg, ast.Name):
                fn = self._lookup_fn(arg.id)
                if fn is not None:
                    fn.jit_root = True
                    nums, names = _static_args_from_call(call)
                    fn.static_argnums |= nums
                    fn.static_argnames |= names
                    dnums, dnames = _donate_args_from_call(call)
                    fn.donated_argnums |= dnums
                    fn.donated_argnames |= dnames

    def visit_Call(self, node: ast.Call) -> None:
        if _is_jit_wrapper(node.func):
            self._mark_wrapped_root(node)
        self.generic_visit(node)

    def visit_Return(self, node: ast.Return) -> None:
        if (self.fn_stack and node.value is not None
                and self._value_is_jitted(node.value)):
            me = self.fn_stack[-1]
            me.returns_jitted = True
            root: Optional[ast.AST] = None
            if isinstance(node.value, ast.Name):
                root = node.value
            elif isinstance(node.value, ast.Call) and node.value.args:
                root = node.value.args[0]  # return jax.jit(fn, ...)
            if isinstance(root, ast.Name):
                fn = self._lookup_fn(root.id)
                if fn is not None and fn.jit_root and fn not in me.returned_jit_roots:
                    me.returned_jit_roots.append(fn)
        self.generic_visit(node)


@dataclass
class CallSite:
    caller: FunctionInfo
    node: ast.Call
    callees: list[FunctionInfo]  # resolved candidates (may be empty)


class FactIndex:
    """All modules + the resolution/call-graph layer."""

    def __init__(self, root: Path):
        self.root = root
        self.modules: dict[str, ModuleFacts] = {}
        self.parse_errors: list[tuple[str, int, str]] = []
        # False when the index covers only a slice of the package (a
        # single-file or --changed scan): checkers whose rules reason
        # from the ABSENCE of facts (an axis no scanned mesh binds, a
        # function no scanned shard_map reaches) must stand down — the
        # missing fact may live in an unscanned module. Same contract as
        # the KVM032 docs-drift full-scan rule. run_lint sets it.
        self.full_scan: bool = True
        # dotted module name -> repo-relative path (for import resolution)
        self._by_dotted: dict[str, str] = {}
        # call_sites is re-requested per taint-fixpoint round and again by
        # each checker; the AST walk + name resolution dominate runtime,
        # and resolution is deterministic once the index is built
        self._call_sites_cache: dict[tuple[str, str], list["CallSite"]] = {}

    # -- construction -------------------------------------------------------
    @classmethod
    def build(cls, root: Path, files: Iterable[Path]) -> "FactIndex":
        idx = cls(root)
        for f in files:
            try:
                rel = f.relative_to(root).as_posix()
            except ValueError:  # outside the lint root: keep the path as-is
                rel = f.as_posix()
            try:
                source = f.read_text(encoding="utf-8")
                tree = ast.parse(source)
            except (SyntaxError, UnicodeDecodeError) as e:
                idx.parse_errors.append((rel, getattr(e, "lineno", 0) or 0, str(e)))
                continue
            facts = ModuleFacts(
                path=rel, source=source, tree=tree,
                suppressions=Suppressions.scan(source),
            )
            _ModuleWalker(facts).visit(tree)
            idx.modules[rel] = facts
            dotted = rel[:-3].replace("/", ".") if rel.endswith(".py") else rel
            if dotted.endswith(".__init__"):
                dotted = dotted[: -len(".__init__")]
            idx._by_dotted[dotted] = rel
        idx._propagate_returns_jitted()
        return idx

    def _propagate_returns_jitted(self) -> None:
        """`def _get_spec_fn(self): return build_spec_step(...)` — a getter
        returning another jitted-returning factory's result is itself a
        jitted-value source. Cross-module, so it runs after all modules
        parse; small fixpoint (getter chains are short)."""
        for _ in range(4):
            changed = False
            for mod in self.modules.values():
                for fn in mod.functions.values():
                    for node in iter_scope(fn.node):
                        if not (isinstance(node, ast.Return)
                                and isinstance(node.value, ast.Call)):
                            continue
                        for callee in self._resolve_expr(mod, fn, node.value.func):
                            if callee.returns_jitted and not fn.returns_jitted:
                                fn.returns_jitted = True
                                changed = True
                            if callee.returns_jitted:
                                for root in callee.returned_jit_roots:
                                    if root not in fn.returned_jit_roots:
                                        fn.returned_jit_roots.append(root)
                                        changed = True
            if not changed:
                return

    # -- lookups ------------------------------------------------------------
    def functions(self) -> Iterable[FunctionInfo]:
        for m in self.modules.values():
            yield from m.functions.values()

    def module_for_dotted(self, dotted: str) -> Optional[ModuleFacts]:
        rel = self._by_dotted.get(dotted)
        if rel is None and dotted:
            # suffix match: `from models.llama import x` inside the package
            for d, r in self._by_dotted.items():
                if d.endswith("." + dotted) or d == dotted:
                    rel = r
                    break
        return self.modules.get(rel) if rel else None

    def _resolve_name(self, mod: ModuleFacts, caller: Optional[FunctionInfo],
                      name: str, _depth: int = 0) -> list[FunctionInfo]:
        """A bare name in `caller`'s scope -> function candidates."""
        if _depth > 4:
            return []
        out: list[FunctionInfo] = []
        fi = caller
        while fi is not None:
            cand = mod.functions.get(fi.qualname + ".<locals>." + name)
            if cand is not None:
                return [cand]
            for aliased in fi.local_aliases.get(name, []):
                # resolve the aliased expression in fi's OWN scope — the
                # binding may point at one of fi's nested defs
                out += self._resolve_expr(mod, fi, aliased, _depth + 1)
            if out:
                return out
            fi = fi.parent
        if name in mod.functions:
            return [mod.functions[name]]
        if caller is not None and caller.class_name:
            cand = mod.functions.get(f"{caller.class_name}.{name}")
            if cand is not None:
                return [cand]
        if name in mod.from_imports:
            src_mod, orig = mod.from_imports[name]
            target = self.module_for_dotted(src_mod)
            if target is not None and orig in target.functions:
                return [target.functions[orig]]
        return out

    def _resolve_expr(self, mod: ModuleFacts, caller: Optional[FunctionInfo],
                      expr: ast.AST, _depth: int = 0) -> list[FunctionInfo]:
        if isinstance(expr, ast.Name):
            return self._resolve_name(mod, caller, expr.id, _depth)
        if isinstance(expr, ast.Attribute):
            if isinstance(expr.value, ast.Name):
                if expr.value.id == "self" and caller is not None and caller.class_name:
                    cand = mod.functions.get(f"{caller.class_name}.{expr.attr}")
                    if cand is not None:
                        return [cand]
                    out = []
                    for aliased in mod.class_attr_fn_aliases.get(
                            (caller.class_name, expr.attr), []):
                        out += self._resolve_name(mod, None, aliased, _depth + 1)
                    return out
                dotted = mod.import_aliases.get(expr.value.id)
                if dotted is not None:
                    target = self.module_for_dotted(dotted)
                    if target is not None and expr.attr in target.functions:
                        return [target.functions[expr.attr]]
        return []

    def resolve_call(self, mod: ModuleFacts, caller: FunctionInfo,
                     call: ast.Call) -> list[FunctionInfo]:
        return self._resolve_expr(mod, caller, call.func)

    def call_sites(self, mod: ModuleFacts, fn: FunctionInfo) -> list[CallSite]:
        key = fn.key()
        cached = self._call_sites_cache.get(key)
        if cached is not None:
            return cached
        out = []
        for node in iter_scope(fn.node):
            if isinstance(node, ast.Call):
                out.append(CallSite(fn, node, self.resolve_call(mod, fn, node)))
        self._call_sites_cache[key] = out
        return out

    # -- jit dispatch detection --------------------------------------------
    def calls_jitted_value(self, mod: ModuleFacts, fn: FunctionInfo,
                           call: ast.Call) -> bool:
        """Does this callsite invoke a jit-compiled callable (directly, via a
        jitted binding, or via a name bound from a jitted-returning getter)?"""
        f = call.func
        if isinstance(f, ast.Call) and _is_jit_wrapper(f.func):
            return True  # jax.jit(fn)(args)
        if isinstance(f, ast.Name):
            if f.id in mod.jitted_names:
                return True
            fi = fn
            while fi is not None:
                for aliased in fi.local_aliases.get(f.id, []):
                    if isinstance(aliased, ast.Call):
                        for g in self._resolve_expr(mod, fi, aliased.func):
                            if g.returns_jitted:
                                return True
                    for g in self._resolve_expr(mod, fi, aliased):
                        if g.jit_root:
                            return True
                fi = fi.parent
            for g in self._resolve_name(mod, fn, f.id):
                if g.jit_root:
                    return True
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
            if f.value.id == "self" and fn.class_name:
                if (fn.class_name, f.attr) in mod.jitted_attrs:
                    return True
        if isinstance(f, ast.Subscript) and isinstance(f.value, ast.Attribute):
            sub = f.value
            if (isinstance(sub.value, ast.Name) and sub.value.id == "self"
                    and fn.class_name
                    and (fn.class_name, sub.attr) in mod.jitted_attrs):
                return True  # self._prefill_fns[key](...)
        return False

    # -- lockstep anchors ---------------------------------------------------
    def follower_replayed_methods(self) -> set[str]:
        """Method names a multihost follower replays: `<obj>.<m>(...)` calls
        inside any function named run_follower*/run_replica*."""
        out: set[str] = set()
        for mod in self.modules.values():
            for fn in mod.functions.values():
                if not fn.name.startswith(("run_follower", "run_replica")):
                    continue
                for node in ast.walk(fn.node):
                    if (isinstance(node, ast.Call)
                            and isinstance(node.func, ast.Attribute)
                            and isinstance(node.func.value, ast.Name)):
                        out.add(node.func.attr)
        return out
