"""KVM011-KVM015 — jit purity, static shapes, and host-sync hygiene.

Scope is computed from the fact index, not from file names:

- **jit-traced code**: every jit root (``@jax.jit``/``@partial(jax.jit)``
  inner defs, ``jax.jit(fn)`` wrap sites, ``shard_map``/``pjit``) plus
  everything reachable from a root's body through the resolved call
  graph. A cross-function *taint* pass tracks which parameters carry
  traced values: root params are tainted (minus ``static_argnums`` /
  ``static_argnames``), and a callee's param is tainted only when some
  observed callsite passes it a tainted expression — so Python-static
  trace branches like ``forward(..., fresh_prefill=True)`` stay legal,
  exactly the convention docs/LINTING.md promises.
- **jit-dispatch code** (KVM015 only): host functions that *call* a
  compiled callable (the decode hot path). An unannotated
  ``jax.device_get``/``.item()``/``.tolist()`` there is a silent
  pipeline stall (docs/DECODE_PIPELINE.md); intended sync points carry
  ``# kvmini: sync-ok``.

Shape/structure reads are exempt from taint (``.shape``/``.ndim``/
``.dtype``, ``len()``, ``isinstance``, ``is None`` checks): they are
static under trace. Plain iteration over a traced pytree is likewise
static structure; only ``while <traced>`` and ``for _ in range(<traced>)``
are data-dependent loops.
"""

from __future__ import annotations

import ast
from typing import Optional

from kserve_vllm_mini_tpu.lint.diagnostics import Diagnostic
from kserve_vllm_mini_tpu.lint.facts import (
    FactIndex,
    FunctionInfo,
    ModuleFacts,
    _last_attr,
    iter_scope,
)

SHAPE_EXEMPT_ATTRS = {"shape", "ndim", "dtype", "size", "sharding", "aval"}
EXEMPT_CALLS = {"len", "isinstance", "hasattr", "getattr", "type"}
WALL_CLOCK_ATTRS = {
    "time", "time_ns", "monotonic", "monotonic_ns",
    "perf_counter", "perf_counter_ns", "process_time",
}
DATETIME_ATTRS = {"now", "utcnow", "today"}
SYNC_METHOD_ATTRS = {"item", "tolist"}


def _module_alias_target(mod: ModuleFacts, name: str) -> Optional[str]:
    t = mod.import_aliases.get(name)
    if t is not None:
        return t
    fi = mod.from_imports.get(name)
    if fi is not None:
        return f"{fi[0]}.{fi[1]}" if fi[0] else fi[1]
    return None


def _is_wall_clock_call(mod: ModuleFacts, call: ast.Call) -> bool:
    f = call.func
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
        base = _module_alias_target(mod, f.value.id) or f.value.id
        if base == "time" and f.attr in WALL_CLOCK_ATTRS:
            return True
        if base.startswith("datetime") and f.attr in DATETIME_ATTRS:
            return True
    if isinstance(f, ast.Name):  # `from time import time` / `... as now`
        fi = mod.from_imports.get(f.id)
        if fi is not None:
            src_mod, orig = fi
            if src_mod == "time" and orig in WALL_CLOCK_ATTRS:
                return True
            if src_mod.startswith("datetime") and orig in DATETIME_ATTRS:
                return True
    return False


def _is_host_random_call(mod: ModuleFacts, call: ast.Call) -> bool:
    f = call.func
    if isinstance(f, ast.Attribute):
        if isinstance(f.value, ast.Name):
            base = _module_alias_target(mod, f.value.id) or f.value.id
            if base == "random" or base == "uuid":
                return True
            if base == "os" and f.attr == "urandom":
                return True
        if (isinstance(f.value, ast.Attribute) and f.value.attr == "random"
                and isinstance(f.value.value, ast.Name)):
            base = _module_alias_target(mod, f.value.value.id) or f.value.value.id
            if base == "numpy":  # np.random.*
                return True
    if isinstance(f, ast.Name):
        fi = mod.from_imports.get(f.id)
        if fi is not None and fi[0] == "random":
            return True
    return False


def _is_numpy_materialize(mod: ModuleFacts, call: ast.Call) -> bool:
    f = call.func
    if (isinstance(f, ast.Attribute) and f.attr in {"asarray", "array"}
            and isinstance(f.value, ast.Name)):
        return (_module_alias_target(mod, f.value.id) or f.value.id) == "numpy"
    return False


def _is_device_get(mod: ModuleFacts, call: ast.Call) -> bool:
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr in {"device_get", "block_until_ready"}:
        return True
    if isinstance(f, ast.Name) and f.id == "device_get":
        return True
    return False


class _Taint:
    """Per-function local taint over names, seeded from tainted params."""

    def __init__(self, tainted_names: set[str]):
        self.names = set(tainted_names)

    def expr(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Attribute) and node.attr in SHAPE_EXEMPT_ATTRS:
            return False
        if isinstance(node, ast.Call):
            fname = _last_attr(node.func)
            if fname in EXEMPT_CALLS:
                return False
            # is_quantized(w) / has_lora(p): structure predicates over a
            # pytree are trace-static, same as `.shape` or key membership
            if fname and (fname.startswith("is_") or fname.startswith("has_")):
                return False
        if isinstance(node, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
                return False
            # `"k_s" in cache` — membership of an UNTRACED key in a traced
            # pytree is structure, not data (static under trace)
            if all(isinstance(op, (ast.In, ast.NotIn)) for op in node.ops) \
                    and not self.expr(node.left):
                return False
        if isinstance(node, ast.Name):
            return node.id in self.names
        return any(self.expr(c) for c in ast.iter_child_nodes(node))

    def assign(self, target: ast.AST, tainted: bool) -> None:
        for n in ast.walk(target):
            if isinstance(n, ast.Name):
                if tainted:
                    self.names.add(n.id)
                else:
                    self.names.discard(n.id)


class JitPurityChecker:
    def __init__(self, index: FactIndex):
        self.index = index
        self.diags: list[Diagnostic] = []
        # (path, qualname) -> set of tainted param names (monotonic)
        self.tainted_params: dict[tuple[str, str], set[str]] = {}
        self.reachable: set[tuple[str, str]] = set()

    # -- scope construction -------------------------------------------------
    def _seed_roots(self) -> list[FunctionInfo]:
        roots = []
        for fn in self.index.functions():
            if not fn.jit_root:
                continue
            tainted = set()
            for i, p in enumerate(fn.params):
                if p in ("self", "cls") or p in fn.annotated_static:
                    continue
                if p in fn.static_argnames or i in fn.static_argnums:
                    continue
                tainted.add(p)
            self.tainted_params[fn.key()] = tainted
            self.reachable.add(fn.key())
            roots.append(fn)
        return roots

    def _propagate(self) -> None:
        """Fixpoint: push taint through resolved callsites."""
        for _ in range(12):
            changed = False
            for key in list(self.reachable):
                path, qual = key
                mod = self.index.modules[path]
                fn = mod.functions[qual]
                local = self._local_taint(mod, fn)
                for cs in self.index.call_sites(mod, fn):
                    # fns passed as values (`lax.scan(body, ...)`) are traced
                    # when invoked: reachable, params conservatively traced
                    for arg in list(cs.node.args) + [
                            kw.value for kw in cs.node.keywords]:
                        if not isinstance(arg, ast.Name):
                            continue
                        for hof in self.index._resolve_name(mod, fn, arg.id):
                            hk = hof.key()
                            taints = self.tainted_params.setdefault(hk, set())
                            want = {p for p in hof.params
                                    if p not in ("self", "cls")
                                    and p not in hof.annotated_static}
                            if hk not in self.reachable or not want <= taints:
                                self.reachable.add(hk)
                                taints |= want
                                changed = True
                    for callee in cs.callees:
                        ck = callee.key()
                        prev = self.tainted_params.setdefault(ck, set())
                        if ck not in self.reachable:
                            self.reachable.add(ck)
                            changed = True
                        params = callee.params
                        offset = 1 if params[:1] in (["self"], ["cls"]) and (
                            isinstance(cs.node.func, ast.Attribute)
                        ) else 0
                        for i, arg in enumerate(cs.node.args):
                            if isinstance(arg, ast.Starred):
                                continue
                            pi = i + offset
                            if (pi < len(params) and local.expr(arg)
                                    and params[pi] not in callee.annotated_static):
                                if params[pi] not in prev:
                                    prev.add(params[pi])
                                    changed = True
                        for kw in cs.node.keywords:
                            if (kw.arg and kw.arg in params
                                    and kw.arg not in callee.annotated_static
                                    and local.expr(kw.value)):
                                if kw.arg not in prev:
                                    prev.add(kw.arg)
                                    changed = True
            if not changed:
                return

    def _local_taint(self, mod: ModuleFacts, fn: FunctionInfo) -> _Taint:
        t = _Taint(self.tainted_params.get(fn.key(), set()))
        # two passes so names assigned late still taint early reads in loops
        for _ in range(2):
            for node in iter_scope(fn.node):
                if isinstance(node, ast.Assign):
                    tainted = t.expr(node.value)
                    for tgt in node.targets:
                        t.assign(tgt, tainted)
                elif isinstance(node, ast.AugAssign):
                    if t.expr(node.value):
                        t.assign(node.target, True)
                elif isinstance(node, (ast.For, ast.AsyncFor)):
                    t.assign(node.target, t.expr(node.iter))
        return t

    # -- checks -------------------------------------------------------------
    def run(self) -> list[Diagnostic]:
        self._seed_roots()
        self._propagate()
        for key in sorted(self.reachable):
            path, qual = key
            mod = self.index.modules[path]
            self._check_traced_fn(mod, mod.functions[qual])
        self._check_dispatch_fns()
        return self.diags

    def _emit(self, mod: ModuleFacts, node: ast.AST, code: str, msg: str,
              context: str) -> None:
        line = getattr(node, "lineno", 0)
        if mod.suppressions.is_suppressed(line, code):
            return
        self.diags.append(Diagnostic(mod.path, line, code, msg, context=context))

    def _check_traced_fn(self, mod: ModuleFacts, fn: FunctionInfo) -> None:
        taint = self._local_taint(mod, fn)
        ctx = fn.qualname
        for node in iter_scope(fn.node):
            if isinstance(node, ast.If) and taint.expr(node.test):
                self._emit(
                    mod, node, "KVM011",
                    f"data-dependent `if` on a traced value inside jitted "
                    f"`{fn.name}` — use lax.cond / jnp.where, or mark the "
                    "branch `# kvmini: static-shape` if it is trace-static",
                    ctx)
            elif isinstance(node, ast.While) and taint.expr(node.test):
                self._emit(
                    mod, node, "KVM012",
                    f"data-dependent `while` in jitted `{fn.name}` — use "
                    "lax.while_loop, or mark `# kvmini: static-shape`",
                    ctx)
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                it = node.iter
                if (isinstance(it, ast.Call) and _last_attr(it.func)
                        in {"range", "arange"}
                        and any(taint.expr(a) for a in it.args)):
                    self._emit(
                        mod, node, "KVM012",
                        f"loop bound depends on a traced value in jitted "
                        f"`{fn.name}` — use lax.scan/fori_loop, or mark "
                        "`# kvmini: static-shape`",
                        ctx)
            elif isinstance(node, ast.Call):
                self._check_traced_call(mod, fn, taint, node, ctx)

    def _check_traced_call(self, mod: ModuleFacts, fn: FunctionInfo,
                           taint: _Taint, node: ast.Call, ctx: str) -> None:
        if _is_wall_clock_call(mod, node):
            self._emit(
                mod, node, "KVM013",
                f"wall-clock read inside jitted `{fn.name}` is baked in at "
                "trace time (every retrace changes it; lockstep replicas "
                "disagree) — pass times in as operands",
                ctx)
            return
        if _is_host_random_call(mod, node):
            self._emit(
                mod, node, "KVM014",
                f"host randomness inside jitted `{fn.name}` — thread a "
                "jax.random key through the call instead",
                ctx)
            return
        if _last_attr(node.func) == "PRNGKey":
            for sub in node.args:
                for inner in ast.walk(sub):
                    if isinstance(inner, ast.Call) and (
                        _is_wall_clock_call(mod, inner)
                        or _is_host_random_call(mod, inner)
                    ):
                        self._emit(
                            mod, node, "KVM014",
                            f"PRNGKey seeded from a nondeterministic source "
                            f"in `{fn.name}` — seeds must be explicit "
                            "operands (lockstep replicas must agree)",
                            ctx)
                        return
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in SYNC_METHOD_ATTRS:
            self._emit(
                mod, node, "KVM015",
                f".{f.attr}() inside jitted `{fn.name}` forces a host sync "
                "(concretizes the tracer) — keep the value on device, or "
                "mark `# kvmini: sync-ok`",
                ctx)
        elif _is_numpy_materialize(mod, node) or _is_device_get(mod, node):
            self._emit(
                mod, node, "KVM015",
                f"host materialization inside jitted `{fn.name}` — use "
                "jnp on device, or mark `# kvmini: sync-ok`",
                ctx)
        elif (isinstance(f, ast.Name) and f.id in {"float", "int", "bool"}
              and node.args and taint.expr(node.args[0])):
            self._emit(
                mod, node, "KVM015",
                f"{f.id}() of a traced value inside jitted `{fn.name}` "
                "forces a host sync — keep it a jnp scalar, or mark "
                "`# kvmini: sync-ok`",
                ctx)

    # -- dispatch hot path --------------------------------------------------
    def _check_dispatch_fns(self) -> None:
        for mod in self.modules_with_jit():
            for fn in mod.functions.values():
                if fn.key() in self.reachable:
                    continue
                sites = [
                    n for n in iter_scope(fn.node)
                    if isinstance(n, ast.Call)
                    and self.index.calls_jitted_value(mod, fn, n)
                ]
                if not sites:
                    continue
                for node in iter_scope(fn.node):
                    if not isinstance(node, ast.Call):
                        continue
                    f = node.func
                    is_sync = (
                        (isinstance(f, ast.Attribute)
                         and f.attr in SYNC_METHOD_ATTRS)
                        or _is_device_get(mod, node)
                    )
                    if is_sync:
                        name = (f.attr if isinstance(f, ast.Attribute)
                                else "device_get")
                        self._emit(
                            mod, node, "KVM015",
                            f"host sync `{name}` in jit-dispatch function "
                            f"`{fn.name}` stalls the decode pipeline — move "
                            "it after dispatch, or mark the intended sync "
                            "point `# kvmini: sync-ok`",
                            fn.qualname)

    def modules_with_jit(self) -> list[ModuleFacts]:
        return [
            m for m in self.index.modules.values()
            if m.jitted_names or m.jitted_attrs
            or any(fn.jit_root or fn.returns_jitted for fn in m.functions.values())
        ]


def check(index: FactIndex) -> list[Diagnostic]:
    return JitPurityChecker(index).run()
