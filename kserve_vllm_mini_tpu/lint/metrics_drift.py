"""KVM031-KVM033 — metrics/schema drift across the four telemetry surfaces.

The same counter exists (or silently doesn't) in four places: the
engine's ``self.stats``/``snapshot_stats`` dict, the ``/metrics``
Prometheus exposition, the analysis layer's scrape mappings, and the
documentation (docs/*.md + dashboards/*.json promql). The energy/
serving-efficiency methodology (docs/ENERGY_METHOD.md, PAPERS.md) is
only as truthful as these stay aligned — so drift is a lint failure,
not a code-review hope.

Surface extraction (all static, all generic over the fact index):

- **stats keys**: string keys of a dict literal assigned to an attribute
  named ``stats``, plus string-subscript assignments inside a function
  named ``snapshot_stats`` (the derived gauges).
- **exposition**: f-strings whose first literal chunk matches
  ``kvmini_tpu_<name>`` — the formatted ``s['key']`` subscripts inside
  give the (metric, stats-key) pairing. Any string constant in an
  *emitter* module (``runtime/``) naming a full metric also counts as
  emitted (histogram family bases in runtime/tracing.py).
- **consumers**: every ``kvmini_tpu_*`` token in string constants of
  *consumer* modules (``analysis/`` et al), docs markdown, and
  dashboards JSON.
- **results keys**: dict-literal keys passed to ``merge_into_results``
  and the string *values* of metric→results mapping dicts, checked
  against the ``Results`` dataclass fields in core/schema.py.

Checks: KVM031 stats key never exported; KVM032 name consumed or
documented but never emitted / emitted but never documented; KVM033
results key not declared in the schema. Suppress deliberate internals
(raw inputs like ``busy_s`` whose exposition is a derived gauge) with
``# kvmini: metrics-ok`` on the key's line.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Optional

from kserve_vllm_mini_tpu.lint.diagnostics import Diagnostic
from kserve_vllm_mini_tpu.lint.facts import FactIndex, ModuleFacts

METRIC_TOKEN = re.compile(r"kvmini_tpu_\w+")
EXPOSITION_PREFIX = re.compile(r"^(?:#\s*(?:TYPE|HELP)\s+)?(kvmini_tpu_\w+)")
EMITTER_PATH = re.compile(r"(^|/)runtime/")
CONSUMER_PATH = re.compile(
    r"(^|/)(analysis|loadgen|probes|energy|compare|gates|report|costs|monitor)/"
)
HISTOGRAM_SUFFIXES = ("_bucket", "_sum", "_count")


@dataclass
class Surfaces:
    # metric name -> (path, line) of first sighting per surface
    emitted: dict[str, tuple[str, int]] = field(default_factory=dict)
    consumed: dict[str, tuple[str, int]] = field(default_factory=dict)
    documented: dict[str, tuple[str, int]] = field(default_factory=dict)
    # stats dict: key -> (path, line)
    stats_keys: dict[str, tuple[str, int]] = field(default_factory=dict)
    # stats keys referenced by exposition f-strings
    exposed_keys: set[str] = field(default_factory=set)
    # results.json writes: key -> (path, line)
    results_keys: dict[str, tuple[str, int]] = field(default_factory=dict)
    schema_fields: set[str] = field(default_factory=set)
    has_schema: bool = False


def _first_const(js: ast.JoinedStr) -> Optional[str]:
    if js.values and isinstance(js.values[0], ast.Constant) and isinstance(
            js.values[0].value, str):
        return js.values[0].value
    return None


def _subscript_keys(node: ast.AST) -> list[str]:
    out = []
    for n in ast.walk(node):
        if isinstance(n, ast.Subscript) and isinstance(n.slice, ast.Constant) \
                and isinstance(n.slice.value, str):
            out.append(n.slice.value)
    return out


def _docstring_nodes(tree: ast.Module) -> set[ast.AST]:
    out: set[ast.AST] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.Module, ast.ClassDef, ast.FunctionDef,
                             ast.AsyncFunctionDef)):
            body = node.body
            if (body and isinstance(body[0], ast.Expr)
                    and isinstance(body[0].value, ast.Constant)
                    and isinstance(body[0].value.value, str)):
                out.add(body[0].value)
    return out


def _collect_module(mod: ModuleFacts, s: Surfaces) -> None:
    is_emitter = bool(EMITTER_PATH.search(mod.path))
    is_consumer = bool(CONSUMER_PATH.search(mod.path))
    docstrings = _docstring_nodes(mod.tree)
    for node in mod.walk():
        if node in docstrings:
            continue  # prose examples aren't emitted/consumed names
        # exposition f-strings pair metric <-> stats key wherever they live
        if isinstance(node, ast.JoinedStr):
            head = _first_const(node)
            m = EXPOSITION_PREFIX.match(head or "")
            if m:
                s.emitted.setdefault(m.group(1), (mod.path, node.lineno))
                for key in _subscript_keys(node):
                    s.exposed_keys.add(key)
        elif isinstance(node, ast.Constant) and isinstance(node.value, str):
            for tok in METRIC_TOKEN.findall(node.value):
                if is_emitter:
                    s.emitted.setdefault(tok, (mod.path, node.lineno))
                elif is_consumer:
                    s.consumed.setdefault(tok, (mod.path, node.lineno))
        elif isinstance(node, ast.ClassDef) and node.name == "Results":
            s.has_schema = True
            for stmt in node.body:
                if isinstance(stmt, ast.AnnAssign) and isinstance(
                        stmt.target, ast.Name):
                    s.schema_fields.add(stmt.target.id)
        elif isinstance(node, ast.Assign):
            _collect_stats_dict(mod, node, s)
        elif isinstance(node, ast.Call):
            _collect_merge_call(mod, node, s)
        elif isinstance(node, ast.Dict):
            _collect_mapping_dict(mod, node, s)
    for fn in mod.functions.values():
        if fn.name != "snapshot_stats":
            continue
        for node in ast.walk(fn.node):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Subscript)):
                sl = node.targets[0].slice
                if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
                    s.stats_keys.setdefault(sl.value, (mod.path, node.lineno))


def _collect_stats_dict(mod: ModuleFacts, node: ast.Assign, s: Surfaces) -> None:
    for tgt in node.targets:
        if isinstance(tgt, ast.Attribute) and tgt.attr == "stats" \
                and isinstance(node.value, ast.Dict):
            for k in node.value.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    s.stats_keys.setdefault(k.value, (mod.path, k.lineno))


def _collect_merge_call(mod: ModuleFacts, node: ast.Call, s: Surfaces) -> None:
    if not (isinstance(node.func, ast.Attribute)
            and node.func.attr == "merge_into_results"):
        return
    for arg in node.args[:1]:
        if isinstance(arg, ast.Dict):
            for k in arg.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    s.results_keys.setdefault(k.value, (mod.path, k.lineno))


def _collect_mapping_dict(mod: ModuleFacts, node: ast.Dict, s: Surfaces) -> None:
    """PIPELINE_METRIC_KEYS-style dicts: kvmini_tpu_* keys -> results keys."""
    keys = [k for k in node.keys if isinstance(k, ast.Constant)
            and isinstance(k.value, str)]
    if not keys or not all(METRIC_TOKEN.fullmatch(k.value) for k in keys):
        return
    for k, v in zip(node.keys, node.values):
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            s.results_keys.setdefault(v.value, (mod.path, v.lineno))


def _scan_text_surface(path: str, text: str, into: dict[str, tuple[str, int]]) -> None:
    for i, line in enumerate(text.splitlines(), start=1):
        for tok in METRIC_TOKEN.findall(line):
            into.setdefault(tok, (path, i))


def _emitted_covers(name: str, emitted: set[str]) -> bool:
    if name in emitted:
        return True
    for suf in HISTOGRAM_SUFFIXES:
        if name.endswith(suf) and name[: -len(suf)] in emitted:
            return True
    return False


def _documented_covers(name: str, documented: set[str]) -> bool:
    if name in documented:
        return True
    # a histogram base counts as documented if any family member is
    return any(name + suf in documented for suf in HISTOGRAM_SUFFIXES)


def check(index: FactIndex,
          doc_texts: Optional[dict[str, str]] = None) -> list[Diagnostic]:
    s = Surfaces()
    for mod in index.modules.values():
        _collect_module(mod, s)
    for path, text in (doc_texts or {}).items():
        target = s.documented if path.endswith(".md") else s.consumed
        _scan_text_surface(path, text, target)

    diags: list[Diagnostic] = []

    def emit(where: tuple[str, int], code: str, msg: str, ctx: str) -> None:
        path, line = where
        mod = index.modules.get(path)
        if mod is not None and mod.suppressions.is_suppressed(line, code):
            return
        diags.append(Diagnostic(path, line, code, msg, context=ctx))

    # KVM031 — every stats key must reach an exposition line
    if s.emitted:  # only meaningful when an exposition surface exists
        for key, where in sorted(s.stats_keys.items()):
            if key not in s.exposed_keys:
                emit(where, "KVM031",
                     f"stats counter '{key}' is never exported on /metrics — "
                     "operators can't see it; export it or mark the raw "
                     "input `# kvmini: metrics-ok`",
                     key)

    # KVM032 — name-level drift between emitted / consumed / documented.
    # Only meaningful when an exposition surface was scanned: a partial
    # scan (one fixture dir, one subpackage) has no emitter to drift from.
    emitted_names = set(s.emitted)
    if emitted_names:
        for name, where in sorted(s.consumed.items()):
            if not _emitted_covers(name, emitted_names):
                emit(where, "KVM032",
                     f"'{name}' is consumed here but the runtime never emits "
                     "it — the fallback silently yields nothing",
                     name)
        for name, where in sorted(s.documented.items()):
            if not _emitted_covers(name, emitted_names):
                emit(where, "KVM032",
                     f"'{name}' is documented but the runtime never emits it",
                     name)
    if emitted_names and s.documented:  # docs present: require enumeration
        for name, where in sorted(s.emitted.items()):
            if not _documented_covers(name, set(s.documented)):
                emit(where, "KVM032",
                     f"'{name}' is emitted on /metrics but undocumented — "
                     "add it to the docs/API.md metrics table",
                     name)

    # KVM033 — results.json writes must land on declared schema fields
    if s.has_schema:
        for key, where in sorted(s.results_keys.items()):
            if key not in s.schema_fields:
                emit(where, "KVM033",
                     f"results.json key '{key}' is not declared in "
                     "core/schema.py Results — it silently lands in extras, "
                     "invisible to gates/reports typing",
                     key)
    return diags
