"""KVM121-KVM124 — asyncio event-loop discipline.

The fleet router (fleet/router.py) is a large asyncio program: placement
scoring, the tracing intermediate, the decision audit ring, and every
HTTP handler all run on ONE event loop thread. Its thread-safety story
used to rest on hand-written "event-loop-only" comments; this family
checks the discipline those comments claimed, in four rules:

- **KVM121 — blocking calls on the loop.** The event-loop-root table
  (aiohttp ``router.add_*`` handlers, ``app.on_startup.append``
  lifecycle callbacks, ``create_task``/``ensure_future`` targets,
  ``asyncio.run``/``run_until_complete`` targets) is propagated through
  the cross-file call graph; any reachable call to ``time.sleep``, sync
  ``subprocess``, blocking HTTP (``requests``/sync ``httpx``/
  ``urlopen``), ``socket.create_connection``, an un-timed
  ``Lock.acquire``, or sync file IO (``open``/``read_text``/...) stalls
  EVERY in-flight request on the loop at once. Callees handed to
  ``run_in_executor``/``asyncio.to_thread`` are thread roots, so
  reachability never crosses into them — the blessed offload pattern is
  exempt by construction.
- **KVM122 — fire-and-forget tasks.** A ``create_task``/
  ``ensure_future`` whose handle is neither stored, awaited, returned,
  passed on, nor given a done-callback: an exception inside the task is
  swallowed silently (and CPython may garbage-collect the task
  mid-flight). The router's respawn/scrape paths are exactly where a
  silent death matters.
- **KVM123 — loop-affinity violations.** Reusing the KVM05x access
  facts (lint/concurrency.py): an attribute mutated by BOTH
  loop-reachable code and thread-rooted code, with no common lock and
  no ``call_soon_threadsafe`` routing. Routed designs pass by
  construction — a ``call_soon_threadsafe(cb, ...)`` callback is itself
  an event-loop root, so a thread that routes its writes has no
  thread-side access left to flag. KVM051 defers these attribute sets
  here: the right fix is loop routing, not "add a lock".
- **KVM124 — read-modify-write straddling an await.** Loop state read
  into a local before an ``await`` and written back (from that local)
  after it — another task interleaves at the await and the update is
  lost (the placement-scoreboard bug class). The single-statement form
  (``self.total += await f()``) loads, awaits, then stores, and is
  flagged too. The correct ``self.x += 1 ... await ... self.x -= 1``
  pattern (each RMW atomic between awaits) is NOT flagged.

Same under-approximation contract as KVM05x: unresolved targets
contribute no roots, unattributed state contributes no findings.
Suppress deliberate designs with ``# kvmini: async-ok`` plus a one-line
justification (docs/LINTING.md); on subset scans the token's staleness
is not judged — the registration that makes a function loop-reachable
may live in an unscanned module.
"""

from __future__ import annotations

import ast
from typing import Optional

from kserve_vllm_mini_tpu.lint.concurrency import (
    ADMIN_EXECUTOR_METHODS,
    DRIVER_ROOT,
    LOOP_ROOT,
    TASK_SPAWNERS,
    THREAD_CTORS,
    _LOCKISH_NAME,
    _self_attr,
    shared_facts,
)
from kserve_vllm_mini_tpu.lint.diagnostics import Diagnostic
from kserve_vllm_mini_tpu.lint.facts import (
    FactIndex,
    FunctionInfo,
    ModuleFacts,
    _last_attr,
    iter_scope,
)

# module-attribute calls that block the calling thread: receiver name ->
# blocking attrs. (subprocess.Popen itself returns immediately and is
# not listed; requests.Session() constructs without IO.)
_BLOCKING_MODULE_CALLS = {
    "time": {"sleep"},
    "subprocess": {"run", "call", "check_call", "check_output"},
    "requests": {"get", "post", "put", "delete", "head", "patch", "request"},
    "httpx": {"get", "post", "put", "delete", "head", "patch", "request",
              "stream"},
    "socket": {"create_connection", "getaddrinfo", "gethostbyname"},
}
# sync file IO methods (pathlib / io objects) — "large" is not statically
# knowable, so every loop-side sync read/write is surfaced; intentional
# tiny reads annotate async-ok, real ones move to run_in_executor
_BLOCKING_IO_METHODS = {"read_text", "write_text", "read_bytes",
                        "write_bytes"}
_THREADISH_PREFIXES = ("thread:", "pool:")


def _threadish(roots: set[str]) -> set[str]:
    return {r for r in roots
            if r.startswith(_THREADISH_PREFIXES) or r == DRIVER_ROOT}


class AsyncFlowChecker:
    def __init__(self, index: FactIndex):
        self.index = index
        self.diags: list[Diagnostic] = []
        # piggyback on the KVM05x fact phases: class facts, root labels
        # (incl. the event-loop-root table), per-access records, and
        # held-lock propagation — memoized per index, so whichever of
        # KVM05x/KVM12x runs first builds them and the other reuses
        self.cc = shared_facts(index)
        self._offload_cache: dict[tuple[str, str], frozenset[int]] = {}
        self.loop_keys = self._loop_reachable()

    def _offloaded_nodes(self, fn: FunctionInfo) -> frozenset[int]:
        """Node ids inside executor-offload argument subtrees of ``fn``.

        ``run_in_executor(None, lambda: load_peft(...))`` wraps the
        blocking work in a lambda, which has no FunctionInfo of its own —
        without this exclusion the call edge out of the lambda body would
        propagate loop context straight into the offloaded callee and
        flag exactly the blessed pattern."""
        key = fn.key()
        cached = self._offload_cache.get(key)
        if cached is None:
            excluded: set[int] = set()
            for node in iter_scope(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                la = _last_attr(node.func)
                if (la in ("run_in_executor", "to_thread", "submit")
                        or la in ADMIN_EXECUTOR_METHODS
                        or la in THREAD_CTORS):
                    for sub in list(node.args) + [kw.value
                                                  for kw in node.keywords]:
                        excluded.update(id(x) for x in ast.walk(sub))
            cached = frozenset(excluded)
            self._offload_cache[key] = cached
        return cached

    def _loop_reachable(self) -> set[tuple[str, str]]:
        """BFS from the event-loop roots through the call graph, stopping
        at root boundaries (a function spawned as a thread/pool target
        runs in ITS context, not the loop's) and never following a call
        edge that originates inside an offload argument subtree."""
        out: set[tuple[str, str]] = set()
        work: list[FunctionInfo] = []
        for fn, label in self.cc.raw_roots:
            if label == LOOP_ROOT and fn.key() not in out:
                out.add(fn.key())
                work.append(fn)
        while work:
            fn = work.pop()
            mod = self.index.modules.get(fn.path)
            if mod is None:
                continue
            excluded = self._offloaded_nodes(fn)
            seen_here: set[tuple[str, str]] = set()
            for cs in self.index.call_sites(mod, fn):
                if id(cs.node) in excluded:
                    continue
                for callee in self.cc._callees(mod, fn, cs.node):
                    ck = callee.key()
                    if (ck in seen_here or ck in out
                            or ck in self.cc.root_targets):
                        continue
                    seen_here.add(ck)
                    out.add(ck)
                    work.append(callee)
        return out

    def _emit(self, mod: ModuleFacts, line: int, code: str, msg: str,
              ctx: str) -> None:
        if mod.suppressions.is_suppressed(line, code):
            return
        self.diags.append(Diagnostic(mod.path, line, code, msg, context=ctx))

    # -- KVM121 ---------------------------------------------------------------

    def _blocking_desc(self, mod: ModuleFacts, fn: FunctionInfo,
                       call: ast.Call) -> Optional[str]:
        f = call.func
        if isinstance(f, ast.Attribute):
            recv = f.value
            if isinstance(recv, ast.Name):
                if f.attr in _BLOCKING_MODULE_CALLS.get(recv.id, ()):
                    return f"{recv.id}.{f.attr}"
            if f.attr in _BLOCKING_IO_METHODS:
                return f"{f.attr}()"
            if f.attr == "acquire":
                lock_attr = _self_attr(recv)
                timed = bool(call.args) or any(
                    kw.arg in ("timeout", "blocking") for kw in call.keywords)
                if lock_attr is not None and not timed and fn.class_name:
                    ci = self.cc.class_info(mod.path, fn.class_name)
                    if (lock_attr in ci.lock_attrs
                            or _LOCKISH_NAME.search(lock_attr)):
                        return f"self.{lock_attr}.acquire()"
            return None
        if isinstance(f, ast.Name):
            if f.id == "open":
                return "open()"
            if f.id == "urlopen":
                return "urlopen()"
            src = mod.from_imports.get(f.id)
            if src is not None and f.id in _BLOCKING_MODULE_CALLS.get(
                    src[0], ()):
                return f"{src[0]}.{f.id}"
        return None

    def _check_blocking(self) -> None:
        for rec in self.cc.call_records:
            if rec.fn.key() not in self.loop_keys or rec.awaited:
                continue
            if id(rec.node) in self._offloaded_nodes(rec.fn):
                continue  # inside a run_in_executor/to_thread argument
            desc = self._blocking_desc(rec.mod, rec.fn, rec.node)
            if desc is None:
                continue
            self._emit(
                rec.mod, rec.node.lineno, "KVM121",
                f"`{desc}` blocks the event loop (reachable from a "
                f"loop root via `{rec.fn.name}`) — every in-flight "
                "request on the loop stalls until it returns; use the "
                "async equivalent, offload with "
                "`loop.run_in_executor`/`asyncio.to_thread`, or mark "
                "`# kvmini: async-ok`",
                rec.fn.qualname)

    # -- KVM122 ---------------------------------------------------------------

    def _check_fire_and_forget(self) -> None:
        for mod in self.index.modules.values():
            for fn in mod.functions.values():
                for node in iter_scope(fn.node):
                    if not (isinstance(node, ast.Expr)
                            and isinstance(node.value, ast.Call)):
                        continue
                    call = node.value
                    name = _last_attr(call.func)
                    if name not in TASK_SPAWNERS:
                        continue
                    # `t = create_task(...)` / `return ...` / an arg /
                    # `create_task(...).add_done_callback(...)` are all
                    # NOT bare-Expr spawns and never reach here
                    self._emit(
                        mod, node.lineno, "KVM122",
                        f"`{name}(...)` handle is neither stored, "
                        "awaited, nor given a done-callback — an "
                        "exception inside the task vanishes silently "
                        "(and the loop may GC the task mid-flight); "
                        "keep the handle and await/cancel it, or chain "
                        "`.add_done_callback` that surfaces the "
                        "exception, or mark `# kvmini: async-ok`",
                        fn.qualname)

    # -- KVM123 ---------------------------------------------------------------

    def _check_loop_affinity(self) -> None:
        for (path, cls, attr), accs in sorted(self.cc.accesses.items()):
            ci = self.cc.class_info(path, cls)
            if attr in ci.threadsafe_attrs or attr in ci.thread_attrs:
                continue
            muts = [a for a in accs if a.mutation]
            if not muts:
                continue
            roots: set[str] = set()
            for a in accs:
                roots |= self.cc._fn_labels(a.fn)
            foreign = _threadish(roots)
            if LOOP_ROOT not in roots or not foreign:
                continue
            guard_sets = [self.cc._guards(a) for a in accs]
            if frozenset.intersection(*guard_sets):
                continue  # one lock consistently guards every access
            # anchor the thread-side access (the one that should be
            # routed through call_soon_threadsafe), mutations first
            thread_accs = [
                a for a in accs
                if _threadish(set(self.cc._fn_labels(a.fn)))
            ]
            anchor = min(
                thread_accs or accs,
                key=lambda a: (not a.mutation, a.mod.path, a.line))
            self._emit(
                anchor.mod, anchor.line, "KVM123",
                f"`self.{attr}` is event-loop state "
                f"(roots: {', '.join(sorted(roots))}) but thread-rooted "
                "code touches it with no `call_soon_threadsafe` routing "
                "and no common lock — the loop observes torn state; "
                "route the thread-side access through "
                "`loop.call_soon_threadsafe(...)` (or guard every "
                "access with one lock), or mark `# kvmini: async-ok`",
                f"{cls}.{attr}")

    # -- KVM124 ---------------------------------------------------------------

    def _check_straddled_rmw(self) -> None:
        for key in sorted(self.loop_keys):
            mod = self.index.modules.get(key[0])
            if mod is None:
                continue
            fn = mod.functions.get(key[1])
            if fn is None or not isinstance(fn.node, ast.AsyncFunctionDef):
                continue
            self._scan_rmw(mod, fn)

    @staticmethod
    def _reads_of_self(expr: ast.AST) -> set[str]:
        out = set()
        for n in ast.walk(expr):
            a = _self_attr(n)
            if a is not None:
                out.add(a)
        return out

    @staticmethod
    def _contains_await(expr: ast.AST) -> bool:
        return any(isinstance(n, ast.Await) for n in ast.walk(expr))

    @staticmethod
    def _names_in(expr: ast.AST) -> set[str]:
        return {n.id for n in ast.walk(expr) if isinstance(n, ast.Name)}

    def _scan_rmw(self, mod: ModuleFacts, fn: FunctionInfo) -> None:
        awaits: list[int] = []
        binds: list[tuple[str, str, int]] = []  # (local, attr, line)
        flagged: set[int] = set()

        def flag(line: int, attr: str, detail: str) -> None:
            if line in flagged:
                return
            flagged.add(line)
            self._emit(
                mod, line, "KVM124",
                f"read-modify-write of `self.{attr}` straddles an await "
                f"in `{fn.name}` ({detail}) — another task interleaves "
                "at the await and this write clobbers its update; "
                "recompute from current state after the await, or keep "
                "the RMW atomic between awaits, or mark "
                "`# kvmini: async-ok`",
                fn.qualname)

        # pass 1: collect every await and local<-self bind up front —
        # iter_scope yields in reverse document order, so sequential
        # accumulation would never see an await before the write it
        # straddles; the bline < await < write line comparison below
        # encodes the ordering instead
        for node in iter_scope(fn.node):
            if isinstance(node, ast.Await):
                awaits.append(node.lineno)
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        for battr in self._reads_of_self(node.value):
                            binds.append((t.id, battr, node.lineno))

        for node in iter_scope(fn.node):
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                value = node.value
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    attr = _self_attr(t)
                    if attr is None:
                        continue
                    # single-statement form: the value awaits between
                    # the implicit load and the store
                    reads = self._reads_of_self(value) | (
                        {attr} if isinstance(node, ast.AugAssign) else set())
                    if attr in reads and self._contains_await(value):
                        flag(node.lineno, attr,
                             "the value awaits between load and store")
                        continue
                    # bound form: local read before an await, written
                    # back (via that local) after it
                    used = self._names_in(value)
                    for local, battr, bline in binds:
                        if (battr == attr and local in used
                                and any(bline < la < node.lineno
                                        for la in awaits)):
                            flag(node.lineno, attr,
                                 f"read into `{local}` at line {bline}")
                            break

    # -- driver ---------------------------------------------------------------

    def run(self) -> list[Diagnostic]:
        self._check_blocking()
        self._check_fire_and_forget()
        self._check_loop_affinity()
        self._check_straddled_rmw()
        return self.diags


def check(index: FactIndex) -> list[Diagnostic]:
    return AsyncFlowChecker(index).run()
