"""KVM071-KVM074 — donation/aliasing discipline and paged-KV block lifecycle.

Two buffer ownership models meet in the runtime, and both have a
"surrendered but still referenced" failure mode the type system can't see:

- **XLA donation** (``donate_argnums``): a donated operand's device buffer
  is handed to the compiled program, which may write outputs into it in
  place. The Python reference that was passed still exists — reading it
  after dispatch observes undefined contents (or deadlocks a pending
  transfer). KVM071 flags reads of a donated argument after the dispatch
  callsite (rebinding the name to the call's result is the legal pattern:
  ``cache, logits = step(params, cache, ...)``). KVM072 flags the inverse
  omission: a jit root that *threads* a cache-like buffer (param in,
  updated value out) without donating it — both generations stay resident
  and steady-state HBM doubles (the engine's donated-decode-state
  convention, runtime/engine.py module docstring).
- **Paged-KV block ids** (``Engine._paged_*``): integer block ids move
  between the free list, per-slot block tables, and the retained
  (content-addressed, evictable) LRU. KVM073 flags a block id freed twice
  or used as an index after it went back to the free list — the id may
  already belong to another request, so a stale write corrupts *their* KV.
  KVM074 flags bumping a block's refcount while the retained LRU is in
  play without popping the block out of the LRU — eviction scans the LRU
  and would reap a block in active use.

Donation facts come from the shared FactIndex (decorator, ``partial``,
``jax.jit(fn, ...)`` wrap — including roots handed out by getter
functions, the engine's ``_get_*_fn`` idiom). Ordering is *suite-aware
lexical*: node A is "after" node B only when both sit under a common
statement suite and A's statement index is strictly greater — sibling
``if``/``elif`` branches are unordered (mutually exclusive), and an exit
statement (``return``/``raise``/``continue``/``break``) between the two
events cancels the pair (the freeing path never reaches the use). Code
the checker cannot order is never flagged — misses over false alarms,
like every kvmini-lint family.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, Optional, Union

from kserve_vllm_mini_tpu.lint.diagnostics import Diagnostic
from kserve_vllm_mini_tpu.lint.facts import (
    FactIndex,
    FunctionInfo,
    ModuleFacts,
    iter_scope,
)

BUFFERISH = re.compile(r"cache|kv|buf", re.IGNORECASE)
FREELIST = re.compile(r"^_?free(_blocks|_list|_slots|list)?$")
RC_NAME = re.compile(r"(^|_)(block_)?rc$|refcount")
RETAINED = re.compile(r"retained")

# a donated-arg token: a bare name, or ("self", attr)
Token = Union[str, tuple[str, str]]


def _token_of(node: ast.AST) -> Optional[Token]:
    if isinstance(node, ast.Name):
        return node.id
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return ("self", node.attr)
    return None


def _token_events(fn_node: ast.AST, token: Token,
                  skip: set[int]) -> tuple[list[ast.AST], list[ast.AST]]:
    """(load nodes, store nodes) of `token` in the function scope,
    skipping nodes whose id() is in `skip` (the dispatch call subtree)."""
    loads: list[ast.AST] = []
    stores: list[ast.AST] = []
    for n in iter_scope(fn_node):
        if id(n) in skip:
            continue
        if isinstance(token, str):
            hit = isinstance(n, ast.Name) and n.id == token
        else:
            hit = (isinstance(n, ast.Attribute) and n.attr == token[1]
                   and isinstance(n.value, ast.Name) and n.value.id == "self")
        if not hit:
            continue
        if isinstance(n.ctx, ast.Store):
            stores.append(n)
        elif isinstance(n.ctx, ast.Load):
            loads.append(n)
    return loads, stores


Path = tuple[tuple[int, int], ...]  # ((suite id, stmt index), ...)


def _positions(fn_node: ast.AST) -> dict[int, Path]:
    """id(node) -> position path: one (suite id, statement index) entry per
    enclosing statement suite, innermost last. Two nodes are lexically
    ordered iff their paths agree up to some suite and differ in index
    there; sibling branches of one statement share every path entry and
    are therefore unordered. Nested def/class bodies are skipped (they run
    at another time, like iter_scope)."""
    pos: dict[int, Path] = {}

    def visit(node: ast.AST, path: Path) -> None:
        pos[id(node)] = path
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)) and node is not fn_node:
            return
        for _field, value in ast.iter_fields(node):
            if isinstance(value, list):
                stmts = [v for v in value if isinstance(v, ast.stmt)]
                if stmts and len(stmts) == len(value):
                    for i, child in enumerate(value):
                        visit(child, path + ((id(value), i),))
                else:
                    for child in value:
                        if isinstance(child, ast.AST):
                            visit(child, path)
            elif isinstance(value, ast.AST):
                visit(value, path)

    visit(fn_node, ())
    return pos


def _after(pos: dict[int, Path], a: ast.AST, b: ast.AST) -> bool:
    """Does `a` execute strictly after `b` (same-suite lexical order)?"""
    pa, pb = pos.get(id(a)), pos.get(id(b))
    if pa is None or pb is None:
        return False
    for (sa, ia), (sb, ib) in zip(pa, pb):
        if sa != sb:
            return False  # sibling branches: unordered
        if ia != ib:
            return ia > ib
    return False  # one contains the other (or same statement)


def _exit_between(pos: dict[int, Path], exits: list[ast.AST],
                  first: ast.AST, later: ast.AST) -> bool:
    """An exit statement strictly between the two events means the path
    that executed `first` never reaches `later`."""
    return any(_after(pos, x, first) and _after(pos, later, x)
               for x in exits)


def _exits(fn_node: ast.AST) -> list[ast.AST]:
    return [n for n in iter_scope(fn_node)
            if isinstance(n, (ast.Return, ast.Raise, ast.Continue, ast.Break))]


class BufferLifecycleChecker:
    def __init__(self, index: FactIndex):
        self.index = index
        self.diags: list[Diagnostic] = []

    def run(self) -> list[Diagnostic]:
        for mod in self.index.modules.values():
            for fn in mod.functions.values():
                self._check_donated_reads(mod, fn)
                if fn.jit_root:
                    self._check_undonated_carry(mod, fn)
                self._check_block_lifecycle(mod, fn)
                self._check_retained_claim(mod, fn)
        return self.diags

    def _emit(self, mod: ModuleFacts, node: ast.AST, code: str, msg: str,
              context: str) -> None:
        line = getattr(node, "lineno", 0)
        if mod.suppressions.is_suppressed(line, code):
            return
        self.diags.append(Diagnostic(mod.path, line, code, msg, context=context))

    # -- KVM071: donated argument read after dispatch ----------------------
    def _jit_roots_for_call(self, mod: ModuleFacts, fn: FunctionInfo,
                            call: ast.Call,
                            callees: list[FunctionInfo]) -> list[FunctionInfo]:
        roots = [c for c in callees if c.jit_root]
        f = call.func
        if isinstance(f, ast.Name):
            # step = self._get_step_fn(...); step(...): the local alias
            # binds a getter call whose returned jit roots we know
            fi: Optional[FunctionInfo] = fn
            while fi is not None:
                for aliased in fi.local_aliases.get(f.id, []):
                    if isinstance(aliased, ast.Call):
                        for g in self.index._resolve_expr(
                                mod, fi, aliased.func):
                            roots += g.returned_jit_roots
                fi = fi.parent
        return roots

    def _check_donated_reads(self, mod: ModuleFacts, fn: FunctionInfo) -> None:
        pos: Optional[dict[int, Path]] = None
        exits: list[ast.AST] = []
        for cs in self.index.call_sites(mod, fn):
            node = cs.node
            for root in self._jit_roots_for_call(mod, fn, node, cs.callees):
                if not (root.donated_argnums or root.donated_argnames):
                    continue
                if pos is None:
                    pos = _positions(fn.node)
                    exits = _exits(fn.node)
                offset = 1 if root.params[:1] in (["self"], ["cls"]) and (
                    isinstance(node.func, ast.Attribute)) else 0
                donated: list[ast.AST] = []
                for p in root.donated_argnums:
                    ai = p - offset
                    if 0 <= ai < len(node.args):
                        donated.append(node.args[ai])
                for kw in node.keywords:
                    if kw.arg in root.donated_argnames:
                        donated.append(kw.value)
                skip = {id(n) for n in ast.walk(node)}
                for arg in donated:
                    token = _token_of(arg)
                    if token is None:
                        continue
                    loads, stores = _token_events(fn.node, token, skip)
                    for read in sorted(loads, key=lambda n: n.lineno):
                        if not _after(pos, read, node):
                            continue
                        # a rebind at/after dispatch that isn't after the
                        # read legalizes it (`cache, y = step(params,
                        # cache)` rebinding in the dispatch stmt included)
                        if any(not _after(pos, node, s)
                               and not _after(pos, s, read)
                               for s in stores):
                            continue
                        if _exit_between(pos, exits, node, read):
                            continue
                        label = (token if isinstance(token, str)
                                 else f"self.{token[1]}")
                        self._emit(
                            mod, node, "KVM071",
                            f"`{label}` is donated to `{root.name}` here "
                            f"but read again on line {read.lineno} — the "
                            "buffer was surrendered to XLA (contents "
                            "undefined after dispatch); rebind it to the "
                            "call's result, or mark `# kvmini: buffer-ok`",
                            fn.qualname)
                        break

    # -- KVM072: buffer threaded through a root without donation ----------
    def _check_undonated_carry(self, mod: ModuleFacts,
                               fn: FunctionInfo) -> None:
        for idx, p in enumerate(fn.params):
            if not BUFFERISH.search(p):
                continue
            if idx in fn.donated_argnums or p in fn.donated_argnames:
                continue
            derived = {p}
            for _ in range(3):
                grew = False
                for node in iter_scope(fn.node):
                    if not isinstance(node, ast.Assign):
                        continue
                    if not any(isinstance(n, ast.Name) and n.id in derived
                               and isinstance(n.ctx, ast.Load)
                               for n in ast.walk(node.value)):
                        continue
                    for tgt in node.targets:
                        for t in ast.walk(tgt):
                            if (isinstance(t, ast.Name)
                                    and BUFFERISH.search(t.id)
                                    and t.id not in derived):
                                derived.add(t.id)
                                grew = True
                if not grew:
                    break
            for node in iter_scope(fn.node):
                if not (isinstance(node, ast.Return)
                        and node.value is not None):
                    continue
                hit = next((n.id for n in ast.walk(node.value)
                            if isinstance(n, ast.Name) and n.id in derived),
                           None)
                if hit is not None:
                    self._emit(
                        mod, node, "KVM072",
                        f"jit root `{fn.name}` returns updated buffer "
                        f"`{hit}` but does not donate param `{p}` — both "
                        "generations stay resident (steady-state HBM "
                        "doubles); add donate_argnums, or mark "
                        "`# kvmini: buffer-ok`", fn.qualname)
                    break
            else:
                continue
            break

    # -- KVM073: free-list double-free / use-after-free --------------------
    @staticmethod
    def _free_event(stmt: ast.AST) -> Iterable[tuple[str, ast.Call]]:
        """(freed bare-name, call node) for `<freelist>.append(x)` sites."""
        for n in ast.walk(stmt):
            if not (isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr == "append"
                    and len(n.args) == 1
                    and isinstance(n.args[0], ast.Name)):
                continue
            base = n.func.value
            base_name = (base.attr if isinstance(base, ast.Attribute)
                         else base.id if isinstance(base, ast.Name) else "")
            if FREELIST.match(base_name):
                yield n.args[0].id, n

    def _check_block_lifecycle(self, mod: ModuleFacts,
                               fn: FunctionInfo) -> None:
        # one cheap pre-scan: almost no function frees blocks, and the
        # suite machinery below re-walks each nesting level
        if not any(True for _ in self._free_event(fn.node)):
            return
        pos = _positions(fn.node)
        exits = _exits(fn.node)
        for suite in self._suites(fn.node):
            # freed name -> the free call (first wins); cleared on rebind
            freed: dict[str, ast.Call] = {}
            for stmt in suite:
                for name, call in self._free_event(stmt):
                    first = freed.get(name)
                    if first is not None:
                        if not _exit_between(pos, exits, first, call):
                            self._emit(
                                mod, call, "KVM073",
                                f"block id `{name}` freed twice — the "
                                "first free already returned it to the "
                                "pool (another request may own it now); "
                                "drop this one, or mark "
                                "`# kvmini: buffer-ok`", fn.qualname)
                    else:
                        self._use_after_free_scan(mod, fn, suite, stmt,
                                                  name, call, pos, exits)
                        freed[name] = call
                for n in ast.walk(stmt):
                    if (isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store)
                            and n.id in freed
                            and n.lineno > freed[n.id].lineno):
                        freed.pop(n.id, None)

    def _use_after_free_scan(self, mod: ModuleFacts, fn: FunctionInfo,
                             suite: list[ast.AST], free_stmt: ast.AST,
                             name: str, call: ast.Call,
                             pos: dict[int, Path],
                             exits: list[ast.AST]) -> None:
        """Flag `table[<name>]`-style index uses in later sibling stmts."""
        started = False
        for stmt in suite:
            if stmt is free_stmt:
                started = True
                continue
            if not started:
                continue
            for n in ast.walk(stmt):
                if (isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store)
                        and n.id == name):
                    return  # rebound: new block id, tracking ends
                if (isinstance(n, ast.Subscript)
                        and any(isinstance(s, ast.Name) and s.id == name
                                for s in ast.walk(n.slice))):
                    if _exit_between(pos, exits, call, n):
                        # the freeing path returns/raises before this use
                        # (early-error cleanup followed by the happy path)
                        return
                    self._emit(
                        mod, n, "KVM073",
                        f"block id `{name}` used as an index after being "
                        f"freed on line {call.lineno} — the id may already "
                        "belong to another request (stale write corrupts "
                        "their KV); use it before freeing, or mark "
                        "`# kvmini: buffer-ok`", fn.qualname)
                    return

    @staticmethod
    def _suites(fn_node: ast.AST) -> Iterable[list[ast.AST]]:
        """Every statement suite (ordered sibling list) in the function."""
        stack = [fn_node]
        while stack:
            n = stack.pop()
            for field in ("body", "orelse", "finalbody"):
                suite = getattr(n, field, None)
                if isinstance(suite, list) and suite:
                    yield suite
            for c in ast.iter_child_nodes(n):
                if not isinstance(c, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.ClassDef)):
                    stack.append(c)

    # -- KVM074: retained-LRU claim without unpin --------------------------
    def _check_retained_claim(self, mod: ModuleFacts,
                              fn: FunctionInfo) -> None:
        touches_retained = False
        unpins = False
        claims: list[ast.AST] = []
        for node in iter_scope(fn.node):
            if isinstance(node, ast.Attribute) and RETAINED.search(node.attr):
                touches_retained = True
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in {"pop", "popitem"}):
                base = node.func.value
                if (isinstance(base, ast.Attribute)
                        and RETAINED.search(base.attr)):
                    unpins = True
            is_claim = False
            if (isinstance(node, ast.Assign)
                    and isinstance(node.targets[0], ast.Subscript)
                    and any(isinstance(b, ast.BinOp)
                            and isinstance(b.op, ast.Add)
                            for b in ast.walk(node.value))):
                tgt = node.targets[0].value
                is_claim = isinstance(tgt, ast.Attribute) and bool(
                    RC_NAME.search(tgt.attr))
            elif (isinstance(node, ast.AugAssign)
                    and isinstance(node.op, ast.Add)
                    and isinstance(node.target, ast.Subscript)):
                tgt = node.target.value
                is_claim = isinstance(tgt, ast.Attribute) and bool(
                    RC_NAME.search(tgt.attr))
            if is_claim:
                claims.append(node)
        if touches_retained and claims and not unpins:
            for node in claims:
                self._emit(
                    mod, node, "KVM074",
                    f"refcount bumped in `{fn.name}` while the retained "
                    "LRU is in play, but the block is never popped from "
                    "the LRU — eviction can reap a block in active use; "
                    "pop it when claiming, or mark `# kvmini: buffer-ok`",
                    fn.qualname)


def check(index: FactIndex) -> list[Diagnostic]:
    return BufferLifecycleChecker(index).run()
