"""Runtime-side request tracing: bounded ring-buffer span recorder.

The loadgen already traces the client leg (loadgen/tracing.py) and
propagates W3C ``traceparent`` headers; this module is the SERVER leg.
The engine stamps per-request phase spans (queue wait, prefill, decode,
cancellation) plus engine-lane dispatch->retire window spans, and
runtime/server.py exposes the buffer at ``GET /traces`` in the same
OTLP/JSON shape the loadgen exports — so the analyzer can join the two
legs by trace_id into one ``runs/<id>/traces/traces.json``
(analysis/traces.py, docs/TRACING.md).

Design constraints (the overhead guard, pinned by tests/test_tracing.py):

- **Bounded memory**: spans land in a ``deque(maxlen=capacity)`` — old
  spans evict, recording never grows the buffer past capacity.
- **Bounded allocations per request**: the engine stamps at most
  ``MAX_REQUEST_SPANS`` spans per request (one tuple + one small dict
  each); no per-token recording ever happens on the decode hot path.
- **JAX-free**: importable by the harness layers (mock server, analyzer
  tests) without touching the accelerator stack.

Phase histograms (``kvmini_tpu_phase_seconds``) live here too: plain
cumulative-bucket counters the engine observes once per phase transition
and /metrics renders in Prometheus histogram exposition.
"""

from __future__ import annotations

import secrets
from collections import deque
from typing import Any, Iterable, Optional

# the engine's per-request span ceiling: server.queue + server.handoff
# (disaggregated admissions only, docs/DISAGGREGATION.md) +
# server.prefill + server.decode + server.cancel. A request can never
# allocate more spans than this — the recorder-overhead contract tests
# pin against it.
MAX_REQUEST_SPANS = 5

# request phases with /metrics histograms (kvmini_tpu_phase_seconds);
# "emit" is the per-sweep host emission window of the decode pipeline,
# "handoff" the prefill-lane route->consume window of disaggregated
# admissions (zero observations on colocated engines)
PHASES = ("queue", "handoff", "prefill", "decode", "emit")

# OTLP scope name every server-leg exporter uses (the real runtime AND the
# mock); the analyzer's merge keys off it to stay idempotent — re-analyzing
# a run replaces the previously merged server leg instead of duplicating it
SERVER_SCOPE = "kserve_vllm_mini_tpu.runtime"

# OTLP scope name the fleet router's span ring exports under
# (fleet/router.py GET /traces). A separate scope keeps the analyzer's
# idempotent strip-and-replace working per LANE: re-stitching a run
# replaces the router leg and the server leg independently.
ROUTER_SCOPE = "kserve_vllm_mini_tpu.fleet"

# histogram bucket upper bounds (seconds). Spans request-phase scales from
# sub-ms queue waits on an idle engine to multi-second long decodes.
PHASE_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
)


def new_trace_id() -> str:
    return secrets.token_hex(16)


def new_span_id() -> str:
    return secrets.token_hex(8)


_HEX_CHARS = frozenset("0123456789abcdef")


def is_hex_id(v: Any, width: int) -> bool:
    """Strict lowercase-hex id of exactly ``width`` chars — the W3C
    trace-context charset and the TRACES_JSON_SCHEMA pattern. int(v, 16)
    is NOT equivalent: it accepts uppercase, '0x' prefixes and underscore
    separators, which would let ids through that the published schema
    rejects."""
    return (
        isinstance(v, str) and len(v) == width and _HEX_CHARS.issuperset(v)
    )


def parse_traceparent(header: Optional[str]) -> Optional[tuple[str, str]]:
    """W3C trace-context header -> (trace_id, parent_span_id), or None on
    anything malformed. Accepts the ``00-<32hex>-<16hex>-<2hex>`` shape
    the loadgen emits (loadgen/tracing.py traceparent()); hex is
    lowercase-only per the W3C spec."""
    if not header:
        return None
    parts = header.strip().split("-")
    if len(parts) != 4:
        return None
    _ver, trace_id, span_id, _flags = parts
    if not is_hex_id(trace_id, 32) or not is_hex_id(span_id, 16):
        return None
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return trace_id, span_id


def _otlp_attr(k: str, v: Any) -> dict[str, Any]:
    if isinstance(v, bool):
        val: dict[str, Any] = {"boolValue": v}
    elif isinstance(v, int):
        val = {"intValue": str(v)}
    elif isinstance(v, float):
        val = {"doubleValue": v}
    else:
        val = {"stringValue": str(v)}
    return {"key": k, "value": val}


def span_to_otlp(rec: tuple) -> dict[str, Any]:
    """One recorded span tuple -> OTLP/JSON span. Tuples are 8-wide
    (legacy engine records, SPAN_KIND_SERVER implied) or 9-wide with an
    explicit OTLP kind as the last element (the router's fleet.proxy
    client-leg spans record kind 3)."""
    name, trace_id, span_id, parent_span_id, start_ns, end_ns, ok, attrs = (
        rec[:8]
    )
    kind = rec[8] if len(rec) > 8 else 2  # SPAN_KIND_SERVER default
    if end_ns < start_ns:
        # never-ended / clock-skewed record: clamp rather than export a
        # negative duration (same rule the client tracer applies at export)
        end_ns, ok = start_ns, False
    return {
        "traceId": trace_id,
        "spanId": span_id,
        **({"parentSpanId": parent_span_id} if parent_span_id else {}),
        "name": name,
        "kind": kind,
        "startTimeUnixNano": str(start_ns),
        "endTimeUnixNano": str(end_ns),
        "attributes": [_otlp_attr(k, v) for k, v in (attrs or {}).items()],
        "status": {"code": 1 if ok else 2},
    }


class SpanRecorder:
    """Bounded ring buffer of completed spans.

    Spans are recorded post-hoc (start AND end already known) as flat
    tuples — no open-span bookkeeping, no growth past ``capacity``. The
    scheduler thread appends; /traces snapshots from the aiohttp thread
    (deque append/iteration are atomic enough under the GIL for this
    monitoring surface — a torn read costs at most one span)."""

    def __init__(self, capacity: int = 4096) -> None:
        if capacity < 1:
            raise ValueError(f"trace buffer capacity {capacity} must be >= 1")
        self.capacity = capacity
        self._spans: "deque[tuple]" = deque(maxlen=capacity)
        self.dropped = 0  # evicted span count (buffer wrapped)

    def __len__(self) -> int:
        # Deliberately lock-free monitoring surface (class docstring):
        # scheduler-thread appends are atomic under the GIL and snapshot()
        # takes a C-level copy; a torn read costs at most one span.
        return len(self._spans)

    def record(
        self,
        name: str,
        trace_id: str,
        start_ns: int,
        end_ns: int,
        parent_span_id: Optional[str] = None,
        ok: bool = True,
        attrs: Optional[dict[str, Any]] = None,
        kind: int = 2,
        span_id: Optional[str] = None,
    ) -> str:
        """Append one completed span; returns its span id (generated when
        ``span_id`` is not supplied — the router pre-mints attempt span
        ids so it can rewrite the outgoing traceparent BEFORE the span's
        end time is known)."""
        sid = span_id or new_span_id()
        if len(self._spans) == self.capacity:
            self.dropped += 1  # kvmini: async-ok — single-writer counter
        # kvmini: async-ok — lock-free by contract (class docstring)
        self._spans.append(
            (name, trace_id, sid, parent_span_id, start_ns, end_ns, ok,
             attrs, kind)
        )
        return sid

    def snapshot(self) -> list[tuple]:
        return list(self._spans)

    def to_otlp(
        self,
        service_name: str = "kvmini-tpu-runtime",
        scope: str = SERVER_SCOPE,
    ) -> dict[str, Any]:
        """Same resourceSpans document shape as loadgen/tracing.py, so the
        analyzer merges both legs with one parser. Renders from snapshot():
        iterating the live deque directly would race the scheduler thread's
        appends (RuntimeError: deque mutated during iteration) — list(deque)
        is one C-level copy and safe under the GIL. The router exports
        under ``scope=ROUTER_SCOPE`` so the analyzer can strip/replace its
        lane independently of the server leg."""
        return {
            "resourceSpans": [
                {
                    "resource": {
                        "attributes": [
                            {
                                "key": "service.name",
                                "value": {"stringValue": service_name},
                            }
                        ]
                    },
                    "scopeSpans": [
                        {
                            "scope": {"name": scope},
                            "spans": [span_to_otlp(r) for r in self.snapshot()],
                        }
                    ],
                }
            ],
            # Monotonic int bumped only by the recording thread; a stale
            # read costs an off-by-one drop count in a monitoring doc.
            "droppedSpans": self.dropped,
        }


class PhaseHistogram:
    """Cumulative-bucket histogram (Prometheus semantics) for one phase.
    ``observe`` is two int increments and a float add — cheap enough to
    stay on even when span recording is disabled."""

    __slots__ = ("counts", "sum", "count")

    def __init__(self) -> None:
        self.counts = [0] * (len(PHASE_BUCKETS) + 1)  # +1 = +Inf bucket
        self.sum = 0.0
        self.count = 0

    def observe(self, seconds: float) -> None:
        if seconds < 0:
            seconds = 0.0
        i = 0
        for i, le in enumerate(PHASE_BUCKETS):  # noqa: B007 — small, fixed
            if seconds <= le:
                break
        else:
            i = len(PHASE_BUCKETS)
        self.counts[i] += 1
        self.sum += seconds
        self.count += 1

    def snapshot(self) -> dict[str, Any]:
        cum, total = [], 0
        for c in self.counts[: len(PHASE_BUCKETS)]:
            total += c
            cum.append(total)
        return {"buckets": cum, "sum": self.sum, "count": self.count}


def render_phase_histograms(
    hists: dict[str, "PhaseHistogram"],
    metric: str = "kvmini_tpu_phase_seconds",
) -> list[str]:
    """Prometheus text-exposition lines for the phase histograms — shared
    by runtime/server.py /metrics and tests/mock_server.py so the scrape
    path is exercised end-to-end without the JAX engine."""
    lines = [f"# TYPE {metric} histogram"]
    for phase, h in hists.items():
        snap = h.snapshot()
        for le, cum in zip(PHASE_BUCKETS, snap["buckets"]):
            lines.append(
                f'{metric}_bucket{{phase="{phase}",le="{le}"}} {cum}'
            )
        lines.append(
            f'{metric}_bucket{{phase="{phase}",le="+Inf"}} {snap["count"]}'
        )
        lines.append(f'{metric}_sum{{phase="{phase}"}} {snap["sum"]:.6f}')
        lines.append(f'{metric}_count{{phase="{phase}"}} {snap["count"]}')
    return lines


def spans_from_otlp(doc: dict[str, Any]) -> Iterable[tuple[str, dict[str, Any]]]:
    """Yield (service_name, span) pairs from an OTLP/JSON document —
    the one parser both report/html.py and analysis/traces.py use."""
    for rs in doc.get("resourceSpans", []) or []:
        service = "unknown"
        for a in (rs.get("resource") or {}).get("attributes", []) or []:
            if a.get("key") == "service.name":
                service = (a.get("value") or {}).get("stringValue", service)
        for ss in rs.get("scopeSpans", []) or []:
            for s in ss.get("spans", []) or []:
                yield service, s
