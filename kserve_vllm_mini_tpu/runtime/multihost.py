"""Multi-host serving: one HTTP URL over a process-spanning mesh.

The 70B-on-v5p-16 serving story (BASELINE.md configs[4]) needs the model
sharded across HOSTS, not just chips: 4 hosts x 4 chips join one
``jax.distributed`` runtime (parallel/distributed.py), the engine's params
and KV cache shard over the global mesh, and every jitted step is a
collective program all processes must execute in lockstep. The reference
only passes TP knobs through to engine images
(/root/reference/runners/backends/vllm/deploy.sh:78-79); here the runtime
is in-repo, so the multi-host split is explicit:

- **Process 0 (primary)** owns the HTTP frontend and the scheduler: it
  decides, per loop iteration, whether to admit a request or run a decode
  sweep — and PUBLISHES each decision (with the request payload) to the
  other processes over a host-level TCP channel before executing it.
- **Followers** replay the identical decision stream against their own
  ``Engine`` instance. Engine state evolves deterministically from the
  decision stream (same seed -> same rng splits, same slot bookkeeping,
  same readback values — outputs are replicated when dp == 1), so every
  process issues the SAME jitted calls in the SAME order with the SAME
  operands, which is exactly the contract XLA's multi-controller model
  requires. The channel carries only small host-side payloads (prompt ids,
  sampling params); tensors never cross it.

V1 scope (checked, not silent): dp == 1 meshes (tp/pp sharding — the
natural multi-host serving layouts; dp>1 would make per-slot outputs
non-addressable per process), no grammar constraints (their masks are
host-built per step; payload plumbing is straightforward but not wired),
no speculative drafter. Logprobs and sampling work — both are
deterministic device-side computations.

Lockstep hazard note: if the primary dies mid-publish, followers block in
a collective or on the channel; deploy with the pod-level failure domain
(one InferenceService replica = one process group), which is how the
reference's engines handle it too.
"""

from __future__ import annotations

import pickle
import socket
import struct
import threading
from typing import Any, Iterator, Optional

from kserve_vllm_mini_tpu.runtime.engine import Engine, GenRequest, RequestHandle

_LEN = struct.Struct("!I")


class CommandPublisher:
    """Primary-side channel: accepts ``n_followers`` connections, then
    publishes pickled commands, length-prefixed, to all of them."""

    def __init__(self, host: str, port: int, n_followers: int,
                 accept_timeout_s: float = 60.0) -> None:
        self._srv = socket.create_server((host, port))
        self._srv.settimeout(accept_timeout_s)
        self._conns: list[socket.socket] = []
        for _ in range(n_followers):
            conn, _addr = self._srv.accept()
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._conns.append(conn)
        self._lock = threading.Lock()

    def publish(self, cmd: tuple) -> None:
        data = pickle.dumps(cmd, protocol=pickle.HIGHEST_PROTOCOL)
        msg = _LEN.pack(len(data)) + data
        with self._lock:
            for c in self._conns:
                c.sendall(msg)

    def close(self) -> None:
        for c in self._conns:
            try:
                c.close()
            except OSError:
                pass
        self._srv.close()


class CommandSubscriber:
    """Follower-side channel: connects (with retries — the primary may not
    be listening yet) and yields commands in publish order."""

    def __init__(self, host: str, port: int, connect_timeout_s: float = 60.0) -> None:
        import time as _time

        deadline = _time.time() + connect_timeout_s
        while True:
            try:
                self._conn = socket.create_connection((host, port), timeout=5.0)
                break
            except OSError:
                if _time.time() > deadline:
                    raise
                _time.sleep(0.2)
        self._conn.settimeout(None)  # commands may be minutes apart

    def _read_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self._conn.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("publisher closed the command channel")
            buf += chunk
        return buf

    def commands(self) -> Iterator[tuple]:
        while True:
            (n,) = _LEN.unpack(self._read_exact(_LEN.size))
            yield pickle.loads(self._read_exact(n))

    def close(self) -> None:
        self._conn.close()


# -- request payload (host-side fields only; tensors never cross) -----------

_REQ_FIELDS = (
    "prompt_tokens", "max_new_tokens", "temperature", "top_k", "top_p",
    "eos_id", "request_id", "truncated", "truncated_tokens",
    "logprobs", "top_logprobs",
)


def req_payload(req: GenRequest) -> dict[str, Any]:
    if req.constraint is not None:
        raise ValueError(
            "multi-host serving does not support grammar constraints (v1)"
        )
    return {f: getattr(req, f) for f in _REQ_FIELDS}


def req_from_payload(payload: dict[str, Any]) -> GenRequest:
    return GenRequest(**payload)


def check_multihost_engine(engine: Engine) -> None:
    """Fail fast on configurations outside the lockstep contract."""
    if engine.mesh is None:
        raise ValueError("multi-host serving needs a process-spanning mesh")
    if engine.mesh.shape.get("dp", 1) > 1:
        raise ValueError(
            "multi-host serving requires dp == 1 (per-slot outputs must be "
            "replicated so every process reads identical values); use tp/pp"
        )
    if engine.ecfg.spec_tokens > 0:
        raise ValueError("multi-host serving does not support a drafter (v1)")


def run_primary(engine: Engine, publisher: CommandPublisher,
                stop_event: threading.Event) -> None:
    """Engine's own scheduling policy (_schedule_once), with every
    state-advancing decision published to the followers before it executes
    locally — one policy, two drivers, no drift."""
    check_multihost_engine(engine)

    def publish(decision: tuple) -> None:
        if decision[0] == "admit":
            publisher.publish(("admit", req_payload(decision[1])))
        else:
            publisher.publish(decision)

    try:
        while not stop_event.is_set():
            engine._schedule_once(on_decision=publish)
    except Exception as exc:  # noqa: BLE001 — propagate as request failures
        import traceback

        traceback.print_exc()
        engine._fail_all(exc)
    finally:
        publisher.publish(("stop",))


def run_follower(engine: Engine, subscriber: CommandSubscriber) -> None:
    """Replay the primary's decision stream. Blocks until ('stop',)."""
    check_multihost_engine(engine)
    for cmd in subscriber.commands():
        op = cmd[0]
        if op == "admit":
            # bypass submit(): the primary already applied truncation; the
            # payload is the exact request its engine admitted
            engine._admit_one(RequestHandle(req_from_payload(cmd[1])))
        elif op == "sweep":
            engine._decode_sweep()
        elif op == "stop":
            return
        else:
            raise ValueError(f"unknown multihost command {op!r}")


def serve_multihost(
    engine: Engine,
    *,
    primary: bool,
    coordinator_host: str,
    command_port: int,
    n_followers: int,
) -> Optional[threading.Event]:
    """Start the lockstep drivers. On the primary returns a stop Event (set
    it to shut down; the HTTP app runs separately); on followers BLOCKS
    until the primary publishes stop, then returns None."""
    if primary:
        publisher = CommandPublisher("0.0.0.0", command_port, n_followers)
        stop = threading.Event()
        t = threading.Thread(
            target=run_primary, args=(engine, publisher, stop),
            daemon=True, name="multihost-primary",
        )
        t.start()
        return stop
    sub = CommandSubscriber(coordinator_host, command_port)
    run_follower(engine, sub)
    return None
