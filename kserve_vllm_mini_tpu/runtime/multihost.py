"""Multi-host serving: one HTTP URL over a process-spanning mesh.

The 70B-on-v5p-16 serving story (BASELINE.md configs[4]) needs the model
sharded across HOSTS, not just chips: 4 hosts x 4 chips join one
``jax.distributed`` runtime (parallel/distributed.py), the engine's params
and KV cache shard over the global mesh, and every jitted step is a
collective program all processes must execute in lockstep. The reference
only passes TP knobs through to engine images
(/root/reference/runners/backends/vllm/deploy.sh:78-79); here the runtime
is in-repo, so the multi-host split is explicit:

- **Process 0 (primary)** owns the HTTP frontend and the scheduler: it
  decides, per loop iteration, whether to admit a request or run a decode
  sweep — and PUBLISHES each decision (with the request payload) to the
  other processes over a host-level TCP channel before executing it.
- **Followers** replay the identical decision stream against their own
  ``Engine`` instance. Engine state evolves deterministically from the
  decision stream (same seed -> same rng splits, same slot bookkeeping,
  same readback values — outputs are replicated when dp == 1), so every
  process issues the SAME jitted calls in the SAME order with the SAME
  operands, which is exactly the contract XLA's multi-controller model
  requires. The channel carries only small host-side payloads (prompt ids,
  sampling params); tensors never cross it.

V1 scope (checked, not silent): dp == 1 meshes (tp/pp sharding — the
natural multi-host serving layouts; dp>1 would make per-slot outputs
non-addressable per process), no grammar constraints (their masks are
host-built per step; payload plumbing is straightforward but not wired),
no speculative drafter. Logprobs and sampling work — both are
deterministic device-side computations.

Lockstep hazard note: if the primary dies mid-publish, followers block in
a collective or on the channel; deploy with the pod-level failure domain
(one InferenceService replica = one process group), which is how the
reference's engines handle it too.
"""

from __future__ import annotations

import hmac
import json
import os
import socket
import struct
import threading
import time
from typing import Any, Iterator, Optional

from kserve_vllm_mini_tpu.runtime.engine import Engine, GenRequest, RequestHandle

_LEN = struct.Struct("!I")


def _channel_timeout_s() -> float:
    """Handshake window: every process must finish build_engine (minutes
    for a sharded 70B weight load) before the channel forms."""
    return float(os.environ.get("KVMINI_COMMAND_TIMEOUT", "600"))


def _channel_token() -> str:
    """Shared channel secret (KVMINI_COMMAND_TOKEN). The empty default
    still rejects stray scanners via the handshake structure; production
    deployments set a real token — the admit stream carries user
    prompts."""
    return os.environ.get("KVMINI_COMMAND_TOKEN", "")


def engine_fingerprint(engine: Engine) -> dict[str, Any]:
    """Everything that must MATCH across the process group for lockstep
    replay to produce identical jitted programs and identical state."""
    import jax

    e = engine.ecfg
    return {
        "model": engine.cfg.name,
        "vocab_size": engine.cfg.vocab_size,
        "n_layers": engine.cfg.n_layers,
        "max_slots": e.max_slots,
        "max_seq_len": e.max_seq_len,
        "max_prefill_len": e.max_prefill_len,
        "min_prefill_bucket": e.min_prefill_bucket,
        "decode_chunk": e.decode_chunk,
        "decode_pipeline": e.decode_pipeline,
        "seed": e.seed,
        "kv_cache_dtype": e.kv_cache_dtype,
        "spec_tokens": e.spec_tokens,
        "pp_microbatches": e.pp_microbatches,
        "mesh": dict(engine.mesh.shape) if engine.mesh is not None else None,
        "jax": jax.__version__,
    }


def _send_msg(conn: socket.socket, obj: Any) -> None:
    # JSON, never pickle: the hello arrives from an UNAUTHENTICATED peer,
    # and unpickling attacker bytes is arbitrary code execution — a token
    # check after the fact cannot protect the deserializer itself. Every
    # payload on this channel (hello, ack, admit/sweep/stop commands) is
    # JSON-able by construction.
    data = json.dumps(obj).encode()
    conn.sendall(_LEN.pack(len(data)) + data)


def _recv_msg(conn: socket.socket, max_len: int = 1 << 24) -> Any:
    def read_exact(n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = conn.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("command channel peer closed")
            buf += chunk
        return buf

    (n,) = _LEN.unpack(read_exact(_LEN.size))
    if n > max_len:
        raise ConnectionError(f"oversized channel message ({n} bytes)")
    try:
        return json.loads(read_exact(n).decode("utf-8"))
    except (ValueError, UnicodeDecodeError) as e:
        raise ConnectionError(f"malformed channel message: {e}") from e


class CommandPublisher:
    """Primary-side channel: accepts follower connections, verifies each
    one's handshake (shared token + engine-config fingerprint), then
    publishes JSON commands, length-prefixed, to all of them."""

    def __init__(self, host: str, port: int, n_followers: int,
                 fingerprint: Optional[dict] = None,
                 accept_timeout_s: Optional[float] = None) -> None:
        timeout = accept_timeout_s or _channel_timeout_s()
        token = _channel_token()
        self._srv = socket.create_server((host, port))
        self._conns: list[socket.socket] = []
        deadline = time.time() + timeout
        while len(self._conns) < n_followers:
            self._srv.settimeout(max(deadline - time.time(), 0.1))
            conn, addr = self._srv.accept()
            mismatch_diff = None
            try:
                conn.settimeout(10.0)
                hello = _recv_msg(conn)
                peer_tok = (hello or {}).get("token") if isinstance(hello, dict) else None
                # compare as BYTES: compare_digest on str raises for
                # non-ASCII, and a random-secret token may well contain it
                if not (isinstance(peer_tok, str)
                        and hmac.compare_digest(peer_tok.encode(), token.encode())):
                    # wrong/garbage secret: explicit rejection so a typo'd
                    # deployment fails fast on the follower side, slot NOT
                    # consumed so a scanner can't starve the real follower
                    _send_msg(conn, {"ok": False, "reason": "token mismatch"})
                    conn.close()
                    continue
                peer_fp = hello.get("fingerprint") or {}
                if fingerprint is not None and peer_fp != fingerprint:
                    mismatch_diff = {
                        k: (fingerprint.get(k), peer_fp.get(k))
                        for k in set(fingerprint) | set(peer_fp)
                        if fingerprint.get(k) != peer_fp.get(k)
                    }
                    # ack + close are best-effort: the fatal raise below
                    # must fire even if the peer already went away
                    try:
                        _send_msg(conn, {"ok": False, "reason": "config mismatch",
                                         "diff": {k: list(v) for k, v in
                                                  mismatch_diff.items()}})
                    except OSError:  # kvmini: workload-ok — best-effort nack;
                        pass         # the fatal mismatch raise below still fires
                    try:
                        conn.close()
                    except OSError:  # kvmini: workload-ok — peer already gone
                        pass
                else:
                    _send_msg(conn, {"ok": True})
                    # finite SEND timeout: publish() must never block the
                    # scheduler (or shutdown) forever on a silently-dead
                    # follower — this socket only ever sends
                    conn.settimeout(30.0)
                    conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                    self._conns.append(conn)
            except Exception:  # noqa: BLE001 — kvmini: workload-ok —
                # garbage traffic must not take the primary down;
                # authenticated-path errors surface later on publish
                try:
                    conn.close()
                except OSError:  # kvmini: workload-ok — peer already gone
                    pass
                continue
            if mismatch_diff is not None:
                # an AUTHENTICATED follower with a different engine config
                # is fatal for the whole group — lockstep would diverge
                raise ValueError(
                    f"follower {addr} engine config mismatches primary: "
                    f"{mismatch_diff}"
                )
        self._lock = threading.Lock()
        self._stopped = False

    def publish(self, cmd: tuple) -> None:
        data = json.dumps(cmd).encode()
        msg = _LEN.pack(len(data)) + data
        with self._lock:
            if self._stopped and cmd[0] == "stop":
                return  # idempotent shutdown
            if cmd[0] == "stop":
                self._stopped = True
            # attempt EVERY follower before raising: on a partial failure
            # the survivors must still get the command (above all 'stop'),
            # and any failure is fatal for lockstep so it propagates after
            first_err: Optional[OSError] = None
            for c in self._conns:
                try:
                    c.sendall(msg)
                except OSError as e:
                    first_err = first_err or e
            if first_err is not None:
                raise first_err

    def close(self) -> None:
        for c in self._conns:
            try:
                c.close()
            except OSError:
                pass
        self._srv.close()


class CommandSubscriber:
    """Follower-side channel: connects (with retries — the primary may not
    be listening yet), handshakes (token + fingerprint), and yields
    commands in publish order."""

    def __init__(self, host: str, port: int,
                 fingerprint: Optional[dict] = None,
                 connect_timeout_s: Optional[float] = None) -> None:
        timeout = connect_timeout_s or _channel_timeout_s()
        deadline = time.time() + timeout
        while True:
            try:
                self._conn = socket.create_connection((host, port), timeout=5.0)
                self._conn.settimeout(30.0)
                _send_msg(self._conn, {
                    "token": _channel_token(), "fingerprint": fingerprint,
                })
                ack = _recv_msg(self._conn)
                if not (isinstance(ack, dict) and ack.get("ok")):
                    # explicit rejection (config mismatch): NOT retryable —
                    # ValueError escapes the OSError retry loop
                    raise ValueError(f"primary rejected handshake: {ack!r}")
                break
            except OSError:
                if time.time() > deadline:
                    raise
                time.sleep(0.2)
        self._conn.settimeout(None)  # commands may be minutes apart

    def commands(self) -> Iterator[tuple]:
        while True:
            msg = _recv_msg(self._conn)
            yield tuple(msg) if isinstance(msg, list) else msg

    def close(self) -> None:
        self._conn.close()


# -- request payload (host-side fields only; tensors never cross) -----------

_REQ_FIELDS = (
    "prompt_tokens", "max_new_tokens", "temperature", "top_k", "top_p",
    "presence_penalty", "frequency_penalty",
    "eos_id", "request_id", "truncated", "truncated_tokens",
    "logprobs", "top_logprobs",
)

# every sampling-relevant GenRequest field must cross to the followers, or
# lockstep decode diverges (each process builds its own sampling arrays) —
# this guard turns "someone added a field" into a loud test failure instead
# of silent divergence
_HOST_ONLY_FIELDS = {"constraint", "adapter", "trace_id", "parent_span_id",
                     # deadline shedding is lockstep-DISABLED (engine
                     # _admit_one): the wall-clock shed decision is
                     # host-local, so the field never crosses
                     "deadline_s"}
assert set(_REQ_FIELDS) | _HOST_ONLY_FIELDS == {
    f.name for f in __import__("dataclasses").fields(GenRequest)
}, "GenRequest fields changed: update _REQ_FIELDS (or _HOST_ONLY_FIELDS)"


def req_payload(req: GenRequest) -> dict[str, Any]:
    if req.constraint is not None:
        raise ValueError(
            "multi-host serving does not support grammar constraints (v1)"
        )
    if req.adapter is not None:
        # the adapter name is resolved against the PRIMARY's bank registry;
        # followers would silently serve the base model (lockstep divergence)
        raise ValueError("multi-host serving does not support LoRA (v1)")
    return {f: getattr(req, f) for f in _REQ_FIELDS}


def req_from_payload(payload: dict[str, Any]) -> GenRequest:
    return GenRequest(**payload)


def check_multihost_engine(engine: Engine) -> None:
    """Fail fast on configurations outside the lockstep contract."""
    if engine.mesh is None:
        raise ValueError("multi-host serving needs a process-spanning mesh")
    if engine._disagg is not None:
        # the prefill lane and its handoff queue are host-local state the
        # decision stream does not carry: a follower replaying ("admit",)
        # against a lane-routed primary would prefill colocated and
        # diverge its cache/rng sequence. Loud, not silent — the v2 path
        # is a PUBLISHED handoff decision (ROADMAP item 1 notes).
        raise ValueError(
            "disaggregated prefill (disagg) is not supported under "
            "multi-host lockstep serving (v1); drop --disagg or "
            "--distributed"
        )
    if engine.mesh.shape.get("dp", 1) > 1:
        raise ValueError(
            "multi-host serving requires dp == 1 (per-slot outputs must be "
            "replicated so every process reads identical values); use tp/pp"
        )
    if engine.ecfg.spec_tokens > 0:
        raise ValueError("multi-host serving does not support a drafter (v1)")
    if engine._lora is not None:
        raise ValueError(
            "multi-host serving does not support LoRA (v1): adapter routing "
            "is resolved against the primary's bank only"
        )


def run_primary(engine: Engine, publisher: CommandPublisher,
                stop_event: threading.Event) -> None:
    """Engine's own scheduling policy (_schedule_once), with every
    state-advancing decision published to the followers before it executes
    locally — one policy, two drivers, no drift."""
    check_multihost_engine(engine)
    engine._lockstep = True  # host-local-race shortcuts off (see engine)

    def publish(decision: tuple) -> None:
        # publish_drop injection point (docs/RESILIENCE.md): an armed
        # fault silently loses this decision on the wire — the follower
        # replay diverges exactly the way a dropped packet would make
        # it, which is what the chaos scenario measures. The registry
        # is internally locked; un-armed it costs one dict miss.
        if engine._faults.check("publish_drop"):
            return
        if decision[0] == "admit":
            publisher.publish(("admit", req_payload(decision[1])))
        else:
            publisher.publish(decision)

    try:
        while not stop_event.is_set():
            engine._schedule_once(on_decision=publish)
    except Exception as exc:  # noqa: BLE001 — propagate as request failures
        import traceback

        traceback.print_exc()
        engine._fail_all(exc)
    finally:
        publisher.publish(("stop",))


def run_follower(engine: Engine, subscriber: CommandSubscriber) -> None:
    """Replay the primary's decision stream. Blocks until ('stop',)."""
    check_multihost_engine(engine)
    engine._lockstep = True
    for cmd in subscriber.commands():
        op = cmd[0]
        if op == "admit":
            # bypass submit(): the primary already applied truncation; the
            # payload is the exact request its engine admitted
            engine._admit_one(RequestHandle(req_from_payload(cmd[1])))
        elif op == "sweep":
            engine._decode_sweep()
        elif op == "dispatch":
            # double-buffered steady state (docs/DECODE_PIPELINE.md): the
            # primary dispatched sweep N+1 before retiring sweep N. The
            # active set is deterministic from the replayed stream, so the
            # follower issues the identical jitted call with identical
            # operands (the token feed is the previous sweep's on-device
            # carry on both sides).
            engine._replay_dispatch()
        elif op == "retire":
            engine._retire_one()
        elif op == "cancel":
            # mirror the primary's early finish so the follower's slot
            # free-list stays identical for the replayed admissions
            _rid, reason = cmd[1], cmd[2]
            for slot in range(engine.ecfg.max_slots):
                h = engine._slot_req[slot]
                if h is not None and h.request.request_id == _rid:
                    engine._finish_slot(slot, reason)
                    break
        elif op == "stop":
            return
        else:
            raise ValueError(f"unknown multihost command {op!r}")


class PrimaryHandle:
    """Lifecycle of the primary's scheduler thread + command channel.

    ``shutdown()`` is SYNCHRONOUS: it publishes the stop command itself
    (idempotent with the thread's own finally), so followers always get
    released even when interpreter exit would otherwise freeze the daemon
    thread mid-``finally``. ``is_alive()`` feeds the HTTP health gate — a
    dead scheduler must turn the frontend unhealthy, not let requests
    queue forever."""

    def __init__(self, publisher: CommandPublisher, stop: threading.Event,
                 thread: threading.Thread) -> None:
        self._publisher = publisher
        self._stop = stop
        self._thread = thread

    def is_alive(self) -> bool:
        return self._thread.is_alive()

    def shutdown(self) -> None:
        self._stop.set()
        self._thread.join(timeout=10.0)
        try:
            self._publisher.publish(("stop",))
        except OSError:
            pass
        self._publisher.close()


def serve_multihost(
    engine: Engine,
    *,
    primary: bool,
    coordinator_host: str,
    command_port: int,
    n_followers: int,
) -> Optional[PrimaryHandle]:
    """Start the lockstep drivers. On the primary returns a PrimaryHandle
    (call ``shutdown()`` when the HTTP app exits); on followers BLOCKS
    until the primary publishes stop, then returns None."""
    fp = engine_fingerprint(engine)
    if primary:
        publisher = CommandPublisher(
            "0.0.0.0", command_port, n_followers, fingerprint=fp
        )
        stop = threading.Event()
        t = threading.Thread(
            target=run_primary, args=(engine, publisher, stop),
            daemon=True, name="multihost-primary",
        )
        t.start()
        return PrimaryHandle(publisher, stop, t)
    sub = CommandSubscriber(coordinator_host, command_port, fingerprint=fp)
    run_follower(engine, sub)
    return None
