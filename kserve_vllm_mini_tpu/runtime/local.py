"""In-process local serving: boot the JAX runtime + OpenAI HTTP server.

One helper shared by the bench pipeline, every sweep, the backend comparator,
and the chaos harness — the reference has no analog because its engines are
external container images (SURVEY.md §0); here "deploy" can mean "start a
thread", which is what makes the whole framework runnable with no cluster.
"""

from __future__ import annotations

import asyncio
import os
import socket
import threading
import time
import urllib.request
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Iterator, Optional


def _free_port() -> int:
    sock = socket.socket()
    sock.bind(("127.0.0.1", 0))
    port = sock.getsockname()[1]
    sock.close()
    return port


@dataclass
class LocalServer:
    url: str
    engine: Any
    tokenizer: Any
    model_name: str
    boot_began: float            # cold-start instant (pod-startedAt analog)
    boot_seconds: float = 0.0
    _stop: Optional[Any] = field(default=None, repr=False)

    def stop(self) -> None:
        if self._stop is not None:
            self._stop()
            self._stop = None


def start_local_server(
    profile: dict[str, Any],
    host: str = "127.0.0.1",
    ready_timeout_s: float = 120.0,
) -> LocalServer:
    """Boot engine + aiohttp server from a bench profile dict. The measured
    boot window (model init + first readiness) is the run's cold start."""
    from aiohttp import web

    from kserve_vllm_mini_tpu.runtime.server import build_engine, make_app

    port = _free_port()
    t0 = time.time()
    engine, tok, name = build_engine(
        model=profile.get("model", "llama-tiny"),
        checkpoint=profile.get("checkpoint"),
        max_slots=int(profile.get("max_slots", 8)),
        max_seq_len=int(profile.get("max_model_len", 1024)),
        topology=profile.get("jax_topology"),
        quantization=profile.get("quantization", "none") or "none",
        quant_mode=profile.get("quant_mode", "dequant") or "dequant",
        kv_cache_dtype=profile.get("kv_cache_dtype"),
        decode_chunk=int(profile.get("decode_chunk", 1)),
        # disaggregated prefill/decode lanes (docs/DISAGGREGATION.md)
        disagg=bool(profile.get("disagg", False)),
        disagg_min_prompt=int(profile.get("disagg_min_prompt", 0)),
        prefill_lane_devices=int(profile.get("prefill_lane_devices", 0)),
        scan_unroll=int(profile.get("scan_unroll", 1)),
        pp=int(profile.get("pp", 0)),
        pp_microbatches=int(profile.get("pp_microbatches", 1)),
        drafter=profile.get("drafter"),
        spec_tokens=int(
            profile.get("spec_tokens", 4 if profile.get("drafter") else 0)
        ),
        prefix_cache=bool(profile.get("prefix_cache", False)),
        kv_layout=profile.get("kv_layout", "dense"),
        kv_block_size=int(profile.get("kv_block_size", 64)),
        kv_pool_blocks=(
            int(profile["kv_pool_blocks"])
            if profile.get("kv_pool_blocks") is not None
            else None
        ),
        # live economics (docs/ECONOMICS.md): same precedence as the
        # serve CLI — profile key, then env — so a self-serve bench can
        # price itself on any backend; TPU backends auto-detect anyway
        econ_accelerator=(
            profile.get("econ_accelerator")
            or os.environ.get("KVMINI_ECON_ACCELERATOR") or None
        ),
        lora_adapters=profile.get("lora"),
        lora_demo=int(profile.get("lora_demo", 0)),
        lora_rank=int(profile.get("lora_rank", 8)),
        lora_slots=int(profile.get("lora_slots", 4)),
        # resilience knobs (docs/RESILIENCE.md): fault injection config,
        # the wedged-sweep watchdog, and deadline-aware shedding
        faults=profile.get("faults"),
        fault_seed=int(profile.get("fault_seed", 0)),
        watchdog=bool(profile.get("watchdog", False)),
        default_deadline_s=(
            float(profile["default_deadline_s"])
            if profile.get("default_deadline_s") is not None
            else None
        ),
    )
    if profile.get("watchdog_min_s") is not None:
        engine.ecfg.watchdog_min_s = float(profile["watchdog_min_s"])
    engine.start()
    app = make_app(
        engine, tok, name,
        allow_fault_injection=bool(profile.get("allow_fault_injection", False)),
    )
    runner = web.AppRunner(app)
    loop = asyncio.new_event_loop()

    def _serve() -> None:
        asyncio.set_event_loop(loop)
        loop.run_until_complete(runner.setup())
        site = web.TCPSite(runner, host, port)
        loop.run_until_complete(site.start())
        loop.run_forever()

    thread = threading.Thread(target=_serve, daemon=True, name="local-server")
    thread.start()
    url = f"http://{host}:{port}"

    deadline = time.time() + ready_timeout_s
    last_err: Optional[Exception] = None
    while time.time() < deadline:
        try:
            urllib.request.urlopen(url + "/healthz", timeout=1)
            break
        except Exception as e:  # noqa: BLE001 — readiness probe, any failure retries
            last_err = e
            time.sleep(0.2)
    else:
        engine.stop()
        raise TimeoutError(f"local server not ready after {ready_timeout_s}s: {last_err}")

    def _stop() -> None:
        engine.stop()
        loop.call_soon_threadsafe(loop.stop)
        thread.join(timeout=5.0)

    return LocalServer(
        url=url,
        engine=engine,
        tokenizer=tok,
        model_name=name,
        boot_began=t0,
        boot_seconds=time.time() - t0,
        _stop=_stop,
    )


@contextmanager
def local_server(profile: dict[str, Any], **kwargs: Any) -> Iterator[LocalServer]:
    srv = start_local_server(profile, **kwargs)
    try:
        yield srv
    finally:
        srv.stop()
