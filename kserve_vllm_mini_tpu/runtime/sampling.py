"""Token sampling under jit with per-slot parameters.

The decode step samples for all engine slots in one fused call: temperature,
top-k, and top-p are [B] vectors so heterogeneous requests batch together
(continuous batching must not re-trace when a new request's temperature
differs). Greedy is temperature == 0 via jnp.where, not Python branching —
everything stays traceable.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def apply_penalties(
    logits: jnp.ndarray,      # [B, V] float32
    counts: jnp.ndarray,      # [B, V] int32: per-slot GENERATED-token counts
    presence: jnp.ndarray,    # [B] float32; 0 => disabled
    frequency: jnp.ndarray,   # [B] float32; 0 => disabled
) -> jnp.ndarray:
    """OpenAI presence/frequency penalties over the generated-token counts.

    vLLM semantics (the reference's flagship backend, which its load
    generator exercises with these knobs — reference scripts/loadtest.py:
    260-342): penalties consider OUTPUT tokens only, not the prompt.
    ``counts`` is device-resident engine state updated inside the decode
    scan, so fused multi-step chunks see each step's emission immediately.

    Zero penalties are bit-exact identity (``x - 0.0 == x`` for every
    float including ±inf), so unpenalized requests keep oracle equality.
    """
    cf = counts.astype(logits.dtype)
    pen = frequency[:, None] * cf + jnp.where(
        counts > 0, presence[:, None], jnp.zeros_like(presence)[:, None]
    )
    return logits - pen


def count_tokens(counts: jnp.ndarray, tokens: jnp.ndarray) -> jnp.ndarray:
    """Record one sampled token per slot in the counts table: [B, V] += 1
    at (row, tokens[row])."""
    B = counts.shape[0]
    return counts.at[jnp.arange(B), tokens].add(1)


def sample_tokens(
    logits: jnp.ndarray,        # [B, V] float32
    rng: jax.Array,
    temperature: jnp.ndarray,   # [B] float32; 0 => greedy
    top_k: jnp.ndarray,         # [B] int32; 0 => disabled
    top_p: jnp.ndarray,         # [B] float32; 1.0 => disabled
) -> jnp.ndarray:
    """Returns [B] int32 sampled token ids.

    Expressed over ``filter_logits`` so the sampler and the speculative
    rejection test share ONE masking pipeline: spec decode's distribution-
    exactness depends on p/q being exactly this sampler's distribution,
    and a masking fix applied to only one copy would silently break it.
    Greedy rows still take the explicit argmax (bit-stable, and rows whose
    filtered logits are one-hot sample that token with probability 1
    anyway)."""
    greedy = jnp.argmax(logits, axis=-1)
    filtered = filter_logits(logits, temperature, top_k, top_p)
    sampled = jax.random.categorical(rng, filtered, axis=-1)
    return jnp.where(temperature > 0, sampled, greedy).astype(jnp.int32)


def filter_logits(
    logits: jnp.ndarray,        # [B, V] float32
    temperature: jnp.ndarray,   # [B] float32; 0 => greedy (one-hot dist)
    top_k: jnp.ndarray,         # [B] int32; 0 => disabled
    top_p: jnp.ndarray,         # [B] float32; 1.0 => disabled
) -> jnp.ndarray:
    """The filtered/scaled logits whose softmax IS each row's sampling
    distribution — the single masking pipeline ``sample_tokens`` samples
    from and the speculative rejection test computes p/q with (one
    implementation, so they can never drift apart).

    Temperature-0 rows become a one-hot at the argmax, which makes
    rejection-sampling verification DEGENERATE to the exact greedy accept
    rule: accept prob p(x)/q(x) is 1 on an argmax match and 0 otherwise,
    and the residual distribution is a one-hot at the target's argmax — so
    greedy requests under the sampled spec path emit bit-identical tokens
    to plain greedy decode.
    """
    B, V = logits.shape
    greedy = jnp.argmax(logits, axis=-1)

    safe_t = jnp.where(temperature > 0, temperature, 1.0)
    scaled = logits / safe_t[:, None]

    sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]
    k_idx = jnp.clip(top_k - 1, 0, V - 1)
    kth = jnp.take_along_axis(sorted_desc, k_idx[:, None], axis=-1)
    scaled = jnp.where(
        (top_k[:, None] > 0) & (scaled < kth), -jnp.inf, scaled
    )

    sorted_desc2 = jnp.sort(scaled, axis=-1)[:, ::-1]
    probs_sorted = jax.nn.softmax(sorted_desc2, axis=-1)
    cum = jnp.cumsum(probs_sorted, axis=-1)
    inside = cum - probs_sorted < top_p[:, None]
    cut = jnp.min(jnp.where(inside, sorted_desc2, jnp.inf), axis=-1)
    scaled = jnp.where(scaled < cut[:, None], -jnp.inf, scaled)

    greedy_lg = jnp.where(
        jax.nn.one_hot(greedy, V, dtype=bool), 0.0, -jnp.inf
    )
    return jnp.where(temperature[:, None] > 0, scaled, greedy_lg)


# OpenAI caps top_logprobs at 5; one static K keeps a single decode
# executable regardless of what each request asked for (the host slices)
TOP_LOGPROBS_K = 5


def token_logprobs(
    logits: jnp.ndarray,   # [B, V] float32 (post-mask: the real sampling dist)
    tokens: jnp.ndarray,   # [B] int32 chosen ids
    k: int = TOP_LOGPROBS_K,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """(chosen logprob [B], top-k ids [B,k], top-k logprobs [B,k]).

    Computed on device inside the decode dispatch: a logsumexp + gather +
    top_k over [B, V] is noise next to the model forward, and returning it
    unconditionally keeps one executable (no logprobs-variant recompiles).
    """
    lse = jax.scipy.special.logsumexp(logits, axis=-1, keepdims=True)  # [B,1]
    logp = logits - lse
    chosen = jnp.take_along_axis(logp, tokens[:, None].astype(jnp.int32), axis=-1)[:, 0]
    top_vals, top_ids = jax.lax.top_k(logp, k)
    return chosen, top_ids.astype(jnp.int32), top_vals
