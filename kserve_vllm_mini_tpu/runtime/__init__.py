"""In-repo TPU serving runtime: continuous-batching engine + OpenAI server.

The reference outsources serving to external container images (SURVEY.md
§2.1); this package is the TPU-native equivalent — the framework works with
no cluster at all."""
